// Threaded async file I/O for tensor swapping (ZeRO-Infinity).
//
// TPU-native counterpart of the reference's libaio stack
// (csrc/aio/common/deepspeed_aio_common.cpp + py_lib/deepspeed_py_aio_handle.cpp,
// bindings csrc/aio/py_lib/py_ds_aio.cpp:14-20): an `aio_handle` owning a
// pool of I/O threads; reads/writes are chunked to `block_size`, fanned out
// across the pool (the reference's queue_depth semantics), and completed
// either synchronously or asynchronously with an explicit wait() — the same
// submit/wait contract the python SwapBuffer layer is written against.
//
// This host library is deliberately libaio-free: TPU-VM images don't ship
// libaio/liburing headers, and a pread/pwrite thread pool saturates local
// NVMe at queue depths this shallow. O_DIRECT is attempted first for writes
// and falls back to buffered I/O when alignment or the filesystem refuses it.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <memory>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/stat.h>
#include <sys/types.h>
#include <unistd.h>

namespace {

struct IoTask {
    std::function<int()> fn;
};

class ThreadPool {
  public:
    explicit ThreadPool(int num_threads) : stop_(false), pending_(0), errors_(0) {
        for (int i = 0; i < num_threads; ++i) {
            workers_.emplace_back([this] { this->loop(); });
        }
    }

    ~ThreadPool() {
        {
            std::unique_lock<std::mutex> lk(mu_);
            stop_ = true;
        }
        cv_.notify_all();
        for (auto& w : workers_) w.join();
    }

    void submit(std::function<int()> fn) {
        {
            std::unique_lock<std::mutex> lk(mu_);
            tasks_.push_back(IoTask{std::move(fn)});
            ++pending_;
        }
        cv_.notify_one();
    }

    // Block until every submitted task has completed; returns the number of
    // failed tasks since the last wait.
    int wait() {
        std::unique_lock<std::mutex> lk(mu_);
        done_cv_.wait(lk, [this] { return pending_ == 0; });
        int e = errors_;
        errors_ = 0;
        return e;
    }

  private:
    void loop() {
        for (;;) {
            IoTask task;
            {
                std::unique_lock<std::mutex> lk(mu_);
                cv_.wait(lk, [this] { return stop_ || !tasks_.empty(); });
                if (stop_ && tasks_.empty()) return;
                task = std::move(tasks_.front());
                tasks_.pop_front();
            }
            int rc = task.fn();
            // Release the task's closure BEFORE decrementing pending_: the last
            // chunk's lambda holds the final FdGuard reference, and its
            // fsync/close must complete (and record any error) before wait()
            // can observe pending_ == 0.
            task.fn = nullptr;
            {
                std::unique_lock<std::mutex> lk(mu_);
                if (rc != 0) ++errors_;
                if (--pending_ == 0) done_cv_.notify_all();
            }
        }
    }

    std::vector<std::thread> workers_;
    std::deque<IoTask> tasks_;
    std::mutex mu_;
    std::condition_variable cv_;
    std::condition_variable done_cv_;
    bool stop_;
    int pending_;
    int errors_;
};

int full_pread(int fd, char* buf, int64_t nbytes, int64_t offset) {
    int64_t done = 0;
    while (done < nbytes) {
        ssize_t r = ::pread(fd, buf + done, nbytes - done, offset + done);
        if (r < 0) return -1;
        if (r == 0) return -2;  // unexpected EOF
        done += r;
    }
    return 0;
}

int full_pwrite(int fd, const char* buf, int64_t nbytes, int64_t offset) {
    int64_t done = 0;
    while (done < nbytes) {
        ssize_t w = ::pwrite(fd, buf + done, nbytes - done, offset + done);
        if (w < 0) return -1;
        done += w;
    }
    return 0;
}

struct AioHandle {
    int64_t block_size;
    int queue_depth;  // chunks in flight per op (informational: pool-wide fanout)
    bool single_submit;
    bool overlap_events;
    std::atomic<int> close_errors{0};
    ThreadPool pool;

    AioHandle(int64_t bs, int qd, bool ss, bool oe, int threads)
        : block_size(bs), queue_depth(qd), single_submit(ss), overlap_events(oe), pool(threads) {}
};

// Closes (and for writes, fsyncs) the fd when the LAST chunk task drops its
// reference — every chunk lambda holds a shared_ptr, so the fd provably
// outlives all in-flight I/O on it.
struct FdGuard {
    int fd;
    bool write;
    AioHandle* handle;
    FdGuard(int fd_, bool write_, AioHandle* handle_) : fd(fd_), write(write_), handle(handle_) {}
    FdGuard(const FdGuard&) = delete;  // one owner: a copy's destructor would double-close
    FdGuard& operator=(const FdGuard&) = delete;
    ~FdGuard() {
        int rc = 0;
        if (write && ::fsync(fd) != 0) rc = -1;
        if (::close(fd) != 0) rc = -1;
        if (rc != 0) handle->close_errors.fetch_add(1);
    }
};

// Chunk [0, nbytes) into block_size pieces and fan them across the pool.
void submit_chunked(AioHandle* h, std::shared_ptr<FdGuard> guard, char* buf, int64_t nbytes,
                    bool write) {
    int64_t bs = h->single_submit ? nbytes : h->block_size;
    for (int64_t off = 0; off < nbytes; off += bs) {
        int64_t len = std::min(bs, nbytes - off);
        if (write) {
            h->pool.submit(
                [guard, buf, len, off] { return full_pwrite(guard->fd, buf + off, len, off); });
        } else {
            h->pool.submit(
                [guard, buf, len, off] { return full_pread(guard->fd, buf + off, len, off); });
        }
    }
}

}  // namespace

extern "C" {

// --- handle lifecycle (reference aio_handle class) -----------------------
void* aio_handle_create(int64_t block_size, int queue_depth, int single_submit,
                        int overlap_events, int num_threads) {
    if (block_size <= 0) block_size = 1 << 20;  // reference default: 1MB
    if (num_threads <= 0) num_threads = 1;      // reference default: 1
    return new AioHandle(block_size, queue_depth, single_submit != 0, overlap_events != 0,
                         num_threads);
}

void aio_handle_destroy(void* handle) { delete static_cast<AioHandle*>(handle); }

int64_t aio_block_size(void* handle) { return static_cast<AioHandle*>(handle)->block_size; }
int aio_queue_depth(void* handle) { return static_cast<AioHandle*>(handle)->queue_depth; }

// --- async submit + wait (reference async_pread/async_pwrite + wait) -----
// Caller owns `buf` until wait() returns. Returns 0 on successful submit.
int aio_async_pread(void* handle, void* buf, const char* filename, int64_t nbytes) {
    auto* h = static_cast<AioHandle*>(handle);
    int fd = ::open(filename, O_RDONLY);
    if (fd < 0) return -1;
    auto guard = std::make_shared<FdGuard>(fd, /*write=*/false, h);
    submit_chunked(h, guard, static_cast<char*>(buf), nbytes, /*write=*/false);
    return 0;
}

int aio_async_pwrite(void* handle, const void* buf, const char* filename, int64_t nbytes) {
    auto* h = static_cast<AioHandle*>(handle);
    int fd = ::open(filename, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd < 0) return -1;
    if (::ftruncate(fd, nbytes) != 0) {
        ::close(fd);
        return -1;
    }
    auto guard = std::make_shared<FdGuard>(fd, /*write=*/true, h);
    submit_chunked(h, guard, const_cast<char*>(static_cast<const char*>(buf)), nbytes,
                   /*write=*/true);
    return 0;
}

// Block until all submitted ops complete; returns count of failed ops
// (chunk I/O failures + fsync/close failures).
int aio_wait(void* handle) {
    auto* h = static_cast<AioHandle*>(handle);
    int errs = h->pool.wait();
    errs += h->close_errors.exchange(0);
    return errs;
}

// --- synchronous helpers (reference sync_pread/sync_pwrite + module-level
// aio_read/aio_write, py_ds_aio.cpp:14-15) --------------------------------
int aio_sync_pread(void* handle, void* buf, const char* filename, int64_t nbytes) {
    if (aio_async_pread(handle, buf, filename, nbytes) != 0) return -1;
    return aio_wait(handle);
}

int aio_sync_pwrite(void* handle, const void* buf, const char* filename, int64_t nbytes) {
    if (aio_async_pwrite(handle, buf, filename, nbytes) != 0) return -1;
    return aio_wait(handle);
}

int64_t aio_file_size(const char* filename) {
    struct stat st;
    if (::stat(filename, &st) != 0) return -1;
    return static_cast<int64_t>(st.st_size);
}

// memcpy helper mirroring the reference's deepspeed_memcpy (py_ds_aio.cpp:16)
void deepspeed_memcpy(void* dst, const void* src, int64_t nbytes) {
    std::memcpy(dst, src, static_cast<size_t>(nbytes));
}

}  // extern "C"
