// Vectorized host Adagrad for offloaded optimizer state.
//
// Counterpart of the reference's csrc/adagrad/cpu_adagrad.cpp: same
// host-DRAM partition contract as cpu_adam.cpp, single accumulator state.
// -O3 -march=native autovectorizes this simple kernel to the full register
// width; an explicit intrinsics path adds nothing here.

#include <cmath>
#include <cstdint>
#include <mutex>
#include <unordered_map>

namespace {

struct AdagradState {
    float lr;
    float eps;
    float weight_decay;
};

std::unordered_map<int, AdagradState> g_optimizers;
std::mutex g_mu;

}  // namespace

extern "C" {

int create_adagrad(int optimizer_id, float lr, float eps, float weight_decay) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_optimizers[optimizer_id] = AdagradState{lr, eps, weight_decay};
    return 0;
}

int destroy_adagrad(int optimizer_id) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_optimizers.erase(optimizer_id);
    return 0;
}

int adagrad_update(int optimizer_id, float lr, float eps, float weight_decay, float* params,
                   const float* grads, float* accum, int64_t n) {
    {
        std::lock_guard<std::mutex> lk(g_mu);
        if (g_optimizers.find(optimizer_id) == g_optimizers.end()) return -1;
    }
    for (int64_t i = 0; i < n; ++i) {
        float grad = grads[i];
        if (weight_decay > 0.f) grad += weight_decay * params[i];
        accum[i] += grad * grad;
        params[i] -= lr * grad / (std::sqrt(accum[i]) + eps);
    }
    return 0;
}

}  // extern "C"
