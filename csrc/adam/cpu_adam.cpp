// Vectorized host Adam / AdamW for offloaded optimizer state (ZeRO-Infinity).
//
// TPU-native counterpart of the reference's DeepSpeedCPUAdam
// (csrc/adam/cpu_adam_impl.cpp + csrc/includes/simd.h AVX512/AVX2 paths):
// the fp32 master partition and moments live in host DRAM; the TPU chip
// computes grads, and this library applies the fused Adam update on the
// host's vector units while the chip proceeds with the next microbatch.
//
// SIMD: AVX-512/AVX2 intrinsics when compiled in (-march=native on the
// TPU-VM's x86 host), scalar fallback otherwise. Large tensors are sliced
// across a small thread fan-out (the reference parallelizes via OpenMP).

#include <cmath>
#include <cstdint>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#if defined(__AVX512F__) || defined(__AVX2__)
#include <immintrin.h>
#endif

namespace {

struct AdamState {
    float lr;
    float beta1;
    float beta2;
    float eps;
    float weight_decay;
    bool adamw_mode;
};

std::unordered_map<int, AdamState> g_optimizers;
std::mutex g_mu;

// Scalar reference path; also the tail handler for the SIMD paths.
void adam_scalar(float* p, const float* g, float* m, float* v, int64_t lo, int64_t hi,
                 float lr, float beta1, float beta2, float eps, float weight_decay,
                 float bc1, float bc2, bool adamw) {
    const float step_size = lr / bc1;
    for (int64_t i = lo; i < hi; ++i) {
        float grad = g[i];
        if (!adamw && weight_decay > 0.f) grad += weight_decay * p[i];
        m[i] = beta1 * m[i] + (1.f - beta1) * grad;
        v[i] = beta2 * v[i] + (1.f - beta2) * grad * grad;
        float denom = std::sqrt(v[i]) / std::sqrt(bc2) + eps;
        // torch-AdamW convention: decoupled decay is lr*wd*p, NOT scaled by
        // the bias correction (matches ops/adam/fused_adam.py:77-81)
        if (adamw && weight_decay > 0.f) p[i] -= lr * weight_decay * p[i];
        p[i] -= step_size * (m[i] / denom);
    }
}

#if defined(__AVX512F__)
constexpr int64_t kWidth = 16;
void adam_simd(float* p, const float* g, float* m, float* v, int64_t lo, int64_t hi,
               float lr, float beta1, float beta2, float eps, float weight_decay,
               float bc1, float bc2, bool adamw) {
    const __m512 vb1 = _mm512_set1_ps(beta1);
    const __m512 vb2 = _mm512_set1_ps(beta2);
    const __m512 vomb1 = _mm512_set1_ps(1.f - beta1);
    const __m512 vomb2 = _mm512_set1_ps(1.f - beta2);
    const __m512 veps = _mm512_set1_ps(eps);
    const __m512 vwd = _mm512_set1_ps(weight_decay);
    const __m512 vstep = _mm512_set1_ps(-lr / bc1);
    const __m512 vlrwd = _mm512_set1_ps(lr * weight_decay);
    const __m512 vrsqrt_bc2 = _mm512_set1_ps(1.f / std::sqrt(bc2));
    int64_t i = lo;
    for (; i + kWidth <= hi; i += kWidth) {
        __m512 vp = _mm512_loadu_ps(p + i);
        __m512 vg = _mm512_loadu_ps(g + i);
        if (!adamw && weight_decay > 0.f) vg = _mm512_fmadd_ps(vwd, vp, vg);
        __m512 vm = _mm512_fmadd_ps(vb1, _mm512_loadu_ps(m + i), _mm512_mul_ps(vomb1, vg));
        __m512 vv = _mm512_fmadd_ps(vb2, _mm512_loadu_ps(v + i),
                                    _mm512_mul_ps(vomb2, _mm512_mul_ps(vg, vg)));
        _mm512_storeu_ps(m + i, vm);
        _mm512_storeu_ps(v + i, vv);
        __m512 denom = _mm512_add_ps(_mm512_mul_ps(_mm512_sqrt_ps(vv), vrsqrt_bc2), veps);
        __m512 upd = _mm512_div_ps(vm, denom);
        if (adamw && weight_decay > 0.f) vp = _mm512_fnmadd_ps(vlrwd, vp, vp);
        _mm512_storeu_ps(p + i, _mm512_fmadd_ps(vstep, upd, vp));
    }
    adam_scalar(p, g, m, v, i, hi, lr, beta1, beta2, eps, weight_decay, bc1, bc2, adamw);
}
#elif defined(__AVX2__)
constexpr int64_t kWidth = 8;
void adam_simd(float* p, const float* g, float* m, float* v, int64_t lo, int64_t hi,
               float lr, float beta1, float beta2, float eps, float weight_decay,
               float bc1, float bc2, bool adamw) {
    const __m256 vb1 = _mm256_set1_ps(beta1);
    const __m256 vb2 = _mm256_set1_ps(beta2);
    const __m256 vomb1 = _mm256_set1_ps(1.f - beta1);
    const __m256 vomb2 = _mm256_set1_ps(1.f - beta2);
    const __m256 veps = _mm256_set1_ps(eps);
    const __m256 vwd = _mm256_set1_ps(weight_decay);
    const __m256 vstep = _mm256_set1_ps(-lr / bc1);
    const __m256 vlrwd = _mm256_set1_ps(lr * weight_decay);
    const __m256 vrsqrt_bc2 = _mm256_set1_ps(1.f / std::sqrt(bc2));
    int64_t i = lo;
    for (; i + kWidth <= hi; i += kWidth) {
        __m256 vp = _mm256_loadu_ps(p + i);
        __m256 vg = _mm256_loadu_ps(g + i);
        if (!adamw && weight_decay > 0.f) vg = _mm256_fmadd_ps(vwd, vp, vg);
        __m256 vm = _mm256_fmadd_ps(vb1, _mm256_loadu_ps(m + i), _mm256_mul_ps(vomb1, vg));
        __m256 vv = _mm256_fmadd_ps(vb2, _mm256_loadu_ps(v + i),
                                    _mm256_mul_ps(vomb2, _mm256_mul_ps(vg, vg)));
        _mm256_storeu_ps(m + i, vm);
        _mm256_storeu_ps(v + i, vv);
        __m256 denom = _mm256_add_ps(_mm256_mul_ps(_mm256_sqrt_ps(vv), vrsqrt_bc2), veps);
        __m256 upd = _mm256_div_ps(vm, denom);
        if (adamw && weight_decay > 0.f) vp = _mm256_fnmadd_ps(vlrwd, vp, vp);
        _mm256_storeu_ps(p + i, _mm256_fmadd_ps(vstep, upd, vp));
    }
    adam_scalar(p, g, m, v, i, hi, lr, beta1, beta2, eps, weight_decay, bc1, bc2, adamw);
}
#else
void adam_simd(float* p, const float* g, float* m, float* v, int64_t lo, int64_t hi,
               float lr, float beta1, float beta2, float eps, float weight_decay,
               float bc1, float bc2, bool adamw) {
    adam_scalar(p, g, m, v, lo, hi, lr, beta1, beta2, eps, weight_decay, bc1, bc2, adamw);
}
#endif

constexpr int64_t kParallelThreshold = 1 << 20;  // 1M elements

template <typename Fn>
void parallel_for(int64_t n, Fn body) {
    if (n < kParallelThreshold) {
        body(0, n);
        return;
    }
    int threads = std::min<int64_t>(std::thread::hardware_concurrency(), 8);
    if (threads < 2) {
        body(0, n);
        return;
    }
    std::vector<std::thread> pool;
    int64_t chunk = (n + threads - 1) / threads;
    chunk = (chunk + 63) & ~int64_t(63);  // cache-line-multiple split points
    for (int t = 0; t < threads; ++t) {
        int64_t lo = t * chunk;
        int64_t hi = std::min(n, lo + chunk);
        if (lo >= hi) break;
        pool.emplace_back([=] { body(lo, hi); });
    }
    for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// Mirrors the reference bindings (csrc/adam/cpu_adam.cpp:8-15):
// create_adam / adam_update / destroy_adam keyed by optimizer_id.
int create_adam(int optimizer_id, float lr, float beta1, float beta2, float eps,
                float weight_decay, int adamw_mode) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_optimizers[optimizer_id] = AdamState{lr, beta1, beta2, eps, weight_decay, adamw_mode != 0};
    return 0;
}

int destroy_adam(int optimizer_id) {
    std::lock_guard<std::mutex> lk(g_mu);
    g_optimizers.erase(optimizer_id);
    return 0;
}

// One fused update over a flat fp32 partition. `step` is 1-based.
int adam_update(int optimizer_id, int64_t step, float lr, float beta1, float beta2, float eps,
                float weight_decay, int bias_correction, float* params, const float* grads,
                float* exp_avg, float* exp_avg_sq, int64_t n) {
    bool adamw;
    {
        std::lock_guard<std::mutex> lk(g_mu);
        auto it = g_optimizers.find(optimizer_id);
        if (it == g_optimizers.end()) return -1;
        adamw = it->second.adamw_mode;
    }
    float bc1 = 1.f, bc2 = 1.f;
    if (bias_correction) {
        bc1 = 1.f - std::pow(beta1, static_cast<float>(step));
        bc2 = 1.f - std::pow(beta2, static_cast<float>(step));
    }
    parallel_for(n, [&](int64_t lo, int64_t hi) {
        adam_simd(params, grads, exp_avg, exp_avg_sq, lo, hi, lr, beta1, beta2, eps,
                  weight_decay, bc1, bc2, adamw);
    });
    return 0;
}

// Returns the SIMD lane width compiled in (16 = AVX-512, 8 = AVX2, 1 = scalar).
int adam_simd_width() {
#if defined(__AVX512F__)
    return 16;
#elif defined(__AVX2__)
    return 8;
#else
    return 1;
#endif
}

}  // extern "C"
