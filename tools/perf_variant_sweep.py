"""Config-1 perf variants on the live TPU: (micro, flash[, scan]) combos.

Usage: python tools/perf_variant_sweep.py "8,1" "16,1" "12,0" "8,1,0"
Third field: scan_layers (default 1); 0 = unrolled Python layer loop.
Drains via the SMALLEST param leaf (see PERF.md: fetching a large leaf
inside the timed window costs ~1.5s over the tunnel). Persistent compile
cache on, so reruns skip compiles.
"""
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

cache = os.path.join(REPO, ".jax_cache")
os.makedirs(cache, exist_ok=True)
jax.config.update("jax_compilation_cache_dir", cache)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM, gpt2_config

combos = [tuple(int(x) for x in a.split(",")) for a in sys.argv[1:]] or [(8, 1), (16, 1)]
combos = [c if len(c) == 3 else (*c, 1) for c in combos]
seq = 1024
PEAK = 197e12

for micro, flash, scan in combos:
    mesh_mod.reset_topology()
    mcfg = gpt2_config("125m", max_seq_len=seq, remat=False, flash_attention=bool(flash), scan_layers=bool(scan))
    engine, _, _, _ = ds.initialize(
        model=TransformerLM(mcfg),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4, "weight_decay": 0.01}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
        dist_init_required=False,
    )
    rs = np.random.RandomState(0)
    toks = rs.randint(0, mcfg.vocab_size, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    placed = engine._place_batch(batch)

    def drain():
        lv = jax.tree_util.tree_leaves(engine.get_params())
        jax.device_get(min(lv, key=lambda a: a.size))

    try:
        for _ in range(3):
            loss = engine(placed)
            engine.backward(loss)
            engine.step()
        drain()
        steps = 20
        t0 = time.perf_counter()
        for _ in range(steps):
            loss = engine(placed)
            engine.backward(loss)
            engine.step()
        drain()
        dt = time.perf_counter() - t0
        tps = steps * micro * seq / dt
        n = engine.num_parameters()
        mfu = tps * (6 * n + 12 * mcfg.num_layers * mcfg.hidden_size * seq) / PEAK
        print(
            f"micro={micro} flash={flash} scan={scan}: {tps:,.0f} tok/s/chip  mfu={mfu:.4f}  "
            f"vs_ns={mfu / 0.40:.4f}  ({dt:.3f}s / {steps} steps)",
            flush=True,
        )
    except Exception as e:
        print(f"micro={micro} flash={flash} scan={scan}: FAILED {type(e).__name__}: {str(e)[:160]}", flush=True)
