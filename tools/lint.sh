#!/bin/sh
# Static-analysis gate: repo AST lint + the tiny-config analysis pass suite.
# Error findings in deepspeed_tpu/ fail the run (tests/ findings are
# warn-only); the pytest leg runs every pass against deliberately-broken
# miniature programs (red) and the real engine programs (green), so a
# regression in either the passes or the properties they guard trips CI.
# Wired into tools/fast_tests.sh; also runnable standalone.
cd "$(dirname "$0")/.." || exit 1
echo "== tools/lint.sh: repo AST lint =="
python tools/lint.py deepspeed_tpu tests bench.py || exit 1
echo "== tools/lint.sh: analysis pass suite =="
python -m pytest -q tests/unit/analysis -p no:cacheprovider || exit 1
