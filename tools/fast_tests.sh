#!/bin/sh
# Fast test tier — target <10 min on the 1-core harness box (the full
# 650+-test suite on the 8-device virtual CPU mesh runs for hours there).
# Covers the core surface: engine + config, the fused grad-accum path
# (single-dispatch parity + donation/retrace guards — catches dispatch and
# recompile regressions per commit), the whole ZeRO stack
# (1/2/3/offload/zero++), mesh/groups, collectives, op-builder registry,
# MoQ, and compression. Run the FULL suite (python -m pytest tests/ -q)
# before shipping cross-cutting changes; this tier is the per-commit loop.
# Measured 2026-07-31: ~5 min, 195 tests (+22 fused/telemetry 2026-08-03,
# +24 paged-KV serving 2026-08-03: pool allocator, paged attention parity,
# continuous-batching vs dense token-exactness + retrace/dispatch guards;
# +static-analysis gate 2026-08-03: tools/lint.sh runs the repo AST lint —
# errors in deepspeed_tpu/ fail the tier — and the analysis pass suite,
# red fixtures + green sweep over the real step/serving programs;
# +13 speculative-decoding tests 2026-08-03: drafter units, spec-on vs
# spec-off vs dense token-exactness incl. preemption/EOS/budget clamp,
# one-dispatch-per-round + compile-bound guards, rollback accounting;
# +18 comm-overlap tests 2026-08-03: pipelined-vs-unpipelined bit-identity
# across ZeRO-1/3 × gas × precision (remat incl.), overlap-pass green on
# the real ZeRO-3 step / red on a serialized schedule, PLD-disables-
# prefetch gating, DS-R006 lint. The old known-failure
# set (zero_stage_trains[0-3] + zeropp qwZ/qgZ "did not learn in 5 steps"
# rng flakes) is GONE: those tests now use deterministic learnable data +
# a relative loss-decrease criterion — expect 0 failures on this box).
# +production-traffic tests 2026-08-03 (test_traffic.py + extended
# test_kv_pool.py): prefix-cache token-exactness vs sharing-off incl.
# preemption, pages-allocated-once refcount accounting, CoW/invalidation,
# randomized pool partition invariant, SLA no-starvation replay smoke
# (2 tenants, shared prefix, flood-vs-trickle on a virtual clock),
# admission control, DS-R007 lint, traffic green sweep.
# +ragged serving 2026-08-04 (test_ragged_serving.py + extended
# test_paged_attention.py + analysis compile gate): ragged-vs-bucketed
# byte-identical streams across admission/preemption/prefix/spec-K-mix/
# EOS, ≤2-compiled-programs + 1-dispatch-per-step + 3-wave retrace
# guards, ragged attention kernel parity (XLA fallback + Pallas
# interpret), ragged program green sweep.
# +fault tolerance 2026-08-04 (test_fault_tolerance.py +
# test_journal_recovery.py + test_chaos.py): atomic staged-commit
# checkpoint layout, in-process chaos kills at every ckpt/serve injection
# point, auto_resume bit-identical losses (bf16 + fp16 dynamic scale),
# async-snapshot parity + zero-new-programs telemetry guard, torn-file /
# torn-journal red tests, byte-identical stream recovery, DS-R008 lint.
# The FULL subprocess kill -9 matrix is `pytest -m slow
# tests/unit/checkpoint/test_chaos_matrix.py` (excluded here and from
# tier-1).
# +observability 2026-08-04 (test_tracer.py + test_flight_recorder.py +
# test_telemetry_free.py + test_request_spans.py + monitor suite): unified
# tracing plane — span nesting/ring/percentiles/thread-safety-with-async-
# writer, serving request-lifecycle spans across admission/preemption/
# spec-decode, chaos-kill flight-recorder postmortems (subprocess exit
# case is `-m slow`), telemetry-is-free guard (0 new programs, host-
# transfer pass clean, <2% overhead bound), engine.observability() merged
# reports + Perfetto export, monitor block + JSONL backend + hub feed,
# DS-R009 lint.
# +multi-step windows 2026-08-04 (test_multistep_serving.py + extended
# test_journal_recovery.py + analysis window gate): N-decode-rounds-per-
# dispatch fused windows — window vs single-step vs bucketed vs dense
# byte-identical across EOS-in-window/window-edge/admission-break/
# preemption/prefix-attach/spec-handoff, steady-state dispatches/token
# ≤ 1/horizon via telemetry, ≤4-compiled-programs + retrace guards,
# mid-window crash recovery + one-journal-sync-per-window, window-program
# green sweep (donation through the lax.scan carry, 0 host transfers).
# +serving fleet 2026-08-04 (test_fleet.py + fleet green gate + DS-R010
# lint): replicated engines behind the FleetRouter — byte-identical
# streams under replica kills at every fleet chaos point, live migration
# mid-prefill/mid-decode with the acked prefix audited, drain-to-empty +
# journal compaction, prefix-affinity-beats-random routing, SLA/goodput
# across a mid-trace kill on the loadgen replay, circuit breaker,
# prefill/decode role split, elasticity resize policy + journal-catch-up
# join, fleet-adds-0-programs compile gate. The real kill -9
# restart-and-adopt case is `-m slow`.
# +multi-chip TP serving 2026-08-04 (test_tp_serving.py + extended
# test_source_lint.py; the analysis gate test_passes.py::
# test_green_tp_serving rides the lint.sh analysis suite below):
# tensor-parallel sharded ragged serving on the virtual CPU mesh —
# byte-identical greedy streams at tp∈{1,2,4} vs the single-chip oracle
# across admission/preemption/prefix-attach/spec-K/multi-step windows,
# ≤2-compiled-programs + 1-dispatch-per-step + retrace guards ON the
# mesh, int8 weight roundtrip ≤ max|w_ch|/254 + logits-allclose bound,
# EQuARX quantized all-reduce allclose + wire-bytes = fp/4 accounting,
# DS-R005/DS-R007 TP-path lint extensions.
# +multi-step TRAINING windows 2026-08-04 (test_multistep_training.py +
# test_passes.py::test_green_multistep_training_program on the lint.sh
# analysis suite + DS-R009 window/Loader lint extension): N-optimizer-
# steps-per-dispatch fused windows — window vs sequential BIT-identical
# losses/master-trees/loss-scale across zero{1,3} × {bf16, fp16-forced-
# overflow} × gas{1,2} × horizon{2,4}, checkpoint/monitor/data/profiler
# break accounting (windows never straddle a checkpoint interval),
# train.mid_window chaos kill → auto_resume bit-identical, prefetching-
# loader cursor exact-resume roundtrips, steady-state dispatches/opt-step
# ≤ 1/N via compile telemetry + 3-wave retrace guard, deferred-loss-drain
# value identity, mid-window protocol guards, window-program green sweep
# (full state tuple donated THROUGH the lax.scan carry, 0 in-program host
# transfers).
# +ZeRO-Infinity streamed host offload 2026-08-07 (test_host_offload.py
# rides the tests/unit/runtime/zero dir below; test_passes.py::
# test_green_infinity_offload_program rides the lint.sh analysis suite;
# DS-R008/DS-R009 Streamer-family lint extensions ride
# test_source_lint.py): fp32 master + Adam moments live in pinned host
# buffers and stream per-bucket through a depth-2 double-buffered async
# pipeline — streamed vs on-device BIT-identical losses/master across
# zero{1,3} × {fp32,bf16,fp16-forced-overflow} × gas{1,2}, fully-windowed
# multi_step bit-identity (same window trace both engines), declared
# stream schedule == measured bytes + 0 exposed ms with both pipeline
# knobs on / red overlap verdict with pipeline_write off, host-resident
# checkpoint snapshot roundtrip + streamed/legacy format guards,
# train.mid_offload_stream chaos kill → auto_resume bit-identical,
# legacy cpu_offload* config-routing red tests, bench bisection-probe
# unit.
# +static HBM ledger 2026-08-07 (test_memory.py + test_passes.py::
# test_green_memory_ledger_{offload,tp_serving} ride the lint.sh analysis
# suite; DS-R011/DS-R012 lint + the --json/--rule CLI ride
# test_source_lint.py): per-program peak-HBM estimator (backend
# memory_analysis() + optimized-HLO walk fallback with donation-alias
# dedup), sharding auditor (replicated-leaf-vs-declared-rule +
# pjit-inserted-collective-vs-declared-schedule red/green), whole-run
# residency ledger behind engine.memory_report() gated by
# analysis.hbm_budget_bytes (off|warn|raise, over-budget raises with
# per-buffer attribution). The two green gates statically reproduce the
# runtime claims: streamed zero-3 offload holds ≤2 buckets on device with
# the fp32 master host-side, and tp=4 serving holds KV bytes/chip ==
# total/tp with page tables host-side + 0 undeclared reshard collectives.
# +expert-parallel MoE fast path 2026-08-07 (tests/unit/moe below;
# test_passes.py::test_green_moe_programs rides the lint.sh analysis
# suite; DS-R005/DS-R009 *Gate/*MoE/*MoELayer routing-path lint
# extensions ride test_source_lint.py): expert-sharded training with
# explicit overlapped dispatch/combine all-to-alls (moe/a2a.py) — top-1/
# top-2 gating parity vs the dense-dispatch reference, deterministic
# capacity-overflow drops, expert-sharded checkpoint roundtrip bit-
# identity, train.mid_step chaos resume on the MoE config; the green
# gate pins 1 dispatch/step + full donation + every a2a hidden (exposed
# loop-collective bytes == 0) + int8 a2a wire == fp/4, and MoE routing
# inside the ragged serving programs at ≤2 compiles with zero retraces
# over shifting expert mixes.
cd "$(dirname "$0")/.." || exit 1
sh tools/lint.sh || exit 1
exec python -m pytest -q \
  tests/unit/runtime/test_engine.py \
  tests/unit/runtime/test_fused_grad_accum.py \
  tests/unit/runtime/test_multistep_training.py \
  tests/unit/runtime/test_compile_telemetry.py \
  tests/unit/runtime/test_config.py \
  tests/unit/runtime/test_lr_schedules.py \
  tests/unit/runtime/test_loss_scaler.py \
  tests/unit/runtime/test_runtime_utils.py \
  tests/unit/runtime/test_moq.py \
  tests/unit/runtime/zero \
  tests/unit/checkpoint/test_fault_tolerance.py \
  tests/unit/inference/test_journal_recovery.py \
  tests/unit/utils/test_chaos.py \
  tests/unit/profiling/test_tracer.py \
  tests/unit/profiling/test_flight_recorder.py \
  tests/unit/profiling/test_telemetry_free.py \
  tests/unit/inference/test_request_spans.py \
  tests/unit/monitor/test_monitor.py \
  tests/unit/inference/test_kv_pool.py \
  tests/unit/inference/test_serving.py \
  tests/unit/inference/test_ragged_serving.py \
  tests/unit/inference/test_multistep_serving.py \
  tests/unit/inference/test_spec_decode.py \
  tests/unit/inference/test_tp_serving.py \
  tests/unit/inference/test_traffic.py \
  tests/unit/inference/test_fleet.py \
  tests/unit/ops/test_paged_attention.py \
  tests/unit/ops/test_op_builder.py \
  tests/unit/parallel/test_mesh.py \
  tests/unit/utils/test_groups.py \
  tests/unit/comm/test_collectives.py \
  tests/unit/compression/test_compression.py \
  tests/unit/moe \
  "$@"
