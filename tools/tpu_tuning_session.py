"""Hardware autotuning session — run in a TPU tunnel window.

    timeout 1500 python tools/tpu_tuning_session.py

Tunes (zero stage × micro batch) for a GPT-2-small-class model on the real
chip with reference-style isolated subprocess trials (a stalled tunnel or
an HBM OOM fails one trial, not the session) and records the session under
``autotuning_results_tpu/`` (session_summary.json + best_config.json) — the
artifact VERDICT r4 asked for (autotuner row: "no hardware tuning session
has ever been run or recorded").

This file doubles as the ``--script`` contract for the trial children:
``model_factory`` / ``batch_factory`` / ``base_config`` below.
"""

import numpy as np


def model_factory():
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    return TransformerLM(gpt2_config("125m", max_seq_len=512, remat=False))


def batch_factory(n):
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 50257, (max(n, 1), 513)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


base_config = {
    "train_micro_batch_size_per_gpu": 4,
    "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "steps_per_print": 10_000,
}


def main():
    import json
    import os

    from deepspeed_tpu.autotuning.autotuner import Autotuner

    tuner = Autotuner(
        model_factory,
        base_config,
        batch_factory,
        micro_batches=[4, 8, 12],
        stages=[1, 2],
        trial_steps=10,
        warmup_steps=3,
        isolation="subprocess",
        user_script=os.path.abspath(__file__),
        trial_timeout_s=420.0,
        session_dir="autotuning_results_tpu",
    )
    best = tuner.tune()
    print(json.dumps(best, indent=2, default=str) if best else "no feasible config")


if __name__ == "__main__":
    main()
