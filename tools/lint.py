#!/usr/bin/env python
"""Repo AST lint CLI — thin wrapper over ``deepspeed_tpu.analysis.source_lint``.

Usage::

    python tools/lint.py                      # lint deepspeed_tpu + tests
    python tools/lint.py deepspeed_tpu bench.py --format json
    python tools/lint.py --json               # shorthand for --format json
    python tools/lint.py --rule DS-R011       # only the named rule(s)

Rules (DS-R001 repeat-on-cache through DS-R011 unsharded-pool-placement /
DS-R012 baked-constant-in-jit) are documented in the module and README
("Static analysis"). Findings under ``tests/`` are always
warn-only; error findings anywhere else exit nonzero — that is the CI gate
``tools/lint.sh`` wires into ``tools/fast_tests.sh``. Suppress a deliberate
site with ``# lint: allow(DS-RXXX)`` on the offending line.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from deepspeed_tpu.analysis.source_lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
