"""Benchmarks for the BASELINE target configs, one JSON line each.

Printed order (the driver parses the LAST line as the headline):

  2. llama-style ZeRO-3 fused training    (config 2, sized to one chip's HBM)
  3. ZeRO-Infinity max trainable params   (config 3, layer-streamed offload)
  4. 32k-sequence training                (config 4, flash attention + remat)
  5. MoE inference vs dense               (config 5, expert dispatch overhead)
  1. GPT-2 125M ZeRO-1 training           (config 1, tokens/s/chip — headline)

``vs_baseline`` semantics per line: training configs report measured MFU
over the 0.40 north star (BASELINE.json); the Infinity line reports trained
params over the ~1B in-HBM ceiling of this chip; the MoE line reports MoE
throughput over an active-param-matched dense model.
"""

from __future__ import annotations

import json
import os
import time
import traceback

import numpy as np

SEED = 0
NORTH_STAR_MFU = 0.40
# DS_BENCH_TINY=1: shrink every config so the whole bench smoke-tests on CPU
TINY = os.environ.get("DS_BENCH_TINY") == "1"


def _peak_tflops_bf16() -> float:
    """Per-chip bf16 peak. v5e (v5 lite): 197 TFLOP/s; fallbacks for others."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6": 918e12,
        "cpu": 1e12,  # nominal, keeps the math defined on CPU runs
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12


def _drain(engine):
    """Sync via a value at the END of the dependency chain (params feed the
    next step, so the fetch waits for every queued step); block_until_ready
    is unreliable on the tunneled backend."""
    import jax

    params = engine.get_params()
    leaf = jax.tree_util.tree_leaves(params)[-1]
    jax.device_get(leaf)


def _train_engine(model, config):
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod

    mesh_mod.reset_topology()
    engine, _, _, _ = ds.initialize(model=model, config=config, dist_init_required=False)
    return engine


def _timed_steps(engine, batch, warmup=3, steps=20):
    """Place the batch once (a real input pipeline prefetches to device;
    re-uploading identical tokens every step would measure the host link,
    not the chip), run warmup + timed steps, external wall clock."""
    placed = engine._place_batch(batch)
    for _ in range(warmup):
        loss = engine(placed)
        engine.backward(loss)
        engine.step()
    _drain(engine)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine(placed)
        engine.backward(loss)
        engine.step()
    _drain(engine)
    return time.perf_counter() - t0, loss


def _mfu(tokens_per_sec, n_params, num_layers, hidden, seq):
    # 6N per token (fwd+bwd) + attention 12*L*H*T
    flops_per_token = 6 * n_params + 12 * num_layers * hidden * seq
    return tokens_per_sec * flops_per_token / _peak_tflops_bf16()


# ---------------------------------------------------------------------------
def bench_gpt2_zero1():
    """Config 1: GPT-2 125M ZeRO-1, tokens/s/chip (the headline)."""
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    seq, micro = (128, 2) if TINY else (1024, 8)
    mcfg = gpt2_config("tiny" if TINY else "125m", max_seq_len=seq, remat=False)
    engine = _train_engine(
        TransformerLM(mcfg),
        {
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4, "weight_decay": 0.01}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    n_chips = max(engine.data_parallel_world_size(), 1)
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro * n_chips, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    dt, _ = _timed_steps(engine, batch, warmup=3, steps=20)
    tps_chip = 20 * micro * n_chips * seq / dt / n_chips
    mfu = _mfu(tps_chip, engine.num_parameters(), mcfg.num_layers, mcfg.hidden_size, seq)
    return {
        "metric": "gpt2_125m_zero1_tokens_per_sec_per_chip",
        "value": round(tps_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4),
    }


def bench_llama_zero3():
    """Config 2 (scaled to one chip's HBM): llama-architecture ~0.8B,
    ZeRO-3 + fused Adam, bf16, remat."""
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    seq, micro = (256, 1) if TINY else (2048, 1)
    mcfg = TransformerConfig(
        vocab_size=1024 if TINY else 32000,
        hidden_size=256 if TINY else 2048,
        num_layers=2 if TINY else 16,
        num_heads=16,
        num_kv_heads=4,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=False,
        remat=True,
    )
    engine = _train_engine(
        TransformerLM(mcfg),
        {
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    dt, _ = _timed_steps(engine, batch, warmup=2, steps=8)
    tps = 8 * micro * seq / dt
    mfu = _mfu(tps, engine.num_parameters(), mcfg.num_layers, mcfg.hidden_size, seq)
    # remat recomputes the forward in the backward: the chip does ~8N useful
    # FLOPs/token but MFU counts the 6N model FLOPs (standard accounting)
    return {
        "metric": "llama_0p8b_zero3_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4),
    }


def bench_infinity_max_params():
    """Config 3: ZeRO-Infinity parameter offload — train a model ~3x over
    the in-HBM ceiling (params + fp32 master + moments in host DRAM, layers
    streamed through HBM). Value = trained params; vs_baseline = multiple
    of the ~1e9-param in-HBM training ceiling of one 16GB chip."""
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    seq, micro = (128, 1) if TINY else (1024, 1)
    mcfg = TransformerConfig(
        vocab_size=1024 if TINY else 32000,
        hidden_size=256 if TINY else 2560,
        num_layers=4 if TINY else 32,
        num_heads=4 if TINY else 20,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=True,
        remat=False,
        dtype="bfloat16",
    )
    engine = _train_engine(
        TransformerLM(mcfg),
        {
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3, "offload_param": {"device": "cpu"}},
            "steps_per_print": 10_000,
        },
    )
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    t0 = time.perf_counter()
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    step_s = time.perf_counter() - t0
    assert np.isfinite(float(loss)), "non-finite streamed loss"
    n_params = engine.num_parameters()
    return {
        "metric": "zero_infinity_trainable_params_per_chip",
        "value": int(n_params),
        "unit": f"params (1 step {step_s:.1f}s, loss {float(loss):.3f})",
        "vs_baseline": round(n_params / 1.0e9, 2),
    }


def bench_long_seq():
    """Config 4 (one chip): 32k-token sequences via the Pallas flash kernel
    + remat (the single-chip leg of Ulysses; the seq axis itself needs a
    multi-chip mesh, validated in dryrun phase 1)."""
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    seq, micro = (2048, 1) if TINY else (32768, 1)
    mcfg = TransformerConfig(
        vocab_size=1024 if TINY else 32000,
        hidden_size=128 if TINY else 1024,
        num_layers=2 if TINY else 8,
        num_heads=2 if TINY else 8,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=True,
        remat=True,
        flash_attention=True,
    )
    engine = _train_engine(
        TransformerLM(mcfg),
        {
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10_000,
        },
    )
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    dt, _ = _timed_steps(engine, batch, warmup=2, steps=5)
    tps = 5 * micro * seq / dt
    mfu = _mfu(tps, engine.num_parameters(), mcfg.num_layers, mcfg.hidden_size, seq)
    return {
        "metric": "seq32k_flash_tokens_per_sec_per_chip",
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4),
    }


def bench_moe_inference():
    """Config 5 (one chip): MoE prefill throughput vs a dense model with the
    same ACTIVE parameters — vs_baseline ≥ ~1 means the expert dispatch
    (gate + capacity einsums) adds no material overhead."""
    import jax

    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.models.moe_transformer import MoETransformerConfig, MoETransformerLM

    seq, B = (128, 2) if TINY else (1024, 8)
    base = dict(
        vocab_size=1024 if TINY else 32000,
        hidden_size=128 if TINY else 1024,
        num_layers=2 if TINY else 8,
        num_heads=2 if TINY else 8,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=True,
    )
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, base["vocab_size"], (B, seq)).astype(np.int32)

    def prefill_tps(model):
        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="bf16")
        engine.init_params(toks)
        out = engine(toks)
        jax.device_get(np.asarray(out[0, -1, :8]))  # compile + drain
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            out = engine(toks)
        jax.device_get(np.asarray(out[0, -1, :8]))
        return reps * B * seq / (time.perf_counter() - t0)

    moe_tps = prefill_tps(
        MoETransformerLM(MoETransformerConfig(num_experts=8, moe_top_k=1, **base))
    )
    dense_tps = prefill_tps(TransformerLM(TransformerConfig(**base)))
    return {
        "metric": "moe8x_top1_prefill_tokens_per_sec",
        "value": round(moe_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(moe_tps / dense_tps, 4),
    }


def _run_one(fn):
    try:
        return fn()
    except Exception as e:  # one failed config must not kill the bench
        traceback.print_exc()
        return {
            "metric": fn.__name__,
            "value": 0,
            "unit": f"error: {type(e).__name__}: {str(e)[:160]}",
            "vs_baseline": 0,
        }


def main():
    # headline FIRST (on record even if a later config hangs) and re-emitted
    # LAST (the driver parses the final JSON line)
    headline = _run_one(bench_gpt2_zero1)
    print(json.dumps(headline), flush=True)
    for fn in (bench_llama_zero3, bench_infinity_max_params, bench_long_seq, bench_moe_inference):
        print(json.dumps(_run_one(fn)), flush=True)
    print(json.dumps(headline), flush=True)


if __name__ == "__main__":
    main()
