"""Benchmarks for the BASELINE target configs, one JSON line each.

Resilience architecture (the round-3 run produced zero numbers because a
~25-minute backend-init stall on the tunneled TPU consumed the whole budget
before the first byte of JSON):

- The PARENT process never imports jax. It probes the device in a killable
  subprocess (75s timeout, 3 attempts with backoff), then runs each config
  in its own subprocess with a hard per-config timeout.
- Every result line is printed the instant it exists AND appended to
  ``bench_partial.jsonl`` — a killed run still leaves everything it measured.
- Children enable JAX's persistent compilation cache (``.jax_cache/``), so a
  retried config skips its multi-minute XLA compile.
- A total wall-clock budget (DS_BENCH_BUDGET_S, default 22 min) gates each
  launch; configs that don't fit emit an explicit "skipped: budget" line.

Printed order (the driver parses the LAST line as the headline; each metric
is emitted EXACTLY once — the headline is MEASURED first, while the budget
is freshest, but its line prints last):

  2. llama-style ZeRO-3 fused training    (config 2, sized to one chip's HBM)
  3. ZeRO-Infinity max trainable params   (config 3, layer-streamed offload)
  4. 32k-sequence training                (config 4, flash attention + remat)
  5. MoE inference vs dense               (config 5, expert dispatch overhead)
  6. Paged-KV continuous-batching serving (config 6, decode tokens/s/chip)
  6b. Tensor-parallel sharded serving     (config 6b, tokens/s/chip at tp∈{1,2,4},
                                           scaling efficiency + quantized comm bytes)
  7. Serving fleet under replica kill     (config 7, goodput vs single replica)
  1. GPT-2 125M ZeRO-1 training           (config 1, tokens/s/chip — headline, LAST)

``vs_baseline`` semantics per line: training configs report measured MFU
over the 0.40 north star (BASELINE.json); the Infinity line reports trained
params over the ~1B in-HBM ceiling of this chip; the MoE line reports MoE
throughput over an active-param-matched dense model; the fleet line
reports 3-replica goodput UNDER a mid-trace replica kill over the
single-replica replay of the same trace (>1 = the fleet beats one replica
even while losing a member).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import traceback

import numpy as np

SEED = 0
NORTH_STAR_MFU = 0.40
# DS_BENCH_TINY=1: shrink every config so the whole bench smoke-tests on CPU
TINY = os.environ.get("DS_BENCH_TINY") == "1"
# Tiny mode (or an explicit JAX_PLATFORMS=cpu) means CPU-only: children must
# not touch the axon/TPU tunnel at all. The axon sitecustomize registers the
# PJRT plugin in EVERY python process via PYTHONPATH, and backend init then
# dials the (possibly down) tunnel even when the caller asked for cpu — so
# CPU children need the axon env stripped, not just JAX_PLATFORMS=cpu.
CPU_ONLY = TINY or os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
REPO = os.path.dirname(os.path.abspath(__file__))

# Canonical metric name per config — single source of truth for the return
# dicts below AND for error/stale records, so every BENCH file keys
# consistently and the known-good store never drifts from the emit path.
METRICS = {
    "gpt2_zero1": "gpt2_125m_zero1_tokens_per_sec_per_chip",
    "llama_zero3": "llama_0p8b_zero3_tokens_per_sec_per_chip",
    "infinity": "zero_infinity_trainable_params_per_chip",
    "long_seq": "seq32k_flash_tokens_per_sec_per_chip",
    "moe_inference": "moe8x_top1_prefill_tokens_per_sec",
    "moe_train": "moe_ep_train_tokens_per_sec",
    "decode_serving": "decode_tokens_per_sec_per_chip",
    "decode_serving_tp": "tp_decode_tokens_per_sec_per_chip",
    "fleet_serving": "fleet_goodput_tokens_per_sec",
}


def _child_env():
    """Environment for bench children. In CPU_ONLY mode, force the cpu
    backend and remove the axon plugin triggers so sitecustomize doesn't
    register the tunnel-backed PJRT plugin (see CPU_ONLY comment)."""
    env = dict(os.environ)
    if CPU_ONLY:
        env["JAX_PLATFORMS"] = "cpu"
        for key in ("PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE",
                    "PALLAS_AXON_REMOTE_COMPILE", "AXON_LOOPBACK_RELAY"):
            env.pop(key, None)
    return env


def _enable_compile_cache():
    """Persistent compilation cache: a retried config (same process tree or a
    later bench run) skips the multi-minute from-scratch XLA compile."""
    import jax

    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass  # older jax without these options: run uncached


def _peak_tflops_bf16() -> float:
    """Per-chip bf16 peak. v5e (v5 lite): 197 TFLOP/s; fallbacks for others."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6": 918e12,
        "cpu": 1e12,  # nominal, keeps the math defined on CPU runs
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12


def _drain(engine):
    """Sync via a value at the END of the dependency chain (params feed the
    next step, so the fetch waits for every queued step); block_until_ready
    is unreliable on the tunneled backend.

    Fetch the SMALLEST param leaf: any output of the step program waits for
    the whole step, but the fetch's transfer time lands inside the timed
    window — a 14MB leaf costs ~1.5s over the tunneled link (measured
    2026-07-31: same 20-step block read 86.5k tok/s with a 1.5KB leaf and
    43-47k with the 14MB one; this constant was the round-4 'regression')."""
    import jax

    params = engine.get_params()
    leaf = min(jax.tree_util.tree_leaves(params), key=lambda a: a.size)
    jax.device_get(leaf)


def _train_engine(model, config):
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod

    mesh_mod.reset_topology()
    engine, _, _, _ = ds.initialize(model=model, config=config, dist_init_required=False)
    return engine


def _compile_fields(engine):
    """Compile telemetry for the result record: total compiles + wall time,
    and the step program's dispatch count. Makes dispatch/recompile
    regressions visible in the BENCH files (a healthy steady-state run
    compiles each program once; the timed window adds zero compiles)."""
    try:
        stats = engine.compile_stats()
    except Exception:
        return {}
    step = (
        stats.get("fused_accum_step")
        or stats.get("fused_step")
        or stats.get("step")
        or {}
    )
    if not step:
        # inference serving engines: the steady-state program is the ragged
        # step (≤2 programs, one dispatch per scheduler step) — the fused
        # multi-step window when armed — or, on the bucketed oracle path,
        # the paged decode step per slot bucket
        paged = [rec for name, rec in sorted(stats.items())
                 if name.startswith(
                     ("paged_ragged_", "paged_multistep_", "paged_decode_"))]
        if paged:
            step = {"dispatches": sum(rec["dispatches"] for rec in paged)}
    return {
        "compiles": int(sum(rec["compiles"] for rec in stats.values())),
        "compile_s": round(sum(rec["compile_seconds"] for rec in stats.values()), 1),
        "step_dispatches": int(step.get("dispatches", 0)),
    }


def _analysis_fields(engine):
    """Static-analysis summary for the result record: the per-config comms
    budget (collective op count + per-device payload bytes, summed over the
    dispatched hot programs) and the donation-verified flag, derived from
    the compiled HLO by ``engine.analysis_report()``. BENCH_r*.json then
    tracks the communication schedule alongside throughput — a perf PR
    that silently adds an all-gather or drops a buffer alias shows up in
    the record even when the wall clock is too noisy to catch it. Runs
    after the timed window (it re-traces + re-compiles each program once)."""
    try:
        rep = engine.analysis_report(
            passes=["donation", "collectives", "host_transfer", "overlap"]
        )
        t = rep["totals"]
        return {
            "static_collective_ops": int(t.get("collective_count", 0)),
            "static_collective_bytes": int(t.get("collective_bytes", 0)),
            "donation_verified": bool(t.get("donation_verified", False)),
            "analysis_violations": int(t.get("violations", 0)),
            # comm/compute overlap verifier (ISSUE 5): True only when no
            # loop-body collective is exposed on the critical path; the byte
            # split says how much of the schedule's collective traffic has
            # real compute to hide behind vs how much is serialized.
            "overlap_verified": t.get("overlap_verified"),
            "hidden_collective_bytes": int(t.get("hidden_collective_bytes", 0)),
            "exposed_collective_bytes": int(t.get("exposed_collective_bytes", 0)),
        }
    except Exception as e:
        # never fail a bench record over analysis, but never vanish
        # silently either: the missing-fields case must be distinguishable
        # from "analysis ran clean" in the BENCH files
        traceback.print_exc()
        return {"analysis_error": f"{type(e).__name__}: {e}"[:200]}


def _memory_fields(engine):
    """Static HBM-ledger summary for the result record (ISSUE 18): the
    engine's whole-run per-chip residency peak (persistent buffers + the
    largest program's transient footprint, from ``memory_report`` with
    the per-program estimates folded in), the bytes sitting fully
    replicated across the mesh, and the ``analysis.hbm_budget_bytes``
    verdict (None when no budget is configured). On the CPU bench backend
    the estimator's temp bytes are a lower bound (see PERF.md). Runs after
    the timed window — folding the programs re-traces each one once."""
    try:
        led = engine.memory_report(include_programs=True, enforce=False)
        return {
            "peak_hbm_bytes_per_chip": int(led["peak_hbm_bytes_per_chip"]),
            "replicated_bytes": int(led["replicated_bytes"]),
            "hbm_budget_verified": led["hbm_budget_verified"],
        }
    except Exception as e:
        # same contract as _analysis_fields: never fail the record, never
        # vanish silently
        traceback.print_exc()
        return {"memory_error": f"{type(e).__name__}: {e}"[:200]}


def _trace_fields(engine, name, timed_window=None, overhead_reps=8):
    """Unified-tracing fields for a result record (ISSUE 10):

    - ``step_phase_ms`` — mean ms of the top-4 leaf phases by total time
      over a FRESH traced window of the measured configuration (the ring
      is cleared first: by this point it holds every comparison pass the
      config ran — spec-on, bucketed oracle, dense baseline warmup — and a
      breakdown labeled "the measured server" must not mix them in). The
      outer ``train.step``/``serve.step`` aggregates are excluded: this is
      the WHERE-did-the-step-go breakdown, not the step time again;
    - ``trace_overhead_pct`` — the same window re-run with the tracer
      disabled vs enabled ((t_on - t_off)/t_off; the fast tier pins the
      deterministic per-span bound under 2%, this is the in-situ
      wall-clock cross-check and rides informationally);
    - ``trace_file`` — a Perfetto/Chrome trace of that window's timeline,
      exported next to the other bench artifacts.

    Runs AFTER the headline timed window; the re-runs add no compiles
    (tracing is host-side only — the telemetry-free tests gate that
    globally)."""
    try:
        if timed_window is not None:
            # min-of-2 windows per arm: the signal is sub-percent, so one
            # noisy window would swamp it. The ring holds exactly these
            # traced windows afterwards — the phase snapshot below reads
            # the measured configuration only.
            engine.tracer.clear()
            t_on = min(timed_window(overhead_reps) for _ in range(2))
        phases = engine.tracer.phase_summary()
        # step-loop phases only: the outer step aggregates repeat the step
        # time, and the async writer's ckpt.stage/commit run OFF the step
        # loop (ckpt.d2h_stall is the step-loop piece and stays in; it
        # only appears when the window itself checkpoints — the record's
        # ckpt_stall_ms field carries the measured stall regardless)
        leaf = {
            k: v
            for k, v in phases.items()
            if k.split(".", 1)[0] in ("train", "serve", "eval", "timer", "comm", "fleet")
            and k not in ("train.step", "serve.step", "fleet.step")
            or k == "ckpt.d2h_stall"
        }
        top = sorted(leaf.items(), key=lambda kv: kv[1]["total_ms"], reverse=True)[:4]
        fields = {"step_phase_ms": {k: v["mean_ms"] for k, v in top}}
        trace_path = os.path.join(REPO, f"bench_trace_{name}.json")
        engine.observability_hub.export_chrome_trace(trace_path)
        fields["trace_file"] = os.path.basename(trace_path)
        if timed_window is not None:
            engine.tracer.enabled = False
            try:
                t_off = min(timed_window(overhead_reps) for _ in range(2))
            finally:
                engine.tracer.enabled = True
            if t_off > 0:
                fields["trace_overhead_pct"] = round((t_on - t_off) / t_off * 100, 3)
        return fields
    except Exception as e:
        traceback.print_exc()
        return {"trace_error": f"{type(e).__name__}: {e}"[:160]}


def _multistep_fields(engine_factory, batch, tokens_per_step, horizon=None):
    """Multi-step TRAINING window A/B (ISSUE 14), same-seed: two fresh
    engines from ``engine_factory(multi_step_on, horizon)`` — identical
    config seed, identical repeated batch, both driven through
    ``train_batch(data_iter)`` so the measured loops pay the same data/h2d
    structure — one with ``compile.multi_step`` armed, one without.

    Records the windowed tokens/s (``multistep_value``), the A/B ratio
    (``multistep_vs_singlestep``; on the tunneled TPU the ~2 ms dispatch
    RTT amortizes to 1/N, on this CPU box the enqueue overhead does),
    ``dispatches_per_opt_step`` from the engine's window stats (telemetry-
    derived: the tentpole's 1/N target), and the tracer phase deltas the
    windows exist to crush — data_fetch / h2d / dispatch / loss_fetch mean
    ms as ``[single_step, windowed]`` pairs (the windowed loss_fetch is
    the deferred ``train.loss_drain``). Runs AFTER the headline window on
    its own engines; the headline record's compile counters are untouched."""
    import itertools

    try:
        H = int(horizon or (4 if TINY else 8))

        def run(ms_on):
            engine = engine_factory(ms_on, H)
            it = itertools.repeat(batch)
            # warmup to a window boundary: 1 sequential init step (compiles
            # the single-step program) + one full window (compiles the
            # window program); the single-step arm just compiles + settles
            for _ in range(1 + (H if ms_on else 1)):
                engine.train_batch(data_iter=it)
            if ms_on:
                engine.flush_loss_drain()
            _drain(engine)
            engine.tracer.clear()
            steps = 2 * H
            t0 = time.perf_counter()
            for _ in range(steps):
                engine.train_batch(data_iter=it)
            if ms_on:
                engine.flush_loss_drain()
            _drain(engine)
            dt = time.perf_counter() - t0
            return engine, steps, dt, engine.tracer.phase_summary()

        seq_engine, steps, seq_dt, seq_ph = run(False)
        seq_tps = steps * tokens_per_step / seq_dt if seq_dt > 0 else 0.0
        win_engine, steps, win_dt, win_ph = run(True)
        win_tps = steps * tokens_per_step / win_dt if win_dt > 0 else 0.0
        ws = win_engine.window_stats()

        def mean_ms(ph, key):
            v = ph.get(key)
            return round(v["mean_ms"], 3) if v else 0.0

        return {
            "multistep_horizon": H,
            "multistep_value": round(win_tps, 1),
            "multistep_vs_singlestep": round(win_tps / seq_tps, 4) if seq_tps else 0.0,
            "dispatches_per_opt_step": round(ws["dispatches_per_opt_step"], 4),
            "train_window_steps": ws["window_steps"],
            "train_window_break_reasons": {
                k: v for k, v in ws["window_break_reasons"].items() if v
            },
            "multistep_phase_ms": {
                k: [mean_ms(seq_ph, k), mean_ms(win_ph, k)]
                for k in (
                    "train.data_fetch", "train.h2d", "train.dispatch",
                    "train.loss_fetch", "train.loss_drain",
                )
            },
        }
    except Exception as e:
        traceback.print_exc()
        return {"multistep_error": f"{type(e).__name__}: {e}"[:160]}


def _ckpt_fields(engine):
    """Fault-tolerance telemetry for a training record (ISSUE 9), measured
    AFTER the timed window on a scratch dir:

    - ``ckpt_stall_ms`` — how long ``save_checkpoint(asynchronous=True)``
      blocks the step loop. By construction that is ONLY the device→host
      snapshot (the staged atomic write + commit + latest update run on the
      background writer while subsequent steps dispatch), so the target is
      ~0 relative to the step time; the acceptance bar is ≤5% of it.
    - ``ckpt_save_s`` — the full background persist (stage → fsync →
      rename), i.e. what a SYNCHRONOUS save would have stalled.
    - ``ckpt_restore_s`` — ``load_checkpoint(auto_resume=True)`` wall time
      (scan + validate + restore of the full replay state).

    The async path is jit-free — the no-new-programs guarantee is enforced
    by compile telemetry in tests/unit/checkpoint/test_fault_tolerance.py —
    so these fields ride AFTER _compile_fields/_analysis_fields and do not
    disturb the record's compile counters."""
    import shutil
    import tempfile

    ckpt_dir = tempfile.mkdtemp(prefix="dsbench_ckpt_")
    try:
        t0 = time.perf_counter()
        engine.save_checkpoint(ckpt_dir, asynchronous=True)
        stall_ms = (time.perf_counter() - t0) * 1e3
        engine.wait_pending_checkpoint()
        save_s = engine.checkpoint_stats()["last_save_s"]
        t0 = time.perf_counter()
        engine.load_checkpoint(ckpt_dir, auto_resume=True)
        restore_s = time.perf_counter() - t0
        return {
            "ckpt_stall_ms": round(stall_ms, 2),
            "ckpt_save_s": round(save_s, 3),
            "ckpt_restore_s": round(restore_s, 3),
        }
    except Exception as e:
        traceback.print_exc()
        return {"ckpt_error": f"{type(e).__name__}: {e}"[:160]}
    finally:
        shutil.rmtree(ckpt_dir, ignore_errors=True)


def _timed_steps(engine, batch, warmup=3, steps=20):
    """Place the batch once (a real input pipeline prefetches to device;
    re-uploading identical tokens every step would measure the host link,
    not the chip), run warmup + timed steps, external wall clock."""
    placed = engine._place_batch(batch)
    for _ in range(warmup):
        loss = engine(placed)
        engine.backward(loss)
        engine.step()
    _drain(engine)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine(placed)
        engine.backward(loss)
        engine.step()
    _drain(engine)
    return time.perf_counter() - t0, loss


def _mfu(tokens_per_sec, n_params, num_layers, hidden, seq):
    # 6N per token (fwd+bwd) + attention 12*L*H*T
    flops_per_token = 6 * n_params + 12 * num_layers * hidden * seq
    return tokens_per_sec * flops_per_token / _peak_tflops_bf16()


def _max_params_under_budget(fits, lo, hi):
    """Largest rung index in [lo, hi] whose model still fits, by bisection.

    ``fits`` must be monotone (a bigger model never fits when a smaller one
    didn't) — true for the HBM-residency predicate: model bytes grow with
    the rung, the budget is fixed. Pure so the unit suite can pin the
    bisection against synthetic predicates; returns ``lo - 1`` when even
    the smallest rung doesn't fit."""
    if not fits(lo):
        return lo - 1
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if fits(mid):
            lo = mid
        else:
            hi = mid - 1
    return lo


def _live_device_bytes():
    """Resident device bytes right now: what an HBM would be holding. On
    the CPU test backend this is the accounting stand-in for real HBM
    occupancy (the probe compares offload-on vs off under the SAME
    measure, so the stand-in cancels out of the ratio)."""
    import jax

    return int(sum(int(getattr(a, "nbytes", 0)) for a in jax.live_arrays()))


def _offload_stream_fields(engine_factory, batch, steps=4):
    """Streamed host-offload stream timings for a result record: build the
    offload variant of the scenario's engine, take a few optimizer steps,
    and report per-step H2D / D2H issue time plus the EXPOSED time (waits
    the depth-2 pipeline failed to hide — the number the overlap gate
    pins to ~0). Never fails the parent record."""
    try:
        engine = engine_factory()
        for _ in range(steps):
            engine.train_batch(batch=batch)
        stats = engine.offload_stream_stats()
        if not stats or not stats.get("steps"):
            return {"offload_stream_error": "streamed offload path not active"}
        n = stats["steps"]
        return {
            "offload_stream_h2d_ms": round(stats["h2d_ms"] / n, 3),
            "offload_stream_d2h_ms": round(stats["d2h_ms"] / n, 3),
            "offload_stream_exposed_ms": round(stats["exposed_ms"] / n, 3),
        }
    except Exception as e:
        traceback.print_exc()
        return {"offload_stream_error": f"{type(e).__name__}: {e}"[:160]}


# ---------------------------------------------------------------------------
def bench_gpt2_zero1():
    """Config 1: GPT-2 125M ZeRO-1, tokens/s/chip (the headline)."""
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    seq, micro = (128, 2) if TINY else (1024, 8)
    micro = int(os.environ.get("DS_BENCH_MICRO", micro))
    mcfg = gpt2_config("tiny" if TINY else "125m", max_seq_len=seq, remat=False)
    engine = _train_engine(
        TransformerLM(mcfg),
        {
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4, "weight_decay": 0.01}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    n_chips = max(engine.data_parallel_world_size(), 1)
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro * n_chips, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    dt, _ = _timed_steps(engine, batch, warmup=3, steps=20)
    tps_chip = 20 * micro * n_chips * seq / dt / n_chips
    mfu = _mfu(tps_chip, engine.num_parameters(), mcfg.num_layers, mcfg.hidden_size, seq)
    rec = {
        "metric": METRICS["gpt2_zero1"],
        "value": round(tps_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4),
    }
    rec.update(_compile_fields(engine))
    rec.update(_analysis_fields(engine))
    rec.update(_memory_fields(engine))
    rec.update(_ckpt_fields(engine))
    rec.update(
        _trace_fields(
            engine, "gpt2_zero1",
            timed_window=lambda n: _timed_steps(engine, batch, warmup=0, steps=n)[0],
        )
    )

    def _ms_engine(ms_on, horizon):
        return _train_engine(
            TransformerLM(mcfg),
            {
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "adam", "params": {"lr": 3e-4, "weight_decay": 0.01}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 1},
                "gradient_clipping": 1.0,
                "steps_per_print": 10_000,
                "compile": {"multi_step": {"enable": ms_on, "horizon": horizon}},
            },
        )

    rec.update(_multistep_fields(_ms_engine, batch, micro * n_chips * seq))

    def _offload_engine():
        return _train_engine(
            TransformerLM(mcfg),
            {
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "adam", "params": {"lr": 3e-4, "weight_decay": 0.01}},
                "bf16": {"enabled": True},
                "zero_optimization": {
                    "stage": 1,
                    "offload_optimizer": {
                        "device": "cpu",
                        "pin_memory": True,
                        "pipeline_read": True,
                        "pipeline_write": True,
                    },
                },
                "gradient_clipping": 1.0,
                "steps_per_print": 10_000,
            },
        )

    rec.update(_offload_stream_fields(_offload_engine, batch))
    return rec


def bench_llama_zero3():
    """Config 2 (scaled to one chip's HBM): llama-architecture ~0.8B,
    ZeRO-3 + fused Adam, bf16, remat."""
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    seq, micro = (256, 1) if TINY else (2048, 1)
    mcfg = TransformerConfig(
        vocab_size=1024 if TINY else 32000,
        hidden_size=256 if TINY else 2048,
        num_layers=2 if TINY else 16,
        num_heads=16,
        num_kv_heads=4,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=False,
        remat=True,
    )
    engine = _train_engine(
        TransformerLM(mcfg),
        {
            "train_micro_batch_size_per_gpu": micro,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
            "steps_per_print": 10_000,
        },
    )
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    steps = 8
    dt, _ = _timed_steps(engine, batch, warmup=2, steps=steps)
    tps = steps * micro * seq / dt
    mfu = _mfu(tps, engine.num_parameters(), mcfg.num_layers, mcfg.hidden_size, seq)
    # remat recomputes the forward in the backward: the chip does ~8N useful
    # FLOPs/token but MFU counts the 6N model FLOPs (standard accounting)
    rec = {
        "metric": METRICS["llama_zero3"],
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "steps": steps,
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4),
    }
    rec.update(_compile_fields(engine))
    rec.update(_analysis_fields(engine))
    rec.update(_memory_fields(engine))
    rec.update(_ckpt_fields(engine))

    def _ms_engine(ms_on, horizon):
        return _train_engine(
            TransformerLM(mcfg),
            {
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
                "gradient_clipping": 1.0,
                "steps_per_print": 10_000,
                "compile": {"multi_step": {"enable": ms_on, "horizon": horizon}},
            },
        )

    rec.update(
        _multistep_fields(
            _ms_engine, batch, micro * seq,
            horizon=4 if TINY else 8,
        )
    )

    def _offload_engine():
        return _train_engine(
            TransformerLM(mcfg),
            {
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": {
                    "stage": 3,
                    "offload_optimizer": {
                        "device": "cpu",
                        "pin_memory": True,
                        "pipeline_read": True,
                        "pipeline_write": True,
                    },
                },
                "gradient_clipping": 1.0,
                "steps_per_print": 10_000,
            },
        )

    rec.update(_offload_stream_fields(_offload_engine, batch, steps=3))
    return rec


def bench_infinity_max_params():
    """Config 3: ZeRO-Infinity optimizer-state offload — the trainable-
    params ceiling probe. A ladder of transformer sizes is bisected twice
    under the SAME device-byte budget: once with the fp32 master +
    moments resident on device (offload off), once with them streamed
    from host DRAM (offload on). Value = largest param count that still
    trains offload-ON; vs_baseline = multiple of the offload-OFF ceiling
    (the headroom the host offload buys — Adam states are 12 bytes/param
    of the ~18 the on-device path keeps resident, so ~3x is the
    theoretical ceiling on this measure)."""
    import gc

    import jax

    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    seq, micro = (64, 1) if TINY else (256, 1)
    hidden = 128 if TINY else 512
    ladder = [2, 4, 8, 12, 16, 24, 32]  # num_layers rungs, sizes ascending

    def _mcfg(layers):
        return TransformerConfig(
            vocab_size=512 if TINY else 8192,
            hidden_size=hidden,
            num_layers=layers,
            num_heads=4,
            max_seq_len=seq,
            norm="rmsnorm",
            position="rope",
            activation="swiglu",
            use_bias=False,
            tie_embeddings=True,
            remat=False,
            dtype="bfloat16",
        )

    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, 512 if TINY else 8192, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}

    def _probe(layers, offload):
        """(trained ok, resident device bytes, n_params, stream stats)."""
        zero = {"stage": 1}
        if offload:
            zero["offload_optimizer"] = {
                "device": "cpu",
                "pin_memory": True,
                "pipeline_read": True,
                "pipeline_write": True,
                # several buckets per model: the resident transient is one
                # bucket deep, not the whole Adam state
                "bucket_size": 500_000 if TINY else 2_000_000,
            }
        gc.collect()
        base = _live_device_bytes()
        engine = _train_engine(
            TransformerLM(_mcfg(layers)),
            {
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
                "bf16": {"enabled": True},
                "zero_optimization": zero,
                "steps_per_print": 10_000,
            },
        )
        try:
            loss = float(engine.train_batch(batch=batch))
            ok = np.isfinite(loss)
            if engine._host_offload is not None:
                # land the in-flight D2H writes: a kept-pending write pins
                # its device bucket, which is stream state, not residency
                engine._host_offload.drain_writes()
            used = _live_device_bytes() - base
            return ok, used, int(engine.num_parameters()), engine.offload_stream_stats()
        finally:
            del engine
            jax.clear_caches()
            gc.collect()

    # the budget is synthetic on the CPU test backend (no real HBM wall):
    # sized so the MIDDLE rung just fits with Adam state resident — both
    # probes then bisect against the same wall, and the record reports how
    # much further the streamed-offload run climbs
    _, mid_bytes, _, _ = _probe(ladder[2], offload=False)
    budget = int(mid_bytes * 1.05)

    t0 = time.perf_counter()
    results = {}
    stream_stats = {}

    def _fits(offload):
        def fits(idx):
            ok, used, n_params, stats = _probe(ladder[idx], offload)
            fit = ok and used <= budget
            if fit:
                results[(offload, idx)] = n_params
                if stats:
                    stream_stats.update(stats)
            return fit

        return fits

    top_off = _max_params_under_budget(_fits(False), 0, len(ladder) - 1)
    top_on = _max_params_under_budget(_fits(True), 0, len(ladder) - 1)
    probe_s = time.perf_counter() - t0
    params_off = results.get((False, top_off), 0)
    params_on = results.get((True, top_on), 0)
    assert params_on > 0, "offload-on probe fit nothing under the budget"
    assert params_on > params_off, (
        f"host offload bought no headroom: on={params_on} off={params_off}"
    )
    rec = {
        "metric": METRICS["infinity"],
        "value": int(params_on),
        "unit": f"params (bisection, {probe_s:.0f}s)",
        "vs_baseline": round(params_on / max(params_off, 1), 2),
        "offload_off_params": int(params_off),
        "budget_bytes": budget,
        "ladder_layers": [ladder[max(top_off, 0)], ladder[max(top_on, 0)]],
    }
    n = stream_stats.get("steps") or 1
    rec.update(
        {
            "offload_stream_h2d_ms": round(stream_stats.get("h2d_ms", 0.0) / n, 3),
            "offload_stream_d2h_ms": round(stream_stats.get("d2h_ms", 0.0) / n, 3),
            "offload_stream_exposed_ms": round(stream_stats.get("exposed_ms", 0.0) / n, 3),
        }
    )
    return rec


def bench_long_seq():
    """Config 4: long sequences. Full-size: 32k tokens via the Pallas flash
    kernel + remat on one chip. TINY: the 2k config instead trains through
    ``sequence/layer.py``'s Ulysses attention on a ``sequence=2`` mesh, so
    the recorded collectives budget carries the head-scatter/seq-gather
    all-to-alls (``ulysses_a2a_bytes`` — previously this bench ran single
    chip and the a2a metric read 0; full-size sequence-parallel training
    stays future work)."""
    ulysses = bool(TINY)
    if ulysses and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        # the sequence axis needs a real mesh in this child (same pattern
        # as the tp serving arm)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        )
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    seq, micro = (2048, 1) if TINY else (32768, 1)
    mcfg = TransformerConfig(
        vocab_size=1024 if TINY else 32000,
        hidden_size=128 if TINY else 1024,
        num_layers=2 if TINY else 8,
        num_heads=2 if TINY else 8,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=True,
        # Ulysses: the a2a exchange owns the head/seq reshard; the
        # interpret-mode flash kernel can't run under it on CPU
        remat=not ulysses,
        flash_attention=not ulysses,
        sequence_parallel=ulysses,
        sequence_parallel_mode="ulysses",
    )
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "adam", "params": {"lr": 1e-4}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 10_000,
    }
    if ulysses:
        config["mesh"] = {"sequence": 2, "data": 2}
    engine = _train_engine(TransformerLM(mcfg), config)
    dp = engine.data_parallel_world_size()
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro * dp, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    steps = 5
    dt, _ = _timed_steps(engine, batch, warmup=2, steps=steps)
    tps = steps * micro * dp * seq / dt
    mfu = _mfu(tps, engine.num_parameters(), mcfg.num_layers, mcfg.hidden_size, seq)
    rec = {
        "metric": METRICS["long_seq"],
        "value": round(tps, 1),
        "unit": "tokens/s/chip",
        "steps": steps,
        "vs_baseline": round(mfu / NORTH_STAR_MFU, 4),
    }
    rec.update(_compile_fields(engine))
    rec.update(_analysis_fields(engine))
    if ulysses:
        a2a = _a2a_wire_summary(engine)
        rec["sequence_parallel"] = "ulysses"
        rec["ulysses_a2a_bytes"] = int(a2a["bytes"]) if a2a else 0
    return rec


def bench_moe_inference():
    """Config 5 (one chip): MoE prefill throughput vs a dense model with the
    same ACTIVE parameters — vs_baseline ≥ ~1 means the expert dispatch
    (gate + capacity einsums) adds no material overhead."""
    import jax

    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.models.moe_transformer import MoETransformerConfig, MoETransformerLM

    seq, B = (128, 2) if TINY else (1024, 8)
    base = dict(
        vocab_size=1024 if TINY else 32000,
        hidden_size=128 if TINY else 1024,
        num_layers=2 if TINY else 8,
        num_heads=2 if TINY else 8,
        max_seq_len=seq,
        norm="rmsnorm",
        position="rope",
        activation="swiglu",
        use_bias=False,
        tie_embeddings=True,
    )
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, base["vocab_size"], (B, seq)).astype(np.int32)

    def prefill_tps(model):
        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="bf16")
        engine.init_params(toks)
        out = engine(toks)
        jax.device_get(np.asarray(out[0, -1, :8]))  # compile + drain
        t0 = time.perf_counter()
        reps = 5
        for _ in range(reps):
            out = engine(toks)
        jax.device_get(np.asarray(out[0, -1, :8]))
        return reps * B * seq / (time.perf_counter() - t0), engine

    moe_tps, moe_engine = prefill_tps(
        MoETransformerLM(MoETransformerConfig(num_experts=8, moe_top_k=1, **base))
    )
    # the full structural snapshot from the MoE engine (the measured
    # object), before the dense baseline rebuilds the topology: compile
    # telemetry, the comms/donation/overlap budget, and the HBM ledger
    # (expert shards land in peak_hbm_bytes_per_chip via the PR-18
    # estimator)
    moe_fields = {}
    moe_fields.update(_compile_fields(moe_engine))
    moe_fields.update(_analysis_fields(moe_engine))
    moe_fields.update(_memory_fields(moe_engine))
    dense_tps, _ = prefill_tps(TransformerLM(TransformerConfig(**base)))
    rec = {
        "metric": METRICS["moe_inference"],
        "value": round(moe_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(moe_tps / dense_tps, 4),
    }
    rec.update(moe_fields)
    return rec


def _a2a_wire_summary(engine):
    """The collectives-pass ``all-to-all`` pricing for the engine's step
    program: ``{count, bytes, wire_bytes, quantized{...}}`` or None when the
    schedule has no a2a (the analysis never fails the bench record)."""
    try:
        rep = engine.analysis_report(passes=["collectives"])
        for prog in rep["programs"].values():
            coll = prog.get("passes", {}).get("collectives")
            if not coll:
                continue
            a2a = coll.get("summary", {}).get("ops", {}).get("all-to-all")
            if a2a:
                return a2a
    except Exception:
        traceback.print_exc()
    return None


def bench_moe_train():
    """Config 5b (data×expert mesh): expert-parallel MoE training — the
    shard_map fast path with explicit dispatch/combine all-to-alls (ISSUE
    20). ``value`` is trained tokens/s on the fp-wire arm; the int8 arm
    re-prices the same schedule with the EQuARX-style wire format and
    ``vs_baseline`` is its fp-equivalent-over-wire byte ratio (4.0 when
    every a2a payload quantizes cleanly — the pure fp32/int8 dtype ratio).
    ``overlap_verified`` rides the standard analysis block: every dispatch/
    combine a2a must hide behind the PR-MoE residual / next-layer gating
    compute (exposed loop-collective bytes == 0 — the
    ``test_green_moe_programs`` training gate, recorded here per round)."""
    # the expert axis needs a real mesh: force the 8-device CPU host mesh
    # before this child initializes its backend (same pattern as the tp
    # serving arm)
    if CPU_ONLY and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        )
    import jax

    from deepspeed_tpu.models.moe_transformer import MoETransformerConfig, MoETransformerLM

    n = len(jax.devices())
    if n < 4 or n % 2:
        return _error_record("moe_train", f"expert mesh needs >=4 even devices, have {n}")
    mesh = {"data": n // 2, "expert": 2}
    seq, micro = (32, 8) if TINY or CPU_ONLY else (512, 8)

    def build(quantized):
        # mirrors the gate-green config: PR-MoE residual gives the overlap
        # pass real compute to hide the exchanges behind; fp32 keeps the
        # int8-vs-fp wire ratio an exact dtype ratio; flash/remat off is
        # the repo's CPU multi-device convention
        cfg = MoETransformerConfig(
            vocab_size=1024 if TINY or CPU_ONLY else 32000,
            hidden_size=128 if TINY or CPU_ONLY else 1024,
            num_layers=2 if TINY or CPU_ONLY else 8,
            num_heads=2 if TINY or CPU_ONLY else 8,
            max_seq_len=seq, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=True,
            num_experts=4 if TINY or CPU_ONLY else 8, moe_top_k=1,
            scan_layers=True, use_residual=True, dtype="float32",
            flash_attention=False, remat=False, moe_quantized_a2a=quantized,
        )
        engine = _train_engine(
            MoETransformerLM(cfg),
            {
                "train_micro_batch_size_per_gpu": micro,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3, "overlap_comm": True},
                "mesh": mesh,
                "steps_per_print": 10_000,
            },
        )
        return cfg, engine

    mcfg, engine = build(quantized=False)
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (micro, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    steps = 5 if TINY or CPU_ONLY else 20
    dt, _ = _timed_steps(engine, batch, warmup=2, steps=steps)
    tps = steps * micro * seq / dt
    rec = {
        "metric": METRICS["moe_train"],
        "value": round(tps, 1),
        "unit": "tokens/s",
        "steps": steps,
        "mesh": mesh,
    }
    rec.update(_compile_fields(engine))
    rec.update(_analysis_fields(engine))
    rec.update(_memory_fields(engine))
    fp_a2a = _a2a_wire_summary(engine)
    rec["a2a_wire_bytes_fp"] = int(fp_a2a["wire_bytes"]) if fp_a2a else 0

    # int8 wire arm: same schedule, quantized dispatch/combine payloads —
    # priced statically by the collectives pass (one engine, one step)
    _qcfg, q_engine = build(quantized=True)
    q_engine.train_batch(batch=batch)
    q_a2a = _a2a_wire_summary(q_engine)
    quant = (q_a2a or {}).get("quantized") or {}
    rec["a2a_wire_bytes_int8"] = int(quant.get("wire_bytes", 0))
    fp_equiv = int(quant.get("fp_equiv_wire_bytes", 0))
    reduction = (
        round(fp_equiv / quant["wire_bytes"], 4) if quant.get("wire_bytes") else 0
    )
    rec["a2a_wire_reduction"] = reduction
    rec["vs_baseline"] = reduction
    return rec


def bench_decode_serving():
    """Config 6 (one chip): continuous-batching serving over the paged KV
    pool (``engine.serve()``) — generated tokens/s/chip on a ragged request
    mix, speculation OFF (``value``) and ON (``spec_on_value`` +
    ``spec_accept_rate``: n-gram drafting, one verify dispatch per round).
    The measured path is the RAGGED one-program dispatch (the default):
    mixed prefill+decode rows share every step, ``compiled_programs``
    (≤ 2 expected) and ``cold_start_compile_s`` record the collapsed
    compile matrix, and ``bucketed_value`` / ``ragged_vs_bucketed`` replay
    the same mixed traffic through the bucketed per-shape oracle for
    comparison. ``vs_baseline`` = paged serving throughput over the dense
    lockstep ``generate`` on the same prompts padded to one max-budget
    batch (≥ ~1 means request-level batching serves ragged traffic at
    least as fast as the fixed-shape batch that can't retire rows early);
    ``spec_vs_off`` = spec-on over spec-off (the drafter is model-free, so
    the ratio tracks how much repetitive structure the mix exposes ×
    acceptance — see PERF.md round 9 for the expected-speedup math)."""
    import time as _time

    import jax.numpy as jnp

    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    if TINY:
        n_req, prompt_len, max_new = 6, 12, 24
        mcfg = TransformerConfig(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=128, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
            flash_attention=False,
        )
        paged = {"page_size": 8, "max_slots": 4, "prefill_chunk": 8}
    else:
        n_req, prompt_len, max_new = 16, 128, 128
        mcfg = TransformerConfig(
            vocab_size=32000, hidden_size=1024, num_layers=8, num_heads=16,
            num_kv_heads=4, max_seq_len=1024, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
        )
        paged = {"page_size": 64, "max_slots": 8, "prefill_chunk": 128}

    mesh_mod.reset_topology()
    engine = ds.init_inference(TransformerLM(mcfg), dtype="bf16", paged_kv=paged)
    rs = np.random.RandomState(SEED)
    # half of each prompt is a tiled motif: serving traffic (code, templated
    # text) has repetitive spans the n-gram drafter can exploit; the random
    # half keeps the prefix from being a degenerate single pattern
    def _prompt():
        m = max(2, prompt_len // 32)  # short enough to repeat in the tail
        motif = rs.randint(0, mcfg.vocab_size, (m,)).astype(np.int32)
        head = rs.randint(0, mcfg.vocab_size, (prompt_len // 2,)).astype(np.int32)
        tail = np.tile(motif, -(-(prompt_len - head.size) // m))[: prompt_len - head.size]
        return np.concatenate([head, tail])

    prompts = [_prompt() for _ in range(n_req)]
    toks = np.stack(prompts)
    engine.init_params(toks)
    engine._ds_config = mcfg  # flagship family: take the KV-cached decode path
    # ragged budgets: early finishers make room for admissions mid-stream
    budgets = [max(1, max_new - (i * max_new) // (2 * n_req)) for i in range(n_req)]

    def timed_serve():
        t0 = _time.perf_counter()
        outs = engine.serve(prompts, max_new_tokens=budgets)
        gen = sum(len(o) - prompt_len for o in outs)
        return gen / (_time.perf_counter() - t0)

    timed_serve()  # cold start: compiles the (≤2) ragged serving programs
    # the collapsed compile matrix, measured at the cold boundary: program
    # count and the wall time the first serve spent compiling
    from deepspeed_tpu.inference.scheduler import compiled_serving_programs

    cold_stats = engine.compile_stats()
    compiled_programs = compiled_serving_programs(cold_stats)
    cold_start_compile_s = sum(
        rec["compile_seconds"] for name, rec in cold_stats.items()
        if name.startswith("paged_")
    )
    paged_tps = timed_serve()
    # serving SLOs + prefix-cache effectiveness of the measured (spec-off)
    # server: p50/p99 TTFT (submit -> first token, queue wait included) and
    # TPOT from serve_stats(), plus the pool's prefix hit rate — the warm
    # pass re-serves the same prompts, so shared full pages attach instead
    # of re-prefilling (the production shared-system-prompt pattern)
    base_stats = engine.serve_stats()
    # speculation ON through the same engine/telemetry: the server is
    # rebuilt from the flipped knob, verify programs compile once, and the
    # second pass is the measured one
    engine._config.spec_decode.enable = True
    engine._paged_server = None
    timed_serve()  # compile every (bucket, K) verify program
    pre = dict(engine._paged_server.stats)  # counters cover the warm-up too
    spec_tps = timed_serve()
    post = engine._paged_server.stats
    rounds = post["spec_rounds"] - pre["spec_rounds"]
    drafted = post["spec_drafted"] - pre["spec_drafted"]
    accepted = post["spec_accepted"] - pre["spec_accepted"]
    spec_stats = {  # deltas of the MEASURED pass only
        "spec_rounds": rounds,
        "spec_accept_rate": accepted / drafted if drafted else 0.0,
        "spec_mean_accepted_per_round": accepted / rounds if rounds else 0.0,
    }
    engine._config.spec_decode.enable = False
    engine._paged_server = None
    # multi-step windows through the same engine (ISSUE 11): N decode
    # rounds fuse into one dispatch whenever the running set is stable, so
    # the host gap/packing/journal amortize to 1/N — the A/B runs the SAME
    # trace with windows armed, and dispatches_per_token is measured from
    # the scheduler's own dispatch/token counters over the measured pass
    engine._config.paged_kv.multi_step.enable = True
    timed_serve()  # compile the window program (one per armed horizon)
    ms_srv = engine._paged_server
    # every reported window field is a MEASURED-pass delta (the warm-up
    # pass forms windows too — lifetime totals would overstate them
    # relative to the dispatches_per_token they explain)
    ms_pre = {
        k: ms_srv.stats[k]
        for k in ("dispatches", "emitted_tokens", "window_steps")
    }
    ms_breaks_pre = dict(ms_srv.stats["window_break_reasons"])
    ms_tps = timed_serve()
    ms_disp = ms_srv.stats["dispatches"] - ms_pre["dispatches"]
    ms_toks = ms_srv.stats["emitted_tokens"] - ms_pre["emitted_tokens"]
    ms_stats = {
        "multistep_horizon": int(engine._config.paged_kv.multi_step.horizon),
        "window_steps": int(ms_srv.stats["window_steps"] - ms_pre["window_steps"]),
        "dispatches_per_token": round(ms_disp / ms_toks, 4) if ms_toks else 0.0,
        "window_break_reasons": {
            k: int(v - ms_breaks_pre[k])
            for k, v in ms_srv.stats["window_break_reasons"].items()
        },
    }
    engine._config.paged_kv.multi_step.enable = False
    engine._paged_server = None
    # the same mixed prefill+decode traffic through the bucketed per-shape
    # oracle (slot-bucket × chunk programs, prefill steps stealing from
    # decode): the ragged_vs_bucketed ratio is the headline of ISSUE 8
    engine._config.paged_kv.ragged = False
    engine._paged_server = None
    timed_serve()  # compile the bucketed program matrix
    bucketed_tps = timed_serve()
    engine._config.paged_kv.ragged = True
    engine._paged_server = None
    # snapshot AFTER the bucketed comparison and BEFORE the dense baseline
    # runs: the record's compile/analysis fields describe every paged
    # serving program (ragged + the multi-step window + the bucketed
    # comparison set), not kv_decode_loop
    compile_fields = _compile_fields(engine)
    compile_fields.update(_analysis_fields(engine))
    compile_fields.update(_memory_fields(engine))
    # unified-tracing fields for the measured (ragged, spec-off) server:
    # phase breakdown + overhead A/B + the Perfetto trace artifact. The
    # timed window returns seconds-per-token (1/tps), so the on/off ratio
    # is the wall-clock overhead of tracing the serving loop.
    compile_fields.update(
        _trace_fields(engine, "decode_serving",
                      timed_window=lambda n: 1.0 / timed_serve())
    )

    def timed_dense():
        t0 = _time.perf_counter()
        out = engine.generate(jnp.asarray(toks), max_new_tokens=max_new)
        np.asarray(out[..., -1:])  # drain
        return n_req * max_new / (_time.perf_counter() - t0)

    timed_dense()  # compile
    dense_tps = timed_dense()
    ttft = base_stats.get("ttft_ms", {})
    tpot = base_stats.get("tpot_ms", {})
    prefix = base_stats.get("prefix", {})
    rec = {
        "metric": METRICS["decode_serving"],
        "value": round(paged_tps, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(paged_tps / dense_tps, 4),
        # the ragged one-program dispatch (ISSUE 8): collapsed compile
        # matrix + the same mixed traffic through the bucketed oracle
        "compiled_programs": int(compiled_programs),
        "cold_start_compile_s": round(cold_start_compile_s, 3),
        "bucketed_value": round(bucketed_tps, 1),
        "ragged_vs_bucketed": round(paged_tps / bucketed_tps, 4),
        # serving SLO percentiles (TTFT includes queue wait; the headline
        # for serving is latency distribution, not aggregate tokens/s —
        # arXiv 2605.25645's TTFT/TPOT framing)
        "ttft_p50_ms": round(ttft.get("p50", 0.0), 2),
        "ttft_p99_ms": round(ttft.get("p99", 0.0), 2),
        "tpot_p50_ms": round(tpot.get("p50", 0.0), 3),
        "tpot_p99_ms": round(tpot.get("p99", 0.0), 3),
        # prefix caching: fraction of looked-up prompt tokens attached from
        # the page index instead of re-prefilled, + CoW divergence copies
        "prefix_hit_rate": round(prefix.get("prefix_hit_rate", 0.0), 4),
        "prefix_cow_copies": int(prefix.get("cow_copies", 0)),
        # multi-step windows (ISSUE 11): same trace with N-round fused
        # dispatches armed — dispatches_per_token is the amortization the
        # tentpole buys (steady state → 1/horizon), multistep_vs_singlestep
        # the wall-clock win (≈ 1 + host_gap_fraction × (1 − 1/N) once the
        # ~2 ms tunnel RTT is back in the loop; ~1 on a local CPU backend)
        "multistep_value": round(ms_tps, 1),
        "multistep_vs_singlestep": round(ms_tps / paged_tps, 4),
        **ms_stats,
        # speculative serving: same metric with n-gram draft-and-verify on
        "spec_on_value": round(spec_tps, 1),
        "spec_vs_off": round(spec_tps / paged_tps, 4),
        "spec_accept_rate": round(spec_stats.get("spec_accept_rate", 0.0), 4),
        "spec_rounds": spec_stats.get("spec_rounds", 0),
        "spec_mean_accepted_per_round": round(
            spec_stats.get("spec_mean_accepted_per_round", 0.0), 3
        ),
    }
    rec.update(compile_fields)
    return rec


def bench_decode_serving_tp():
    """Config 6b (multi-chip): tensor-parallel sharded serving (ISSUE 13)
    — the same ragged continuous-batching trace served at tp ∈ {1, 2, 4}
    with the weights column/row-parallel and the paged KV pool sharded
    over the kv-head axis. ``value`` is generated tokens/s **per chip** at
    the widest tp arm (the number that must stay ~flat for linear
    scaling); ``scaling_efficiency`` is (tokens/s/chip at tp) over the
    tp=1 throughput per arm. On a CPU host every "chip" is a forced host
    device, so absolute numbers are smoke-scale and the per-chip ratio is
    dominated by the emulation — the structural fields
    (``compiled_programs`` ≤ 2 on the mesh, ``quantized_comm`` wire-byte
    accounting = fp/4) are the portable signal. ``quantized_value``
    re-serves the widest arm with the EQuARX int8 all-reduce armed."""
    # multi-device CPU smoke: the forced host-device count must land
    # before this child process first initializes its backend
    if CPU_ONLY and "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=4"
        )
    import time as _time

    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.inference.scheduler import PagedServer, compiled_serving_programs
    from deepspeed_tpu.inference.tp import TPServing, serving_mesh
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry

    if TINY:
        n_req, prompt_len, max_new = 6, 12, 24
        mcfg = TransformerConfig(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=8,
            num_kv_heads=4, max_seq_len=128, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
            flash_attention=False, dtype="float32",
        )
        paged = {"page_size": 8, "max_slots": 4, "prefill_chunk": 8}
    else:
        n_req, prompt_len, max_new = 16, 128, 128
        mcfg = TransformerConfig(
            vocab_size=32000, hidden_size=1024, num_layers=8, num_heads=16,
            num_kv_heads=4, max_seq_len=1024, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
        )
        paged = {"page_size": 64, "max_slots": 8, "prefill_chunk": 128}

    n_dev = len(jax.devices())
    arms = [t for t in (1, 2, 4) if t <= n_dev and mcfg.num_kv_heads % t == 0]
    dtype = jnp.float32 if TINY else jnp.bfloat16
    model = TransformerLM(mcfg)
    rs = np.random.RandomState(SEED)
    prompts = [
        rs.randint(0, mcfg.vocab_size, (prompt_len,)).astype(np.int32)
        for _ in range(n_req)
    ]
    params = model.init(
        jax.random.PRNGKey(SEED), np.stack(prompts)[:1]
    )
    budgets = [max(1, max_new - (i * max_new) // (2 * n_req)) for i in range(n_req)]

    def timed_serve(server):
        t0 = _time.perf_counter()
        outs = server.serve(prompts, max_new_tokens=budgets)
        gen = sum(len(o) - prompt_len for o in outs)
        return gen / (_time.perf_counter() - t0)

    def build(tp_degree, quantized=False):
        tel = CompileTelemetry()
        tp = (
            None
            if tp_degree == 1
            else TPServing(mesh=serving_mesh(tp_degree), quantized_allreduce=quantized)
        )
        server = PagedServer(
            mcfg, params, attn_impl="xla" if CPU_ONLY else "auto",
            dtype=dtype, telemetry=tel, tp=tp, **paged,
        )
        return tel, server

    arm_tps = {}
    compiled = {}
    for t in arms:
        tel, server = build(t)
        timed_serve(server)  # cold: compiles the (≤2) sharded programs
        arm_tps[t] = timed_serve(server)
        compiled[t] = compiled_serving_programs(tel.stats())
    widest = arms[-1]
    per_chip = arm_tps[widest] / widest
    # quantized all-reduce arm at the widest tp + its static comm account
    q_tel, q_server = build(widest, quantized=True)
    timed_serve(q_server)
    q_tps = timed_serve(q_server)
    q_wire = q_fp_equiv = 0
    if widest > 1:
        q_rep = run_program_passes(q_tel, passes=["collectives"])
        for prog in q_rep["programs"].values():
            qs = prog["passes"]["collectives"]["summary"]["quantized"]
            q_wire += qs["wire_bytes"]
            q_fp_equiv += qs["fp_equiv_wire_bytes"]
    # static HBM ledger fields (ISSUE 18). No engine wraps this server, so
    # the per-chip program peak / replicated bytes come from the memory
    # pass over the quantized arm's telemetry — audited against the tp
    # plan's declared sharding rules + comm schedule — and the KV-pool
    # residency straight from the pool (bytes/chip == total/tp with the
    # page tables host-side: the ledger gate's serving invariant).
    try:
        q_srv = getattr(q_server, "server", q_server)
        pool_rep = q_srv.pool.memory_report()
        mem_cfg = None
        if q_srv.tp is not None and q_srv.tp.degree > 1:
            mem_cfg = {
                "declared_collectives": q_srv.tp.declared_collectives(),
                "sharding_rules": q_srv.tp.sharding_rules(),
            }
        mem_tot = run_program_passes(q_tel, passes=["memory"], config=mem_cfg)[
            "totals"
        ]
        mem_fields = {
            "peak_hbm_bytes_per_chip": int(mem_tot["peak_hbm_bytes_per_chip"]),
            "replicated_bytes": int(mem_tot["replicated_bytes"]),
            # no analysis.hbm_budget_bytes configured for the bench arms
            "hbm_budget_verified": None,
            "kv_bytes_per_chip": int(pool_rep["kv_bytes_per_chip"]),
            "undeclared_collectives": int(mem_tot["undeclared_collectives"]),
        }
    except Exception as e:
        traceback.print_exc()
        mem_fields = {"memory_error": f"{type(e).__name__}: {e}"[:200]}
    return {
        **mem_fields,
        "metric": METRICS["decode_serving_tp"],
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "tp_degree": int(widest),
        "tp_arms_tokens_per_sec": {str(t): round(v, 1) for t, v in arm_tps.items()},
        # (tokens/s/chip at tp) / (tokens/s at tp=1): 1.0 = linear scaling
        "scaling_efficiency": {
            str(t): round((arm_tps[t] / t) / arm_tps[1], 4) for t in arms
        },
        "vs_baseline": round((arm_tps[widest] / widest) / arm_tps[1], 4),
        "compiled_programs": int(compiled[widest]),
        "quantized_value": round(q_tps / widest, 1),
        # static per-scan-body wire bytes of the int8 exchanges, summed
        # over the compiled sharded programs, + the exact fp-equivalent
        # (= 4x: the EQuARX accounting identity the analysis gate asserts)
        "quantized_comm_wire_bytes": int(q_wire),
        "quantized_comm_fp_equiv_bytes": int(q_fp_equiv),
    }


def bench_fleet_serving():
    """Config 7: the serving fleet under a mid-trace replica kill
    (``inference/fleet.py``). Three SLA-scheduled replicas replay a
    deterministic heavy-tailed two-tenant trace (``utils/loadgen.py``) on
    the virtual clock — each replica is modeled as its own service lane,
    which is the fleet premise (a single host cannot physically host
    three chips, so the wall clock cannot measure fleet scaling; the
    virtual replay is the deterministic capacity model, and all byte-
    exactness claims are checked for real). One replica is chaos-killed
    at 40% of the trace and its live requests re-route onto the
    survivors from its journal.

    ``value`` = fleet goodput (SLA-meeting tokens per virtual second)
    WITH the kill; ``vs_baseline`` = that over the single-replica replay
    of the same trace (the acceptance bar is > 1 even while losing a
    replica mid-trace). ``p99_ttft_under_kill_ms`` vs ``p99_ttft_ms``
    (the same fleet, no kill) is the bounded-latency claim, and
    ``migrated_token_divergence`` MUST be 0 — every re-routed stream's
    acked prefix reproduced verbatim."""
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.inference.fleet import FleetRouter, ReplicaHandle
    from deepspeed_tpu.inference.journal import RequestJournal
    from deepspeed_tpu.inference.scheduler import (
        PagedServer,
        compiled_serving_programs,
    )
    from deepspeed_tpu.inference.traffic import MultiTenantServer, TenantSpec
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry
    from deepspeed_tpu.utils.loadgen import (
        TenantLoad,
        VirtualClock,
        make_trace,
        replay,
    )

    if TINY:
        mcfg = TransformerConfig(
            vocab_size=1024, hidden_size=128, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=128, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
            flash_attention=False,
        )
        paged = {"page_size": 8, "max_slots": 4, "prefill_chunk": 8}
        rate, horizon_s = 40.0, 1.0
    else:
        mcfg = TransformerConfig(
            vocab_size=32000, hidden_size=512, num_layers=4, num_heads=8,
            num_kv_heads=4, max_seq_len=256, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
        )
        paged = {"page_size": 16, "max_slots": 8, "prefill_chunk": 16}
        rate, horizon_s = 60.0, 2.0

    model = TransformerLM(mcfg)
    rs = np.random.RandomState(SEED)
    toks = rs.randint(0, mcfg.vocab_size, (1, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()
    tenants = [
        TenantSpec(name="gold", weight=3.0, priority=1, ttft_target_ms=4000),
        TenantSpec(name="free", weight=1.0),
    ]
    import shutil
    import tempfile

    workdir = tempfile.mkdtemp(prefix="dsbench_fleet_")

    def replica(tag):
        jdir = os.path.join(workdir, tag)
        srv = PagedServer(
            mcfg, params, attn_impl="xla", dtype=jnp.bfloat16, telemetry=tel,
            prefix_cache=True, journal=RequestJournal(jdir), **paged,
        )
        return ReplicaHandle(
            name=tag, server=MultiTenantServer(srv, tenants=tenants),
            journal_dir=jdir,
        )

    trace = make_trace(
        [
            TenantLoad(name="gold", rate=rate, prompt_len=(8, 24),
                       max_new_tokens=(4, 10), prefix_len=paged["page_size"] * 2),
            TenantLoad(name="free", rate=rate, prompt_len=(8, 24),
                       max_new_tokens=(4, 10), prefix_len=paged["page_size"] * 2),
        ],
        horizon_s=horizon_s,
        vocab_size=mcfg.vocab_size,
        seed=SEED,
    )

    def kill_busy(router):
        victim = next(
            (n for n, h in router.replicas.items() if h.inner.has_work()),
            next(iter(router.replicas)),
        )
        router.kill_replica(victim)

    try:
        # fleet WITH the mid-trace kill (the measured configuration)
        fleet = FleetRouter([replica(f"kill_r{i}") for i in range(3)])
        rep_kill = replay(
            fleet, trace, clock=VirtualClock(step_cost_s=0.02),
            events=[(0.4 * horizon_s, kill_busy)], keep_outputs=False,
        )
        fs = fleet.fleet_stats()
        # the same fleet shape, uninterrupted (the p99-TTFT comparison arm)
        fleet_ok = FleetRouter([replica(f"ok_r{i}") for i in range(3)])
        rep_ok = replay(
            fleet_ok, trace, clock=VirtualClock(step_cost_s=0.02),
            keep_outputs=False,
        )
        # the single-replica baseline on the same trace
        single = FleetRouter([replica("solo")])
        rep_one = replay(
            single, trace, clock=VirtualClock(step_cost_s=0.02),
            keep_outputs=False,
        )
    finally:
        shutil.rmtree(workdir, ignore_errors=True)
    goodput = rep_kill["goodput_tokens_per_s"]
    baseline = max(rep_one["goodput_tokens_per_s"], 1e-9)
    rec = {
        "metric": METRICS["fleet_serving"],
        "value": round(goodput, 1),
        "unit": "tokens/s (3-replica virtual-clock replay, mid-trace kill)",
        "vs_baseline": round(goodput / baseline, 4),
        "replicas": 3,
        "clock": "virtual",
        "n_requests": rep_kill["n_requests"],
        # bounded-p99 claim: the kill arm vs the uninterrupted arm
        "p99_ttft_under_kill_ms": round(rep_kill["ttft_ms"].get("p99", 0.0), 1),
        "p99_ttft_ms": round(rep_ok["ttft_ms"].get("p99", 0.0), 1),
        "single_replica_goodput": round(rep_one["goodput_tokens_per_s"], 1),
        "replica_kills": fs["replica_kills"],
        # every cooperative + failure-driven move, and the audit that no
        # migrated stream's acked prefix ever diverged
        "migration_count": fs["migrations"] + fs["rerouted"],
        "migrated_token_divergence": fs["migrated_token_divergence"],
        "starved_tenants": rep_kill["starved_tenants"],
        "prefix_hit_rate": round(rep_kill.get("prefix_hit_rate", 0.0), 4),
        # the fleet adds no programs: all replicas share the ragged set
        "compiled_programs": int(compiled_serving_programs(tel.stats())),
    }
    return rec


# ---------------------------------------------------------------------------
# Orchestration. The parent never imports jax; every jax-touching activity
# (including the device probe — backend init alone stalled 25 minutes in
# round 3) runs in a subprocess the parent can kill.

CONFIGS = {
    "gpt2_zero1": (bench_gpt2_zero1, 420),
    "llama_zero3": (bench_llama_zero3, 330),
    "infinity": (bench_infinity_max_params, 360),
    "long_seq": (bench_long_seq, 360),
    "moe_inference": (bench_moe_inference, 300),
    "moe_train": (bench_moe_train, 420),
    "decode_serving": (bench_decode_serving, 330),
    "decode_serving_tp": (bench_decode_serving_tp, 330),
    "fleet_serving": (bench_fleet_serving, 330),
}
HEADLINE = "gpt2_zero1"
PARTIAL_PATH = os.path.join(REPO, "bench_partial.jsonl")
KNOWN_GOOD_PATH = os.path.join(REPO, "bench_known_good.json")


def _atomic_write_json(path, obj, **dump_kwargs):
    """Write-to-temp → fsync → rename → fsync dir (DS-R008): records
    another process trusts — the known-good store, the per-config child
    result files — must never be readable half-written (the parent polls
    for the child json while the child may be dying). A local copy of
    ``runtime/checkpoint_engine/atomic.py``'s pattern ON PURPOSE: the
    bench PARENT never imports the package (importing deepspeed_tpu pulls
    jax, and backend init alone stalled 25 minutes in round 3)."""
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, **dump_kwargs)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:  # the rename is not durable until the directory entry is
        fd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        os.fsync(fd)
        os.close(fd)
    except OSError:
        pass


def _load_known_good():
    """metric -> last real (hardware, non-error) record, persisted across
    rounds. A down-tunnel round re-emits these tagged ``"stale": true`` so
    the last real measurement is never lost to a tunnel flap."""
    try:
        with open(KNOWN_GOOD_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _save_known_good(store):
    try:
        _atomic_write_json(KNOWN_GOOD_PATH, store, indent=1, sort_keys=True)
    except Exception:
        pass


def _record_known_good(store, rec, platform):
    """Remember a real measurement: hardware (non-cpu) platform only, never
    errors, never re-emitted stale records. Gating on the PROBED platform —
    not the env flags — keeps a full-size run that silently landed on the
    cpu backend from overwriting the TPU record."""
    if CPU_ONLY or platform in (None, "cpu") or rec.get("stale") or not rec.get("value"):
        return
    if str(rec.get("unit", "")).startswith(("error:", "skipped:")):
        return
    entry = dict(rec)
    entry["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    store[rec["metric"]] = entry
    _save_known_good(store)


def _stale_or_error(store, name, msg):
    """Prefer re-emitting the last known-good number (tagged stale) over an
    all-zero error line; fall back to the error record."""
    known = store.get(METRICS[name])
    if known is not None:
        rec = dict(known)
        rec["stale"] = True
        rec["stale_reason"] = f"this run: {msg[:120]}"
        return rec
    return _error_record(name, msg)


def _error_record(name, msg):
    return {"metric": METRICS[name], "value": 0, "unit": f"error: {msg[:160]}", "vs_baseline": 0}


def _run_child(args, timeout_s, log_path):
    """Run ``python bench.py <args>`` in its own session; kill the whole
    process group on timeout (jax spawns threads that survive a plain kill).
    Returns (rc, timed_out)."""
    with open(log_path, "ab") as log:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)] + args,
            stdout=log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
            cwd=REPO,
            env=_child_env(),
        )
        try:
            return proc.wait(timeout=timeout_s), False
        except subprocess.TimeoutExpired:
            try:
                os.killpg(os.getpgid(proc.pid), 9)
            except (ProcessLookupError, PermissionError):
                proc.kill()
            proc.wait()
            return -9, True


def _probe(budget_left):
    """Probe the backend; returns (platform|None, detail).

    The total probe budget is CAPPED (default 2 attempts ≈ 2.5 min
    worst-case). Round 5 measured the old spread-across-the-budget policy
    burning 13 × 75 s ≈ 16 min per down-tunnel run before the first stale
    record was emitted — the whole round's budget spent learning the same
    fact 13 times. One verdict serves the entire run: every config reuses
    it (the per-config children never re-probe), and a down backend
    completes the full bench — probe, stale re-emits, exit — in under five
    minutes. DS_BENCH_PROBE_ATTEMPTS raises the cap when chasing a flaky
    tunnel window is actually wanted.

    The result file, not the child's rc, is the success signal: a child that
    wrote it and then hung in backend teardown still counts."""
    if CPU_ONLY:
        return "cpu", "cpu-only mode: tunnel probe bypassed"
    log = os.path.join(REPO, "bench_child_probe.log")
    out_path = os.path.join(REPO, ".bench_probe.json")
    detail = "no probe ran"
    max_attempts = max(1, int(os.environ.get("DS_BENCH_PROBE_ATTEMPTS", "2")))
    for attempt in range(1, max_attempts + 1):
        # stale/error emission after the loop needs only seconds; a verdict
        # that would leave no room for even one warm config is still useful
        if budget_left() <= 90:
            break
        if os.path.exists(out_path):
            os.remove(out_path)
        timeout_s = min(75, max(20, budget_left() - 30))
        rc, timed_out = _run_child(["--child-probe"], timeout_s, log)
        if os.path.exists(out_path):
            try:
                with open(out_path) as f:
                    return json.load(f)["platform"], "ok"
            except Exception:
                return "unknown", "ok"
        detail = (
            f"probe attempt {attempt} "
            + (f"timed out after {timeout_s:.0f}s" if timed_out else f"exited rc={rc}")
        )
        print(f"[bench] {detail}", file=sys.stderr, flush=True)
        # a fast non-timeout exit is USUALLY deterministic (import error,
        # bad env) but can be transient (tunnel proxy bouncing →
        # connection-refused in seconds) — so it spends an attempt from the
        # same cap instead of aborting the whole probe; fast failures cost
        # seconds, so the <5-min down-backend guarantee is unaffected and
        # DS_BENCH_PROBE_ATTEMPTS governs every failure mode
        if attempt < max_attempts:
            time.sleep(2 if not timed_out else min(20, max(2, budget_left() - 75)))
    return None, detail


def _child_probe():
    import jax

    devs = jax.devices()
    _atomic_write_json(
        os.path.join(REPO, ".bench_probe.json"),
        {"platform": devs[0].platform, "n": len(devs)},
    )


def _child_run(name):
    _enable_compile_cache()
    fn, _ = CONFIGS[name]
    try:
        rec = fn()
    except Exception as e:
        traceback.print_exc()
        rec = _error_record(name, f"{type(e).__name__}: {e}")
    _atomic_write_json(os.path.join(REPO, f".bench_{name}.json"), rec)


def main():
    t_start = time.monotonic()
    budget = float(os.environ.get("DS_BENCH_BUDGET_S", "1320"))  # 22 min

    def budget_left():
        return budget - (time.monotonic() - t_start)

    cache_dir = os.environ.get(
        "JAX_COMPILATION_CACHE_DIR", os.path.join(REPO, ".jax_cache")
    )
    os.makedirs(cache_dir, exist_ok=True)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = cache_dir

    open(PARTIAL_PATH, "w").close()
    results = {}
    known_good = _load_known_good()

    def emit(rec):
        line = json.dumps(rec)
        print(line, flush=True)
        with open(PARTIAL_PATH, "a") as f:
            f.write(line + "\n")

    platform, probe_detail = _probe(budget_left)
    if platform is None:
        # No usable backend this run: re-emit the last real measurement per
        # config tagged "stale": true (VERDICT r4 weak #1 — a tunnel flap
        # must not erase the last hardware number from the round's record),
        # falling back to an honest error line where none exists. Exit 0 so
        # the driver records parsed output instead of a timeout. Each metric
        # exactly once, headline last (BENCH_r05's tail carried the headline
        # twice, which double-counts it in any per-metric consumer).
        msg = f"backend unavailable: {probe_detail}"
        for name in CONFIGS:
            if name != HEADLINE:
                emit(_stale_or_error(known_good, name, msg))
        emit(_stale_or_error(known_good, HEADLINE, msg))
        return
    print(f"[bench] backend ready: {platform}", file=sys.stderr, flush=True)

    def run_config(name, retries=0):
        fn, timeout_s = CONFIGS[name]
        out_path = os.path.join(REPO, f".bench_{name}.json")
        log_path = os.path.join(REPO, f"bench_child_{name}.log")
        for attempt in range(retries + 1):
            left = budget_left()
            if left < 75:
                return _error_record(name, f"skipped: budget ({left:.0f}s left)")
            eff = min(timeout_s, left - 15)
            if os.path.exists(out_path):
                os.remove(out_path)
            rc, timed_out = _run_child(["--child-run", name], eff, log_path)
            # The result file, not rc, is the success signal: the file is
            # deleted before each launch, so its existence proves THIS
            # attempt measured something — even if the child then hung in
            # backend teardown and was killed.
            rec = None
            if os.path.exists(out_path):
                try:
                    with open(out_path) as f:
                        rec = json.load(f)
                except Exception:
                    rec = None
            if rec is not None:
                # a child-level exception already produced an error record;
                # retry those too (warm compile cache makes retries cheap)
                if not str(rec.get("unit", "")).startswith("error:") or attempt == retries:
                    return rec
            elif attempt == retries:
                msg = f"timeout after {eff:.0f}s" if timed_out else f"child rc={rc}"
                return _error_record(name, msg)
            print(f"[bench] retrying {name}", file=sys.stderr, flush=True)
        return _error_record(name, "unreachable")

    def finalize(name, rec):
        """Record real hardware numbers; degrade error lines to stale
        re-emits. In CPU_ONLY smoke mode keep the honest error line — a
        stale TPU number would mask a broken tiny config and mix hardware
        numbers into a CPU-only output."""
        unit = str(rec.get("unit", ""))
        if unit.startswith(("error:", "skipped:")):
            if not CPU_ONLY:
                msg = unit[len("error: "):] if unit.startswith("error: ") else unit
                rec = _stale_or_error(known_good, name, msg)
        else:
            _record_known_good(known_good, rec, platform)
        results[name] = rec
        return rec

    # Headline MEASURED first — its number is on record (bench_known_good /
    # child json) even if everything after stalls — but EMITTED last and
    # exactly once: the driver parses the last line as the headline, and a
    # duplicated metric line double-counts in any per-metric consumer
    # (BENCH_r05 carried the headline twice).
    finalize(HEADLINE, run_config(HEADLINE, retries=1))
    # Everything between measuring the headline and emitting it is
    # exception-proofed: a raise inside a later config's orchestration must
    # not cost the run its headline line (only a hard kill can, and the
    # child json + known-good store still hold the number then).
    try:
        for name in ("llama_zero3", "infinity", "long_seq", "moe_inference",
                     "decode_serving", "decode_serving_tp", "fleet_serving"):
            emit(finalize(name, run_config(name)))

        # If the headline errored earlier but budget remains, give it one
        # more try now (the compile cache is warm from earlier attempts).
        headline_is_fresh = not (
            results[HEADLINE].get("stale")
            or str(results[HEADLINE].get("unit", "")).startswith("error:")
        )
        if not headline_is_fresh and budget_left() > 120:
            retry = run_config(HEADLINE)
            if not str(retry.get("unit", "")).startswith(("error:", "skipped:")):
                finalize(HEADLINE, retry)
    except Exception:
        traceback.print_exc()
        print("[bench] continuing to headline emit after error", file=sys.stderr, flush=True)
    emit(results[HEADLINE])


if __name__ == "__main__":
    if "--child-probe" in sys.argv:
        _child_probe()
    elif "--child-run" in sys.argv:
        _child_run(sys.argv[sys.argv.index("--child-run") + 1])
    else:
        main()
