"""Benchmark: GPT-2 125M ZeRO-1 single-chip training throughput (BASELINE
config 1), printed as one JSON line.

Metric: tokens/sec/chip. ``vs_baseline`` is measured MFU divided by the 0.40
MFU north-star (BASELINE.json): 1.0 means the target is met on this chip.
"""

from __future__ import annotations

import json
import time

import numpy as np


def _peak_tflops_bf16() -> float:
    """Per-chip bf16 peak. v5e (v5 lite): 197 TFLOP/s; fallbacks for others."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    table = {
        "v5 lite": 197e12,
        "v5e": 197e12,
        "v5p": 459e12,
        "v4": 275e12,
        "v6": 918e12,
        "cpu": 1e12,  # nominal, keeps the math defined on CPU runs
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12


def main():
    import jax

    import deepspeed_tpu as ds
    from deepspeed_tpu.models import TransformerLM, gpt2_config

    seq = 1024
    micro = 8
    # 125M @ micro=8 fits HBM with room to spare: full activation remat would
    # burn ~33% extra FLOPs for memory we don't need
    mcfg = gpt2_config("125m", max_seq_len=seq, remat=False)
    model = TransformerLM(mcfg)
    config = {
        "train_micro_batch_size_per_gpu": micro,
        "optimizer": {"type": "adam", "params": {"lr": 3e-4, "weight_decay": 0.01}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "gradient_clipping": 1.0,
        # keep the throughput timer's sync windows out of the measured region
        # (the bench does its own end-of-run drain)
        "steps_per_print": 10_000,
    }
    engine, _, _, _ = ds.initialize(model=model, config=config, dist_init_required=False)
    n_chips = max(engine.data_parallel_world_size(), 1)

    rs = np.random.RandomState(0)
    toks = rs.randint(0, mcfg.vocab_size, (micro * n_chips, seq + 1)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}

    # NOTE: sync via device_get of a value at the END of the dependency chain
    # (params feed the next step, so the final fetch waits for every step);
    # block_until_ready is unreliable on the tunneled backend.
    def drain():
        jax.device_get(engine.get_params()["final_norm_scale"])

    # warmup (compile)
    for _ in range(3):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    drain()

    steps = 20
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    drain()
    dt = time.perf_counter() - t0

    tokens_per_step = micro * n_chips * seq
    tokens_per_sec = steps * tokens_per_step / dt
    tokens_per_sec_per_chip = tokens_per_sec / n_chips

    n_params = engine.num_parameters()
    # 6N per token (fwd+bwd) + attention: 12*L*H*T ≈ 6*L*H*T*2
    attn_flops_per_token = 12 * mcfg.num_layers * mcfg.hidden_size * seq
    flops_per_token = 6 * n_params + attn_flops_per_token
    mfu = tokens_per_sec_per_chip * flops_per_token / _peak_tflops_bf16()

    print(
        json.dumps(
            {
                "metric": "gpt2_125m_zero1_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec_per_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.40, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
