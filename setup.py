"""Packaging (reference: DeepSpeed ``setup.py`` + ``op_builder`` AOT flags).

TPU-native build: the compute path is pure JAX/Pallas (no AOT CUDA arches),
and the native host ops (AVX CPUAdam, async disk I/O) compile lazily at
import via the C toolchain (see ``deepspeed_tpu/ops/native/build.py``) —
the JIT path of the reference's op_builder. ``DS_BUILD_NATIVE=1`` forces
them to compile at install time instead.
"""

import os

from setuptools import find_packages, setup

version = "0.1.0"

if os.environ.get("DS_BUILD_NATIVE", "0") == "1":
    try:
        from deepspeed_tpu.ops.native.build import build_all

        build_all()
    except Exception as e:  # pragma: no cover - best effort AOT
        print(f"warning: native op AOT build failed ({e}); ops build lazily at import")

setup(
    name="deepspeed_tpu",
    version=version,
    description="TPU-native distributed training and inference framework",
    packages=find_packages(include=["deepspeed_tpu", "deepspeed_tpu.*"]),
    include_package_data=True,
    scripts=[
        "bin/deepspeed",
        "bin/ds_report",
        "bin/ds_bench",
        "bin/ds_ssh",
        "bin/ds_elastic",
    ],
    install_requires=[
        "jax",
        "flax",
        "optax",
        "numpy",
        "pydantic>=2",
    ],
    python_requires=">=3.10",
)
