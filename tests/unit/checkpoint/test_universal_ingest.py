"""Ingest of the reference's UNIVERSAL checkpoint directory layout.

The reference's ``ds_to_universal.py`` writes one folder per parameter with
``fp32.pt`` / ``exp_avg.pt`` / ``exp_avg_sq.pt`` (full TP-merged tensors
under the ``param`` key) — the format its ``universal_checkpoint.py:12``
``load_hp_checkpoint_state`` consumes. These tests synthesize that exact
layout from the Megatron fixture and verify the ingest maps weights AND
Adam moments into the fused TPU layout, trainable on a fresh mesh."""

from __future__ import annotations

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402

import deepspeed_tpu as ds  # noqa: E402
import deepspeed_tpu.parallel.mesh as mesh_mod  # noqa: E402
from deepspeed_tpu.checkpoint import (  # noqa: E402
    ingest_universal_checkpoint,
    read_universal_dir,
)
from tests.unit.inference.test_containers import _MegatronCfg, _megatron_sd  # noqa: E402


def _write_universal(root, sd):
    """The ds_to_universal folder-per-param layout, moments = weight±1."""
    zero = os.path.join(root, "zero")
    for name, w in sd.items():
        d = os.path.join(zero, name)
        os.makedirs(d, exist_ok=True)
        w32 = torch.from_numpy(np.asarray(w, np.float32))
        torch.save({"param": w32, "cat_dim": 0}, os.path.join(d, "fp32.pt"))
        torch.save({"param": w32 + 1.0}, os.path.join(d, "exp_avg.pt"))
        # raw-tensor form (older writers): the reader must tolerate it
        torch.save(w32 + 2.0, os.path.join(d, "exp_avg_sq.pt"))
    return root


@pytest.fixture
def universal_dir(tmp_path):
    return _write_universal(str(tmp_path / "uni"), _megatron_sd()), _megatron_sd()


def test_read_universal_dir(universal_dir):
    path, sd = universal_dir
    state = read_universal_dir(path)
    assert set(state) == {"fp32", "exp_avg", "exp_avg_sq"}
    name = "language_model.embedding.word_embeddings.weight"
    np.testing.assert_array_equal(state["fp32"][name], np.asarray(sd[name], np.float32))
    np.testing.assert_allclose(
        state["exp_avg"][name], np.asarray(sd[name], np.float32) + 1.0
    )
    np.testing.assert_allclose(
        state["exp_avg_sq"][name], np.asarray(sd[name], np.float32) + 2.0
    )


def test_ingest_weights_and_moments_aligned(universal_dir):
    path, _ = universal_dir
    mesh_mod.reset_topology()
    ds_model, params, moments = ingest_universal_checkpoint(
        path, _MegatronCfg(), model_type="megatron_gpt"
    )
    assert moments is not None
    # the moments trees mirror the param tree leaf-for-leaf, offset by the
    # fixture's +1/+2 construction
    p_leaves = jax.tree_util.tree_leaves(params)
    m1_leaves = jax.tree_util.tree_leaves(moments["exp_avg"])
    m2_leaves = jax.tree_util.tree_leaves(moments["exp_avg_sq"])
    assert len(p_leaves) == len(m1_leaves) == len(m2_leaves)
    for p, m1, m2 in zip(p_leaves, m1_leaves, m2_leaves):
        assert p.shape == m1.shape == m2.shape
        np.testing.assert_allclose(
            np.asarray(m1, np.float32), np.asarray(p, np.float32) + 1.0, atol=1e-6
        )
        np.testing.assert_allclose(
            np.asarray(m2, np.float32), np.asarray(p, np.float32) + 2.0, atol=1e-6
        )


def test_ingested_params_train_on_fresh_mesh(universal_dir, eight_devices):
    path, _ = universal_dir
    mesh_mod.reset_topology()
    ds_model, params, _ = ingest_universal_checkpoint(
        path, _MegatronCfg(), model_type="megatron_gpt", load_optimizer=False
    )
    from deepspeed_tpu.models import TransformerLM

    engine, *_ = ds.initialize(
        model=TransformerLM(ds_model.config),
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3},
            "mesh": {"data": 8},
            "steps_per_print": 10_000,
        },
        dist_init_required=False,
    )
    rs = np.random.RandomState(0)
    toks = rs.randint(0, ds_model.config.vocab_size, (8, 33)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    assert np.isfinite(float(jax.device_get(loss)))


def test_missing_layout_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="universal"):
        read_universal_dir(str(tmp_path / "nope"))
    os.makedirs(tmp_path / "empty" / "zero")
    with pytest.raises(FileNotFoundError, match="universal"):
        read_universal_dir(str(tmp_path / "empty"))
