"""Universal checkpoint tests (reference: ``tests/unit/checkpoint/``)."""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.checkpoint import (
    DeepSpeedCheckpoint,
    convert_to_universal,
    load_hp_checkpoint_state,
    merge_tp_slices,
    reshape_tp_degree,
    split_tp_slices,
    universal_param_names,
)
from tests.unit.simple_model import SimpleModel


class TestReshapeUtils:
    def test_split_merge_roundtrip(self):
        w = np.arange(64).reshape(8, 8).astype(np.float32)
        shards = split_tp_slices(w, 4, axis=1)
        assert all(s.shape == (8, 2) for s in shards)
        np.testing.assert_array_equal(merge_tp_slices(shards, axis=1), w)

    def test_reshape_degree(self):
        w = np.arange(64).reshape(8, 8).astype(np.float32)
        old = split_tp_slices(w, 4, axis=0)
        new = reshape_tp_degree(old, 4, 2, axis=0)
        assert len(new) == 2 and new[0].shape == (4, 8)
        np.testing.assert_array_equal(merge_tp_slices(new, axis=0), w)

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            split_tp_slices(np.zeros((6, 6)), 4, axis=0)


def _make_ckpt(tmp_path, zero_stage=2):
    mesh_mod.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": zero_stage},
        "steps_per_print": 100,
    }
    engine, _, _, _ = ds.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, dist_init_required=False
    )
    rs = np.random.RandomState(0)
    batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
    for _ in range(2):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    engine.save_checkpoint(str(tmp_path / "ckpt"))
    return engine


class TestUniversal:
    def test_convert_and_load_fragments(self, tmp_path):
        engine = _make_ckpt(tmp_path)
        out = convert_to_universal(str(tmp_path / "ckpt"), str(tmp_path / "universal"))
        assert out.endswith(".npz")
        names = universal_param_names(str(tmp_path / "universal"))
        assert names == ["w0", "w1"]
        frag = load_hp_checkpoint_state(str(tmp_path / "universal"), "w0")
        assert set(frag) == {"fp32", "exp_avg", "exp_avg_sq"}
        from deepspeed_tpu.utils.tensor_fragment import (
            safe_get_full_fp32_param,
            safe_get_full_optimizer_state,
        )

        np.testing.assert_allclose(frag["fp32"], safe_get_full_fp32_param(engine, "w0"))
        np.testing.assert_allclose(
            frag["exp_avg"], safe_get_full_optimizer_state(engine, "w0", "exp_avg")
        )

    def test_checkpoint_inspector(self, tmp_path):
        _make_ckpt(tmp_path)
        ckpt = DeepSpeedCheckpoint(str(tmp_path / "ckpt"))
        assert ckpt.get_iteration() == 2
        assert "w0" in ckpt.get_module()

    def test_missing_param_raises(self, tmp_path):
        _make_ckpt(tmp_path)
        convert_to_universal(str(tmp_path / "ckpt"), str(tmp_path / "universal"))
        with pytest.raises(KeyError):
            load_hp_checkpoint_state(str(tmp_path / "universal"), "nope")


class TestNebulaEngine:
    def test_async_save_roundtrip(self, tmp_path):
        from deepspeed_tpu.runtime.checkpoint_engine.nebula_checkpoint_engine import (
            NebulaCheckpointEngine,
        )

        eng = NebulaCheckpointEngine()
        state = {"module": {"w": np.arange(8, dtype=np.float32)}, "global_steps": 3}
        eng.save(state, str(tmp_path / "nebula"))
        eng.commit("tag")  # fences the background write
        loaded = eng.load(str(tmp_path / "nebula"))
        np.testing.assert_array_equal(loaded["module"]["w"], state["module"]["w"])
        assert loaded["global_steps"] == 3
