"""Checkpoint round-trips per optimizer family (reference:
tests/unit/checkpoint/test_other_optimizer.py): each optimizer carries a
different state tree (moments, trust ratios, error feedback, accumulators)
and all of it must survive save -> fresh engine -> load -> identical
continued trajectory."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel, random_dataloader


def _cfg(opt_type, **opt_params):
    params = {"lr": 1e-2}
    params.update(opt_params)
    return {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": opt_type, "params": params},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }


def _engine(cfg):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg)
    return engine


def _step(engine, batch):
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    return float(jax.device_get(loss))


@pytest.mark.parametrize(
    "opt_type,opt_params",
    [
        ("adam", {}),
        ("adamw", {"weight_decay": 0.01}),
        ("lamb", {}),
        ("adagrad", {}),
        ("sgd", {"momentum": 0.9}),
    ],
)
def test_checkpoint_roundtrip_preserves_optimizer_state(tmp_path, opt_type, opt_params):
    cfg = _cfg(opt_type, **opt_params)
    batch = next(random_dataloader(total_samples=8, batch_size=8))

    # uninterrupted: 6 steps
    ref = _engine(cfg)
    ref_losses = [_step(ref, batch) for _ in range(6)]

    # interrupted at step 3
    a = _engine(cfg)
    for _ in range(3):
        _step(a, batch)
    a.save_checkpoint(str(tmp_path / opt_type))

    b = _engine(cfg)
    b.init_params(batch)
    b.load_checkpoint(str(tmp_path / opt_type))
    resumed = [_step(b, batch) for _ in range(3)]

    # optimizer state (moments/accumulators/momentum) resumed exactly:
    # the continued trajectory matches the uninterrupted one
    assert resumed == pytest.approx(ref_losses[3:], rel=1e-5), (
        opt_type,
        resumed,
        ref_losses[3:],
    )


def test_fresh_optimizer_diverges_without_state(tmp_path):
    """Control: loading weights only (fresh moments) must NOT reproduce the
    uninterrupted trajectory — proving the test above really exercises
    optimizer-state restoration."""
    cfg = _cfg("adam")
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    ref = _engine(cfg)
    ref_losses = [_step(ref, batch) for _ in range(6)]

    a = _engine(cfg)
    for _ in range(3):
        _step(a, batch)
    a.save_checkpoint(str(tmp_path))

    b = _engine(cfg)
    b.init_params(batch)
    b.load_checkpoint(str(tmp_path), load_optimizer_states=False)
    resumed = [_step(b, batch) for _ in range(3)]
    assert resumed != pytest.approx(ref_losses[3:], rel=1e-6)
