"""Checkpoint tag validation across REAL processes (reference:
tests/unit/checkpoint/test_tag_validation.py; engine.py:2944 all-gathers
the tag and asserts equality, config checkpoint.tag_validation
Warn/Fail/Ignore)."""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu import comm as dist
from tests.unit.simple_model import SimpleModel, random_dataloader

mode = os.environ["TAG_MODE"]          # Warn | Fail | Ignore
mismatch = os.environ["TAG_MISMATCH"] == "1"
ckpt_dir = os.environ["TAG_CKPT_DIR"]

ds.init_distributed()
rank = dist.get_rank()
engine, *_ = ds.initialize(model=SimpleModel(), config={
    "train_micro_batch_size_per_gpu": 8,
    "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
    "bf16": {"enabled": True},
    "checkpoint": {"tag_validation": mode},
})
batch = next(random_dataloader(total_samples=8, batch_size=8))
loss = engine(batch); engine.backward(loss); engine.step()

tag = f"tag_rank{rank}" if mismatch else "tag_same"
try:
    engine.save_checkpoint(ckpt_dir, tag=tag)
    # warn mode normalizes mismatched tags to rank 0's so the collective
    # save stays coherent — the latest file must name THAT tag
    with open(os.path.join(ckpt_dir, "latest")) as f:
        saved_tag = f.read().strip()
    expect = "tag_rank0" if mismatch else "tag_same"
    assert saved_tag == expect, (saved_tag, expect)
    print(f"RANK{rank} SAVED", flush=True)
except RuntimeError as e:
    assert "mismatch" in str(e), e
    print(f"RANK{rank} REJECTED", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run(mode, mismatch, tmp_path):
    port = _free_port()
    procs = []
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            RANK=str(rank),
            WORLD_SIZE="2",
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            TAG_MODE=mode,
            TAG_MISMATCH="1" if mismatch else "0",
            TAG_CKPT_DIR=str(tmp_path / f"ck_{mode}"),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=repo,
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    return procs, outs


_ROUNDTRIP_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

import numpy as np
import deepspeed_tpu as ds
from deepspeed_tpu import comm as dist
from tests.unit.simple_model import SimpleModel, random_dataloader

ds.init_distributed()
rank = dist.get_rank()
engine, *_ = ds.initialize(model=SimpleModel(), config={
    "train_micro_batch_size_per_gpu": 8,
    "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
    "bf16": {"enabled": True},
    "zero_optimization": {"stage": 2},
})
batch = next(random_dataloader(total_samples=8, batch_size=8))
for _ in range(2):
    loss = engine(batch); engine.backward(loss); engine.step()
engine.save_checkpoint(os.environ["TAG_CKPT_DIR"])
# reload: orbax hands back GLOBAL arrays across both processes; the load
# path must reshard them without a local device_put
engine.load_checkpoint(os.environ["TAG_CKPT_DIR"])
loss = engine(batch); engine.backward(loss); engine.step()  # still trains
assert np.isfinite(float(jax.device_get(loss)))
print(f"RANK{rank} ROUNDTRIP", flush=True)
"""


def test_cross_process_zero2_checkpoint_roundtrip(tmp_path):
    """Two real processes: ZeRO-2 save -> load -> continue training (the
    multi-process global-array load path)."""
    port = _free_port()
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            RANK=str(rank),
            WORLD_SIZE="2",
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
            TAG_CKPT_DIR=str(tmp_path / "ck_rt"),
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _ROUNDTRIP_WORKER],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, cwd=repo,
            )
        )
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        assert p.returncode == 0, f"rank {rank}:\n{out[-2500:]}"
        assert f"RANK{rank} ROUNDTRIP" in out


@pytest.mark.parametrize("mode", ["Warn", "Ignore"])
def test_matching_tags_save(mode, tmp_path):
    procs, outs = _run(mode, mismatch=False, tmp_path=tmp_path)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank}:\n{out}"
        assert f"RANK{rank} SAVED" in out


def test_mismatched_tags_fail_mode_raises(tmp_path):
    procs, outs = _run("Fail", mismatch=True, tmp_path=tmp_path)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank}:\n{out}"
        assert f"RANK{rank} REJECTED" in out, out


def test_mismatched_tags_warn_mode_saves(tmp_path):
    procs, outs = _run("Warn", mismatch=True, tmp_path=tmp_path)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank}:\n{out}"
        assert f"RANK{rank} SAVED" in out, out
