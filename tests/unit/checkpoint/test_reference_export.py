"""Reference-layout checkpoint EXPORT tests.

The inverse of ingest (reference ``engine.py:2588,2961`` save layout): a
deepspeed_tpu run must round-trip back into the reference ecosystem — the
exported files carry every key ``zero_to_fp32.py`` reads, and re-ingesting
them reproduces the fp32 masters bitwise.
"""

from __future__ import annotations

import os

import numpy as np
import pytest
import torch

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.checkpoint import (
    merge_reference_model_states,
    merge_reference_zero_fp32,
)
from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths
from tests.unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 16


def _trained_engine(stage=1):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(
        model=SimpleModel(HIDDEN),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": stage},
        },
    )
    for batch in random_dataloader(HIDDEN, total_samples=24, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    return engine


@pytest.mark.parametrize("dp_shards", [1, 2])
def test_export_reingest_bitwise_masters(tmp_path, eight_devices, dp_shards):
    """train → export → re-ingest → the fp32 masters are bitwise equal."""
    engine = _trained_engine()
    root = str(tmp_path / "ref_out")
    path = engine.save_reference_checkpoint(root, dp_shards=dp_shards)
    assert os.path.isdir(path)

    fp32 = merge_reference_zero_fp32(root, "megatron_gpt")
    masters = {
        k: np.asarray(v, np.float32)
        for k, v in _flatten_with_paths(engine.get_master_params()).items()
    }
    assert set(fp32) == set(masters)
    for name in masters:
        np.testing.assert_array_equal(
            fp32[name], masters[name], err_msg=f"master {name} not bitwise equal"
        )


def test_exported_layout_matches_reference_contract(tmp_path, eight_devices):
    """Every key the reference's zero_to_fp32.py reads must be present with
    the right types (parse_model_states / parse_optim_states)."""
    engine = _trained_engine()
    root = str(tmp_path / "ref_out")
    path = engine.save_reference_checkpoint(root, tag="global_step3", dp_shards=2)

    with open(os.path.join(root, "latest")) as f:
        assert f.read().strip() == "global_step3"

    ms = torch.load(
        os.path.join(path, "mp_rank_00_model_states.pt"), weights_only=False
    )
    # parse_model_states requirements
    assert "buffer_names" in ms and isinstance(ms["buffer_names"], list)
    assert "shared_params" in ms
    shapes_groups = ms["param_shapes"]
    assert isinstance(shapes_groups, list) and len(shapes_groups) == 1
    for name, shape in shapes_groups[0].items():
        assert isinstance(shape, torch.Size), name  # zero_to_fp32 calls .numel()
        assert tuple(ms["module"][name].shape) == tuple(shape)

    # parse_optim_states requirements: world_size files, zero_stage <= 2,
    # partition_count matches, flat fp32 groups
    zfiles = sorted(
        f for f in os.listdir(path) if f.endswith("_optim_states.pt")
    )
    assert len(zfiles) == 2
    total = 0
    for zf in zfiles:
        osd = torch.load(os.path.join(path, zf), weights_only=False)["optimizer_state_dict"]
        assert osd["zero_stage"] <= 2
        assert osd["partition_count"] == 2
        groups = osd["single_partition_of_fp32_groups"]
        assert len(groups) == 1 and groups[0].dtype == torch.float32
        total += groups[0].numel()
    numel = sum(s.numel() for s in shapes_groups[0].values())
    assert total >= numel  # flat partitions cover all params (+ padding)


def test_export_reingest_into_new_engine(tmp_path, eight_devices):
    """Full cycle: export → merge module states → weights match the
    consolidated compute-dtype dict exactly."""
    engine = _trained_engine()
    root = str(tmp_path / "ref_out")
    engine.save_reference_checkpoint(root)
    merged, meta = merge_reference_model_states(root, "megatron_gpt")
    sd = engine.consolidated_16bit_state_dict()
    assert meta["tp_degree"] == 1
    assert set(merged) == set(sd)
    for k in sd:
        np.testing.assert_allclose(
            merged[k], np.asarray(sd[k], np.float32), rtol=1e-6, atol=1e-6
        )


def test_reference_own_zero_to_fp32_consumes_export(tmp_path, eight_devices):
    """THE interop proof: run the reference's actual zero_to_fp32.py script
    (its only deepspeed import — checkpoint.constants — stubbed with the
    same key strings) against our exported layout and compare the
    consolidated fp32 state dict bitwise against the engine masters."""
    import importlib.util
    import sys
    import types

    ref_script = "/root/reference/deepspeed/utils/zero_to_fp32.py"
    if not os.path.exists(ref_script):
        pytest.skip("reference tree not available")

    engine = _trained_engine()
    root = str(tmp_path / "ref_out")
    engine.save_reference_checkpoint(root, dp_shards=2)

    # stub the constants module the script imports
    const = types.ModuleType("deepspeed.checkpoint.constants")
    for k, v in dict(
        DS_VERSION="ds_version",
        OPTIMIZER_STATE_DICT="optimizer_state_dict",
        SINGLE_PARTITION_OF_FP32_GROUPS="single_partition_of_fp32_groups",
        FP32_FLAT_GROUPS="fp32_flat_groups",
        ZERO_STAGE="zero_stage",
        PARTITION_COUNT="partition_count",
        PARAM_SHAPES="param_shapes",
        BUFFER_NAMES="buffer_names",
        FROZEN_PARAM_SHAPES="frozen_param_shapes",
        FROZEN_PARAM_FRAGMENTS="frozen_param_fragments",
    ).items():
        setattr(const, k, v)
    import logging

    pkg_ds = types.ModuleType("deepspeed")
    pkg_ds.__path__ = []  # mark as package so submodule imports resolve
    pkg_ck = types.ModuleType("deepspeed.checkpoint")
    pkg_ck.__path__ = []
    pkg_utils = types.ModuleType("deepspeed.utils")
    pkg_utils.__path__ = []
    pkg_utils.logger = logging.getLogger("ref_zero_to_fp32")
    stubs = ("deepspeed", "deepspeed.checkpoint",
             "deepspeed.checkpoint.constants", "deepspeed.utils")
    saved = {k: sys.modules.get(k) for k in stubs}
    sys.modules["deepspeed"] = pkg_ds
    sys.modules["deepspeed.checkpoint"] = pkg_ck
    sys.modules["deepspeed.checkpoint.constants"] = const
    sys.modules["deepspeed.utils"] = pkg_utils
    try:
        spec = importlib.util.spec_from_file_location("ref_zero_to_fp32", ref_script)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        sd = mod.get_fp32_state_dict_from_zero_checkpoint(root)
    finally:
        for k, v in saved.items():
            if v is None:
                sys.modules.pop(k, None)
            else:
                sys.modules[k] = v

    masters = {
        k: np.asarray(v, np.float32)
        for k, v in _flatten_with_paths(engine.get_master_params()).items()
    }
    assert set(sd) == set(masters)
    for name in masters:
        np.testing.assert_array_equal(
            sd[name].numpy(), masters[name],
            err_msg=f"reference-consolidated {name} differs",
        )
