"""Checkpoint resume flows (reference: tests/unit/checkpoint/
test_lr_scheduler.py, test_latest_checkpoint.py, test_shared_weights.py,
test_moe_checkpoint.py): scheduler state resumes the exact lr trajectory,
`latest` routing, tied-weight integrity, MoE expert state round-trips."""

import os

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel, random_dataloader


def _cfg(**over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }
    base.update(over)
    return base


def _steps(engine, n, seed=0):
    last = None
    for i, batch in enumerate(random_dataloader(total_samples=8 * n, batch_size=8, seed=seed)):
        last = engine(batch)
        engine.backward(last)
        engine.step()
    return last


def _fresh_engine(config):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=config)
    engine.init_params(next(random_dataloader(total_samples=8, batch_size=8)))
    return engine


class TestLRSchedulerResume:
    def test_warmup_lr_trajectory_survives_resume(self, tmp_path, eight_devices):
        cfg = _cfg(
            scheduler={"type": "WarmupLR", "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10}}
        )
        # uninterrupted run: 6 steps
        ref = _fresh_engine(cfg)
        _steps(ref, 6)
        ref_lrs = ref.get_lr()

        # interrupted: 3 steps, save, fresh engine, load, 3 more
        a = _fresh_engine(cfg)
        _steps(a, 3)
        a.save_checkpoint(str(tmp_path))
        b = _fresh_engine(cfg)
        b.load_checkpoint(str(tmp_path))
        assert b.global_steps == 3
        assert b.lr_scheduler.state_dict() == a.lr_scheduler.state_dict()
        _steps(b, 3, seed=1)
        assert b.get_lr() == pytest.approx(ref_lrs)

    def test_skip_scheduler_states(self, tmp_path, eight_devices):
        cfg = _cfg(
            scheduler={"type": "WarmupLR", "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10}}
        )
        a = _fresh_engine(cfg)
        _steps(a, 4)
        a.save_checkpoint(str(tmp_path))
        b = _fresh_engine(cfg)
        fresh_state = b.lr_scheduler.state_dict()
        b.load_checkpoint(str(tmp_path), load_lr_scheduler_states=False)
        assert b.lr_scheduler.state_dict() == fresh_state


class TestLatestRouting:
    def test_latest_points_to_newest_tag(self, tmp_path, eight_devices):
        a = _fresh_engine(_cfg())
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path), tag="first")
        w_first = np.asarray(jax.device_get(a.get_params()["w0"]))
        _steps(a, 2, seed=1)
        a.save_checkpoint(str(tmp_path), tag="second")
        with open(os.path.join(tmp_path, "latest")) as f:
            assert f.read().strip() == "second"
        b = _fresh_engine(_cfg())
        b.load_checkpoint(str(tmp_path))  # no tag -> latest -> "second"
        w_loaded = np.asarray(jax.device_get(b.get_params()["w0"]))
        assert not np.allclose(w_loaded, w_first)
        np.testing.assert_array_equal(
            w_loaded, np.asarray(jax.device_get(a.get_params()["w0"]))
        )

    def test_missing_latest_warns_and_returns_none(self, tmp_path, eight_devices):
        b = _fresh_engine(_cfg())
        path, client = b.load_checkpoint(str(tmp_path))
        assert path is None and client == {}

    def test_explicit_tag_bypasses_latest(self, tmp_path, eight_devices):
        a = _fresh_engine(_cfg())
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path), tag="first")
        w_first = np.asarray(jax.device_get(a.get_params()["w0"]))
        _steps(a, 1, seed=1)
        a.save_checkpoint(str(tmp_path), tag="second")
        b = _fresh_engine(_cfg())
        b.load_checkpoint(str(tmp_path), tag="first")
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(b.get_params()["w0"])), w_first
        )


class TestSharedWeights:
    def test_tied_embeddings_stay_tied_after_resume(self, tmp_path, eight_devices):
        from deepspeed_tpu.models import TransformerLM, llama_config

        cfg_model = llama_config("tiny", num_layers=2, tie_embeddings=True, remat=False)
        rs = np.random.RandomState(0)
        toks = rs.randint(0, cfg_model.vocab_size, (8, 17)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}

        mesh_mod.reset_topology()
        a, *_ = ds.initialize(model=TransformerLM(cfg_model), config=_cfg())
        loss = a(batch); a.backward(loss); a.step()
        a.save_checkpoint(str(tmp_path))

        mesh_mod.reset_topology()
        b, *_ = ds.initialize(model=TransformerLM(cfg_model), config=_cfg())
        b.init_params(batch)
        b.load_checkpoint(str(tmp_path))
        # tied: no separate lm_head in the tree; logits come from embed.tokens
        assert "lm_head" not in b.get_params()
        a.eval(); b.eval()
        eval_a = float(jax.device_get(a(batch)))
        eval_b = float(jax.device_get(b(batch)))
        assert eval_a == pytest.approx(eval_b, rel=1e-5)


class TestMoECheckpoint:
    def test_moe_roundtrip_identical_eval(self, tmp_path, eight_devices):
        from deepspeed_tpu.models import MoETransformerLM, moe_llama_config

        mcfg = moe_llama_config(
            "tiny", num_layers=2, num_experts=2, capacity_factor=2.0,
            max_seq_len=64, flash_attention=False,
        )
        rs = np.random.RandomState(0)
        toks = rs.randint(0, mcfg.vocab_size, (8, 65)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        cfg = _cfg()

        mesh_mod.reset_topology()
        a, *_ = ds.initialize(model=MoETransformerLM(mcfg), config=cfg)
        for _ in range(2):
            loss = a(batch); a.backward(loss); a.step()
        a.save_checkpoint(str(tmp_path))
        a.eval()
        eval_a = float(jax.device_get(a(batch)))

        mesh_mod.reset_topology()
        b, *_ = ds.initialize(model=MoETransformerLM(mcfg), config=cfg)
        b.init_params(batch)
        b.load_checkpoint(str(tmp_path))
        b.eval()
        assert float(jax.device_get(b(batch))) == pytest.approx(eval_a, rel=1e-5)
        # expert tensors present and equal across the round-trip
        ea = jax.tree_util.tree_leaves(a.get_params()["layers"]["moe"]["experts"])
        eb = jax.tree_util.tree_leaves(b.get_params()["layers"]["moe"]["experts"])
        for x, y in zip(ea, eb):
            np.testing.assert_array_equal(np.asarray(jax.device_get(x)), np.asarray(jax.device_get(y)))
