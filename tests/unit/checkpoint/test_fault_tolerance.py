"""Fault-tolerant training: async atomic checkpointing, exact resume, and
the seeded crash matrix (fast subset — the full kill-at-every-point ×
subprocess sweep lives in ``test_chaos_matrix.py`` behind ``-m slow``).

The guarantees under test:

* a ``kill -9`` at ANY checkpoint instant (mid array write, pre commit,
  post commit) leaves the newest *valid* checkpoint discoverable — no
  injection point can make ``latest``/``auto_resume`` land on a torn one;
* ``load_checkpoint(auto_resume=True)`` restores the FULL replay state
  (weights, moments, loss scale, LR schedule, counters, PRNG key, data
  cursor) and the resumed losses are **bit-identical** to an uninterrupted
  run — the PR-5 overlap-parity muscle applied to restarts;
* the async snapshot writer adds NO programs to the hot path and produces
  checkpoints identical to the synchronous save.
"""

import os
import pickle
import shutil

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.runtime.checkpoint_engine.atomic import (
    CheckpointCorruptError,
    CheckpointLoadError,
    find_latest_valid,
)
from deepspeed_tpu.utils import chaos
from tests.unit.simple_model import SimpleModel


def _batch(step, dim=16):
    rs = np.random.RandomState(1000 + step)
    return (rs.randn(8, dim).astype(np.float32), rs.randn(8, dim).astype(np.float32))


def _fresh(precision="bf16", over=None, hidden_dim=16):
    mesh_mod.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        precision: {"enabled": True},
        "zero_optimization": {"stage": 1},
        "scheduler": {
            "type": "WarmupLR",
            "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10},
        },
    }
    cfg.update(over or {})
    engine, *_ = ds.initialize(model=SimpleModel(hidden_dim=hidden_dim), config=cfg)
    engine.init_params(_batch(0, dim=hidden_dim))
    return engine


def _steps(engine, n, dim=16):
    losses = []
    for _ in range(n):
        loss = engine(_batch(engine.global_steps, dim=dim))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    chaos.uninstall()


# ---------------------------------------------------------------------------
# atomic layout
# ---------------------------------------------------------------------------
class TestAtomicLayout:
    def test_save_is_staged_until_commit(self, tmp_path, eight_devices):
        from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
            OrbaxCheckpointEngine,
        )

        eng = OrbaxCheckpointEngine()
        final = str(tmp_path / "tagA")
        eng.save({"module": {"w": np.arange(4, dtype=np.float32)}, "step": 1}, final)
        assert not os.path.exists(final), "save must not expose the final dir"
        staged = [n for n in os.listdir(tmp_path) if ".staging" in n]
        assert staged, "save must stage under a .staging sibling"
        eng.commit("tagA")
        assert os.path.isdir(final)
        assert os.path.isfile(os.path.join(final, "_COMPLETE"))
        assert not [n for n in os.listdir(tmp_path) if ".staging" in n]
        loaded = eng.load(final)
        np.testing.assert_array_equal(
            loaded["module"]["w"], np.arange(4, dtype=np.float32)
        )

    def test_torn_missing_meta_raises_clean(self, tmp_path, eight_devices):
        from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
            OrbaxCheckpointEngine,
        )

        torn = tmp_path / "global_step9"
        torn.mkdir()
        with pytest.raises(CheckpointCorruptError, match="meta.pkl"):
            OrbaxCheckpointEngine().load(str(torn))
        with pytest.raises(CheckpointCorruptError, match="no checkpoint"):
            OrbaxCheckpointEngine().load(str(tmp_path / "never_existed"))

    def test_torn_missing_arrays_raises_clean(self, tmp_path, eight_devices):
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))
        tag = find_latest_valid(str(tmp_path))
        shutil.rmtree(os.path.join(tmp_path, tag, "arrays"))
        b = _fresh()
        # an EXPLICIT tag load fails loudly...
        with pytest.raises(CheckpointCorruptError, match="arrays"):
            b.load_checkpoint(str(tmp_path), tag=tag)
        # ...auto_resume treats the torn tag as skippable (nothing older
        # exists here, so it is a clean fresh start)
        path, client = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path is None and client == {}

    def test_auto_resume_falls_back_past_load_time_corruption(
        self, tmp_path, eight_devices
    ):
        """A tag can look structurally complete (meta.pkl present) yet fail
        its restore — auto_resume must fall back to the next newest valid
        checkpoint, not die."""
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))  # global_step1, loadable
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))  # global_step2
        shutil.rmtree(os.path.join(tmp_path, "global_step2", "arrays"))
        b = _fresh()
        path, _ = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path.endswith("global_step1") and b.global_steps == 1

    def test_torn_garbage_meta_raises_clean(self, tmp_path, eight_devices):
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))
        tag = find_latest_valid(str(tmp_path))
        with open(os.path.join(tmp_path, tag, "meta.pkl"), "wb") as f:  # noqa: DS-R008
            f.write(b"\x80garbage")
        b = _fresh()
        with pytest.raises(CheckpointCorruptError, match="unreadable"):
            b.load_checkpoint(str(tmp_path), tag=tag)

    def test_find_latest_valid_skips_torn_dirs(self, tmp_path, eight_devices):
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))  # global_step1
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))  # global_step2
        (tmp_path / "global_step9").mkdir()  # torn: no meta.pkl
        assert find_latest_valid(str(tmp_path)) == "global_step2"


# ---------------------------------------------------------------------------
# seeded kills at every checkpoint injection point (in-process fast subset)
# ---------------------------------------------------------------------------
class TestCheckpointKills:
    @pytest.mark.parametrize(
        "point", ["ckpt.mid_array_write", "ckpt.pre_commit", "ckpt.post_commit"]
    )
    def test_kill_never_exposes_a_torn_checkpoint(self, tmp_path, eight_devices, point):
        """Kill the (synchronous) save of step 2 at each named instant: the
        previous checkpoint must stay discoverable and loadable, and —
        post-commit — the NEW one must be found even though ``latest``
        still names the old."""
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))  # global_step1, committed clean
        _steps(a, 1)
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule(point)]))
        with pytest.raises(chaos.ChaosKilled):
            a.save_checkpoint(str(tmp_path))
        chaos.uninstall()

        expected = "global_step2" if point == "ckpt.post_commit" else "global_step1"
        assert find_latest_valid(str(tmp_path)) == expected
        # the marker can lag (post-commit kill) but must never lead: the
        # tag it names is always valid
        with open(os.path.join(tmp_path, "latest")) as f:
            marker = f.read().strip()
        assert marker == "global_step1"
        b = _fresh()
        path, _ = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path.endswith(expected)
        assert b.global_steps == int(expected.removeprefix("global_step"))

    def test_kill_mid_commit_on_same_tag_resave_restores_previous(
        self, tmp_path, eight_devices
    ):
        """Re-saving an EXISTING tag has one instant where neither the old
        nor the new directory sits under the tag (old moved aside, new not
        yet renamed in). A kill there must not lose the tag: discovery
        restores the moved-aside checkpoint."""
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path), tag="best")
        _steps(a, 1)
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("ckpt.mid_commit")]))
        with pytest.raises(chaos.ChaosKilled):
            a.save_checkpoint(str(tmp_path), tag="best")
        chaos.uninstall()
        assert find_latest_valid(str(tmp_path)) == "best"
        b = _fresh()
        path, _ = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path.endswith("best") and b.global_steps == 1

    def test_killed_save_dir_recovers_on_next_save(self, tmp_path, eight_devices):
        """Staging garbage from a killed save is reclaimed when the same
        tag saves again, and the re-save commits clean."""
        a = _fresh()
        _steps(a, 1)
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("ckpt.pre_commit")]))
        with pytest.raises(chaos.ChaosKilled):
            a.save_checkpoint(str(tmp_path))
        chaos.uninstall()
        assert find_latest_valid(str(tmp_path)) is None
        a.save_checkpoint(str(tmp_path))
        assert find_latest_valid(str(tmp_path)) == "global_step1"
        assert not [n for n in os.listdir(tmp_path) if ".staging" in n]


# ---------------------------------------------------------------------------
# exact resume
# ---------------------------------------------------------------------------
class TestExactResume:
    @pytest.mark.parametrize("precision", ["bf16", "fp16"])
    def test_auto_resume_losses_bit_identical(self, tmp_path, eight_devices, precision):
        ref = _fresh(precision)
        ref_losses = _steps(ref, 6)

        a = _fresh(precision)
        _steps(a, 3)
        a.save_checkpoint(str(tmp_path))
        b = _fresh(precision)
        path, _ = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path is not None and b.global_steps == 3
        resumed = _steps(b, 3)
        assert resumed == ref_losses[3:], (
            f"resumed losses diverge: {resumed} vs {ref_losses[3:]}"
        )
        # the replay state really moved: rng keys advanced identically
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(ref._rng)), np.asarray(jax.device_get(b._rng))
        )

    def test_interval_autosave_resume_bit_identical(self, tmp_path, eight_devices):
        """The production loop: auto-save every N steps (async), die, come
        back with auto_resume, land on the same curve."""
        ref = _fresh()
        ref_losses = _steps(ref, 6)

        a = _fresh(over={"checkpoint": {
            "async_snapshot": True, "interval_steps": 2, "save_dir": str(tmp_path),
        }})
        _steps(a, 5)  # saves fired at steps 2 and 4
        a.wait_pending_checkpoint()
        assert find_latest_valid(str(tmp_path)) == "global_step4"
        b = _fresh()
        b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert b.global_steps == 4
        assert _steps(b, 2) == ref_losses[4:]

    def test_auto_resume_empty_dir_is_fresh_start(self, tmp_path, eight_devices):
        b = _fresh()
        path, client = b.load_checkpoint(str(tmp_path / "nothing"), auto_resume=True)
        assert path is None and client == {}

    def test_data_cursor_roundtrip(self, eight_devices, tmp_path):
        """The engine-owned dataloader's cursor rides the checkpoint."""
        data = [(np.random.RandomState(i).randn(16).astype(np.float32),
                 np.zeros(16, np.float32)) for i in range(32)]
        mesh_mod.reset_topology()
        a, _, loader, _ = ds.initialize(
            model=SimpleModel(),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
            },
            training_data=data,
        )
        it = iter(loader)
        for _ in range(3):
            batch = next(it)
        a.init_params(batch)
        loss = a(batch); a.backward(loss); a.step()
        a.save_checkpoint(str(tmp_path))
        assert loader.state_dict() == {"epoch": 0, "cursor": 3}

        mesh_mod.reset_topology()
        b, _, loader_b, _ = ds.initialize(
            model=SimpleModel(),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
            },
            training_data=data,
        )
        b.init_params(batch)
        b.load_checkpoint(str(tmp_path))
        assert loader_b.state_dict() == {"epoch": 0, "cursor": 3}
        # the canonical resumed loop re-selects the current epoch — that
        # must NOT wipe the restored mid-epoch cursor...
        loader_b.set_epoch(0)
        # ...so the resumed iterator continues where the saved one stood
        np.testing.assert_array_equal(next(iter(loader_b))[0], next(it)[0])
        # a genuinely NEW epoch does reset the cursor
        loader_b.set_epoch(1)
        assert loader_b.state_dict() == {"epoch": 1, "cursor": 0}


# ---------------------------------------------------------------------------
# async snapshot writer
# ---------------------------------------------------------------------------
class TestAsyncSnapshot:
    def test_async_save_matches_sync_and_adds_no_programs(self, tmp_path, eight_devices):
        a = _fresh()
        _steps(a, 2)
        stats_before = {k: v["compiles"] for k, v in a.compile_stats().items()}
        a.save_checkpoint(str(tmp_path / "async"), asynchronous=True)
        a.save_checkpoint(str(tmp_path / "sync"), asynchronous=False)
        a.wait_pending_checkpoint()
        # the async snapshot + writer must not touch the compile path:
        # no new programs, no new compiles (telemetry-verified hot path)
        stats_after = {k: v["compiles"] for k, v in a.compile_stats().items()}
        assert stats_after == stats_before
        st = a.checkpoint_stats()
        assert st["saves"] == 2 and st["async_saves"] == 1 and st["pending"] == 0
        assert st["last_stall_ms"] > 0.0

        from deepspeed_tpu.runtime.checkpoint_engine.orbax_checkpoint_engine import (
            OrbaxCheckpointEngine,
        )

        eng = OrbaxCheckpointEngine()
        sa = eng.load(os.path.join(tmp_path, "async", "global_step2"))
        ss = eng.load(os.path.join(tmp_path, "sync", "global_step2"))
        for key in ("module", "master", "optimizer"):
            for la, ls in zip(
                jax.tree_util.tree_leaves(sa[key]), jax.tree_util.tree_leaves(ss[key])
            ):
                np.testing.assert_array_equal(np.asarray(la), np.asarray(ls))
        assert sa["global_steps"] == ss["global_steps"] == 2
        np.testing.assert_array_equal(np.asarray(sa["rng"]), np.asarray(ss["rng"]))

    def test_writer_killed_midflight_training_continues(self, tmp_path, eight_devices):
        """A chaos kill inside the BACKGROUND writer (pre-commit) must not
        take down the step loop; the next interval save restarts the
        writer and commits clean; auto_resume lands on the newest valid."""
        ref = _fresh()
        ref_losses = _steps(ref, 6)

        a = _fresh(over={"checkpoint": {
            "async_snapshot": True, "interval_steps": 1, "save_dir": str(tmp_path),
        }})
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("ckpt.pre_commit", hit=2)]))
        _steps(a, 3)  # save#2's writer dies at pre-commit; steps keep going
        a.wait_pending_checkpoint()
        chaos.uninstall()
        assert find_latest_valid(str(tmp_path)) == "global_step3"
        b = _fresh()
        b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert b.global_steps == 3
        assert _steps(b, 3) == ref_losses[3:]

    def test_async_error_surfaces_at_fence(self, tmp_path, eight_devices):
        a = _fresh()
        _steps(a, 1)
        target = tmp_path / "blocked"
        target.write_text("a file where the save dir must go")
        a.save_checkpoint(str(target), asynchronous=True)
        with pytest.raises(RuntimeError, match="async checkpoint persist failed"):
            a.wait_pending_checkpoint()


# ---------------------------------------------------------------------------
# load validation
# ---------------------------------------------------------------------------
class TestLoadValidation:
    def test_shape_mismatch_names_leaf_and_both_shapes(self, tmp_path, eight_devices):
        a = _fresh(hidden_dim=16)
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))
        b = _fresh(hidden_dim=8)
        with pytest.raises(CheckpointLoadError) as ei:
            b.load_checkpoint(str(tmp_path), auto_resume=True)
        msg = str(ei.value)
        assert "w0" in msg and "(16, 16)" in msg and "(8, 8)" in msg

    def test_dtype_mismatch_names_leaf(self, tmp_path, eight_devices):
        a = _fresh("bf16")
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))
        b = _fresh("fp16")
        with pytest.raises(CheckpointLoadError, match="dtype mismatch.*w0"):
            b.load_checkpoint(str(tmp_path), auto_resume=True)

    def test_mesh_topology_mismatch_is_loud(self, tmp_path, eight_devices):
        a = _fresh()
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))
        # rewrite the checkpoint's recorded mesh (a save from a 2x wider
        # data axis) without touching the arrays
        tag = find_latest_valid(str(tmp_path))
        meta_path = os.path.join(tmp_path, tag, "meta.pkl")
        with open(meta_path, "rb") as f:
            blob = pickle.load(f)
        for key in blob["meta"]:
            if key.startswith("root/mesh/data"):
                blob["meta"][key] = blob["meta"][key] * 2
        with open(meta_path, "wb") as f:  # noqa: DS-R008 — test tampers in place
            pickle.dump(blob, f)
        b = _fresh()
        with pytest.raises(CheckpointLoadError, match="mesh topology mismatch"):
            b.load_checkpoint(str(tmp_path), tag=tag)

    def test_loose_load_skips_validation(self, tmp_path, eight_devices):
        a = _fresh("bf16")
        _steps(a, 1)
        a.save_checkpoint(str(tmp_path))
        b = _fresh("bf16")
        path, _ = b.load_checkpoint(str(tmp_path), load_module_strict=False)
        assert path is not None
