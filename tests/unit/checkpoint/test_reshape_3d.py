"""General 3D checkpoint reshape (reference ``reshape_meg_2d.py`` /
``reshape_3d_utils.py`` / ``zero_checkpoint.py``): export at one (tp, pp, dp),
re-layout to another, resume with identical state."""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.checkpoint import (
    Model3DDescriptor,
    describe_checkpoint,
    export_megatron_checkpoint,
    load_megatron_checkpoint,
    read_reference_layout,
    reshape_checkpoint_3d,
)
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig


def _make_engine(seed=0):
    mesh_mod.reset_topology()
    mcfg = TransformerConfig(
        vocab_size=64,
        hidden_size=32,
        num_layers=4,
        num_heads=2,
        max_seq_len=16,
        use_bias=False,
        tie_embeddings=False,
    )
    engine, _, _, _ = ds.initialize(
        model=TransformerLM(mcfg),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 1000,
        },
        dist_init_required=False,
    )
    return engine


def _batch(seed=0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, 64, (8, 17)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


def _train(engine, batch, steps):
    losses = []
    for _ in range(steps):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses


def _flatten_canon(canon):
    out = {}
    for key, group in canon["layers"].items():
        for name, arr in group.items():
            out[f"layers/{key}/{name}"] = np.asarray(arr, np.float32)
    for kind in ("fp32", "exp_avg", "exp_avg_sq"):
        if canon.get(kind):
            for key, group in canon[kind].items():
                for name, arr in group.items():
                    out[f"{kind}/{key}/{name}"] = np.asarray(arr, np.float32)
    return out


class TestReshape3D:
    def test_describe_and_lossless_roundtrip(self, tmp_path):
        """tp2×pp2×dp2 → tp1×pp4×dp1 → tp2×pp2×dp2 reproduces every tensor
        (module, fp32 master, both Adam moments) bit-exactly."""
        engine = _make_engine()
        _train(engine, _batch(), 3)
        src = str(tmp_path / "src")
        export_megatron_checkpoint(engine, src, tp=2, pp=2, dp=2, tag="tag")
        assert describe_checkpoint(f"{src}/tag") == Model3DDescriptor(2, 2, 2)

        mid = str(tmp_path / "mid")
        reshape_checkpoint_3d(src, mid, tp=1, pp=4, dp=1)
        assert describe_checkpoint(f"{mid}/tag") == Model3DDescriptor(1, 4, 1)

        back = str(tmp_path / "back")
        reshape_checkpoint_3d(mid, back, tp=2, pp=2, dp=2)

        a = _flatten_canon(read_reference_layout(f"{src}/tag"))
        b = _flatten_canon(read_reference_layout(f"{back}/tag"))
        assert sorted(a) == sorted(b)
        for name in a:
            np.testing.assert_array_equal(a[name], b[name], err_msg=name)

    def test_resume_from_reshaped_identical_loss(self, tmp_path):
        """VERDICT r4 acceptance: resume from the tp1×pp4 reshape of a
        tp2×pp2 checkpoint ≡ resume from the original — identical losses."""
        engine = _make_engine()
        batch = _batch()
        _train(engine, batch, 3)
        src = str(tmp_path / "src")
        export_megatron_checkpoint(engine, src, tp=2, pp=2, dp=2, tag="tag")
        reshaped = str(tmp_path / "reshaped")
        reshape_checkpoint_3d(src, reshaped, tp=1, pp=4, dp=1)

        resumed_src = _make_engine()
        resumed_src.init_params(batch)
        load_megatron_checkpoint(resumed_src, src)
        losses_src = _train(resumed_src, batch, 3)

        resumed_re = _make_engine()
        resumed_re.init_params(batch)
        load_megatron_checkpoint(resumed_re, reshaped)
        losses_re = _train(resumed_re, batch, 3)

        assert resumed_re.global_steps == resumed_src.global_steps
        assert losses_src == losses_re

    def test_resume_continues_training(self, tmp_path):
        """The reshaped resume actually CONTINUES the run: its first loss
        matches the next loss of an uninterrupted engine."""
        engine = _make_engine()
        batch = _batch()
        _train(engine, batch, 3)
        src = str(tmp_path / "src")
        export_megatron_checkpoint(engine, src, tp=2, pp=2, dp=1, tag="tag")
        reshaped = str(tmp_path / "re")
        reshape_checkpoint_3d(src, reshaped, tp=4, pp=1, dp=2)  # expansion too

        uninterrupted = _train(engine, batch, 2)

        resumed = _make_engine()
        resumed.init_params(batch)
        load_megatron_checkpoint(resumed, reshaped)
        resumed_losses = _train(resumed, batch, 2)
        np.testing.assert_allclose(resumed_losses, uninterrupted, rtol=2e-2)

    def test_expansion_beyond_reference(self, tmp_path):
        """The reference refuses expansion reshapes (reshape_3d_utils
        ``can_reshape``); the canonical-form design handles them."""
        engine = _make_engine()
        _train(engine, _batch(), 2)
        src = str(tmp_path / "src")
        export_megatron_checkpoint(engine, src, tp=1, pp=1, dp=1, tag="tag")
        wide = str(tmp_path / "wide")
        reshape_checkpoint_3d(src, wide, tp=2, pp=4, dp=4)
        assert describe_checkpoint(f"{wide}/tag") == Model3DDescriptor(2, 4, 4)
        a = _flatten_canon(read_reference_layout(f"{src}/tag"))
        b = _flatten_canon(read_reference_layout(f"{wide}/tag"))
        for name in a:
            np.testing.assert_array_equal(a[name], b[name], err_msg=name)


class TestSharpEdges:
    def _synthetic_canon(self, n_layers=120, odd_dim=False):
        from collections import OrderedDict

        rs = np.random.RandomState(0)
        layers = OrderedDict()
        tp_axes = {}
        layers["00"] = OrderedDict(
            {"embed/tokens": rs.randn(16, 8).astype(np.float32)}
        )
        tp_axes["00"] = {"embed/tokens": 0}
        for i in range(n_layers):
            key = f"{i + 1:02d}"
            dim = 3 if odd_dim else 4
            layers[key] = OrderedDict(
                # stamp the layer index into the tensor so a permuted
                # restack is detectable
                {"wq": np.full((dim, 4), float(i), np.float32)}
            )
            tp_axes[key] = {"wq": 0}
        return {
            "layers": layers,
            "tp_axes": tp_axes,
            "fp32": None,
            "exp_avg": None,
            "exp_avg_sq": None,
            "global": {"iteration": 7},
        }

    def test_layer_order_past_99(self, tmp_path):
        """String-sorted keys would order '100' before '11'; layer identity
        must survive a 120-layer write/read."""
        from deepspeed_tpu.checkpoint import read_reference_layout, write_reference_layout

        canon = self._synthetic_canon(n_layers=120)
        write_reference_layout(canon, str(tmp_path / "c"), tp=2, pp=4, dp=1)
        back = read_reference_layout(str(tmp_path / "c"))
        keys = [k for k in back["layers"] if k != "00"]
        assert len(keys) == 120
        for i, key in enumerate(sorted(keys, key=int)):
            assert float(back["layers"][key]["wq"][0, 0]) == float(i), key

    def test_non_divisible_tp_dim_stays_replicated(self, tmp_path):
        """A 'model'-axis dim not divisible by tp is stored replicated and
        the recorded effective axis says so — the reader must NOT
        concatenate the replicas (round-4 review finding)."""
        from deepspeed_tpu.checkpoint import read_reference_layout, write_reference_layout

        canon = self._synthetic_canon(n_layers=4, odd_dim=True)  # dim 3, tp 2
        write_reference_layout(canon, str(tmp_path / "c"), tp=2, pp=1, dp=1)
        back = read_reference_layout(str(tmp_path / "c"))
        assert back["layers"]["01"]["wq"].shape == (3, 4)
        # nominal axis survives for future re-splits at a compatible tp
        assert back["tp_axes"]["01"]["wq"] == 0


class TestReferenceApiSurface:
    """Reference deepspeed/checkpoint/__init__.py name parity."""

    def test_aliases_and_constants(self):
        from deepspeed_tpu.checkpoint import (
            MODEL_FILE_PREFIX,
            ZERO_FILE_PREFIX,
            get_layer_ckpt_name_for_rank,
            get_model_ckpt_name_for_rank,
            get_model_3d_descriptor,
            get_zero_ckpt_name_for_rank,
            model_3d_desc,
        )

        assert MODEL_FILE_PREFIX == "mp_rank_"
        assert ZERO_FILE_PREFIX == "zero_pp_rank_"
        assert model_3d_desc is Model3DDescriptor
        assert get_model_3d_descriptor is describe_checkpoint
        assert get_model_ckpt_name_for_rank("/b", "00") == "/b/mp_rank_00_model_states.pt"
        assert (
            get_zero_ckpt_name_for_rank("/b", 3, 1)
            == "/b/zero_pp_rank_3_mp_rank_01_optim_states.pt"
        )
        # the reference's own helper emits the underscore form
        # (utils.py:30: f'{layer_id}-model_{tp:02d}{MODEL_FILE_SUFFIX}')
        assert (
            get_layer_ckpt_name_for_rank("/b", "layer_01", 2)
            == "/b/layer_01-model_02_model_states.pt"
        )

    def test_clone_tensors_for_torch_save(self):
        import jax.numpy as jnp

        from deepspeed_tpu.checkpoint import clone_tensors_for_torch_save

        out = clone_tensors_for_torch_save(
            {"a": jnp.ones((2,)), "b": [jnp.zeros((3,)), 7], "c": "x"}
        )
        assert isinstance(out["a"], np.ndarray)
        assert isinstance(out["b"][0], np.ndarray)
        assert out["b"][1] == 7 and out["c"] == "x"
