"""Cross-ecosystem checkpoint interop against the REFERENCE's own machinery.

Everything else in this directory validates our ingest/export against our
own readers. Here the artifact we export is consumed by the reference's
``deepspeed/checkpoint`` package itself (loaded standalone from
``/root/reference`` — it only needs torch + relative imports), proving the
round trip into the reference ecosystem:

* ``reshape_utils.get_zero_files`` / ``merge_state`` consolidate our
  ``zero_pp_rank_*`` fp32 shards exactly like ``zero_to_fp32.py`` would;
* the merged flat buffer slices back into bitwise-equal fp32 masters using
  the ``param_shapes`` recorded in our ``mp_rank_00_model_states.pt``.

Skips when the reference tree is not present (end-user installs).
"""

from __future__ import annotations

import importlib.util
import os
import sys

import numpy as np
import pytest
import torch

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.utils.tensor_fragment import _flatten_with_paths
from tests.unit.simple_model import SimpleModel, random_dataloader

REF_CKPT_DIR = "/root/reference/deepspeed/checkpoint"
HIDDEN = 16


def _load_reference_checkpoint_pkg():
    if not os.path.isdir(REF_CKPT_DIR):
        pytest.skip("reference tree not available")
    if "refckpt" in sys.modules:
        return sys.modules["refckpt"]
    spec = importlib.util.spec_from_file_location(
        "refckpt",
        os.path.join(REF_CKPT_DIR, "__init__.py"),
        submodule_search_locations=[REF_CKPT_DIR],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules["refckpt"] = mod
    spec.loader.exec_module(mod)
    return mod


def _trained_engine():
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(
        model=SimpleModel(HIDDEN),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
        },
    )
    for batch in random_dataloader(HIDDEN, total_samples=16, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    return engine


def test_reference_machinery_consolidates_our_export(tmp_path, eight_devices):
    refckpt = _load_reference_checkpoint_pkg()
    from refckpt.reshape_utils import get_zero_files  # type: ignore

    engine = _trained_engine()
    root = str(tmp_path / "ref_out")
    tag_dir = engine.save_reference_checkpoint(root, dp_shards=2)

    # 1. the reference's zero-file discovery finds our shards
    zero_files = get_zero_files(tag_dir)
    assert len(zero_files) == 2, zero_files

    # 2. the reference's merge_state concatenates the dp shards (dim 0),
    #    exactly the consolidation zero_to_fp32.py performs
    states = [
        torch.load(f, map_location="cpu", weights_only=False)["optimizer_state_dict"]
        for f in sorted(zero_files)
    ]
    merged = refckpt.merge_state(
        states[0]["single_partition_of_fp32_groups"],
        states[1]["single_partition_of_fp32_groups"],
    )
    flat = merged[0].numpy()

    # 3. slice by the param_shapes our model_states file records → the
    #    engine's live fp32 masters, bitwise
    model_state = torch.load(
        os.path.join(tag_dir, "mp_rank_00_model_states.pt"),
        map_location="cpu",
        weights_only=False,
    )
    (param_shapes,) = model_state["param_shapes"]
    masters = {
        k: np.asarray(v, np.float32)
        for k, v in _flatten_with_paths(engine.get_master_params()).items()
    }
    offset = 0
    for name, shape in param_shapes.items():
        n = int(np.prod(shape)) if len(shape) else 1
        got = flat[offset : offset + n].reshape(tuple(shape))
        np.testing.assert_array_equal(got, masters[name], err_msg=name)
        offset += n


def test_reference_merge_matches_ours(tmp_path, eight_devices):
    """Same artifact, two consolidators: the reference's merge_state and our
    merge_reference_zero_fp32 must produce identical fp32 tensors."""
    refckpt = _load_reference_checkpoint_pkg()
    from deepspeed_tpu.checkpoint import merge_reference_zero_fp32

    engine = _trained_engine()
    root = str(tmp_path / "ref_out")
    tag_dir = engine.save_reference_checkpoint(root, dp_shards=2)

    ours = merge_reference_zero_fp32(root, "megatron_gpt")

    from refckpt.reshape_utils import get_zero_files  # type: ignore

    states = [
        torch.load(f, map_location="cpu", weights_only=False)["optimizer_state_dict"]
        for f in sorted(get_zero_files(tag_dir))
    ]
    merged = refckpt.merge_state(
        states[0]["single_partition_of_fp32_groups"],
        states[1]["single_partition_of_fp32_groups"],
    )[0].numpy()
    model_state = torch.load(
        os.path.join(tag_dir, "mp_rank_00_model_states.pt"),
        map_location="cpu",
        weights_only=False,
    )
    (param_shapes,) = model_state["param_shapes"]
    offset = 0
    for name, shape in param_shapes.items():
        n = int(np.prod(shape)) if len(shape) else 1
        theirs = merged[offset : offset + n].reshape(tuple(shape))
        np.testing.assert_array_equal(theirs, ours[name], err_msg=name)
        offset += n
