"""Cross-layout ingest of reference (DeepSpeed torch) checkpoints.

Fixture: a Megatron-GPT checkpoint written in the reference's exact 3D file
layout — tp=2 ``mp_rank_XX_model_states.pt`` with per-head-interleaved qkv
shards, dp=2 ``zero_pp_rank_D_mp_rank_XX_optim_states.pt`` flat fp32
partitions with ``param_shapes`` — ingested, verified against the unsharded
source tensors, and trained on an 8-device mesh the source never saw
(reference ``reshape_meg_2d.py`` + ``universal_checkpoint.py:95``).
"""

from __future__ import annotations

import os
import re

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import deepspeed_tpu as ds  # noqa: E402
import deepspeed_tpu.parallel.mesh as mesh_mod  # noqa: E402
from deepspeed_tpu.checkpoint import (  # noqa: E402
    ingest_reference_checkpoint,
    merge_reference_model_states,
    merge_reference_zero_fp32,
)
from deepspeed_tpu.checkpoint.reference_ingest import tp_merge_axis  # noqa: E402
from tests.unit.inference.test_containers import _MegatronCfg, _megatron_sd  # noqa: E402

TP, DP = 2, 2


def _split_sd_tp(sd, tp):
    """Inverse of the ingest merge: shard each tensor along its policy axis."""
    shards = [dict() for _ in range(tp)]
    for name, w in sd.items():
        axis = tp_merge_axis(name, "megatron_gpt")
        for r in range(tp):
            if axis is None:
                shards[r][name] = torch.from_numpy(np.asarray(w))
            else:
                shards[r][name] = torch.from_numpy(
                    np.ascontiguousarray(np.split(np.asarray(w), tp, axis=axis)[r])
                )
    return shards


def _write_reference_ckpt(root, sd, tag="global_step7"):
    """Write the reference's exact file layout for tp=2, dp=2, stage-1."""
    path = os.path.join(root, tag)
    os.makedirs(path, exist_ok=True)
    tp_shards = _split_sd_tp(sd, TP)
    for mp, shard in enumerate(tp_shards):
        # fp32 masters = module weights + 7 (so zero ingest is detectable)
        flat = np.concatenate(
            [np.asarray(v, np.float32).ravel() + 7.0 for v in shard.values()]
        )
        pad = (-flat.size) % DP
        flat_padded = np.pad(flat, (0, pad))
        parts = np.split(flat_padded, DP)
        param_shapes = [{k: tuple(v.shape) for k, v in shard.items()}]
        torch.save(
            {
                "module": shard,
                "param_shapes": param_shapes,
                "iteration": 7,
                "dp_world_size": DP,
            },
            os.path.join(path, f"mp_rank_{mp:02d}_model_states.pt"),
        )
        for dp in range(DP):
            torch.save(
                {
                    "optimizer_state_dict": {
                        "single_partition_of_fp32_groups": [
                            torch.from_numpy(parts[dp].copy())
                        ]
                    }
                },
                os.path.join(path, f"zero_pp_rank_{dp}_mp_rank_{mp:02d}_optim_states.pt"),
            )
    with open(os.path.join(root, "latest"), "w") as f:
        f.write(tag)
    return path


@pytest.fixture
def ref_ckpt(tmp_path):
    sd = _megatron_sd(L=2, H=32, NH=4, V=128, I=64)
    root = str(tmp_path / "ref")
    os.makedirs(root)
    _write_reference_ckpt(root, sd)
    return root, sd


def test_model_states_tp_merge_exact(ref_ckpt):
    root, sd = ref_ckpt
    merged, meta = merge_reference_model_states(root, "megatron_gpt")
    assert meta["tp_degree"] == TP and meta["iteration"] == 7
    assert set(merged) == set(sd)
    for name in sd:
        np.testing.assert_array_equal(merged[name], np.asarray(sd[name], np.float32))


def test_zero_fp32_reconstruction(ref_ckpt):
    root, sd = ref_ckpt
    fp32 = merge_reference_zero_fp32(root, "megatron_gpt")
    for name in sd:
        np.testing.assert_allclose(
            fp32[name], np.asarray(sd[name], np.float32) + 7.0, rtol=1e-6
        )


def test_ingest_and_train_on_new_mesh(ref_ckpt, eight_devices):
    """The 2x2 (tp,dp) reference checkpoint loads into an 8-way data mesh
    and trains — the universal-checkpoint 'resume anywhere' property."""
    root, sd = ref_ckpt
    mesh_mod.reset_topology()
    ds_model, params, meta = ingest_reference_checkpoint(
        root, _MegatronCfg(), dtype="float32"
    )
    assert meta["weights_from"] == "zero_fp32_masters"
    # weights match the reconstructed fp32 masters through the layout convert
    np.testing.assert_allclose(
        params["embed"]["tokens"],
        np.asarray(sd["language_model.embedding.word_embeddings.weight"], np.float32) + 7.0,
        rtol=1e-6,
    )

    engine, _, _, _ = ds.initialize(
        model=ds_model,
        model_parameters=params,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3},
        },
        dist_init_required=False,
    )
    rs = np.random.RandomState(0)
    toks = rs.randint(0, 128, (8, 16)).astype(np.int32)
    batch = {"input_ids": toks, "labels": toks}
    engine.init_params(batch)
    # the loaded master IS the ingested fp32 tree (sharded over the new mesh)
    w = np.asarray(engine.get_master_params()["embed"]["tokens"])
    np.testing.assert_allclose(w, params["embed"]["tokens"], rtol=1e-6)
    losses = []
    for _ in range(3):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_pipeline_layout_rejected(tmp_path):
    path = tmp_path / "ref" / "step1"
    os.makedirs(path)
    torch.save({}, str(path / "layer_00-model_00-model_states.pt"))
    with open(tmp_path / "ref" / "latest", "w") as f:
        f.write("step1")
    with pytest.raises(NotImplementedError, match="pipeline"):
        merge_reference_model_states(str(tmp_path / "ref"), "megatron_gpt")


def test_bf16_prefixed_zero_shards(tmp_path):
    """bf16 runs name their ZeRO shards bf16_zero_pp_rank_* (engine
    _get_zero_ckpt_prefix); the fp32-reconstruction glob must find them."""
    sd = _megatron_sd(L=2, H=32, NH=4, V=128, I=64)
    root = str(tmp_path / "ref")
    os.makedirs(root)
    path = _write_reference_ckpt(root, sd)
    for f in os.listdir(path):
        if f.startswith("zero_pp_rank_"):
            os.rename(os.path.join(path, f), os.path.join(path, "bf16_" + f))
    fp32 = merge_reference_zero_fp32(root, "megatron_gpt")
    for name, w in sd.items():
        np.testing.assert_allclose(fp32[name], np.asarray(w, np.float32) + 7.0, rtol=1e-6)


def test_stage3_layout_explicit_error(tmp_path):
    """Stage-3 reference checkpoints (zero_pp_rank_*_model_states.pt) must
    raise a clear unsupported-layout message, not FileNotFoundError."""
    path = tmp_path / "ref" / "global_step3"
    path.mkdir(parents=True)
    torch.save({}, str(path / "zero_pp_rank_0_mp_rank_00_model_states.pt"))
    with open(str(tmp_path / "ref" / "latest"), "w") as f:
        f.write("global_step3")
    with pytest.raises(NotImplementedError, match="stage-3|zero_pp_rank"):
        merge_reference_model_states(str(tmp_path / "ref"), "megatron_gpt")
