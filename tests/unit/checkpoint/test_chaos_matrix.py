"""The FULL crash-restart matrix (``-m slow``): a REAL ``os._exit(137)`` —
no atexit, no flushing, the honest ``kill -9`` — injected at every named
fault point, for both engines, followed by an in-process restart with
``auto_resume=True`` / journal replay.

Assertions, per ISSUE 9's acceptance bar:

* training losses after resume are **bit-identical** to an uninterrupted
  run from the same seed;
* serving streams are **byte-identical** to an uninterrupted serve;
* no injection point can make ``latest``/``find_latest_valid`` resolve to
  a torn checkpoint.

Each kill runs in its own subprocess (the in-process fast subset lives in
``test_fault_tolerance.py`` / ``test_journal_recovery.py``); this matrix is
the expensive, maximum-fidelity sweep.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

pytestmark = pytest.mark.slow

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

_CHILD_PRELUDE = """
import os, sys, json
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
sys.path.insert(0, os.environ["DS_TEST_REPO"])
import numpy as np
import jax
import deepspeed_tpu as ds
from deepspeed_tpu.utils import chaos

POINT = os.environ["DS_TEST_POINT"]
HIT = int(os.environ["DS_TEST_HIT"])
ACTION = os.environ.get("DS_TEST_ACTION", "exit")
WORKDIR = os.environ["DS_TEST_DIR"]
chaos.install(chaos.ChaosSchedule([chaos.ChaosRule(POINT, hit=HIT, action=ACTION)]))
"""

_TRAIN_CHILD = _CHILD_PRELUDE + """
from tests.unit.simple_model import SimpleModel

def batch_for(step):
    rs = np.random.RandomState(1000 + step)
    return (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))

engine, *_ = ds.initialize(model=SimpleModel(), config={
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
    "bf16": {"enabled": True},
    "zero_optimization": {"stage": 1},
    "scheduler": {"type": "WarmupLR", "params": {
        "warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10}},
    "checkpoint": {"interval_steps": 1, "save_dir": WORKDIR,
                   "async_snapshot": os.environ.get("DS_TEST_ASYNC") == "1"},
})
engine.init_params(batch_for(0))
for _ in range(6):
    loss = engine(batch_for(engine.global_steps))
    engine.backward(loss)
    engine.step()
engine.wait_pending_checkpoint()
print("NOCRASH")  # the parent asserts the kill actually fired (rc 137)
"""

_SERVE_CHILD = _CHILD_PRELUDE + """
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig

mcfg = TransformerConfig(
    vocab_size=256, hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
    max_seq_len=96, norm="rmsnorm", position="rope", activation="swiglu",
    use_bias=False, tie_embeddings=False, flash_attention=False)
rs = np.random.RandomState(0)
prompts = [rs.randint(0, 256, (12,)).astype(np.int32) for _ in range(4)]
eng = ds.init_inference(
    TransformerLM(mcfg), dtype="bf16",
    paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8},
    journal={"enabled": True, "dir": WORKDIR})
eng.init_params(np.stack(prompts))
eng._ds_config = mcfg
eng._paged_server = eng._build_paged_server()
srv = eng._paged_server
try:
    # submit() syncs the journal too (admissions are durable at submit),
    # so the kill can land there as well as in the step loop
    uids = [srv.submit(p, max_new_tokens=16) for p in prompts]
    srv.run()
except BaseException:
    # a truncate-action ChaosKilled reaches here: die ABRUPTLY (os._exit,
    # no flushing) so the on-disk state is exactly what the kill left
    os._exit(137)
print("NOCRASH")
"""


def _run_child(code, env_over, timeout=420):
    env = dict(os.environ)
    env["DS_TEST_REPO"] = REPO
    env.update(env_over)
    proc = subprocess.run(
        [sys.executable, "-c", code], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=timeout,
    )
    return proc


def _batch(step):
    rs = np.random.RandomState(1000 + step)
    return (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))


def _fresh_train_engine():
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from tests.unit.simple_model import SimpleModel

    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "scheduler": {"type": "WarmupLR", "params": {
            "warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10}},
    })
    engine.init_params(_batch(0))
    return engine


def _train_losses(engine, n):
    import jax

    out = []
    for _ in range(n):
        loss = engine(_batch(engine.global_steps))
        engine.backward(loss)
        engine.step()
        out.append(float(jax.device_get(loss)))
    return out


class TestTrainingKillMatrix:
    @pytest.mark.parametrize("async_snapshot", [False, True])
    @pytest.mark.parametrize(
        "point,hit",
        [
            ("ckpt.mid_array_write", 2),
            ("ckpt.pre_commit", 2),
            ("ckpt.post_commit", 2),
        ],
    )
    def test_kill_then_auto_resume_bit_identical(
        self, tmp_path, eight_devices, point, hit, async_snapshot
    ):
        from deepspeed_tpu.runtime.checkpoint_engine.atomic import (
            find_latest_valid,
            is_complete_checkpoint,
        )

        proc = _run_child(_TRAIN_CHILD, {
            "DS_TEST_POINT": point, "DS_TEST_HIT": str(hit),
            "DS_TEST_DIR": str(tmp_path),
            "DS_TEST_ASYNC": "1" if async_snapshot else "0",
        })
        assert proc.returncode == 137, (
            f"kill did not fire (rc={proc.returncode}):\n{proc.stdout[-2000:]}"
            f"\n{proc.stderr[-2000:]}"
        )
        assert "NOCRASH" not in proc.stdout

        tag = find_latest_valid(str(tmp_path))
        assert tag is not None, "at least one committed checkpoint must survive"
        assert is_complete_checkpoint(os.path.join(tmp_path, tag))

        ref = _fresh_train_engine()
        ref_losses = _train_losses(ref, 6)

        resumed = _fresh_train_engine()
        path, _ = resumed.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path is not None
        start = resumed.global_steps
        assert 1 <= start <= 6
        tail = _train_losses(resumed, 6 - start)
        assert tail == ref_losses[start:], (
            f"resume from step {start} after kill at {point} diverged:"
            f"\n{tail}\nvs\n{ref_losses[start:]}"
        )


class TestServingKillMatrix:
    @pytest.mark.parametrize(
        "point,hit,action",
        [
            ("serve.mid_step", 2, "exit"),
            ("serve.mid_step", 5, "exit"),
            # journal.append hits 1-4 are the per-submit admission syncs;
            # 3 tears an admission record, 7 tears mid-stream emissions
            ("journal.append", 3, "truncate"),
            ("journal.append", 7, "truncate"),
        ],
    )
    def test_kill_then_replay_byte_identical(
        self, tmp_path, eight_devices, point, hit, action
    ):
        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod
        from deepspeed_tpu.models import TransformerLM
        from deepspeed_tpu.models.config import TransformerConfig

        proc = _run_child(_SERVE_CHILD, {
            "DS_TEST_POINT": point, "DS_TEST_HIT": str(hit),
            "DS_TEST_ACTION": action, "DS_TEST_DIR": str(tmp_path),
        })
        assert proc.returncode == 137, (
            f"kill did not fire (rc={proc.returncode}):\n{proc.stdout[-2000:]}"
            f"\n{proc.stderr[-2000:]}"
        )

        mcfg = TransformerConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, max_seq_len=96, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=False,
            flash_attention=False,
        )
        rs = np.random.RandomState(0)
        prompts = [rs.randint(0, 256, (12,)).astype(np.int32) for _ in range(4)]

        def build(journal):
            mesh_mod.reset_topology()
            kw = dict(dtype="bf16",
                      paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8})
            if journal:
                kw["journal"] = {"enabled": True, "dir": str(tmp_path)}
            eng = ds.init_inference(TransformerLM(mcfg), **kw)
            eng.init_params(np.stack(prompts))
            eng._ds_config = mcfg
            eng._paged_server = eng._build_paged_server()
            return eng

        ref = build(False).serve(prompts, max_new_tokens=16)
        srv = build(True)._paged_server
        srv.run()
        survived = 0
        for uid, want in enumerate(ref):
            got = srv.take_result(uid)
            if got is None:
                # a stream can be missing only when the crash predates its
                # durable admission — the torn submit record itself, or
                # submits that never ran because the process was already
                # dead; either way the client never got an ack for it
                assert action == "truncate", f"acked stream {uid} lost"
                continue
            survived += 1
            np.testing.assert_array_equal(got, want)
        if action == "exit":
            assert survived == len(ref)  # every acked stream resumes
        else:
            assert survived >= 1  # everything durably admitted resumes
        srv.pool.integrity_check()
