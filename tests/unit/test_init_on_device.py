"""OnDevice construction context (reference: tests/unit/utils/
test_init_on_device.py): placement hint for model building; 'meta' leaves
placement untouched (abstract init goes through jax.eval_shape)."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu as ds


def test_on_device_places_arrays():
    target = jax.devices()[1] if len(jax.devices()) > 1 else jax.devices()[0]
    with ds.OnDevice(dtype=jnp.bfloat16, device=str(target)):
        x = jnp.ones((4, 4))
    assert target in x.devices()


def test_on_device_platform_name():
    with ds.OnDevice(dtype=jnp.bfloat16, device="cpu"):
        x = jnp.ones((2,))
    assert next(iter(x.devices())).platform == "cpu"


def test_meta_device_is_inert():
    before = jnp.ones((2,)).devices()
    with ds.OnDevice(dtype=jnp.bfloat16, device="meta"):
        # meta builds use eval_shape: no memory, no placement change
        shape = jax.eval_shape(lambda: jnp.zeros((8, 8), jnp.bfloat16))
        x = jnp.ones((2,))
    assert shape.shape == (8, 8) and shape.dtype == jnp.bfloat16
    assert x.devices() == before


def test_disabled_context_is_inert():
    with ds.OnDevice(dtype=None, device="cpu", enabled=False) as ctx:
        assert ctx._ctx is None
