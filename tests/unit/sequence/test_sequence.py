"""Sequence-parallelism tests.

The reference has NO unit test for deepspeed/sequence (SURVEY §4); these
cover the gap: all-to-all roundtrip, Ulysses == local attention, ring ==
full attention (values and grads), and end-to-end TransformerLM parity.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

# `from jax import shard_map` only exists on jax >= 0.5; the repo's compat
# shim (utils/jax_compat.py) presents the modern signature on every
# supported jax — importing it here is what lets this module COLLECT on
# 0.4.x instead of erroring out of tier-1
from deepspeed_tpu.utils.jax_compat import shard_map

from deepspeed_tpu.parallel.mesh import initialize_topology
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.sequence import DistributedAttention, ring_attention, seq_all_to_all


def _ref_attention(q, k, v, causal=True):
    T = q.shape[1]
    scale = 1.0 / np.sqrt(q.shape[-1])
    s = jnp.einsum("btnd,bsnd->bnts", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
    return jnp.einsum("bnts,bsnd->btnd", p, v)


def _qkv(key, B=2, T=16, N=4, D=8, dtype=jnp.float32):
    ks = jax.random.split(key, 3)
    return tuple(jax.random.normal(ks[i], (B, T, N, D), dtype) for i in range(3))


def test_seq_all_to_all_roundtrip(eight_devices):
    topo = initialize_topology(MeshConfig(sequence=4))
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 4, 4))
    spec = P(None, "sequence", None, None)

    @jax.jit
    def roundtrip(x):
        def body(xl):
            y = seq_all_to_all(xl, scatter_idx=2, gather_idx=1)
            assert y.shape == (2, 8, 1, 4)  # full seq, head shard
            return seq_all_to_all(y, scatter_idx=1, gather_idx=2)

        return shard_map(body, mesh=topo.mesh, in_specs=(spec,), out_specs=spec)(x)

    np.testing.assert_allclose(roundtrip(x), x, rtol=1e-6)


@pytest.mark.parametrize("seq", [2, 4])
def test_ulysses_matches_local(eight_devices, seq):
    topo = initialize_topology(MeshConfig(sequence=seq))
    q, k, v = _qkv(jax.random.PRNGKey(1))
    expect = _ref_attention(q, k, v)

    dist_attn = DistributedAttention(lambda q, k, v: _ref_attention(q, k, v), topo.mesh)
    shard = NamedSharding(topo.mesh, P(None, "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
    got = jax.jit(dist_attn)(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_full(eight_devices, causal):
    topo = initialize_topology(MeshConfig(sequence=4))
    q, k, v = _qkv(jax.random.PRNGKey(2))
    expect = _ref_attention(q, k, v, causal=causal)
    shard = NamedSharding(topo.mesh, P(None, "sequence", None, None))
    qs, ks, vs = (jax.device_put(x, shard) for x in (q, k, v))
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=topo.mesh, causal=causal))(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5)


def test_ring_grads_match(eight_devices):
    topo = initialize_topology(MeshConfig(sequence=4))
    q, k, v = _qkv(jax.random.PRNGKey(3), T=8)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(_ref_attention(q, k, v)))

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(ring_attention(q, k, v, mesh=topo.mesh, causal=True)))

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("mode", ["ulysses", "ring"])
def test_transformer_sp_parity(eight_devices, mode):
    """Same tokens, same seed: SP loss == non-SP loss."""
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.models.transformer import TransformerLM

    def run(sp):
        import deepspeed_tpu.parallel.mesh as mesh_mod

        mesh_mod.reset_topology()
        initialize_topology(MeshConfig(sequence=4 if sp else 1))
        cfg = TransformerConfig(
            vocab_size=64,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            max_seq_len=32,
            dtype="float32",
            flash_attention=False,
            position="rope",
            norm="rmsnorm",
            activation="swiglu",
            use_bias=False,
            sequence_parallel=sp,
            sequence_parallel_mode=mode,
            attn_dropout=0.0,
            hidden_dropout=0.0,
        )
        model = TransformerLM(cfg)
        rng = jax.random.PRNGKey(0)
        tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 16), 0, 64)
        batch = {"input_ids": tokens, "labels": tokens}
        params = model.init(rng, batch)
        return jax.jit(lambda p: model.apply(p, batch, train=False))(params)

    base = run(False)
    sp = run(True)
    np.testing.assert_allclose(np.asarray(sp), np.asarray(base), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("mode", ["ulysses", "ring"])
def test_engine_sp_training(eight_devices, mode):
    """End-to-end: ZeRO over seq×data group (ref engine.py:1111) trains."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.models.transformer import TransformerLM

    mesh_mod.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2},
        "mesh": {"sequence": 2, "data": 4},
    }
    model = TransformerLM(
        TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=4,
            max_seq_len=32, dtype="float32", flash_attention=False,
            position="rope", norm="rmsnorm", use_bias=False,
            sequence_parallel=True, sequence_parallel_mode=mode,
        )
    )
    engine, *_ = ds.initialize(model=model, config=cfg)
    tokens = np.random.randint(0, 64, (8, 16))
    batch = {"input_ids": tokens, "labels": tokens}
    losses = []
    for _ in range(6):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0], f"loss did not drop: {losses}"


def test_ring_gqa(eight_devices):
    """Ring with grouped kv heads (kv stays at NKV through the ppermute)."""
    topo = initialize_topology(MeshConfig(sequence=4))
    key = jax.random.PRNGKey(9)
    ks = jax.random.split(key, 3)
    B, T, NH, NKV, D = 2, 16, 8, 2, 8
    q = jax.random.normal(ks[0], (B, T, NH, D))
    k = jax.random.normal(ks[1], (B, T, NKV, D))
    v = jax.random.normal(ks[2], (B, T, NKV, D))
    k_full = jnp.repeat(k, NH // NKV, axis=2)
    v_full = jnp.repeat(v, NH // NKV, axis=2)
    expect = _ref_attention(q, k_full, v_full)
    got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=topo.mesh, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), rtol=2e-5, atol=2e-5)


def test_bad_sp_mode_raises(eight_devices):
    from deepspeed_tpu.models.config import TransformerConfig

    with pytest.raises(ValueError, match="sequence_parallel_mode"):
        TransformerConfig(sequence_parallel_mode="Ring")
