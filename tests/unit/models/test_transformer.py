"""Model family tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, gpt2_config, llama_config
from deepspeed_tpu.models.transformer import cross_entropy_loss


def _batch(vocab, b=4, t=32, seed=0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, vocab, (b, t + 1)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


@pytest.mark.parametrize(
    "cfg",
    [
        llama_config("tiny", num_layers=2),
        gpt2_config("125m", hidden_size=64, num_layers=2, num_heads=4, vocab_size=256, max_seq_len=64),
    ],
    ids=["llama", "gpt2"],
)
def test_initial_loss_near_uniform(cfg):
    model = TransformerLM(cfg)
    batch = _batch(cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)
    loss = model.apply(params, batch, train=False)
    expected = np.log(cfg.vocab_size)
    assert abs(float(loss) - expected) < 1.0


def test_scan_matches_unrolled():
    cfg_scan = llama_config("tiny", num_layers=3, scan_layers=True, remat=False)
    cfg_loop = llama_config("tiny", num_layers=3, scan_layers=False, remat=False)
    m1, m2 = TransformerLM(cfg_scan), TransformerLM(cfg_loop)
    batch = _batch(cfg_scan.vocab_size)
    params = m1.init(jax.random.PRNGKey(0), batch)
    l1 = m1.apply(params, batch, train=False)
    l2 = m2.apply(params, batch, train=False)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-3)  # bf16 activations


def test_remat_matches_no_remat():
    cfg_a = llama_config("tiny", num_layers=2, remat=True, dtype="float32")
    cfg_b = llama_config("tiny", num_layers=2, remat=False, dtype="float32")
    batch = _batch(cfg_a.vocab_size)
    m_a, m_b = TransformerLM(cfg_a), TransformerLM(cfg_b)
    params = m_a.init(jax.random.PRNGKey(0), batch)

    ga = jax.grad(lambda p: m_a.apply(p, batch, train=False))(params)
    gb = jax.grad(lambda p: m_b.apply(p, batch, train=False))(params)
    la, lb = jax.tree_util.tree_leaves(ga), jax.tree_util.tree_leaves(gb)
    for a, b in zip(la, lb):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_causality():
    """Future tokens must not affect past logits."""
    cfg = llama_config("tiny", num_layers=2, remat=False)
    model = TransformerLM(cfg)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg.vocab_size, (1, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks)
    logits1 = model.apply(params, toks, train=False)
    toks2 = toks.copy()
    toks2[0, -1] = (toks2[0, -1] + 1) % cfg.vocab_size
    logits2 = model.apply(params, toks2, train=False)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :-1]), np.asarray(logits2[0, :-1]), rtol=2e-2, atol=2e-3
    )
    assert not np.allclose(np.asarray(logits1[0, -1]), np.asarray(logits2[0, -1]))


def test_gqa_shapes():
    cfg = llama_config("tiny", num_layers=2, num_kv_heads=2, remat=False)
    model = TransformerLM(cfg)
    batch = _batch(cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)
    assert params["layers"]["wk"].shape[-1] == 2 * cfg.head_dim
    loss = model.apply(params, batch, train=False)
    assert np.isfinite(float(loss))


def test_cross_entropy_ignore_index():
    logits = jnp.zeros((2, 3, 5))
    labels = jnp.array([[1, 2, -100], [-100, -100, 0]])
    loss = cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(5), rtol=1e-6)


def test_train_end_to_end_zero3(eight_devices):
    cfg = llama_config("tiny", num_layers=2)
    engine, *_ = ds.initialize(
        model=TransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 3},
            "gradient_clipping": 1.0,
        },
    )
    batch = _batch(cfg.vocab_size, b=8, t=32)
    losses = []
    for _ in range(5):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert losses[-1] < losses[0]


def test_tp_sharding_rules_applied(eight_devices):
    cfg = llama_config("tiny", num_layers=2)
    engine, *_ = ds.initialize(
        model=TransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adam", "params": {"lr": 3e-4}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 0},
            "mesh": {"model": 2},
        },
    )
    batch = _batch(cfg.vocab_size, b=8, t=32)
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    assert "model" in str(engine.get_params()["layers"]["wq"].sharding.spec)
    assert np.isfinite(float(jax.device_get(loss)))


def test_qwen2_preset_trains(eight_devices):
    """Qwen2 family: llama body + biased q/k/v + GQA — params carry the
    qkv biases and a short training run learns."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.models import qwen2_config

    mesh_mod.reset_topology()
    cfg = qwen2_config("tiny", num_layers=2, remat=False)
    assert cfg.qkv_bias and not cfg.use_bias
    assert cfg.rope_theta == 1e6
    model = TransformerLM(cfg)
    engine, *_ = ds.initialize(model=model, config={
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    })
    batch = _batch(cfg.vocab_size, b=8, t=32)
    losses = []
    for _ in range(5):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0], losses
    layers = engine.get_params()["layers"]
    assert "bq" in layers and "bo" not in layers  # biased qkv, bias-free output
