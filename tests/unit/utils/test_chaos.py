"""Fault-injection harness units: deterministic schedules, nth-hit firing,
file-damage actions, and clean disarm — the foundation the crash-restart
matrix (test_fault_tolerance / test_journal_recovery) stands on."""

import os

import pytest

from deepspeed_tpu.utils import chaos


def teardown_function(_fn):
    chaos.uninstall()  # no test may leak an armed schedule


def test_disarmed_points_are_free():
    chaos.uninstall()
    chaos.point("ckpt.pre_commit")  # no schedule: must be a no-op
    assert chaos.active() is None


def test_fires_on_nth_hit_only():
    sched = chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("p", hit=3)]))
    chaos.point("p")
    chaos.point("p")
    with pytest.raises(chaos.ChaosKilled):
        chaos.point("p")
    # a fired rule never re-fires
    chaos.point("p")
    assert sched.fired_log == ["p#3:raise"]
    assert sched.counts["p"] == 4


def test_points_are_independent_counters():
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("a", hit=1)]))
    chaos.point("b")
    chaos.point("b")
    with pytest.raises(chaos.ChaosKilled):
        chaos.point("a")


def test_chaoskilled_is_not_an_exception():
    """The kill must not be swallowable by ordinary recovery code —
    ``except Exception`` around the injection point must not survive it."""
    assert not issubclass(chaos.ChaosKilled, Exception)
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("p")]))
    with pytest.raises(chaos.ChaosKilled):
        try:
            chaos.point("p")
        except Exception:  # the pattern a kill must punch through
            pytest.fail("ChaosKilled was swallowed by `except Exception`")


def test_truncate_action_tears_the_file(tmp_path):
    path = str(tmp_path / "seg.open")
    with open(path, "wb") as f:
        f.write(b"x" * 100)
    chaos.install(
        chaos.ChaosSchedule([chaos.ChaosRule("j", action="truncate", nbytes=30)])
    )
    with pytest.raises(chaos.ChaosKilled):
        chaos.point("j", path=path)
    assert os.path.getsize(path) == 70


def test_corrupt_action_is_deterministic(tmp_path):
    p1, p2 = str(tmp_path / "a"), str(tmp_path / "b")
    for p in (p1, p2):
        with open(p, "wb") as f:
            f.write(bytes(range(64)))
        chaos.install(
            chaos.ChaosSchedule([chaos.ChaosRule("c", action="corrupt", nbytes=16)])
        )
        with pytest.raises(chaos.ChaosKilled):
            chaos.point("c", path=p)
        chaos.uninstall()
    with open(p1, "rb") as f1, open(p2, "rb") as f2:
        b1, b2 = f1.read(), f2.read()
    assert b1 == b2  # position-keyed garbage, not random
    assert b1[:48] == bytes(range(48)) and b1[48:] != bytes(range(48, 64))


def test_truncate_on_directory_path_still_kills(tmp_path):
    """A truncate/corrupt rule landing on a directory-backed point (the
    checkpoint staging dirs) degrades to the plain kill — never a
    swallowable IsADirectoryError."""
    chaos.install(
        chaos.ChaosSchedule([chaos.ChaosRule("p", action="truncate")])
    )
    with pytest.raises(chaos.ChaosKilled):
        chaos.point("p", path=str(tmp_path))
    assert os.path.isdir(tmp_path)


def test_seeded_schedule_reproducible():
    s1 = chaos.seeded_schedule(7, n_faults=3)
    s2 = chaos.seeded_schedule(7, n_faults=3)
    assert [(r.point, r.hit, r.action) for r in s1.rules] == [
        (r.point, r.hit, r.action) for r in s2.rules
    ]
    s3 = chaos.seeded_schedule(8, n_faults=3)
    assert [(r.point, r.hit) for r in s1.rules] != [(r.point, r.hit) for r in s3.rules]
    assert all(r.point in chaos.POINTS for r in s1.rules)


def test_bad_rule_rejected():
    with pytest.raises(ValueError):
        chaos.ChaosRule("p", action="nuke")
    with pytest.raises(ValueError):
        chaos.ChaosRule("p", hit=0)
