"""Groups accessor API (reference: tests/unit/utils/test_groups.py;
deepspeed/utils/groups.py:51-528): mesh-axis views carrying the comm
facade's group duck-type."""

import pytest

import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.comm import comm as dist
from deepspeed_tpu.parallel.mesh import MeshConfig
from deepspeed_tpu.utils import groups


@pytest.fixture(autouse=True)
def _fresh_topology():
    mesh_mod.reset_topology()
    yield
    mesh_mod.reset_topology()


def test_initialize_builds_expert_axis(eight_devices):
    groups.initialize(ep_size=4)
    assert groups.get_expert_parallel_world_size() == 4
    # EP is carved INSIDE data parallelism: dense-param DP stays full
    assert groups.get_data_parallel_world_size() == 8
    assert groups.get_expert_data_parallel_world_size() == 2
    assert groups._get_max_expert_size_name() == "ep_size_4"
    # the groups surface agrees with the Topology accessors
    topo = mesh_mod.get_topology()
    assert groups.get_data_parallel_world_size() == topo.get_data_parallel_world_size()
    assert (
        groups.get_expert_data_parallel_world_size()
        == topo.get_expert_data_parallel_world_size()
    )


def test_initialize_preserves_other_axes(eight_devices):
    mesh_mod.initialize_topology(MeshConfig(model=2, data=4))
    groups.initialize(ep_size=2)
    topo = mesh_mod.get_topology()
    assert topo.axis_size("model") == 2  # TP survives
    assert topo.axis_size("expert") == 2
    assert topo.axis_size("data") == 2


def test_initialize_is_idempotent_and_validates(eight_devices):
    groups.initialize(ep_size=2)
    groups.initialize(ep_size=2)  # same size: fine
    with pytest.raises(ValueError, match="already sized"):
        groups.initialize(ep_size=4)


def test_indivisible_ep_size_raises(eight_devices):
    with pytest.raises(ValueError, match="does not divide"):
        groups.initialize(ep_size=3)


def test_group_handles_carry_comm_ducktype(eight_devices):
    mesh_mod.initialize_topology(MeshConfig(data=2, model=2, sequence=2))
    dp = groups._get_data_parallel_group()
    assert dp.size == 2 and dp.ranks == [0, 1] and len(dp) == 2
    assert groups._get_model_parallel_group().size == 2
    assert groups._get_sequence_parallel_group().size == 2
    assert groups._get_sequence_data_parallel_group().size == 4
    # the comm facade probes .size on group objects
    assert dist.get_world_size(group=dp) == 2


def test_expert_data_group_is_the_replication_set(eight_devices):
    mesh_mod.initialize_topology(MeshConfig(data=4, expert=2))
    # experts shard over 'expert' and replicate over the inner data axis
    assert groups._get_expert_parallel_group().size == 2
    assert groups._get_expert_data_parallel_group().size == 4
    assert groups.get_data_parallel_world_size() == 8  # data x expert


def test_engine_adopts_groups_topology(eight_devices):
    """A mesh established by groups.initialize must survive engine
    construction when the engine config names no mesh (the reference adopts
    pre-created process groups the same way)."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import SimpleModel

    groups.initialize(ep_size=4)
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
        },
    )
    assert engine.topology.axis_size("expert") == 4
    assert engine.topology.axis_size("data") == 2


def test_engine_config_mesh_overrides_groups(eight_devices):
    """An explicit mesh in the engine config wins over a live topology."""
    import deepspeed_tpu as ds
    from tests.unit.simple_model import SimpleModel

    groups.initialize(ep_size=4)
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "mesh": {"data": 8},
        },
    )
    assert engine.topology.axis_size("expert") == 1
    assert engine.topology.axis_size("data") == 8


def test_ranks_are_rank0_views(eight_devices):
    assert groups.get_model_parallel_rank() == 0
    assert groups.get_expert_parallel_rank() == 0
    assert groups.get_data_parallel_rank() == 0
