"""Top-level API parity (reference ``deepspeed/__init__.py`` exports):
a user of the reference must find every documented entry point."""

import deepspeed_tpu as ds


def test_reference_toplevel_exports_present():
    for name in [
        "initialize",
        "init_inference",
        "init_distributed",
        "add_config_arguments",
        "default_inference_config",
        "zero",
        "comm",
        "ops",
        "PipelineModule",
        "DeepSpeedTransformerLayer",
        "DeepSpeedTransformerConfig",
        "OnDevice",
        "HAS_TRITON",
        "DSModule",
    ]:
        assert hasattr(ds, name), f"missing top-level export: {name}"


def test_zero_namespace_exports():
    for name in [
        "Init",
        "GatheredParameters",
        "TiledLinear",
        "TiledLinearReturnBias",
        "ZeroStageEnum",
        "estimate_zero_memory",
    ]:
        assert hasattr(ds.zero, name), f"missing zero export: {name}"


def test_registered_model_families():
    from deepspeed_tpu.models import (  # noqa: F401
        MoETransformerLM,
        TransformerLM,
        bert_config,
        gpt2_config,
        llama_config,
        mixtral_config,
        moe_llama_config,
    )

    from deepspeed_tpu.module_inject.containers import replace_policies

    assert len(replace_policies) >= 12


def test_comm_facade_surface():
    """Every torch.distributed-shaped entry point of the reference's
    deepspeed.comm facade (comm/comm.py) resolves here."""
    from deepspeed_tpu import comm as dist

    for name in [
        "init_distributed", "is_initialized", "is_available",
        "destroy_process_group", "get_rank", "get_world_size",
        "get_local_rank", "get_global_rank", "get_world_group",
        "get_all_ranks_from_group", "new_group", "barrier",
        "monitored_barrier", "all_reduce", "all_reduce_coalesced",
        "reduce", "all_gather", "all_gather_object", "all_gather_coalesced",
        "all_gather_into_tensor", "allgather_fn", "gather", "broadcast",
        "broadcast_object_list", "reduce_scatter", "reduce_scatter_tensor",
        "reduce_scatter_fn", "all_to_all", "all_to_all_single",
        "inference_all_reduce", "send", "recv", "isend", "irecv",
        "has_all_gather_into_tensor", "has_reduce_scatter_tensor",
        "has_coalescing_manager", "mpi_discovery", "in_aml", "in_aws_sm",
        "in_dlts", "patch_aml_env_for_torch_nccl_backend",
        "patch_aws_sm_env_for_torch_nccl_backend", "log_summary",
        "configure", "timed_op", "ReduceOp",
    ]:
        assert hasattr(dist, name), f"missing comm export: {name}"


def test_checkpoint_namespace_surface():
    from deepspeed_tpu import checkpoint as ckpt

    for name in [
        "DeepSpeedCheckpoint", "convert_to_universal",
        "load_hp_checkpoint_state", "universal_param_names",
        "export_reference_checkpoint", "ingest_reference_checkpoint",
        "ingest_universal_checkpoint", "read_universal_dir",
        "merge_reference_model_states", "merge_reference_zero_fp32",
        "ReshapeMeg2D", "merge_tp_slices", "reshape_tp_degree",
        "split_tp_slices",
    ]:
        assert hasattr(ckpt, name), f"missing checkpoint export: {name}"


def test_generate_signature_covers_hf_controls():
    """InferenceEngine.generate mirrors the HF-generate controls the
    reference dispatches to (sampling + beams)."""
    import inspect

    from deepspeed_tpu.inference.engine import InferenceEngine

    params = set(inspect.signature(InferenceEngine.generate).parameters)
    for name in [
        "max_new_tokens", "eos_token_id", "pad_token_id", "temperature",
        "top_k", "top_p", "num_beams", "length_penalty",
    ]:
        assert name in params, f"generate() missing control: {name}"
