"""Top-level API parity (reference ``deepspeed/__init__.py`` exports):
a user of the reference must find every documented entry point."""

import deepspeed_tpu as ds


def test_reference_toplevel_exports_present():
    for name in [
        "initialize",
        "init_inference",
        "init_distributed",
        "add_config_arguments",
        "default_inference_config",
        "zero",
        "comm",
        "ops",
        "PipelineModule",
        "DeepSpeedTransformerLayer",
        "DeepSpeedTransformerConfig",
        "OnDevice",
        "HAS_TRITON",
        "DSModule",
    ]:
        assert hasattr(ds, name), f"missing top-level export: {name}"


def test_zero_namespace_exports():
    for name in [
        "Init",
        "GatheredParameters",
        "TiledLinear",
        "TiledLinearReturnBias",
        "ZeroStageEnum",
        "estimate_zero_memory",
    ]:
        assert hasattr(ds.zero, name), f"missing zero export: {name}"


def test_registered_model_families():
    from deepspeed_tpu.models import (  # noqa: F401
        MoETransformerLM,
        TransformerLM,
        bert_config,
        gpt2_config,
        llama_config,
        mixtral_config,
        moe_llama_config,
    )

    from deepspeed_tpu.module_inject.containers import replace_policies

    assert len(replace_policies) >= 12
