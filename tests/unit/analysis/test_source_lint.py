"""AST lint unit tests: each rule fires on its minimal bad snippet, stays
quiet on the sanctioned idiom, honors pragmas — and the library itself
lints clean (the CI gate ``tools/lint.sh`` enforces: error findings under
``deepspeed_tpu/`` fail, ``tests/`` findings are warn-only)."""

from __future__ import annotations

import os
import textwrap

from deepspeed_tpu.analysis.source_lint import (
    lint_paths,
    lint_source,
    resolve_severity,
)

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def _rules(src):
    return [f.rule for f in lint_source(textwrap.dedent(src))]


def test_r001_repeat_on_cache_flagged():
    assert "DS-R001" in _rules("""
        import jax.numpy as jnp
        def f(k_cache, G):
            return jnp.repeat(k_cache, G, axis=2)
    """)


def test_r001_method_form_flagged():
    """k_cache.repeat(G) is the same hazard as jnp.repeat(k_cache, G):
    the rule must scan the method receiver, not just args[0]."""
    assert "DS-R001" in _rules("""
        def f(k_cache, G):
            return k_cache.repeat(G, axis=2)
    """)


def test_r001_ignores_non_cache_repeat():
    assert "DS-R001" not in _rules("""
        import jax.numpy as jnp
        def f(logits, G):
            return jnp.repeat(logits, G, axis=0)
    """)


def test_r001_pragma_suppresses():
    assert "DS-R001" not in _rules("""
        import jax.numpy as jnp
        def f(k_cache, G):
            return jnp.repeat(k_cache, G, axis=2)  # lint: allow(DS-R001)
    """)


def test_r002_item_inside_jit():
    assert "DS-R002" in _rules("""
        import jax
        def step(params, batch):
            lr = params["lr"].item()
            return params
        step_fn = jax.jit(step)
    """)


def test_r002_float_on_traced_arg():
    assert "DS-R002" in _rules("""
        import jax
        @jax.jit
        def step(loss, x):
            return x * float(loss)
    """)


def test_r002_float_on_shape_ok():
    assert "DS-R002" not in _rules("""
        import jax
        @jax.jit
        def step(x):
            return x * float(x.shape[0])
    """)


def test_r002_nested_closure_inside_instrument():
    """Functions jitted via telemetry.instrument get the same scrutiny,
    including their nested closures."""
    assert "DS-R002" in _rules("""
        def build(telemetry):
            def fused(params, batch):
                def scaled(p):
                    return float(batch) * 2
                return scaled(params)
            return telemetry.instrument("fused", fused)
    """)


def test_r002_not_flagged_outside_jit():
    assert "DS-R002" not in _rules("""
        def host_logging(loss):
            return float(loss)
    """)


def test_r003_shape_branch_warns():
    findings = lint_source(textwrap.dedent("""
        import jax
        @jax.jit
        def step(x):
            if x.shape[0] > 4:
                return x * 2
            return x
    """))
    assert any(f.rule == "DS-R003" for f in findings)
    f = next(f for f in findings if f.rule == "DS-R003")
    assert resolve_severity(f) == "warn"  # warn-only rule, any path


def test_r004_missing_donation_on_buffer_args():
    findings = lint_source(textwrap.dedent("""
        import jax
        def step(master, opt_state, grad_acc):
            return master, opt_state, grad_acc
        jitted = jax.jit(step)
        donated = jax.jit(step, donate_argnums=(0, 1, 2))
    """))
    r004 = [f for f in findings if f.rule == "DS-R004"]
    assert len(r004) == 1  # only the undonated call site


def test_severity_tests_path_is_warn_only():
    f = lint_source("import jax.numpy as jnp\nx = jnp.repeat(k_cache, 2)\n", path="tests/unit/foo.py")[0]
    assert f.rule == "DS-R001"
    assert resolve_severity(f) == "warn"
    f2 = lint_source("import jax.numpy as jnp\nx = jnp.repeat(k_cache, 2)\n", path="deepspeed_tpu/foo.py")[0]
    assert resolve_severity(f2) == "error"


def test_library_lints_clean():
    """The gate itself: zero error-severity findings in deepspeed_tpu/
    (deliberate sites carry pragmas) — what tools/lint.sh enforces per
    commit."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu")])
    errors = [
        f.render()
        for f in findings
        if resolve_severity(f) == "error"
    ]
    assert not errors, "\n".join(errors)
