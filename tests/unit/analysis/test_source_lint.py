"""AST lint unit tests: each rule fires on its minimal bad snippet, stays
quiet on the sanctioned idiom, honors pragmas — and the library itself
lints clean (the CI gate ``tools/lint.sh`` enforces: error findings under
``deepspeed_tpu/`` fail, ``tests/`` findings are warn-only)."""

from __future__ import annotations

import os
import textwrap

from deepspeed_tpu.analysis.source_lint import (
    lint_paths,
    lint_source,
    resolve_severity,
)

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)


def _rules(src):
    return [f.rule for f in lint_source(textwrap.dedent(src))]


def test_r001_repeat_on_cache_flagged():
    assert "DS-R001" in _rules("""
        import jax.numpy as jnp
        def f(k_cache, G):
            return jnp.repeat(k_cache, G, axis=2)
    """)


def test_r001_method_form_flagged():
    """k_cache.repeat(G) is the same hazard as jnp.repeat(k_cache, G):
    the rule must scan the method receiver, not just args[0]."""
    assert "DS-R001" in _rules("""
        def f(k_cache, G):
            return k_cache.repeat(G, axis=2)
    """)


def test_r001_ignores_non_cache_repeat():
    assert "DS-R001" not in _rules("""
        import jax.numpy as jnp
        def f(logits, G):
            return jnp.repeat(logits, G, axis=0)
    """)


def test_r001_pragma_suppresses():
    assert "DS-R001" not in _rules("""
        import jax.numpy as jnp
        def f(k_cache, G):
            return jnp.repeat(k_cache, G, axis=2)  # lint: allow(DS-R001)
    """)


def test_r002_item_inside_jit():
    assert "DS-R002" in _rules("""
        import jax
        def step(params, batch):
            lr = params["lr"].item()
            return params
        step_fn = jax.jit(step)
    """)


def test_r002_float_on_traced_arg():
    assert "DS-R002" in _rules("""
        import jax
        @jax.jit
        def step(loss, x):
            return x * float(loss)
    """)


def test_r002_float_on_shape_ok():
    assert "DS-R002" not in _rules("""
        import jax
        @jax.jit
        def step(x):
            return x * float(x.shape[0])
    """)


def test_r002_nested_closure_inside_instrument():
    """Functions jitted via telemetry.instrument get the same scrutiny,
    including their nested closures."""
    assert "DS-R002" in _rules("""
        def build(telemetry):
            def fused(params, batch):
                def scaled(p):
                    return float(batch) * 2
                return scaled(params)
            return telemetry.instrument("fused", fused)
    """)


def test_r002_not_flagged_outside_jit():
    assert "DS-R002" not in _rules("""
        def host_logging(loss):
            return float(loss)
    """)


def test_r003_shape_branch_warns():
    findings = lint_source(textwrap.dedent("""
        import jax
        @jax.jit
        def step(x):
            if x.shape[0] > 4:
                return x * 2
            return x
    """))
    assert any(f.rule == "DS-R003" for f in findings)
    f = next(f for f in findings if f.rule == "DS-R003")
    assert resolve_severity(f) == "warn"  # warn-only rule, any path


def test_r004_missing_donation_on_buffer_args():
    findings = lint_source(textwrap.dedent("""
        import jax
        def step(master, opt_state, grad_acc):
            return master, opt_state, grad_acc
        jitted = jax.jit(step)
        donated = jax.jit(step, donate_argnums=(0, 1, 2))
    """))
    r004 = [f for f in findings if f.rule == "DS-R004"]
    assert len(r004) == 1  # only the undonated call site


def test_r005_host_transfers_in_serving_loop_flagged():
    """device_get / .item() / np.asarray-on-a-device-value inside a
    *Server step method are each one synchronous tunnel RTT per round."""
    rules = _rules("""
        import numpy as np, jax
        class PagedServer:
            def _decode_step(self):
                out = np.asarray(self.pending_tokens)
                host = jax.device_get(self.lengths)
                n = self.count.item()
    """)
    assert rules.count("DS-R005") == 3


def test_r005_scoped_to_hot_loop_only():
    """Intake methods, non-scheduler classes, and literal-built arrays are
    host-side work, not device fetches — never flagged."""
    assert "DS-R005" not in _rules("""
        import numpy as np
        class PagedServer:
            def submit(self, prompt):
                return np.asarray(prompt)  # intake, not the step loop
            def _prefill_step(self):
                starts = np.asarray([0, 1], np.int32)  # literal: host array
        class PagePool:
            def _decode_step(self):
                return np.asarray(self.table)  # not a Server/Scheduler
        class CurriculumScheduler:
            def step(self, global_steps):
                # host-only training-side scheduler: no serving round
                # methods anywhere in the class, so step() is out of scope
                return np.asarray(self.schedule[global_steps])
    """)


def test_r005_pragma_suppresses_and_is_error_severity():
    findings = lint_source(textwrap.dedent("""
        import numpy as np
        class TokenScheduler:
            def _verify_round(self):
                a = np.asarray(self.out)
                b = np.asarray(self.out)  # lint: allow(DS-R005)
    """), path="deepspeed_tpu/foo.py")
    r005 = [f for f in findings if f.rule == "DS-R005"]
    assert len(r005) == 1  # the pragma'd line is suppressed
    assert resolve_severity(r005[0]) == "error"


def test_r005_warn_only_under_tests_prefix():
    f = lint_source(
        "import jax\n"
        "class FooServer:\n"
        "    def _decode_step(self):\n"
        "        return jax.device_get(self.x)\n",
        path="tests/unit/inference/fake.py",
    )[0]
    assert f.rule == "DS-R005"
    assert resolve_severity(f) == "warn"


def test_r006_blocking_gather_in_scan_body_flagged():
    """A hand-rolled param all-gather inside a lax.scan body is the gather
    the overlap pipeline (zero.prefetch_layers) should own."""
    assert "DS-R006" in _rules("""
        import jax
        def body(carry, per_layer):
            gathered = jax.lax.all_gather(per_layer, "data")
            return carry, gathered
        def stack(x, layers):
            return jax.lax.scan(body, x, layers)
    """)


def test_r006_psum_on_weights_flagged_and_activations_ok():
    src_w = """
        import jax
        def body(c, w_layer):
            full = jax.lax.psum(w_layer, "data")
            return c, full
        def run(x, ws):
            return jax.lax.scan(body, x, ws)
    """
    assert "DS-R006" in _rules(src_w)
    # activation collectives (sequence-parallel reductions on x / hidden)
    # are not the pipeline's gathers — out of scope
    assert "DS-R006" not in _rules("""
        import jax
        def body(c, x_chunk):
            h = jax.lax.psum(x_chunk, "sequence")
            return c, h
        def run(x, xs):
            return jax.lax.scan(body, x, xs)
    """)


def test_r006_outside_scan_body_not_flagged():
    assert "DS-R006" not in _rules("""
        import jax
        def gather(per_layer):
            return jax.lax.all_gather(per_layer, "data")
    """)


def test_r006_pragma_suppresses():
    assert "DS-R006" not in _rules("""
        import jax
        def body(carry, per_layer):
            g = jax.lax.all_gather(per_layer, "data")  # lint: allow(DS-R006)
            return carry, g
        def stack(x, layers):
            return jax.lax.scan(body, x, layers)
    """)


def test_r007_pool_internal_writes_flagged():
    """Direct mutation of PagePool state outside the pool — table writes,
    free-list surgery, refcount pokes, index edits, cache rebinds — each
    bypasses the CoW/refcount write barrier."""
    rules = _rules("""
        import numpy as np
        class Scheduler:
            def step(self, pool, slot, page):
                pool.page_table[slot, 0] = page
                pool.seq_lens[slot] = 4
                pool._free.append(page)
                pool._refcount[page] += 1
                pool._hash_index.clear()
                self.pool.cache = None
    """)
    assert rules.count("DS-R007") == 6


def test_r007_quiet_inside_pool_and_on_reads():
    """The pool's own methods are the sanctioned writers; reads and
    non-pool receivers with generic attr names stay out of scope."""
    assert "DS-R007" not in _rules("""
        import numpy as np
        class PagePool:
            def free_slot(self, slot):
                self.page_table[slot, :] = -1
                self.seq_lens[slot] = 0
                self._free.append(3)
                self._refcount[3] -= 1
        class SubPool(PagePool):
            def reset(self):
                self._hash_index.clear()
        def reader(pool, slot):
            return pool.page_table[slot], pool.seq_lens[slot]
        class Engine:
            def warm(self):
                self.cache = {}         # generic attr, non-pool receiver
                self._free = [1, 2]     # ditto
    """)


def test_r005_tp_ragged_step_host_transfer_flagged():
    """ISSUE 13 red test: the tensor-parallel scheduler path — ragged
    steps, fused windows, and their settle methods — is inside the
    one-fetch-per-dispatch budget too. A host transfer smuggled into a
    ``_tp_step`` / ``_ragged_step`` / ``_ragged_window`` /
    ``_settle_window_rows`` costs a synchronous RTT on EVERY chip of the
    serving mesh, so DS-R005 must see those methods."""
    rules = _rules("""
        import numpy as np, jax
        class ShardedPagedServer:
            def _ragged_step(self):
                toks = np.asarray(self.pending)      # fetch per dispatch
            def _tp_step(self):
                lens = jax.device_get(self.lengths)  # ditto, tp spelling
            def _ragged_window(self):
                n = self.emitted.item()
            def _settle_window_rows(self, rows, out):
                out = np.asarray(out)
    """)
    assert rules.count("DS-R005") == 4


def test_r005_tp_settle_pragma_budget_still_honored():
    """The sanctioned single packed fetch of a window stays pragma-able —
    the rule polices UNBUDGETED transfers, not the contract fetch."""
    findings = lint_source(textwrap.dedent("""
        import numpy as np
        class ShardedPagedServer:
            def _ragged_step(self):
                pass
            def _settle_ragged_rows(self, rows, out):
                out = np.asarray(out)  # lint: allow(DS-R005)
                extra = np.asarray(self.lengths)
    """), path="deepspeed_tpu/foo.py")
    r005 = [f for f in findings if f.rule == "DS-R005"]
    assert len(r005) == 1  # only the unbudgeted second fetch


def test_r007_kv_sharding_write_flagged():
    """ISSUE 13 red test: the pool's kv-head sharding is part of its
    device-layout invariants — rebinding it outside the pool (e.g. a TP
    helper 'fixing up' placement mid-serve) silently de-aliases every
    donated page buffer. DS-R007 must flag the write on any receiver."""
    rules = _rules("""
        class TPScheduler:
            def rebalance(self, pool, sharding):
                pool.kv_sharding = sharding
                self.server.pool.kv_sharding = None
    """)
    assert rules.count("DS-R007") == 2


def test_r007_kv_sharding_quiet_inside_pool():
    assert "DS-R007" not in _rules("""
        class PagePool:
            def __init__(self, kv_sharding=None):
                self.kv_sharding = kv_sharding
    """)


def test_r007_pragma_suppresses_and_is_error_severity():
    findings = lint_source(textwrap.dedent("""
        def restore(pool, table):
            pool.page_table[:] = table  # lint: allow(DS-R007)
            pool.seq_lens[:] = 0
    """), path="deepspeed_tpu/foo.py")
    r007 = [f for f in findings if f.rule == "DS-R007"]
    assert len(r007) == 1  # the pragma'd line is suppressed
    assert resolve_severity(r007[0]) == "error"


def test_r008_nonatomic_write_in_checkpoint_file_flagged():
    src = """
        def persist(path, data):
            with open(path, "wb") as f:
                f.write(data)
    """
    findings = lint_source(
        textwrap.dedent(src),
        path="deepspeed_tpu/runtime/checkpoint_engine/foo_engine.py",
    )
    assert [f.rule for f in findings] == ["DS-R008"]
    # same code in an unrelated file: out of scope
    assert not lint_source(textwrap.dedent(src), path="deepspeed_tpu/ops/foo.py")


def test_r008_checkpoint_function_flagged_in_any_file():
    src = """
        import os
        def save_checkpoint(save_dir, tag):
            with open(os.path.join(save_dir, "latest"), "w") as f:
                f.write(tag)
    """
    rules = [
        f.rule
        for f in lint_source(textwrap.dedent(src), path="deepspeed_tpu/runtime/engine.py")
    ]
    assert "DS-R008" in rules


def test_r008_sanctioned_patterns_quiet():
    """temp+rename staging, append-only logs, and reads are the sanctioned
    idioms — none may flag."""
    src = """
        import os
        def save_checkpoint(path, data, tag):
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:          # staged: the atomic pattern
                f.write(data)
            os.replace(tmp, path)
            with open(path + ".journal", "ab") as f:  # append-only journal
                f.write(data)
            with open(path, "rb") as f:          # read
                return f.read()
    """
    findings = lint_source(
        textwrap.dedent(src), path="deepspeed_tpu/runtime/checkpoint_engine/x.py"
    )
    assert "DS-R008" not in [f.rule for f in findings]


def test_r008_pragma_suppresses_and_is_error_severity():
    src = """
        def write_journal(path, tag):
            with open(path, "w") as f:  # lint: allow(DS-R008)
                f.write(tag)
    """
    assert "DS-R008" not in [
        f.rule for f in lint_source(textwrap.dedent(src), path="deepspeed_tpu/inference/journal.py")
    ]
    bad = textwrap.dedent(src).replace("  # lint: allow(DS-R008)", "")
    findings = lint_source(bad, path="deepspeed_tpu/inference/journal.py")
    assert [f.rule for f in findings] == ["DS-R008"]
    assert resolve_severity(findings[0]) == "error"


def test_r008_bench_record_paths_in_scope():
    src = """
        import json
        def _save_store(store, path):
            with open(path, "w") as f:
                json.dump(store, f)
    """
    assert "DS-R008" in [
        f.rule for f in lint_source(textwrap.dedent(src), path="bench.py")
    ]


def test_r009_raw_clock_in_step_loop_flagged():
    """A raw perf_counter (or time.time / device_sync) inside a step-loop
    method of an Engine/Server/Scheduler class forks a second timeline
    next to the unified tracer — red."""
    findings = _rules("""
        import time
        class FooServer:
            def step(self):
                t0 = time.perf_counter()
                return t0
        class BarEngine:
            def train_batch(self):
                return time.time()
        class BazScheduler:
            def _ragged_step(self):
                device_sync()
    """)
    assert findings.count("DS-R009") == 3


def test_r009_quiet_outside_scope():
    """Out of scope: non-step methods, non-engine classes, injected clocks,
    and the tracer/timer modules themselves (path exemption)."""
    assert "DS-R009" not in _rules("""
        import time
        class FooServer:
            def __init__(self, clock=None):
                self.clock = clock or time.perf_counter  # reference, not a call
            def save_checkpoint(self):
                return time.perf_counter()  # not a step-loop method
            def step(self):
                return self.clock()  # injected clock is the sanctioned idiom
        class Helper:
            def step(self):
                return time.perf_counter()  # not an Engine/Server/Scheduler
    """)
    src = """
        import time
        class FooServer:
            def step(self):
                return time.perf_counter()
    """
    import textwrap as _tw

    assert [
        f.rule for f in lint_source(_tw.dedent(src), path="deepspeed_tpu/utils/timer.py")
    ] == []
    assert [
        f.rule for f in lint_source(_tw.dedent(src), path="deepspeed_tpu/profiling/tracer.py")
    ] == []
    assert "DS-R009" in [
        f.rule for f in lint_source(_tw.dedent(src), path="deepspeed_tpu/inference/scheduler.py")
    ]


def test_r009_window_and_prefetch_methods_in_scope():
    """ISSUE 14 extension: the multi-step window family (formation,
    per-step commit, deferred drain, lr pre-evaluation) and the
    input-pipeline Loader methods run on the same step critical path —
    a raw clock there is the same fork of the timeline. Red."""
    findings = _rules("""
        import time
        class FooEngine:
            def _try_train_window(self, it):
                t0 = time.perf_counter()
            def _commit_window_step(self):
                return time.time()
            def _drain_pending(self, keep=0):
                time.monotonic()
            def _window_lrs(self, n):
                return time.perf_counter()
        class PrefetchingLoader:
            def __next__(self):
                t = time.perf_counter()
            def _pull(self):
                return time.time()
            def fill(self, n=None):
                device_sync()
    """)
    assert findings.count("DS-R009") == 7


def test_r009_loader_quiet_outside_hot_methods():
    """A Loader's non-pipeline methods (state_dict etc.) may time freely,
    and the REAL dataloader module lints clean under the extended scope."""
    assert "DS-R009" not in _rules("""
        import time
        class PrefetchingLoader:
            def state_dict(self):
                return {"t": time.time()}  # not a hot-path method
        class DataLoader:
            def __len__(self):
                return int(time.perf_counter())
    """)
    import os

    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    path = os.path.join(root, "deepspeed_tpu", "runtime", "dataloader.py")
    with open(path) as fh:
        src = fh.read()
    assert [
        f.rule for f in lint_source(src, path="deepspeed_tpu/runtime/dataloader.py")
    ] == []


def test_r009_pragma_suppresses_and_is_error_severity():
    src = """
        import time
        class FooServer:
            def step(self):
                return time.perf_counter()  # lint: allow(DS-R009)
    """
    assert "DS-R009" not in _rules(src)
    f = lint_source(
        textwrap.dedent(src.replace("  # lint: allow(DS-R009)", "")),
        path="deepspeed_tpu/x.py",
    )[0]
    assert f.rule == "DS-R009"
    assert resolve_severity(f) == "error"


def test_r009_streamer_stream_family_in_scope():
    """ISSUE 16 extension: the host-offload *Streamer bucket methods run
    between every hot dispatch — a raw clock there forks the timeline
    exactly like one in an engine step method. Red."""
    findings = _rules("""
        import time
        class HostOffloadStreamer:
            def h2d_bucket(self, bi):
                t0 = time.perf_counter()
            def d2h_bucket(self, bi, m, ea, eas):
                return time.time()
            def materialize_writes(self, keep=0):
                time.monotonic()
            def gather_device_state(self):
                device_sync()
        class FooEngine:
            def _take_streamed_offload_step(self, lr):
                return time.perf_counter()
    """)
    assert findings.count("DS-R009") == 5


def test_r009_streamer_unsanctioned_host_copy_flagged():
    """Stream-copy discipline: a raw device_put / device_get /
    copy_to_host_async / block_until_ready anywhere in a *Streamer
    OUTSIDE the sanctioned helpers bypasses the stream accounting the
    overlap gate audits. Red on each copy primitive."""
    findings = _rules("""
        import jax
        class HostOffloadStreamer:
            def take_staged(self, bi):
                return jax.device_put(self._exp_avg[0], s)
            def stream_stats(self):
                return jax.device_get(self._pending[0][1])
            def state_dict(self):
                arr.copy_to_host_async()
            def note_step(self):
                x.block_until_ready()
    """)
    assert findings.count("DS-R009") == 4


def test_r009_streamer_sanctioned_helpers_quiet():
    """The sanctioned stream helpers OWN the raw copies (that is the
    point of the rule); __init__ seeds host buffers before stepping and
    set_master_leaves is checkpoint-restore surgery. All green — and the
    real streamer module holds the contract."""
    assert "DS-R009" not in _rules("""
        import jax
        import numpy as np
        class HostOffloadStreamer:
            def __init__(self, tree):
                self._master = [np.array(jax.device_get(l), copy=True) for l in tree]
            def h2d_bucket(self, bi):
                return [jax.device_put(m, s) for m in self._exp_avg]
            def d2h_bucket(self, bi, m, ea, eas):
                m[0].copy_to_host_async()
            def _land(self, bufs, i, arr):
                np.copyto(bufs[i], np.asarray(jax.device_get(arr)))
            def drain_writes(self):
                arr.block_until_ready()
            def set_master_leaves(self, leaves):
                np.copyto(self._master[0], np.asarray(jax.device_get(leaves[0])))
        class BucketPlanner:
            def take_staged(self):
                return jax.device_put(x, s)  # only *Streamer classes are in scope
    """)
    path = os.path.join(REPO, "deepspeed_tpu", "runtime", "zero", "host_offload.py")
    findings = lint_paths([path])
    assert [f.rule for f in findings] == [], [f.render() for f in findings]


def test_r008_host_offload_is_a_persistence_path():
    """host_offload.py persists state checkpoints later trust — a raw
    open('w') there is in DS-R008 scope by path."""
    src = """
        def dump(path, payload):
            with open(path, "w") as fh:
                fh.write(payload)
    """
    hits = [
        f.rule
        for f in lint_source(textwrap.dedent(src), path="deepspeed_tpu/runtime/zero/host_offload.py")
    ]
    assert hits == ["DS-R008"]


def test_r010_jax_import_in_host_only_module_flagged():
    """The fleet router and the tracer are declared pure host code: any
    jax import form trips the rule there — and only there."""
    for src in (
        "import jax\n",
        "import jax.numpy as jnp\n",
        "from jax import numpy\n",
        "from jax.sharding import NamedSharding\n",
    ):
        hits = [
            f.rule
            for f in lint_source(src, path="deepspeed_tpu/inference/fleet.py")
        ]
        assert hits == ["DS-R010"], (src, hits)
    assert "DS-R010" in [
        f.rule
        for f in lint_source("import jax\n", path="deepspeed_tpu/profiling/tracer.py")
    ]


def test_r010_quiet_elsewhere_and_on_host_imports():
    # jax imports are the norm everywhere else in the library
    assert not lint_source(
        "import jax\n", path="deepspeed_tpu/inference/scheduler.py"
    )
    # numpy / stdlib / journal imports in the host-only modules are fine
    assert not lint_source(
        "import numpy as np\nimport zlib\n"
        "from deepspeed_tpu.inference.journal import RequestJournal\n",
        path="deepspeed_tpu/inference/fleet.py",
    )
    # a deliberate (hypothetical) exception carries a pragma
    assert not lint_source(
        "import jax  # lint: allow(DS-R010)\n",
        path="deepspeed_tpu/inference/fleet.py",
    )


def test_r010_fleet_module_actually_lints_clean():
    """The real router module holds the contract (the gate's lint leg)."""
    path = os.path.join(REPO, "deepspeed_tpu", "inference", "fleet.py")
    findings = lint_paths([path])
    assert [f.rule for f in findings] == [], [f.render() for f in findings]


def test_severity_tests_path_is_warn_only():
    f = lint_source("import jax.numpy as jnp\nx = jnp.repeat(k_cache, 2)\n", path="tests/unit/foo.py")[0]
    assert f.rule == "DS-R001"
    assert resolve_severity(f) == "warn"
    f2 = lint_source("import jax.numpy as jnp\nx = jnp.repeat(k_cache, 2)\n", path="deepspeed_tpu/foo.py")[0]
    assert resolve_severity(f2) == "error"


def test_library_lints_clean():
    """The gate itself: zero error-severity findings in deepspeed_tpu/
    (deliberate sites carry pragmas) — what tools/lint.sh enforces per
    commit."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu")])
    errors = [
        f.render()
        for f in findings
        if resolve_severity(f) == "error"
    ]
    assert not errors, "\n".join(errors)


def test_r011_device_put_onto_device_flagged():
    """The PR-12 incident shape: a pool-sized buffer device_put onto a
    bare device — the whole pool transiently commits to one chip."""
    assert "DS-R011" in _rules("""
        import jax, jax.numpy as jnp
        def place_pool(kv_pages):
            return jax.device_put(kv_pages, jax.devices()[0])
    """)


def test_r011_sharded_placement_ok():
    """Placing with a NamedSharding / spec tree is the sanctioned fix."""
    assert "DS-R011" not in _rules("""
        import jax
        def shard(params, shardings):
            return jax.device_put(params, shardings)
    """)
    assert "DS-R011" not in _rules("""
        import jax
        def shard(params, mesh, spec):
            from jax.sharding import NamedSharding
            return jax.device_put(params, NamedSharding(mesh, spec))
    """)


def test_r011_placementless_only_on_mesh_path():
    """A bare device_put of a sized value only flags inside mesh/shard
    code — default-device placement of host data is fine elsewhere."""
    assert "DS-R011" in _rules("""
        import jax
        def build_on_mesh(cache, mesh):
            return jax.device_put(cache)
    """)
    assert "DS-R011" not in _rules("""
        import jax
        def stage(cache):
            return jax.device_put(cache)
    """)


def test_r011_unsized_values_ok():
    assert "DS-R011" not in _rules("""
        import jax
        def f(x, mesh):
            return jax.device_put(x, jax.devices()[0])
    """)


def test_r011_pragma_suppresses_and_is_error_severity():
    findings = lint_source(
        textwrap.dedent("""
        import jax
        def per_shard(master, dev):
            return jax.device_put(master, dev)  # lint: allow(DS-R011)
    """),
        path="deepspeed_tpu/foo.py",
    )
    assert "DS-R011" not in [f.rule for f in findings]
    bad = lint_source(
        textwrap.dedent("""
        import jax
        def per_shard(master, dev):
            return jax.device_put(master, dev)
    """),
        path="deepspeed_tpu/foo.py",
    )
    hit = [f for f in bad if f.rule == "DS-R011"]
    assert hit and resolve_severity(hit[0]) == "error"


def test_r012_module_constant_in_jit_flagged():
    rules = _rules("""
        import jax, numpy as np
        TABLE = np.arange(1024.0)
        @jax.jit
        def f(x):
            return x + TABLE
    """)
    assert "DS-R012" in rules


def test_r012_constant_passed_as_argument_ok():
    assert "DS-R012" not in _rules("""
        import jax, numpy as np
        TABLE = np.arange(1024.0)
        @jax.jit
        def f(x, table):
            return x + table
        def call(x):
            return f(x, TABLE)  # capture-free: rides the arg path
    """)


def test_r012_local_shadow_ok():
    assert "DS-R012" not in _rules("""
        import jax, numpy as np
        TABLE = np.arange(4.0)
        @jax.jit
        def f(x):
            TABLE = x * 2
            return x + TABLE
    """)


def test_r012_is_warn_only():
    f = [
        x
        for x in lint_source(
            textwrap.dedent("""
        import jax, numpy as np
        C = np.zeros(8)
        @jax.jit
        def f(x):
            return x + C
    """),
            path="deepspeed_tpu/foo.py",
        )
        if x.rule == "DS-R012"
    ]
    assert f and resolve_severity(f[0]) == "warn"


def test_cli_json_and_rule_filter(tmp_path, capsys):
    """--json emits machine-readable findings and --rule narrows to the
    named rule ids (the structured interface the CI gates assert on)."""
    import json

    from deepspeed_tpu.analysis.source_lint import main

    bad = tmp_path / "bad.py"
    bad.write_text(
        textwrap.dedent("""
        import jax, jax.numpy as jnp
        def place(kv_pages, k_cache, G):
            jnp.repeat(k_cache, G)
            return jax.device_put(kv_pages, jax.devices()[0])
    """)
    )
    rc = main([str(bad), "--json", "--rule", "DS-R011"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert [f["rule"] for f in out] == ["DS-R011"]
    rc = main([str(bad), "--json", "--rule", "DS-R001"])
    out = json.loads(capsys.readouterr().out)
    assert [f["rule"] for f in out] == ["DS-R001"]
    assert rc == 1


def test_cli_rule_filter_rejects_unknown(tmp_path):
    import pytest

    from deepspeed_tpu.analysis.source_lint import main

    with pytest.raises(SystemExit):
        main([str(tmp_path), "--rule", "DS-R999"])


def test_r005_moe_routing_host_sync_flagged():
    """ISSUE 20 extension: host transfers inside the routing methods of a
    *Gate / *MoE / *MoELayer class run inside every traced step — each is
    one synchronous RTT stalling the a2a overlap pipeline. Red."""
    rules = _rules("""
        import numpy as np, jax
        class TopKGate:
            def forward(self, logits):
                counts = jax.device_get(self.exp_counts)
                return counts
        class MoE:
            def apply(self, params, x):
                n = self.capacity.item()
                return n
        class ShardedMoELayer:
            def dispatch(self, tokens):
                return np.asarray(self.dispatch_mask)
    """)
    assert rules.count("DS-R005") == 3


def test_r009_moe_routing_raw_clock_flagged():
    """A raw clock around the gate/dispatch path forks a second timeline
    next to the tracer and serializes the dispatch a2a. Red."""
    rules = _rules("""
        import time
        class TopKGate:
            def gate(self, logits):
                t0 = time.perf_counter()
                return t0
        class PRMoELayer:
            def combine(self, expert_out):
                return time.time()
    """)
    assert rules.count("DS-R009") == 2


def test_moe_routing_scope_quiet_on_cold_methods():
    """Out of scope: init/partition methods of MoE classes (host-side
    setup, not the routing path), and config-ish classes whose names end
    MoE-ish but define no routing methods."""
    assert "DS-R005" not in _rules("""
        import numpy as np
        class MoE:
            def init(self, rng):
                return np.asarray(self.seed)  # setup, not routing
            def partition_rules(self):
                return np.asarray(self.rules)
        class DeepSpeedMoEConfig:
            def validate(self):
                return np.asarray(self.moe_experts)  # no routing methods
    """)
    assert "DS-R009" not in _rules("""
        import time
        class MoE:
            def init(self, rng):
                return time.perf_counter()  # setup may time freely
    """)


def test_moe_package_lints_clean_under_routing_scope():
    """The real moe/ package (gate + dispatch + a2a fast path) must lint
    clean under the extended routing-path scope — the hot path stays free
    of host syncs and raw clocks by construction."""
    findings = lint_paths([os.path.join(REPO, "deepspeed_tpu", "moe")])
    assert [f for f in findings if f.rule in ("DS-R005", "DS-R009")] == []
