"""Red fixtures: every analysis pass must flag its deliberately-broken
miniature program (ISSUE 3 acceptance — a pass that cannot fail cannot
guard anything). Each fixture is the smallest program exhibiting one
hazard: a donated-but-unaliasable buffer, an un-aliased scan carry, a
silent bf16→f32 upcast feeding a matmul, a host callback inside the
program, and a known collective schedule the extractor must count
exactly. The retrace differ is driven with two signatures of the same
program and must name the argument that changed.
"""

from __future__ import annotations

import os
import warnings

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.analysis import (
    analyze_program,
    diff_trace_signatures,
    find_aval_shapes,
    run_program_passes,
)
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry


def _dispatch(tel, name, fn, *args, **jit_kwargs):
    wrapped = tel.instrument(name, fn, **jit_kwargs)
    with warnings.catch_warnings():
        # the broken-donation fixtures intentionally trip jax's
        # "donated argument was not used" warning
        warnings.simplefilter("ignore")
        wrapped(*args)
    return wrapped


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------
def test_donation_red_unaliasable_buffer():
    """A donated buffer no output can alias (shape matches nothing) must be
    reported with its double-buffered bytes."""
    tel = CompileTelemetry()

    def f(big, x):
        return x * 2.0

    _dispatch(tel, "bad", f, jnp.ones((128, 128)), jnp.ones((4,)), donate_argnums=(0,))
    res = analyze_program("bad", tel.programs()["bad"], passes=["donation"])["donation"]
    assert not res.ok
    assert res.violations, "unhonored donation not reported"


def test_donation_red_unaliased_scan_carry():
    """A scan whose carry is returned at a different dtype than the donated
    input cannot alias it — the pass reports the double-buffer."""
    tel = CompileTelemetry()

    def f(carry, xs):
        def body(c, x):
            return c + x.astype(c.dtype), ()

        out, _ = jax.lax.scan(body, carry, xs)
        return out.astype(jnp.bfloat16)  # dtype change: no alias possible

    _dispatch(
        tel, "scan_carry", f,
        jnp.zeros((64, 64), jnp.float32), jnp.ones((4, 64, 64), jnp.float32),
        donate_argnums=(0,),
    )
    res = analyze_program(
        "scan_carry", tel.programs()["scan_carry"], passes=["donation"]
    )["donation"]
    assert not res.ok
    assert any(v.details.get("bytes", 0) >= 64 * 64 * 4 for v in res.violations) or \
        any("double-buffered" in v.message for v in res.violations)


def test_donation_green_aliased_state():
    tel = CompileTelemetry()

    def step(state):
        return jax.tree_util.tree_map(lambda a: a + 1.0, state)

    _dispatch(
        tel, "ok", step, {"w": jnp.ones((32, 32)), "m": jnp.ones((32, 32))},
        donate_argnums=(0,),
    )
    res = analyze_program("ok", tel.programs()["ok"], passes=["donation"])["donation"]
    assert res.ok
    assert res.summary["declared_donations"] == 2


def test_donation_min_bytes_demotes_small_buffers():
    tel = CompileTelemetry()

    def f(tiny, x):
        return x * 2.0

    _dispatch(tel, "tiny", f, jnp.ones((2,)), jnp.ones((4,)), donate_argnums=(0,))
    res = analyze_program(
        "tiny", tel.programs()["tiny"], passes=["donation"],
        config={"min_donation_bytes": 1024},
    )["donation"]
    # still reported, but below the byte threshold → warn, not error
    assert res.violations
    assert res.ok


# ---------------------------------------------------------------------------
# dtype promotion
# ---------------------------------------------------------------------------
def test_dtype_red_silent_f32_upcast_matmul():
    tel = CompileTelemetry()

    def f(w, x):
        return w.astype(jnp.float32) @ x.astype(jnp.float32)

    _dispatch(tel, "upcast", f, jnp.ones((8, 8), jnp.bfloat16), jnp.ones((8, 8), jnp.bfloat16))
    res = analyze_program(
        "upcast", tel.programs()["upcast"], passes=["dtype_promotion"]
    )["dtype_promotion"]
    assert not res.ok
    assert any("dot_general" in v.message for v in res.violations)


def test_dtype_red_upcast_inside_scan():
    """Taint must follow into control-flow bodies (the fused-accum scan is
    where a silent upcast would actually hide)."""
    tel = CompileTelemetry()

    def f(w, xs):
        def body(c, x):
            return c + (w.astype(jnp.float32) @ x.astype(jnp.float32)), ()

        out, _ = jax.lax.scan(body, jnp.zeros((8, 8), jnp.float32), xs)
        return out

    _dispatch(tel, "scan_upcast", f, jnp.ones((8, 8), jnp.bfloat16), jnp.ones((2, 8, 8), jnp.bfloat16))
    res = analyze_program(
        "scan_upcast", tel.programs()["scan_upcast"], passes=["dtype_promotion"]
    )["dtype_promotion"]
    assert not res.ok


def test_dtype_green_softmax_boundary():
    """Softmax-in-f32 followed by a downcast PV matmul is the sanctioned
    pattern — zero violations."""
    tel = CompileTelemetry()

    def attn(q, k, v):
        s = (q @ k.T).astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        return p @ v

    _dispatch(tel, "attn", attn, *[jnp.ones((8, 8), jnp.bfloat16)] * 3)
    res = analyze_program(
        "attn", tel.programs()["attn"], passes=["dtype_promotion"]
    )["dtype_promotion"]
    assert res.ok, [v.message for v in res.violations]


def test_dtype_green_master_weight_update():
    """The mixed-precision optimizer pattern (bf16 grads upcast to f32 for
    elementwise update math against f32 master) is allowlisted by
    construction: no matmul touches the upcast values."""
    tel = CompileTelemetry()

    def update(master, grad_bf16):
        g32 = grad_bf16.astype(jnp.float32)
        new_master = master - 0.1 * g32
        return new_master, new_master.astype(jnp.bfloat16)

    _dispatch(tel, "update", update, jnp.ones((16, 16), jnp.float32), jnp.ones((16, 16), jnp.bfloat16))
    res = analyze_program(
        "update", tel.programs()["update"], passes=["dtype_promotion"]
    )["dtype_promotion"]
    assert res.ok, [v.message for v in res.violations]


# ---------------------------------------------------------------------------
# host transfer
# ---------------------------------------------------------------------------
def test_host_transfer_red_pure_callback():
    tel = CompileTelemetry()

    def f(x):
        y = jax.pure_callback(
            lambda a: np.asarray(a) * 2.0, jax.ShapeDtypeStruct((4,), jnp.float32), x
        )
        return y + 1.0

    _dispatch(tel, "cb", f, jnp.ones((4,)))
    res = analyze_program(
        "cb", tel.programs()["cb"], passes=["host_transfer"]
    )["host_transfer"]
    assert not res.ok
    assert any("pure_callback" in v.message for v in res.violations)


def test_host_transfer_green_pure_math():
    tel = CompileTelemetry()
    _dispatch(tel, "clean", lambda x: jnp.tanh(x) * 2.0, jnp.ones((16,)))
    res = analyze_program(
        "clean", tel.programs()["clean"], passes=["host_transfer"]
    )["host_transfer"]
    assert res.ok


# ---------------------------------------------------------------------------
# collectives
# ---------------------------------------------------------------------------
def test_collectives_extractor_counts_known_schedule(eight_devices):
    """A program with exactly one dp all-reduce of a known payload: the
    extractor must report op kind, count, and per-device bytes."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    s = NamedSharding(mesh, P("dp"))
    tel = CompileTelemetry()

    def f(x):
        return x - jnp.mean(x)  # mean over the sharded axis → one all-reduce

    x = jax.device_put(jnp.arange(64.0).reshape(64, 1), NamedSharding(mesh, P("dp", None)))
    _dispatch(tel, "ar", f, x)
    res = analyze_program("ar", tel.programs()["ar"], passes=["collectives"])["collectives"]
    ops = res.summary["ops"]
    assert "all-reduce" in ops, res.summary
    assert ops["all-reduce"]["count"] >= 1
    assert res.summary["total_bytes"] >= 4  # ≥ one f32 scalar per device


def test_collectives_budget_gate(eight_devices):
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("dp",))
    tel = CompileTelemetry()

    def f(x):
        return x - jnp.mean(x)

    x = jax.device_put(jnp.ones((64, 8)), NamedSharding(mesh, P("dp", None)))
    _dispatch(tel, "budget", f, x)
    res = analyze_program(
        "budget", tel.programs()["budget"], passes=["collectives"],
        config={"collective_budget_bytes": 0},
    )["collectives"]
    assert not res.ok
    assert "budget" in res.violations[0].message


# ---------------------------------------------------------------------------
# retrace-cause differ
# ---------------------------------------------------------------------------
def test_retrace_differ_names_offending_argument():
    tel = CompileTelemetry()
    f = tel.instrument("prog", lambda a, b: a + b)
    f(jnp.ones((4, 4)), jnp.ones((4, 4)))
    f(jnp.ones((8, 4)), jnp.ones((8, 4)))  # retrace: arg shapes changed
    f(jnp.ones((8, 4)), jnp.ones((8, 4), jnp.bfloat16))  # retrace: b's dtype
    log = tel.program_stats("prog").trace_log
    assert len(log) == 3
    first = diff_trace_signatures(log[0], log[1])
    assert first and all(d["reason"] == "shape" for d in first)
    second = diff_trace_signatures(log[1], log[2])
    assert len(second) == 1
    assert second[0]["reason"] == "dtype"
    assert "[1]" in second[0]["arg"]  # names argument b, not a

    # the report surfaces the same diffs under the program entry
    rep = run_program_passes(tel, programs=["prog"], passes=["host_transfer"])
    retraces = rep["programs"]["prog"]["retraces"]
    assert len(retraces) == 2
    assert retraces[1]["changed"][0]["reason"] == "dtype"


def test_report_aggregates_and_flags():
    """run_program_passes folds per-program results into totals the bench
    and the engines consume (donation_verified, collective bytes)."""
    tel = CompileTelemetry()
    _dispatch(tel, "good", lambda s: jax.tree_util.tree_map(lambda a: a * 2, s),
              {"w": jnp.ones((16, 16))}, donate_argnums=(0,))

    def bad(big, x):
        return x + 1

    _dispatch(tel, "bad", bad, jnp.ones((64, 64)), jnp.ones((4,)), donate_argnums=(0,))
    rep = run_program_passes(tel)
    assert rep["totals"]["programs"] == 2
    assert rep["totals"]["donation_verified"] is False
    assert rep["programs"]["good"]["passes"]["donation"]["ok"] is True
    assert rep["programs"]["bad"]["passes"]["donation"]["ok"] is False
    # never-dispatched programs are skipped by the default selection...
    tel.instrument("never_ran", lambda x: x)
    rep2 = run_program_passes(tel)
    assert "never_ran" not in rep2["programs"]
    # ...but an EXPLICIT request for an unanalyzable or unknown name must
    # surface as a counted failure, never as a clean "verified" report
    rep3 = run_program_passes(tel, programs=["never_ran", "no_such_prog"])
    assert rep3["programs"]["never_ran"]["error"]
    assert rep3["programs"]["no_such_prog"]["error"]
    assert rep3["totals"]["analysis_failures"] == 2
    assert rep3["totals"]["donation_verified"] is False
    # and a report that never ran the donation pass must not claim it:
    # None (indeterminate), not True — even when a requested program fails
    rep4 = run_program_passes(tel, programs=["good"], passes=["collectives"])
    assert rep4["totals"]["donation_verified"] is None
    rep5 = run_program_passes(tel, programs=["no_such_prog"], passes=["collectives"])
    assert rep5["totals"]["analysis_failures"] == 1
    assert rep5["totals"]["donation_verified"] is None


def test_raise_mode_trips_on_analysis_failure():
    """A typo'd pass name (or any artifact build error) must not silently
    disable verify=raise: analysis failures raise, not just violations."""
    import pytest

    from deepspeed_tpu.analysis import AnalysisError, raise_or_warn

    tel = CompileTelemetry()
    _dispatch(tel, "p", lambda x: x + 1, jnp.ones((4,)))
    rep = run_program_passes(tel, programs=["p"], passes=["donations"])  # typo
    assert rep["totals"]["analysis_failures"] == 1
    with pytest.raises(AnalysisError):
        raise_or_warn(rep, "raise")


def test_donation_pruned_partial_shortfall_reported():
    """With an unused (pruned) arg breaking the index mapping, a donated
    buffer that went unhonored must still surface — as a warn-severity
    'partially unverifiable' violation, never as a clean verified pass."""
    tel = CompileTelemetry()

    def f(big, unused, state):
        return big.astype(jnp.bfloat16), state + 1.0  # big cannot alias

    _dispatch(
        tel, "partial", f,
        jnp.ones((256, 256)), jnp.ones((8,)), jnp.ones((16,)),
        donate_argnums=(0, 2),
    )
    res = analyze_program(
        "partial", tel.programs()["partial"], passes=["donation"]
    )["donation"]
    assert "arg_pruning" in res.summary
    assert res.violations, "partial unhonored donation invisible under pruning"


def test_collective_bytes_async_start_equals_sync():
    """Async ``-start`` bundles carry (operands..., results...) tuple
    shapes; the extractor must count only the result half so sync and
    async lowerings of one program report identical byte totals."""
    from deepspeed_tpu.analysis.hlo import collect_collectives

    sync = '%ag = f32[64,256]{1,0} all-gather(f32[8,256]{1,0} %p), dimensions={0}\n'
    async_ = (
        '%ags = (f32[8,256]{1,0}, f32[64,256]{1,0}) all-gather-start(f32[8,256]{1,0} %p), dimensions={0}\n'
        '%agd = f32[64,256]{1,0} all-gather-done((f32[8,256]{1,0}, f32[64,256]{1,0}) %ags)\n'
    )
    s = collect_collectives(sync)["all-gather"]
    a = collect_collectives(async_)["all-gather"]
    assert s["count"] == a["count"] == 1
    assert s["bytes"] == a["bytes"] == 64 * 256 * 4


def test_parse_computations_variadic_combined_async_start():
    """TPU's collective combiner emits variadic async starts whose bundle
    shape nests tuples two deep: ``((operands...), (results...))``. The
    instruction parser must not drop them — an unseen loop collective
    would let the overlap pass report a false overlap_verified: True —
    and the byte counter must count only the result half."""
    from deepspeed_tpu.analysis.hlo import instruction_bytes, parse_computations

    hlo = (
        "ENTRY %main (p0: f32[2,4]) -> f32[8,4] {\n"
        "  %p0 = f32[2,4]{1,0} parameter(0)\n"
        "  %ags = ((f32[2,4]{1,0}, f32[2,4]{1,0}), (f32[8,4]{1,0}, f32[8,4]{1,0}))"
        " all-gather-start(f32[2,4]{1,0} %p0, f32[2,4]{1,0} %p0), dimensions={0}\n"
        "  ROOT %agd = (f32[8,4]{1,0}, f32[8,4]{1,0}) all-gather-done(%ags)\n"
        "}\n"
    )
    comps, entry = parse_computations(hlo)
    ops = {i.name: i for i in comps[entry]}
    assert "ags" in ops, "variadic combined async start dropped by the parser"
    start = ops["ags"]
    assert start.op == "all-gather" and start.suffix == "-start"
    assert instruction_bytes(start) == 2 * 8 * 4 * 4  # results only


def test_async_start_context_scalars_not_counted_as_results():
    """collective-permute-start's bundle is ``(src, dest, u32[], u32[])`` —
    the trailing u32[] scalars are scheduler context, not payload. The
    even-split heuristic must not take them as the "result half" (that
    would report ~8 bytes for an N-element permute)."""
    from deepspeed_tpu.analysis.hlo import instruction_bytes, parse_computations

    hlo = (
        "ENTRY %main (p0: f32[64,32]) -> f32[64,32] {\n"
        "  %p0 = f32[64,32]{1,0} parameter(0)\n"
        "  %cps = (f32[64,32]{1,0}, f32[64,32]{1,0}, u32[], u32[])"
        " collective-permute-start(f32[64,32]{1,0} %p0),"
        " source_target_pairs={{0,1},{1,0}}\n"
        "  ROOT %cpd = f32[64,32]{1,0} collective-permute-done(%cps)\n"
        "}\n"
    )
    comps, entry = parse_computations(hlo)
    start = {i.name: i for i in comps[entry]}["cps"]
    assert start.op == "collective-permute" and start.suffix == "-start"
    assert instruction_bytes(start) == 64 * 32 * 4  # the dest payload only


def test_overlap_loop_membership_is_transitive():
    """An exposed collective in a computation *called from* a while body
    (here via ``call``/``to_apply`` — same shape as a cond branch or a
    nested scan) executes once per iteration, exactly like one written
    directly in the body. The overlap pass must treat it as a loop
    collective: if membership stopped at the body itself, this schedule
    would false-green as overlap_verified."""
    from deepspeed_tpu.analysis.passes import ProgramArtifact, overlap_pass

    hlo = (
        "%gather_and_dot (p: f32[8,64]) -> f32[64,64] {\n"
        "  %p = f32[8,64]{1,0} parameter(0)\n"
        "  %ag = f32[64,64]{1,0} all-gather(f32[8,64]{1,0} %p), dimensions={0}\n"
        "  ROOT %d = f32[64,64]{1,0} dot(f32[64,64]{1,0} %ag, f32[64,64]{1,0}"
        " %ag), lhs_contracting_dims={1}, rhs_contracting_dims={0}\n"
        "}\n"
        "%body (t: (s32[], f32[8,64])) -> (s32[], f32[8,64]) {\n"
        "  %t = (s32[], f32[8,64]{1,0}) parameter(0)\n"
        "  %i = s32[] get-tuple-element((s32[], f32[8,64]{1,0}) %t), index=0\n"
        "  %w = f32[8,64]{1,0} get-tuple-element((s32[], f32[8,64]{1,0}) %t), index=1\n"
        "  %c = f32[64,64]{1,0} call(f32[8,64]{1,0} %w), to_apply=%gather_and_dot\n"
        "  %sl = f32[8,64]{1,0} slice(f32[64,64]{1,0} %c), slice={[0:8], [0:64]}\n"
        "  %one = s32[] constant(1)\n"
        "  %ip = s32[] add(s32[] %i, s32[] %one)\n"
        "  ROOT %r = (s32[], f32[8,64]{1,0}) tuple(s32[] %ip, f32[8,64]{1,0} %sl)\n"
        "}\n"
        "%cond (t: (s32[], f32[8,64])) -> pred[] {\n"
        "  %t = (s32[], f32[8,64]{1,0}) parameter(0)\n"
        "  %i = s32[] get-tuple-element((s32[], f32[8,64]{1,0}) %t), index=0\n"
        "  %n = s32[] constant(4)\n"
        "  ROOT %lt = pred[] compare(s32[] %i, s32[] %n), direction=LT\n"
        "}\n"
        "ENTRY %main (p0: f32[8,64]) -> (s32[], f32[8,64]) {\n"
        "  %p0 = f32[8,64]{1,0} parameter(0)\n"
        "  %zero = s32[] constant(0)\n"
        "  %init = (s32[], f32[8,64]{1,0}) tuple(s32[] %zero, f32[8,64]{1,0} %p0)\n"
        "  ROOT %wh = (s32[], f32[8,64]{1,0}) while((s32[], f32[8,64]{1,0})"
        " %init), condition=%cond, body=%body\n"
        "}\n"
    )
    art = ProgramArtifact("fixture", wrapper=None)
    art._hlo_text = hlo
    res = overlap_pass(art)
    # the gather feeds the only dot, so nothing independent hides it...
    assert res.summary["exposed_count"] == 1, res.summary
    # ...and it sits one call level below the while body: still a loop
    # collective, so the program must NOT verify
    assert res.summary["loop_collectives"] == 1, res.summary
    assert res.summary["overlap_verified"] is False, res.summary
    assert res.violations
    assert res.violations[0].details["computation"] == "gather_and_dot"


# ---------------------------------------------------------------------------
# green sweep: speculative verify programs (ISSUE 4)
# ---------------------------------------------------------------------------
def test_green_spec_verify_programs():
    """The speculative serving programs (paged_verify per (bucket, K), next
    to decode/prefill) verify clean under every pass: donated page buffers
    aliased, zero host transfers, zero upcast-compute sites, zero
    violations overall."""
    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.inference.scheduler import PagedServer
    from deepspeed_tpu.inference.spec_decode import Drafter
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    class TwoTokenDrafter(Drafter):
        # always drafts something: every round is a verify dispatch
        def propose(self, uid, context, k):
            return np.asarray([0, 1][: max(k, 0)], np.int32)

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()
    server = PagedServer(
        cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, telemetry=tel,
        spec_decode={"max_draft": 2}, drafter=TwoTokenDrafter(),
        ragged=False,  # the bucketed oracle's verify programs
    )
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 128, (7,)).astype(np.int32) for _ in range(3)]
    server.serve(prompts, max_new_tokens=4)
    assert server.stats["spec_rounds"] >= 1
    rep = run_program_passes(tel)
    names = set(rep["programs"])
    assert any(n.startswith("paged_verify_") for n in names), names
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name in names:
        passes = rep["programs"][name]["passes"]
        assert passes["host_transfer"]["ok"]
        assert passes["dtype_promotion"]["ok"]
        assert passes["donation"]["ok"]


# ---------------------------------------------------------------------------
# green sweep: the production-traffic serving path (ISSUE 6) — prefix
# caching + multi-tenant scheduling must ride the SAME verified programs
# ---------------------------------------------------------------------------
def test_green_traffic_serving_programs():
    """Serving through the traffic layer (prefix-cached pool + SLA tenant
    scheduler) dispatches only the existing paged programs — donation
    aliased, zero host transfers, zero violations — and sharing adds no
    dispatches: decode dispatches == decode steps, prefill dispatches ==
    prefill chunks, even with prefix attaches happening."""
    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.inference.scheduler import PagedServer
    from deepspeed_tpu.inference.traffic import MultiTenantServer, TenantSpec
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()
    server = MultiTenantServer(
        PagedServer(
            cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
            attn_impl="xla", dtype=jnp.float32, telemetry=tel,
            prefix_cache=True, ragged=False,  # the bucketed oracle's programs
        ),
        tenants=[TenantSpec(name="a", weight=2.0), TenantSpec(name="b")],
    )
    rs = np.random.RandomState(0)
    sys_tokens = rs.randint(0, 128, (16,)).astype(np.int32)  # 2 full pages
    prompts = [
        np.concatenate([sys_tokens, rs.randint(0, 128, (3 + i,)).astype(np.int32)])
        for i in range(4)
    ]
    # the first serve publishes the shared pages, the second attaches them
    server.serve(prompts[:1], max_new_tokens=4, tenant="a")
    server.serve(prompts[1:], max_new_tokens=4, tenant=["b", "a", "b"])
    assert server.pool.stats["prefix_hit_pages"] > 0  # sharing engaged
    stats = tel.stats()
    decode_dispatches = sum(
        rec["dispatches"] for name, rec in stats.items()
        if name.startswith("paged_decode_")
    )
    prefill_dispatches = sum(
        rec["dispatches"] for name, rec in stats.items()
        if name.startswith("paged_prefill_")
    )
    assert decode_dispatches == server.stats["decode_steps"]
    assert prefill_dispatches == server.stats["prefill_chunks"]
    assert all(n.startswith("paged_") for n in stats), stats.keys()
    rep = run_program_passes(tel)
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name, prog in rep["programs"].items():
        assert prog["passes"]["host_transfer"]["ok"], name
        assert prog["passes"]["donation"]["ok"], name


# ---------------------------------------------------------------------------
# green sweep + compile-budget gate: the ragged serving program (ISSUE 8)
# ---------------------------------------------------------------------------
def test_green_ragged_serving_program_and_compile_gate():
    """THE acceptance gate for ragged serving: a full mixed serve (prefill
    chunks + plain decode + drafted verify rows, the mix shifting across 3
    waves) compiles ≤ 2 ``paged_*`` programs TOTAL, dispatches exactly one
    ragged program per scheduler step, never retraces a program after its
    first compile (3-wave retrace guard), and every compiled ragged
    program verifies clean under the donation, host-transfer, and
    dtype-promotion passes."""
    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.inference.scheduler import (
        PagedServer,
        compiled_serving_programs,
    )
    from deepspeed_tpu.inference.spec_decode import Drafter
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    class MixDrafter(Drafter):
        # per-request spec-K mix: row uid drafts uid % 3 tokens, so rounds
        # carry 0-, 1-, and 2-draft rows simultaneously
        def propose(self, uid, context, k):
            return np.arange(min(k, uid % 3), dtype=np.int32)

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()
    server = PagedServer(
        cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, telemetry=tel,
        spec_decode={"max_draft": 2}, drafter=MixDrafter(), prefix_cache=True,
    )
    assert server.ragged  # the default path is the one under the gate
    rs = np.random.RandomState(0)
    # 3 waves of shifting mixes: short prompts (single chunk), long prompts
    # (multi-chunk, so chunks ride WITH in-flight decoders), varying counts
    waves = [
        [rs.randint(0, 128, (int(n),)).astype(np.int32) for n in lens]
        for lens in ([5, 7], [19, 4, 22, 9], [13])
    ]
    compiles_after_wave = []
    for wave in waves:
        server.serve(wave, max_new_tokens=6)
        compiles_after_wave.append(
            sum(r["compiles"] for r in tel.stats().values())
        )
    assert server.stats["spec_rounds"] >= 1, "the mix never drafted"
    assert server.stats["prefill_chunks"] > len(
        [p for w in waves for p in w]
    ), "no multi-chunk prompt: prefill never coexisted with decode"
    stats = tel.stats()
    assert all(n.startswith("paged_ragged_") for n in stats), stats.keys()
    # THE gate: ≤ 2 compiled serving programs for the whole mixed serve
    assert compiled_serving_programs(stats) <= 2, stats
    # retrace guard: wave 1 compiled everything (warmup); waves 2 and 3
    # shifted the prefill/decode/verify mix without a single new trace
    assert compiles_after_wave[1] == compiles_after_wave[0], compiles_after_wave
    assert compiles_after_wave[2] == compiles_after_wave[0], compiles_after_wave
    for name, rec in stats.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    # exactly ONE dispatch per scheduler step
    assert sum(r["dispatches"] for r in stats.values()) == server.stats["ragged_steps"]
    # analysis green sweep: donation aliased, no host transfers, no upcasts
    rep = run_program_passes(tel)
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name in rep["programs"]:
        passes = rep["programs"][name]["passes"]
        assert passes["host_transfer"]["ok"]
        assert passes["dtype_promotion"]["ok"]
        assert passes["donation"]["ok"]


def test_green_tp_serving():
    """THE acceptance gate for multi-chip sharded serving (ISSUE 13): a
    full mixed serve (prefill chunks + decode + drafted verify rows, 3
    shifting waves) through a tp=4 tensor-parallel server with QUANTIZED
    all-reduces compiles ≤ 2 ``paged_*`` programs, dispatches exactly one
    sharded ragged program per scheduler step, never retraces, and every
    program verifies green under donation / host-transfer / dtype. The
    comm schedule is verified quantitatively: the int8 exchange's wire
    bytes are EXACTLY the fp tp=4 program's all-reduce wire bytes / 4 on
    the row-parallel projections (2·(g-1)/g·N int8 vs ·4N fp), equal to
    the analytic per-scan-body budget 2proj·2phase·(g-1)/g·R·W·H bytes, within a
    configured quantized budget, and every quantized loop collective is
    HIDDEN (``overlap_verified`` true — the chunked row matmul gives each
    exchange dependency-free MXU work)."""
    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.inference.scheduler import (
        PagedServer,
        compiled_serving_programs,
    )
    from deepspeed_tpu.inference.spec_decode import Drafter
    from deepspeed_tpu.inference.tp import TPServing, serving_mesh
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    class MixDrafter(Drafter):
        def propose(self, uid, context, k):
            return np.arange(min(k, uid % 3), dtype=np.int32)

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=4, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    G = 4  # tp degree
    rs = np.random.RandomState(0)
    waves = [
        [rs.randint(0, 128, (int(n),)).astype(np.int32) for n in lens]
        for lens in ([5, 7], [19, 4, 22, 9], [13])
    ]

    def serve_all(quantized):
        tel = CompileTelemetry()
        tp = TPServing(mesh=serving_mesh(G), quantized_allreduce=quantized)
        server = PagedServer(
            cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
            attn_impl="xla", dtype=jnp.float32, telemetry=tel,
            spec_decode={"max_draft": 2}, drafter=MixDrafter(),
            prefix_cache=True, tp=tp,
        )
        compiles = []
        outs = []
        for wave in waves:
            outs.append(server.serve(wave, max_new_tokens=6))
            compiles.append(sum(r["compiles"] for r in tel.stats().values()))
        return tel, server, compiles, outs

    telq, srvq, compiles_q, _ = serve_all(quantized=True)
    telf, srvf, _, _ = serve_all(quantized=False)
    assert srvq.stats["spec_rounds"] >= 1, "the mix never drafted"
    stats = telq.stats()
    assert all(n.startswith("paged_ragged_") for n in stats), stats.keys()
    # THE gate: ≤ 2 compiled serving programs, zero retraces, 1 dispatch/step
    assert compiled_serving_programs(stats) <= 2, stats
    assert compiles_q[1] == compiles_q[0] == compiles_q[2], compiles_q
    assert sum(r["dispatches"] for r in stats.values()) == srvq.stats["ragged_steps"]
    # green sweep on the QUANTIZED sharded programs
    rep = run_program_passes(telq)
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name, prog in rep["programs"].items():
        passes = prog["passes"]
        assert passes["host_transfer"]["ok"], name
        assert passes["dtype_promotion"]["ok"], name
        assert passes["donation"]["ok"], name
        # every quantized collective on the layer-scan hot path is HIDDEN
        ov = passes["overlap"]["summary"]
        assert ov["overlap_verified"] is True, (name, ov)
        assert ov["loop_quantized"] > 0, (name, ov)
        assert ov["loop_quantized_hidden"] == ov["loop_quantized"], (name, ov)
    # comm accounting: int8 exchange wire bytes == fp all-reduce wire / 4,
    # exactly — and exactly the analytic budget for the program's shape
    rep_f = run_program_passes(telf, passes=["collectives", "overlap"])
    wf = 2.0 * (G - 1) / G  # fp ring all-reduce wire factor
    for name, prog in rep["programs"].items():
        q = prog["passes"]["collectives"]["summary"]["quantized"]
        assert q["count"] > 0, name
        assert q["fp_equiv_wire_bytes"] == 4 * q["wire_bytes"], q
        fp_name = name.replace(f"_tp{G}q", f"_tp{G}")  # quantized -> fp build
        fp_sum = rep_f["programs"][fp_name]["passes"]["collectives"]["summary"]
        fp_ar_wire = int(round(fp_sum["ops"]["all-reduce"]["bytes"] * wf))
        assert fp_ar_wire == 4 * q["wire_bytes"], (name, fp_ar_wire, q)
        # analytic: 2 row-parallel projections × [R, W, H] int8 elements,
        # each moved twice at (g-1)/g (all-to-all + all-gather). The layer
        # scan's body appears ONCE in the static schedule — per-dispatch
        # wire cost is this × num_layers
        W = int(name.split("_w")[1].split("_")[0])
        R = 4  # max_slots: the ragged row budget
        analytic = int(round(2 * 2 * (G - 1) / G * R * W * cfg.hidden_size))
        assert q["wire_bytes"] == analytic, (name, q["wire_bytes"], analytic)
        # fp program's overlap also holds (chunked psum schedule)
        assert rep_f["programs"][fp_name]["passes"]["overlap"]["summary"][
            "overlap_verified"
        ] is True
    # the quantized-budget gate trips when configured below the schedule
    rep_bad = run_program_passes(
        telq, passes=["collectives"], config={"quantized_budget_bytes": 1}
    )
    assert any(
        not prog["passes"]["collectives"]["ok"]
        for prog in rep_bad["programs"].values()
    ), "quantized budget gate never fired"


def test_green_fleet_serving():
    """THE acceptance gate for fleet serving (ISSUE 12): a 3-replica
    fleet serving a shifting mix — including a chaos replica kill
    mid-serve — adds ZERO compiled programs beyond the single-replica
    ragged budget (≤ 2 ``paged_*`` programs TOTAL across every replica:
    uniform geometry + the shared program cache), never retraces after
    its first wave, keeps the ragged one-dispatch-per-step contract on
    every replica (dispatches/token unchanged vs a single replica —
    telemetry reconciles with the summed scheduler counters), the router
    itself is pure host code (lint DS-R010: no jax import in
    ``inference/fleet.py``), and every compiled program verifies clean
    under the donation / host-transfer / dtype passes."""
    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.analysis.source_lint import lint_paths
    from deepspeed_tpu.inference.fleet import FleetRouter, ReplicaHandle
    from deepspeed_tpu.inference.scheduler import (
        PagedServer,
        compiled_serving_programs,
    )
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.utils import chaos as chaos_mod

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()

    def replica():
        return PagedServer(
            cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
            attn_impl="xla", dtype=jnp.float32, telemetry=tel,
            prefix_cache=True,
        )

    router = FleetRouter(
        [ReplicaHandle(name=f"r{i}", server=replica()) for i in range(3)]
    )
    rs = np.random.RandomState(0)
    waves = [
        [rs.randint(0, 128, (int(n),)).astype(np.int32) for n in lens]
        for lens in ([5, 7, 11], [19, 4, 22, 9], [13, 6])
    ]
    compiles_after_wave = []
    for wi, wave in enumerate(waves):
        if wi == 1:
            # wave 2 serves across a replica kill: the survivors absorb
            # the dead replica's requests without a single new program
            chaos_mod.install(chaos_mod.ChaosSchedule(
                [chaos_mod.ChaosRule("fleet.replica_kill", hit=4)]
            ))
        try:
            outs = router.serve(wave, max_new_tokens=6)
        finally:
            chaos_mod.uninstall()
        assert all(o is not None for o in outs)
        compiles_after_wave.append(
            sum(r["compiles"] for r in tel.stats().values())
        )
    fs = router.fleet_stats()
    assert fs["replica_kills"] == 1, fs
    assert fs["n_active"] == 2
    assert fs["migrated_token_divergence"] == 0
    stats = tel.stats()
    assert all(n.startswith("paged_ragged_") for n in stats), stats.keys()
    # THE gate: the whole 3-replica fleet compiles no more programs than
    # one replica's ragged budget — replicas share the program cache
    assert compiled_serving_programs(stats) <= 2, stats
    # retrace guard: wave 1 compiled everything; the kill wave and the
    # recovery wave added nothing
    assert compiles_after_wave[1] == compiles_after_wave[0], compiles_after_wave
    assert compiles_after_wave[2] == compiles_after_wave[0], compiles_after_wave
    for name, rec in stats.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    # dispatches/token unchanged vs single replica: every replica still
    # runs ONE ragged dispatch per non-empty scheduler step, and the
    # fleet-summed telemetry reconciles exactly with the schedulers'
    # own dispatch counters (the router adds zero device work; the dead
    # replica's pre-kill dispatches stay in the merge)
    merged = router.serve_stats()
    inners = [h.inner for h in router.replicas.values()]
    assert sum(r["dispatches"] for r in stats.values()) == merged["dispatches"]
    assert merged["dispatches"] == sum(s.stats["dispatches"] for s in inners)
    assert merged["dispatches"] == sum(s.stats["ragged_steps"] for s in inners)
    # the router is pure host code: lint-enforced (DS-R010) on the real file
    fleet_path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))),
        "deepspeed_tpu", "inference", "fleet.py",
    )
    findings = lint_paths([fleet_path])
    assert [f.rule for f in findings] == [], [f.render() for f in findings]
    # analysis green sweep over every program the fleet dispatched
    rep = run_program_passes(tel)
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name in rep["programs"]:
        passes = rep["programs"][name]["passes"]
        assert passes["host_transfer"]["ok"]
        assert passes["dtype_promotion"]["ok"]
        assert passes["donation"]["ok"]


def test_green_multistep_window_program_and_compile_gate():
    """THE acceptance gate for multi-step windows (ISSUE 11): a full
    shifting-mix serve with ``multi_step`` armed compiles ≤ 4 ``paged_*``
    programs TOTAL (narrow + mixed + ONE window program for the armed
    horizon), never retraces after its first wave (3-wave retrace guard),
    measures steady-state dispatches/token ≤ 1/horizon through compile
    telemetry, and the window program verifies clean under the donation
    (the scan-carried page pools alias in place), host-transfer (windows
    add ZERO in-program host transfers — the packed ``[R, 1+N]`` token
    fetch is the one sanctioned fetch per window), and dtype-promotion
    passes."""
    from deepspeed_tpu.inference.scheduler import (
        PagedServer,
        compiled_serving_programs,
    )
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()
    H = 4
    server = PagedServer(
        cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, telemetry=tel,
        prefix_cache=True, multi_step={"enable": True, "horizon": H},
    )
    rs = np.random.RandomState(0)
    # 3 waves of shifting mixes: short prompts (single chunk), a long
    # prompt (multi-chunk prefill riding single-step dispatches before the
    # windows form), varying counts — budgets past the horizon so every
    # wave reaches the fused steady state
    waves = [
        [rs.randint(0, 128, (int(n),)).astype(np.int32) for n in lens]
        for lens in ([5, 7], [19, 4, 22, 9], [13])
    ]
    compiles_after_wave = []
    for wave in waves:
        server.serve(wave, max_new_tokens=3 * H + 1)
        compiles_after_wave.append(
            sum(r["compiles"] for r in tel.stats().values())
        )
    st = server.serve_stats()
    assert st["window_steps"] >= 3, "windows never reached steady state"
    assert st["window_break_reasons"]["prefill"] >= 1, "the mix never prefilled mid-serve"
    stats = tel.stats()
    assert any(n.startswith("paged_multistep_") for n in stats), stats.keys()
    # THE gate: ≤ 4 compiled serving programs for the whole windowed serve
    # (narrow + mixed + one window program per armed horizon, 1 horizon)
    assert compiled_serving_programs(stats) <= 4, stats
    assert sum(1 for n in stats if n.startswith("paged_multistep_")) == 1
    # retrace guard: wave 1 compiled everything (warmup); waves 2 and 3
    # shifted the prefill/decode/window mix without a single new trace
    assert compiles_after_wave[1] == compiles_after_wave[0], compiles_after_wave
    assert compiles_after_wave[2] == compiles_after_wave[0], compiles_after_wave
    for name, rec in stats.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    # dispatch amortization, via telemetry: every window was ONE dispatch
    # of the fused program covering H decode rounds per row — windows
    # alone account for ≥ H tokens each, the per-token form of the
    # dispatches/token ≤ 1/H steady-state bound (the per-segment
    # equality is pinned in test_multistep_serving.py)
    window_disp = sum(
        rec["dispatches"] for n, rec in stats.items()
        if n.startswith("paged_multistep_")
    )
    assert window_disp == st["window_steps"]
    assert window_disp * H <= st["emitted_tokens"]
    # telemetry reconciles with the scheduler's own dispatch counter
    assert sum(r["dispatches"] for r in stats.values()) == st["dispatches"]
    # analysis green sweep: donation aliased through the lax.scan carry,
    # no in-program host transfers, no silent upcasts
    rep = run_program_passes(tel)
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name in rep["programs"]:
        passes = rep["programs"][name]["passes"]
        assert passes["host_transfer"]["ok"]
        assert passes["dtype_promotion"]["ok"]
        assert passes["donation"]["ok"]


def test_green_multistep_training_program(eight_devices):
    """THE acceptance gate for multi-step TRAINING windows (ISSUE 14): a
    windowed ZeRO-3 gas=2 run compiles exactly ONE window program for the
    armed horizon, never retraces after its first window, reconciles
    telemetry dispatches with the engine's window stats (steady-state
    dispatches/opt-step ≤ 1/N), and the window program verifies clean
    under donation (the FULL state tuple — params, master, opt_state,
    loss-scale state — aliases through the lax.scan carry, zero
    double-buffered bytes), host_transfer (0 in-program transfers: the
    deferred loss drain is the one sanctioned fetch per window), dtype-
    promotion, and overlap passes."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from tests.unit.simple_model import SimpleModel

    mesh_mod.reset_topology()
    H = 4
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3},
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
            "compile": {
                "fuse_grad_accum": True,
                "multi_step": {"enable": True, "horizon": H},
            },
        },
    )
    rs = np.random.RandomState(0)

    def batches(n):
        return iter(
            [(rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
             for _ in range(2 * n)]
        )

    steps = 1 + 3 * H  # sequential init step + exactly 3 full windows
    it = batches(steps)
    compiles_after_window = []
    for s in range(steps):
        engine.train_batch(data_iter=it)
        compiles_after_window.append(
            sum(r["compiles"] for r in engine.compile_stats().values())
        )
    engine.flush_loss_drain()
    stats = engine.compile_stats()
    window_programs = [n for n in stats if n.startswith("fused_window_step")]
    assert window_programs == [f"fused_window_step_n{H}"], stats.keys()
    wrec = stats[window_programs[0]]
    assert wrec["compiles"] == 1 and wrec["traces"] == 1, wrec
    # no retrace after the first window (step 2 compiled it; every later
    # step added nothing)
    assert compiles_after_window[-1] == compiles_after_window[1], compiles_after_window
    ws = engine.window_stats()
    assert ws["window_steps"] == 3 and wrec["dispatches"] == 3
    assert ws["windowed_opt_steps"] == 3 * H
    # steady state: the windowed segment is exactly 1/H dispatches per step
    assert wrec["dispatches"] / ws["windowed_opt_steps"] == 1.0 / H
    assert ws["dispatches_per_opt_step"] <= 1.0 / H + 1.0 / ws["opt_steps"]
    # analysis green sweep on the window program: donation aliased through
    # the scan carry, 0 in-program host transfers, no silent upcasts, and
    # the overlap pass happy
    rep = engine.analysis_report()
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    wpasses = rep["programs"][window_programs[0]]["passes"]
    for pname in ("donation", "host_transfer", "dtype_promotion", "overlap"):
        assert wpasses[pname]["ok"], (pname, wpasses[pname])
    don = wpasses["donation"]["summary"]
    assert don["unhonored"] == 0 and don["double_buffered_bytes"] == 0, don
    assert don["declared_donations"] >= 4  # params+master+opt+scale leaves


def test_green_infinity_offload_program(eight_devices):
    """THE acceptance gate for streamed ZeRO-Infinity host offload
    (ISSUE 16): with pipeline_read AND pipeline_write on, the engine's
    declared stream schedule hides every H2D master/moment fetch and every
    D2H writeback behind a compute program — the overlap pass verifies the
    stream (nonzero bytes each way, ZERO exposed stream bytes) and the
    whole report stays green: no violations, donation honored on the
    per-bucket update programs, and the measured wall-clock agrees
    (exposed_ms == 0.0)."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from tests.unit.simple_model import SimpleModel, step_batch, train_steps_batch

    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 1,
                "offload_optimizer": {
                    "device": "cpu",
                    "pin_memory": True,
                    "pipeline_read": True,
                    "pipeline_write": True,
                    # 2 buckets on SimpleModel: real double-buffer depth
                    "bucket_size": 300,
                },
            },
            "bf16": {"enabled": True},
            "gradient_clipping": 1.0,
        },
    )
    batch = step_batch(batch_size=8, seed=0)
    train_steps_batch(engine, batch, 3)
    assert engine._streamed_offload
    rep = engine.analysis_report()
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    # the stream contract: every byte declared, every byte hidden
    assert t["stream_verified"] is True, rep
    assert t["stream_h2d_bytes"] > 0 and t["stream_d2h_bytes"] > 0
    assert t["exposed_stream_bytes"] == 0
    # and the clock agrees with the static verdict
    stats = engine.offload_stream_stats()
    assert stats["steps"] == 3 and stats["exposed_ms"] == 0.0


# ---------------------------------------------------------------------------
# jaxpr shape scan (the paged-attention structural guard's engine)
# ---------------------------------------------------------------------------
def test_find_aval_shapes_sees_through_control_flow():
    def f(x):
        def body(c, _):
            return c, jnp.broadcast_to(c, (3, 4, 4))  # materializes [3,4,4]

        _, ys = jax.lax.scan(body, x, jnp.arange(2))
        return ys

    jaxpr = jax.make_jaxpr(f)(jnp.ones((4, 4)))
    assert find_aval_shapes(jaxpr, (3, 4, 4))
    assert not find_aval_shapes(jaxpr, (9, 9, 9))


# ---------------------------------------------------------------------------
# static HBM ledger gates (ISSUE 18)
# ---------------------------------------------------------------------------
def test_green_memory_ledger_offload(eight_devices):
    """THE memory-ledger gate for streamed ZeRO-Infinity offload: the
    static residency ledger must reproduce the shipped claim — fp32
    master + both moments live in HOST RAM while the device-side
    optimizer footprint is bounded by TWO buckets (independent of model
    size), and master/opt_state never appear as device entries. The
    ``analysis.hbm_budget_bytes`` gate is red/green testable on the same
    engine: an impossible budget raises with per-buffer attribution, and
    the observability hub surfaces the same over-budget verdict without
    raising."""
    import pytest

    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.analysis import HbmBudgetError
    from tests.unit.simple_model import SimpleModel, step_batch, train_steps_batch

    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "zero_optimization": {
                "stage": 3,
                "offload_optimizer": {
                    "device": "cpu",
                    "pin_memory": True,
                    "pipeline_read": True,
                    "pipeline_write": True,
                    "bucket_size": 300,  # 2 buckets on SimpleModel
                },
            },
            "bf16": {"enabled": True},
        },
    )
    batch = step_batch(batch_size=8, seed=0)
    train_steps_batch(engine, batch, 3)
    assert engine._streamed_offload
    mem = engine.memory_report()
    entries = {e["name"]: e for e in mem["entries"]}
    # master + moments are HOST resident, exactly 3x the fp32 master bytes
    host = entries["offload_host_state"]
    assert host["location"] == "host"
    master_bytes = sum(m.nbytes for m in engine._host_offload._master)
    assert host["per_chip_bytes"] == 3 * master_bytes == mem["host_bytes"]
    # device-side optimizer footprint: bounded by the 2 largest buckets
    buckets = entries["offload_device_buckets"]
    assert buckets["location"] == "device"
    srep = engine._host_offload.memory_report()
    assert srep["buckets"] == 2
    assert buckets["per_chip_bytes"] == srep["device_residency_bound_bytes"]
    assert buckets["per_chip_bytes"] <= 2 * srep["max_bucket_bytes"]
    # the model-sized master/opt trees must NOT be device entries
    device_names = {e["name"] for e in mem["entries"] if e["location"] == "device"}
    assert "master" not in device_names and "opt_state" not in device_names
    assert "params" in device_names
    assert mem["hbm_budget_verified"] is None  # no budget configured
    # red: an impossible budget raises with per-buffer attribution
    engine._config.analysis_config.hbm_budget_bytes = 1
    with pytest.raises(HbmBudgetError) as ei:
        engine.memory_report()
    assert "params" in str(ei.value) and "bytes/chip" in str(ei.value)
    # the observability hub reads the SAME over-budget verdict, no raise
    obs = engine.observability(analysis=False)
    assert obs["memory"]["hbm_budget_verified"] is False
    # green: a budget above the ledger peak verifies
    engine._config.analysis_config.hbm_budget_bytes = (
        mem["peak_hbm_bytes_per_chip"] + 1
    )
    assert engine.memory_report()["hbm_budget_verified"] is True


def test_green_memory_ledger_tp_serving():
    """THE memory-ledger gate for tp=4 sharded serving: per-chip KV bytes
    are EXACTLY total/tp with the page tables host-side, and the memory
    pass run with the TP context's declared comm schedule + sharding
    rules finds zero undeclared resharding collectives and zero
    replicated-leaf violations across every compiled serving program.
    Red twin: an empty declared schedule flags the quantized exchanges as
    undeclared."""
    from deepspeed_tpu.analysis import run_program_passes
    from deepspeed_tpu.inference.scheduler import PagedServer
    from deepspeed_tpu.inference.tp import TPServing, serving_mesh
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=4, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    G = 4
    tel = CompileTelemetry()
    tp = TPServing(mesh=serving_mesh(G), quantized_allreduce=True)
    server = PagedServer(
        cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, telemetry=tel, tp=tp,
    )
    rs = np.random.RandomState(0)
    for lens in ([5, 7], [19, 4]):
        server.serve(
            [rs.randint(0, 128, (int(n),)).astype(np.int32) for n in lens],
            max_new_tokens=6,
        )
    # the ledger claim: KV bytes/chip == total/tp, page tables host-side
    prep = server.pool.memory_report()
    assert prep["kv_devices"] == G
    assert prep["kv_bytes_per_chip"] * G == prep["kv_total_bytes"]
    assert prep["page_table_location"] == "host"
    assert prep["host_table_bytes"] > 0
    # green: the declared schedule + sharding rules verify every program
    rep = run_program_passes(
        tel,
        passes=["memory"],
        config={
            "declared_collectives": tp.declared_collectives(),
            "sharding_rules": tp.sharding_rules(),
        },
    )
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["memory_verified"] is True
    assert t["undeclared_collectives"] == 0
    assert t["peak_hbm_bytes_per_chip"] > 0
    # red twin: the same programs against an EMPTY declared schedule —
    # every quantized exchange is now an undeclared reshard finding
    rep_red = run_program_passes(
        tel, passes=["memory"], config={"declared_collectives": []}
    )
    assert rep_red["totals"]["undeclared_collectives"] > 0
    assert rep_red["totals"]["memory_verified"] is False


def test_green_moe_programs(eight_devices):
    """THE acceptance gate for the expert-parallel MoE fast path (ISSUE 20).

    Training (ZeRO-3 + overlap_comm on a data×expert mesh): ONE compiled
    step program dispatching once per optimizer step, the full state tuple
    donated (zero double-buffered bytes), and EVERY dispatch/combine
    all-to-all hidden behind independent compute — ``overlap_verified``
    with an empty ``loop_exposed`` (exposed loop-collective bytes == 0).
    The int8-wire arm (``moe_quantized_a2a``) moves exactly fp/4 bytes on
    the wire: ``ops["all-to-all"]["quantized"]`` prices the EQuARX-style
    payloads against their fp32 equivalent, exact because fp32-vs-int8 is
    a pure dtype ratio.

    Serving: the SAME shifting-mix ragged serve as the dense gate, on an
    MoE model (top-2 + PR-MoE residual) — routing runs INSIDE the two
    paged programs (eval-mode gate, static capacity), so the compiled
    budget stays ≤ 2 ``paged_*`` programs, one dispatch per scheduler
    step, zero retraces as the expert-routing mix shifts."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.inference.scheduler import (
        PagedServer,
        compiled_serving_programs,
    )
    from deepspeed_tpu.models.moe_transformer import (
        MoETransformerConfig,
        MoETransformerLM,
    )

    # ---- training: 1 dispatch/step, donation green, every a2a hidden ----
    def train_a2a_summary(quantized):
        mesh_mod.reset_topology()
        # remat=False, flash_attention=False: the repo's CPU multi-device
        # convention (see tests/unit/runtime/zero/test_overlap.py) — the
        # interpret-mode flash loop and the remat transpose carry re-gather
        # sharded values per-iteration on this backend, which has nothing
        # to do with the MoE a2a schedule under test
        # use_residual (PR-MoE): the dense residual branch is the layer's
        # own independent compute — the dispatch a2a is emitted before it
        # and the combine before the next layer's gating, so the overlap
        # pass finds real work to hide the exchanges behind. fp32 keeps
        # the int8-vs-fp wire ratio an exact dtype ratio (= 4).
        cfg = MoETransformerConfig(
            vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
            max_seq_len=32, norm="rmsnorm", position="rope",
            activation="swiglu", use_bias=False, tie_embeddings=True,
            num_experts=4, moe_top_k=1, scan_layers=True, use_residual=True,
            dtype="float32",
            flash_attention=False, remat=False, moe_quantized_a2a=quantized,
        )
        engine, *_ = ds.initialize(
            model=MoETransformerLM(cfg),
            config={
                "train_micro_batch_size_per_gpu": 8,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 3, "overlap_comm": True},
                "mesh": {"data": 4, "expert": 2},
                "steps_per_print": 10_000,
            },
        )
        rs = np.random.RandomState(0)
        toks = rs.randint(0, cfg.vocab_size, (8, 33)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        steps = 3
        for _ in range(steps):
            engine.train_batch(batch=batch)
        step_rec = engine.compile_stats()["fused_step"]
        assert step_rec["compiles"] == 1, step_rec
        assert step_rec["dispatches"] == steps, step_rec
        rep = engine.analysis_report()
        t = rep["totals"]
        assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
        assert t["donation_verified"] is True
        passes = rep["programs"]["fused_step"]["passes"]
        don = passes["donation"]["summary"]
        assert don["unhonored"] == 0 and don["double_buffered_bytes"] == 0, don
        ov = passes["overlap"]["summary"]
        assert ov["overlap_verified"] is True, ov
        assert ov["loop_exposed"] == [], ov
        assert ov["loop_collectives"] > 0, ov  # the scan body has comms
        coll = passes["collectives"]["summary"]
        a2a = coll["ops"].get("all-to-all")
        assert a2a is not None and a2a["count"] > 0, sorted(coll["ops"])
        return a2a

    fp_a2a = train_a2a_summary(quantized=False)
    q_a2a = train_a2a_summary(quantized=True)
    assert fp_a2a["quantized"]["count"] == 0, fp_a2a
    q = q_a2a["quantized"]
    # the scanned layer body appears once in the static schedule: fwd
    # dispatch + fwd combine + their two transposes = 4 int8 exchanges
    assert q["count"] == 4, q_a2a
    assert q["wire_bytes"] > 0, q_a2a
    # THE wire gate: int8 a2a bytes == fp equivalent / 4, exactly
    assert q["fp_equiv_wire_bytes"] == 4 * q["wire_bytes"], q

    # ---- serving: routing inside the ragged paged programs --------------
    mesh_mod.reset_topology()
    scfg = MoETransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
        num_experts=4, moe_top_k=2, use_residual=True,
    )
    model = MoETransformerLM(scfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, scfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    assert "moe" in params["layers"]  # routing params ride the layer scan
    tel = CompileTelemetry()
    server = PagedServer(
        scfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, telemetry=tel,
    )
    rs = np.random.RandomState(0)
    waves = [
        [rs.randint(0, 128, (int(n),)).astype(np.int32) for n in lens]
        for lens in ([5, 7], [19, 4, 22, 9], [13])
    ]
    compiles_after = []
    for wave in waves:
        server.serve(wave, max_new_tokens=6)
        compiles_after.append(sum(r["compiles"] for r in tel.stats().values()))
    stats = tel.stats()
    assert all(n.startswith("paged_ragged_") for n in stats), stats.keys()
    assert compiled_serving_programs(stats) <= 2, stats
    # zero retraces over the shifting expert-routing mix: capacity is a
    # Python int from the static row budget, routing is pure data
    assert compiles_after[1] == compiles_after[0] == compiles_after[2], compiles_after
    for name, rec in stats.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    # one dispatch per scheduler step
    assert sum(r["dispatches"] for r in stats.values()) == server.stats["ragged_steps"]
    rep = run_program_passes(tel)
    t = rep["totals"]
    assert t["analysis_failures"] == 0 and t["violations"] == 0, rep
    assert t["donation_verified"] is True
    for name in rep["programs"]:
        passes = rep["programs"][name]["passes"]
        assert passes["host_transfer"]["ok"], name
        assert passes["dtype_promotion"]["ok"], name
        assert passes["donation"]["ok"], name
