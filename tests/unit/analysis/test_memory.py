"""Red/green fixtures for the static HBM layer (ISSUE 18): the per-program
peak estimator (backend stats + HLO-walk fallback), the sharding auditor
(replicated-leaf and undeclared-collective findings), the ``memory``
program pass's budget gate, and the whole-run :class:`MemoryLedger` behind
``engine.memory_report()`` / ``analysis.hbm_budget_bytes``.
"""

from __future__ import annotations

import logging
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.analysis import (
    HbmBudgetError,
    MemoryLedger,
    ProgramArtifact,
    analyze_program,
    audit_sharding,
    estimate_program_memory,
    run_program_passes,
    tree_device_bytes,
)
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry


def _dispatch(tel, name, fn, *args, **jit_kwargs):
    wrapped = tel.instrument(name, fn, **jit_kwargs)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        wrapped(*args)
    return wrapped


def _art(tel, name) -> ProgramArtifact:
    return ProgramArtifact(name, tel.programs()[name])


class _NoBackendStats:
    """An artifact view whose executable refuses ``memory_analysis()`` —
    forces the estimator down the optimized-HLO buffer walk."""

    def __init__(self, art: ProgramArtifact):
        self.name = art.name
        self.hlo_text = art.hlo_text
        self._wrapper = art._wrapper

    @property
    def compiled(self):
        raise RuntimeError("backend provides no buffer-assignment stats")


# ---------------------------------------------------------------------------
# per-program estimator
# ---------------------------------------------------------------------------
def test_estimator_accounts_argument_and_output_bytes():
    """peak = args + out + temp − alias, and the argument side must cover
    the real input buffers (one 128×128 f32 = 64 KiB here)."""
    tel = CompileTelemetry()

    def f(x):
        return x * 2.0

    _dispatch(tel, "mul", f, jnp.ones((128, 128), jnp.float32))
    est = estimate_program_memory(_art(tel, "mul"))
    assert est["source"] in ("memory_analysis", "hlo_walk")
    assert est["argument_bytes"] >= 128 * 128 * 4
    assert est["output_bytes"] >= 128 * 128 * 4
    assert est["peak_hbm_bytes"] == max(
        est["argument_bytes"]
        + est["output_bytes"]
        + est["temp_bytes"]
        - est["alias_bytes"],
        0,
    )


def test_estimator_hlo_walk_fallback_matches_buffers():
    """With backend stats unavailable the HLO walk must reconstruct the
    same argument/output accounting from the ENTRY computation."""
    tel = CompileTelemetry()

    def f(x, y):
        return x + y.sum()

    _dispatch(
        tel, "walk", f, jnp.ones((64, 64), jnp.float32), jnp.ones((32,), jnp.float32)
    )
    est = estimate_program_memory(_NoBackendStats(_art(tel, "walk")))
    assert est["source"] == "hlo_walk"
    assert est["argument_bytes"] == 64 * 64 * 4 + 32 * 4
    assert est["output_bytes"] >= 64 * 64 * 4
    assert est["temp_bytes"] == 0  # unknowable from text: lower bound


def test_estimator_hlo_walk_dedups_donation_alias():
    """A donated-and-honored input must be subtracted once via the
    input_output_alias table (when the backend honors the donation the
    walk's alias bytes cover the reused parameter)."""
    tel = CompileTelemetry()

    def f(big, x):
        return big + 1.0, x * 2.0

    _dispatch(
        tel,
        "don",
        f,
        jnp.ones((256, 256), jnp.float32),
        jnp.ones((8,), jnp.float32),
        donate_argnums=(0,),
    )
    art = _art(tel, "don")
    est = estimate_program_memory(_NoBackendStats(art))
    from deepspeed_tpu.analysis.hlo import parse_input_output_aliases

    aliased = parse_input_output_aliases(art.hlo_text)
    if aliased:  # CPU may decline the alias; when honored, it must dedup
        assert est["alias_bytes"] >= 256 * 256 * 4
        assert est["peak_hbm_bytes"] < est["argument_bytes"] + est["output_bytes"]
    else:
        assert est["alias_bytes"] == 0


# ---------------------------------------------------------------------------
# sharding auditor
# ---------------------------------------------------------------------------
def test_audit_red_replicated_leaf_against_rule(eight_devices):
    """A large leaf left fully replicated on a 4-chip mesh when a declared
    sharding rule matches it must be an error finding; the properly
    sharded leaf must not."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    sharded = jax.device_put(
        jnp.zeros((64, 128), jnp.float32), NamedSharding(mesh, P(None, "model"))
    )
    replicated = jax.device_put(
        jnp.zeros((64, 128), jnp.float32), NamedSharding(mesh, P(None, None))
    )
    tel = CompileTelemetry()

    def f(a, b):
        return a.sum() + b.sum()

    _dispatch(tel, "aud", f, sharded, replicated)
    summary, violations = audit_sharding(
        _art(tel, "aud"), rules=[{"pattern": "", "min_bytes": 1024}]
    )
    assert summary["mesh_devices"] == 4
    assert summary["replicated_bytes"] == 64 * 128 * 4
    assert summary["sharded_bytes"] == 64 * 128 * 4 // 4
    assert len(violations) == 1, [v.message for v in violations]
    assert "replicated" in violations[0].message


def test_audit_green_no_rules_is_summary_only(eight_devices):
    """No declared rules/schedule → the auditor summarizes, flags nothing
    (the default-config contract the green sweeps rely on)."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    replicated = jax.device_put(
        jnp.zeros((64, 128), jnp.float32), NamedSharding(mesh, P(None, None))
    )
    tel = CompileTelemetry()
    _dispatch(tel, "quiet", lambda a: a.sum(), replicated)
    summary, violations = audit_sharding(_art(tel, "quiet"))
    assert violations == []
    assert "undeclared_collectives" in summary


def test_audit_undeclared_collective_red_and_green(eight_devices):
    """A cross-chip reduction the declared comm schedule does not contain
    is a red finding; declaring it clears the same program."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    x = jax.device_put(
        jnp.ones((64, 8), jnp.float32), NamedSharding(mesh, P("model", None))
    )
    tel = CompileTelemetry()

    def f(x):
        return x - jnp.mean(x)  # mean over the sharded axis → all-reduce

    _dispatch(tel, "reshard", f, x)
    art = _art(tel, "reshard")
    _, red = audit_sharding(art, declared_collectives=[])
    assert red, "pjit-inserted collective not flagged against an empty schedule"
    assert any("all-reduce" in v.message for v in red)
    _, green = audit_sharding(art, declared_collectives=["all-reduce"])
    assert green == []


# ---------------------------------------------------------------------------
# the memory pass + budget gate
# ---------------------------------------------------------------------------
def test_memory_pass_default_config_summary_only():
    tel = CompileTelemetry()
    _dispatch(tel, "plain", lambda x: x + 1.0, jnp.ones((32, 32)))
    res = analyze_program("plain", tel.programs()["plain"], passes=["memory"])[
        "memory"
    ]
    assert res.ok and not res.violations
    assert res.summary["estimate"]["peak_hbm_bytes"] > 0


def test_memory_pass_budget_red_green():
    tel = CompileTelemetry()
    _dispatch(tel, "budget", lambda x: x * 3.0, jnp.ones((64, 64), jnp.float32))
    fn = tel.programs()["budget"]
    red = analyze_program(
        "budget", fn, passes=["memory"], config={"hbm_budget_bytes": 16}
    )["memory"]
    assert not red.ok
    assert "exceeds analysis.hbm_budget_bytes=16" in red.violations[0].message
    off = analyze_program(
        "budget",
        fn,
        passes=["memory"],
        config={"hbm_budget_bytes": 16, "hbm_budget": "off"},
    )["memory"]
    assert off.ok
    green = analyze_program(
        "budget", fn, passes=["memory"], config={"hbm_budget_bytes": 1 << 30}
    )["memory"]
    assert green.ok


def test_report_totals_aggregate_memory(eight_devices):
    """run_program_passes totals must carry the memory tri-state + the
    per-chip peak / replicated-bytes aggregates the bench records read."""
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    replicated = jax.device_put(
        jnp.zeros((32, 64), jnp.float32), NamedSharding(mesh, P(None, None))
    )
    tel = CompileTelemetry()
    _dispatch(tel, "tot", lambda a: a * 2.0, replicated)
    rep = run_program_passes(tel, passes=["memory"])
    t = rep["totals"]
    assert t["memory_verified"] is True
    assert t["peak_hbm_bytes_per_chip"] > 0
    assert t["replicated_bytes"] == 32 * 64 * 4
    assert t["undeclared_collectives"] == 0
    # a report that never ran the memory pass must stay tri-state None
    rep2 = run_program_passes(tel, passes=["donation"])
    assert rep2["totals"]["memory_verified"] is None


# ---------------------------------------------------------------------------
# the residency ledger
# ---------------------------------------------------------------------------
def test_ledger_peak_model_and_attribution():
    led = MemoryLedger(hbm_budget_bytes=1000, mode="raise")
    led.add_persistent("params", per_chip_bytes=600, kind="params")
    led.add_persistent("opt_host", per_chip_bytes=5000, location="host")
    led.add_program(
        "step",
        {"argument_bytes": 600, "output_bytes": 700, "alias_bytes": 600, "temp_bytes": 50},
    )
    rep = led.report()
    # host bytes never count toward the device peak
    assert rep["peak_hbm_bytes_per_chip"] == 600 + (50 + 100)
    assert rep["host_bytes"] == 5000
    assert rep["hbm_budget_verified"] is True
    led.hbm_budget_bytes = 700
    with pytest.raises(HbmBudgetError) as ei:
        led.enforce()
    msg = str(ei.value)
    assert "params" in msg and "600" in msg  # per-buffer attribution
    assert "step" in msg  # transient attribution


def test_ledger_warn_and_off_modes(caplog):
    led = MemoryLedger(hbm_budget_bytes=10, mode="warn")
    led.add_persistent("big", per_chip_bytes=100)
    log = logging.getLogger("test_ledger_warn")
    with caplog.at_level(logging.WARNING, logger="test_ledger_warn"):
        rep = led.enforce(logger=log)  # must not raise
    assert rep["hbm_budget_verified"] is False
    assert any("exceeds" in r.message for r in caplog.records)
    led.mode = "off"
    rep = led.enforce()
    assert rep["hbm_budget_verified"] is None


def test_tree_device_bytes_sharded_vs_replicated(eight_devices):
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("model",))
    tree = {
        "w": jax.device_put(
            jnp.zeros((16, 64), jnp.float32), NamedSharding(mesh, P(None, "model"))
        ),
        "b": jax.device_put(
            jnp.zeros((64,), jnp.float32), NamedSharding(mesh, P(None))
        ),
    }
    acct = tree_device_bytes(tree)
    assert acct["global_bytes"] == 16 * 64 * 4 + 64 * 4
    assert acct["per_chip_bytes"] == 16 * 64 * 4 // 4 + 64 * 4
    assert acct["replicated_bytes"] == 64 * 4
