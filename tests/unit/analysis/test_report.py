"""Green sweep (ISSUE 3 acceptance): the real engine programs pass every
analysis pass clean — donation verified on each step flavor (fused gas=1,
fused-accum gas>1, unfused fwd_bwd+step, fp16 and bf16) and on the paged
serving programs; zero host transfers in any hot-loop program; zero f32
upcast-compute sites; collective schedule extracted with nonzero traffic on
the 8-device training mesh. Plus the ``analysis.verify`` knob contract:
``warn``/``raise`` run at first compile without breaking a clean engine,
and ``raise`` actually raises on a violating program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.analysis import AnalysisError, run_program_passes
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry
from tests.unit.simple_model import (
    SimpleModel,
    step_batch,
    train_steps_batch,
    train_steps_micro,
)


def _engine(**over):
    mesh_mod.reset_topology()
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }
    base.update(over)
    engine, *_ = ds.initialize(model=SimpleModel(), config=base)
    return engine


def _assert_clean(report, expect_programs):
    assert set(expect_programs) <= set(report["programs"]), report["programs"].keys()
    t = report["totals"]
    assert t["analysis_failures"] == 0, report
    assert t["violations"] == 0, [
        v
        for e in report["programs"].values()
        for p in e.get("passes", {}).values()
        for v in p["violations"]
    ]
    assert t["donation_verified"] is True
    for name in expect_programs:
        passes = report["programs"][name]["passes"]
        assert passes["host_transfer"]["ok"]
        assert passes["dtype_promotion"]["ok"]
        assert passes["donation"]["ok"]


def test_green_fused_step_bf16(eight_devices):
    """gas=1 bf16: the fused forward+optimizer program verifies clean and
    its dp collective schedule is nonempty (grad reduction exists)."""
    engine = _engine()
    train_steps_batch(engine, step_batch(batch_size=8), 2)
    rep = engine.analysis_report()
    _assert_clean(rep, ["fused_step"])
    assert rep["totals"]["collective_count"] >= 1
    assert rep["totals"]["collective_bytes"] > 0


def test_green_fused_accum_step(eight_devices):
    """gas=4 fused scan program: donation of the full state tuple verified
    statically (what test_fused_grad_accum asserted via is_deleted)."""
    engine = _engine(
        gradient_accumulation_steps=4, compile={"fuse_grad_accum": True}
    )
    train_steps_batch(engine, step_batch(batch_size=32), 2)
    rep = engine.analysis_report()
    _assert_clean(rep, ["fused_accum_step"])
    don = rep["programs"]["fused_accum_step"]["passes"]["donation"]["summary"]
    assert don["declared_donations"] >= 4  # params+master+opt+scale_state leaves
    assert don.get("unhonored", 0) == 0


def test_green_unfused_fp16_step(eight_devices):
    """fp16 gas=2 per-microbatch protocol: fwd_bwd (accumulator donation)
    and the full-state step program both verify clean."""
    engine = _engine(
        gradient_accumulation_steps=2,
        bf16={"enabled": False},
        fp16={"enabled": True, "initial_scale_power": 4},
    )
    train_steps_micro(engine, step_batch(batch_size=16), 2)
    rep = engine.analysis_report()
    _assert_clean(rep, ["fwd_bwd", "step"])


def test_green_fp32_single_buffer_step(eight_devices):
    """fp32 (params IS master): the single-buffer donation contract."""
    engine = _engine(bf16={"enabled": False})
    train_steps_batch(engine, step_batch(batch_size=8), 1)
    rep = engine.analysis_report()
    _assert_clean(rep, ["fused_step"])


def test_green_paged_serving_programs():
    """The serving programs (paged decode per bucket, chunked prefill)
    verify clean: donated page buffers aliased, no host callback, no
    upcast compute."""
    from deepspeed_tpu.inference.scheduler import PagedServer
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
        activation="swiglu", use_bias=False, tie_embeddings=False,
        flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    tel = CompileTelemetry()
    server = PagedServer(
        cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, telemetry=tel,
        ragged=False,  # the bucketed oracle's decode/prefill programs
    )
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 128, (7,)).astype(np.int32) for _ in range(3)]
    server.serve(prompts, max_new_tokens=4)
    rep = run_program_passes(tel)
    names = set(rep["programs"])
    assert any(n.startswith("paged_decode_") for n in names), names
    assert any(n.startswith("paged_prefill_") for n in names), names
    _assert_clean(rep, sorted(names))


def test_verify_warn_and_raise_clean_engine(eight_devices):
    """analysis.verify on a clean engine: first compile runs the passes
    (visible as extra traces, not extra counted compiles) and training
    proceeds normally under both modes."""
    for mode in ("warn", "raise"):
        engine = _engine(analysis={"verify": mode})
        losses = train_steps_batch(engine, step_batch(batch_size=8), 2)
        assert np.isfinite(losses).all()
        stats = engine.compile_stats()["fused_step"]
        assert stats["compiles"] == 1 and stats["dispatches"] == 2, stats


def test_verify_raise_trips_on_violation():
    """verify=raise must fail fast when a program violates a pass — driven
    through the same telemetry hook the engines install."""
    from deepspeed_tpu.analysis import raise_or_warn

    tel = CompileTelemetry()

    def on_compile(name):
        report = run_program_passes(tel, programs=[name], passes=["host_transfer"])
        raise_or_warn(report, "raise")

    tel.on_compile = on_compile

    def bad(x):
        return jax.pure_callback(
            lambda a: np.asarray(a) + 1.0, jax.ShapeDtypeStruct((4,), jnp.float32), x
        )

    f = tel.instrument("bad", bad)
    with pytest.raises(AnalysisError):
        f(jnp.ones((4,)))


def test_invalid_verify_mode_rejected():
    with pytest.raises(Exception):
        _engine(analysis={"verify": "everything"})
