"""Autotuner tests (reference: ``tests/unit/autotuning/test_autotuning.py``)."""

from __future__ import annotations

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, GridSearchTuner, RandomTuner
from tests.unit.simple_model import SimpleModel


def _batch_factory(n):
    rs = np.random.RandomState(0)
    return (rs.randn(n, 16).astype(np.float32), rs.randn(n, 16).astype(np.float32))


BASE = {
    "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
    "steps_per_print": 1000,
}


def _tuner(**kw):
    return Autotuner(
        model_factory=lambda: SimpleModel(hidden_dim=16),
        base_config=BASE,
        batch_factory=_batch_factory,
        micro_batches=kw.pop("micro_batches", [1, 2]),
        stages=kw.pop("stages", [0, 1]),
        trial_steps=2,
        warmup_steps=1,
        **kw,
    )


class TestTuners:
    def test_grid_exhausts_in_order(self):
        exps = [{"i": i} for i in range(5)]
        t = GridSearchTuner(exps)
        seen = []
        while t.has_next():
            seen += t.next_batch(2)
        assert [e["i"] for e in seen] == [0, 1, 2, 3, 4]

    def test_random_is_permutation(self):
        exps = [{"i": i} for i in range(10)]
        t = RandomTuner(exps, seed=1)
        seen = []
        while t.has_next():
            seen += t.next_batch(3)
        assert sorted(e["i"] for e in seen) == list(range(10))


class TestAutotuner:
    def test_model_info(self):
        info = _tuner().model_info()
        assert info["num_params"] == 2 * 16 * 16

    def test_generate_experiments_grid(self):
        exps = _tuner().generate_experiments()
        assert len(exps) == 4  # 2 stages × 2 micro batches
        combos = {
            (e["zero_optimization"]["stage"], e["train_micro_batch_size_per_gpu"])
            for e in exps
        }
        assert combos == {(0, 1), (0, 2), (1, 1), (1, 2)}

    def test_memory_filter(self):
        t = _tuner(hbm_bytes=10)  # nothing fits in 10 bytes
        assert t.generate_experiments() == []

    def test_tune_end_to_end(self):
        best = _tuner().tune()
        assert best is not None
        assert best["throughput_samples_per_s"] > 0
        assert best["config"]["zero_optimization"]["stage"] in (0, 1)
