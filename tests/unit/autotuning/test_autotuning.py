"""Autotuner tests (reference: ``tests/unit/autotuning/test_autotuning.py``)."""

from __future__ import annotations

import numpy as np
import pytest

from deepspeed_tpu.autotuning import Autotuner, GridSearchTuner, RandomTuner
from tests.unit.simple_model import SimpleModel


def _batch_factory(n):
    rs = np.random.RandomState(0)
    return (rs.randn(n, 16).astype(np.float32), rs.randn(n, 16).astype(np.float32))


BASE = {
    "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
    "steps_per_print": 1000,
}


def _tuner(**kw):
    return Autotuner(
        model_factory=lambda: SimpleModel(hidden_dim=16),
        base_config=BASE,
        batch_factory=_batch_factory,
        micro_batches=kw.pop("micro_batches", [1, 2]),
        stages=kw.pop("stages", [0, 1]),
        trial_steps=2,
        warmup_steps=1,
        **kw,
    )


class TestTuners:
    def test_grid_exhausts_in_order(self):
        exps = [{"i": i} for i in range(5)]
        t = GridSearchTuner(exps)
        seen = []
        while t.has_next():
            seen += t.next_batch(2)
        assert [e["i"] for e in seen] == [0, 1, 2, 3, 4]

    def test_random_is_permutation(self):
        exps = [{"i": i} for i in range(10)]
        t = RandomTuner(exps, seed=1)
        seen = []
        while t.has_next():
            seen += t.next_batch(3)
        assert sorted(e["i"] for e in seen) == list(range(10))


class TestAutotuner:
    def test_model_info(self):
        info = _tuner().model_info()
        assert info["num_params"] == 2 * 16 * 16

    def test_generate_experiments_grid(self):
        exps = _tuner().generate_experiments()
        assert len(exps) == 4  # 2 stages × 2 micro batches
        combos = {
            (e["zero_optimization"]["stage"], e["train_micro_batch_size_per_gpu"])
            for e in exps
        }
        assert combos == {(0, 1), (0, 2), (1, 1), (1, 2)}

    def test_memory_filter(self):
        t = _tuner(hbm_bytes=10)  # nothing fits in 10 bytes
        assert t.generate_experiments() == []

    def test_tune_end_to_end(self):
        best = _tuner().tune()
        assert best is not None
        assert best["throughput_samples_per_s"] > 0
        assert best["config"]["zero_optimization"]["stage"] in (0, 1)


class TestConfigTemplates:
    def test_templates_per_stage(self):
        from deepspeed_tpu.autotuning import STAGE_TEMPLATES, template_for_stage

        assert set(STAGE_TEMPLATES) == {0, 1, 2, 3}
        t3 = template_for_stage(3)
        assert t3["zero_optimization"]["overlap_comm"] is True
        t3["zero_optimization"]["stage"] = 99  # copies, not shared state
        assert STAGE_TEMPLATES[3]["zero_optimization"]["stage"] == 3

    def test_user_values_win_over_template(self):
        from deepspeed_tpu.autotuning import candidate_configs

        base = {
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"reduce_bucket_size": 123},
        }
        cfgs = candidate_configs(base, stages=[2], micro_batches=[1, 4])
        assert len(cfgs) == 2
        for cfg in cfgs:
            assert cfg["zero_optimization"]["stage"] == 2
            assert cfg["zero_optimization"]["reduce_bucket_size"] == 123  # user wins
            assert cfg["zero_optimization"]["reduce_scatter"] is True  # template fills
            assert cfg["optimizer"]["params"]["lr"] == 1e-3
        assert [c["train_micro_batch_size_per_gpu"] for c in cfgs] == [1, 4]


class TestResourceManager:
    def test_schedules_and_tracks_status(self):
        from deepspeed_tpu.autotuning import ExpStatus, ResourceManager

        def run_fn(cfg):
            if cfg.get("boom"):
                raise RuntimeError("exploded")
            if cfg.get("none"):
                return None
            return {"throughput": cfg["id"] * 10}

        rm = ResourceManager(run_fn)
        rm.schedule_all([{"id": 1}, {"id": 3}, {"boom": True}, {"none": True}])
        rm.run()
        statuses = [e.status for e in rm.experiments]
        assert statuses == [
            ExpStatus.DONE,
            ExpStatus.DONE,
            ExpStatus.FAILED,
            ExpStatus.FAILED,
        ]
        assert "exploded" in rm.experiments[2].error
        best = rm.best(key=lambda r: r["throughput"])
        assert best.config["id"] == 3
        summary = rm.summary()
        assert len(summary) == 4 and summary[0]["status"] == "done"

    def test_multi_slot_pool(self):
        from deepspeed_tpu.autotuning import ResourceManager

        import threading

        seen = set()

        def run_fn(cfg):
            seen.add(threading.get_ident())
            return {"v": cfg["id"]}

        rm = ResourceManager(run_fn, num_slots=3)
        rm.schedule_all([{"id": i} for i in range(6)])
        rm.run()
        assert len(rm.successful()) == 6


class TestSubprocessTrials:
    """Reference scheduler.run_job parity: isolated per-experiment
    processes with timeout + a persisted session record."""

    USER_SCRIPT = '''
import numpy as np
from tests.unit.simple_model import SimpleModel

def model_factory():
    return SimpleModel(hidden_dim=16)

def batch_factory(n):
    rs = np.random.RandomState(0)
    return (rs.randn(max(n, 8), 16).astype(np.float32),
            rs.randn(max(n, 8), 16).astype(np.float32))

base_config = {
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
    "steps_per_print": 10000,
}
'''

    def _write_script(self, tmp_path):
        import os

        script = tmp_path / "user_tuning.py"
        script.write_text(self.USER_SCRIPT)
        return str(script)

    def _cpu_env(self):
        import os

        repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
        return {
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
            "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
        }

    def test_subprocess_trial_runs(self, tmp_path):
        from deepspeed_tpu.autotuning.scheduler import SubprocessTrialRunner

        runner = SubprocessTrialRunner(
            self._write_script(tmp_path),
            trial_steps=2,
            warmup_steps=1,
            timeout_s=300,
            env=self._cpu_env(),
            log_path=str(tmp_path / "trial.log"),
        )
        config = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 10000,
        }
        result = runner(config)
        assert result is not None, (tmp_path / "trial.log").read_text()[-2000:]
        assert result["throughput_samples_per_s"] > 0

    def test_timeout_kills_trial(self, tmp_path):
        from deepspeed_tpu.autotuning.scheduler import SubprocessTrialRunner

        script = tmp_path / "hang.py"
        script.write_text("import time\ntime.sleep(600)\n")
        runner = SubprocessTrialRunner(str(script), timeout_s=3, env=self._cpu_env())
        assert runner({"train_micro_batch_size_per_gpu": 1}) is None

    def test_session_record(self, tmp_path):
        import json

        from deepspeed_tpu.autotuning.autotuner import Autotuner
        from tests.unit.simple_model import SimpleModel
        import numpy as np
        import deepspeed_tpu.parallel.mesh as mesh_mod

        mesh_mod.reset_topology()

        def batch_factory(n):
            rs = np.random.RandomState(0)
            return (rs.randn(max(n, 8), 16).astype(np.float32),
                    rs.randn(max(n, 8), 16).astype(np.float32))

        tuner = Autotuner(
            lambda: SimpleModel(hidden_dim=16),
            {
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "steps_per_print": 10000,
            },
            batch_factory,
            micro_batches=[1],
            stages=[0, 1],
            trial_steps=2,
            warmup_steps=1,
            session_dir=str(tmp_path / "session"),
        )
        best = tuner.tune()
        assert best is not None
        summary = json.loads((tmp_path / "session" / "session_summary.json").read_text())
        assert len(summary) == 2
        assert all(row["status"] in ("done", "failed") for row in summary)
        best_rec = json.loads((tmp_path / "session" / "best_config.json").read_text())
        assert best_rec["throughput_samples_per_s"] > 0

    def test_subprocess_requires_script(self):
        import pytest

        from deepspeed_tpu.autotuning.autotuner import Autotuner

        with pytest.raises(ValueError, match="user_script"):
            Autotuner(lambda: None, {}, lambda n: None, isolation="subprocess")
