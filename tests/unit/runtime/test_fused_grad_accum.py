"""Fused gradient-accumulation step (``compile.fuse_grad_accum``).

Covers the PR acceptance contract: numerical parity between the fused
single-dispatch scan program and the unfused per-microbatch path at
gas ∈ {1, 2, 4} (fp32 + bf16, fp16 overflow-revert included), and the
single-dispatch guarantee — with fuse on and gas=4, exactly one jitted
train program executes per optimizer step, verified by the compile
telemetry counters. Runs comm-free on the 8-device virtual CPU mesh.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import (
    SimpleModel,
    master_snapshot,
    step_batch,
    train_steps_batch,
    train_steps_micro,
)

STEPS = 3


def _cfg(gas, fuse, **over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 1},
        "compile": {"fuse_grad_accum": fuse},
        "gradient_clipping": 1.0,
    }
    base.update(over)
    return base


def _engine(gas, fuse, **over):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg(gas, fuse, **over))
    return engine


def _full_batch(gas):
    # micro=1 per chip × 8 chips × gas microbatches
    return step_batch(batch_size=8 * gas, seed=0)


@pytest.mark.parametrize("gas", [1, 2, 4])
@pytest.mark.parametrize("precision", ["fp32", "bf16"])
def test_fused_unfused_parity(gas, precision, eight_devices):
    """Loss, grad norm, and master params after N steps match between the
    fused scan program and the per-microbatch fallback."""
    over = {"bf16": {"enabled": True}} if precision == "bf16" else {}
    batch = _full_batch(gas)
    ref = _engine(gas, fuse=False, **over)
    ref_losses = train_steps_batch(ref, batch, STEPS)
    fused = _engine(gas, fuse=True, **over)
    fused_losses = train_steps_batch(fused, batch, STEPS)
    np.testing.assert_allclose(fused_losses, ref_losses, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(
        fused.get_global_grad_norm(), ref.get_global_grad_norm(), rtol=1e-5
    )
    ref_w = master_snapshot(ref)
    fused_w = master_snapshot(fused)
    for k in ref_w:
        np.testing.assert_allclose(fused_w[k], ref_w[k], rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("gas", [2, 4])
def test_fused_unfused_parity_fp16_overflow_revert(gas, eight_devices):
    """An inf in one microbatch makes the whole fused step a no-op exactly
    like the unfused path: params reverted, step skipped, scale halved."""
    over = {"fp16": {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}}
    batch = _full_batch(gas)
    x, y = batch
    xbad = x.copy()
    xbad[0, 0] = np.inf
    engines = {}
    for fuse in (False, True):
        e = _engine(gas, fuse=fuse, **over)
        good = train_steps_batch(e, batch, 1)
        w_good = master_snapshot(e)
        e.train_batch(batch=(xbad, y))
        assert e.skipped_steps == 1, f"fuse={fuse}: overflow step not skipped"
        assert e.loss_scale == 8.0  # 16 / 2 after overflow with hysteresis=1
        w_after = master_snapshot(e)
        for k in w_good:
            np.testing.assert_array_equal(w_after[k], w_good[k])
        engines[fuse] = (good, master_snapshot(e))
    np.testing.assert_allclose(engines[True][0], engines[False][0], rtol=1e-4)
    for k in engines[False][1]:
        np.testing.assert_allclose(
            engines[True][1][k], engines[False][1][k], rtol=1e-4, atol=1e-5
        )


def test_single_dispatch_per_step_gas4(eight_devices):
    """Acceptance: fuse on + gas=4 → ONE jitted train program per optimizer
    step, and one compile total across the run (retrace guard)."""
    engine = _engine(4, fuse=True, bf16={"enabled": True})
    batch = _full_batch(4)
    train_steps_batch(engine, batch, 5)
    assert engine.global_steps == 5
    stats = engine.compile_stats()
    fused = stats["fused_accum_step"]
    assert fused["dispatches"] == 5, stats
    assert fused["compiles"] == 1, stats
    # no other train-program dispatches: the per-microbatch programs idle
    assert stats["fwd_bwd"]["dispatches"] == 0, stats
    assert stats["step"]["dispatches"] == 0, stats


def test_fused_accum_verified_by_analysis_passes(eight_devices):
    """The PR-1 guarantees, checked statically instead of ad hoc: the fused
    scan program donates-and-aliases the full state tuple (what the old
    is_deleted probes observed at runtime), contains no host callback, and
    its grad-reduction collectives are on the static schedule."""
    engine = _engine(4, fuse=True, bf16={"enabled": True})
    train_steps_batch(engine, _full_batch(4), 2)
    rep = engine.analysis_report(programs=["fused_accum_step"])
    entry = rep["programs"]["fused_accum_step"]["passes"]
    assert entry["donation"]["ok"], entry["donation"]["violations"]
    assert entry["donation"]["summary"].get("double_buffered_bytes", 0) == 0
    assert entry["host_transfer"]["ok"], entry["host_transfer"]["violations"]
    assert entry["dtype_promotion"]["ok"], entry["dtype_promotion"]["violations"]
    assert entry["collectives"]["summary"]["total_count"] >= 1  # dp grad reduce
    assert rep["totals"]["donation_verified"] is True


def test_fused_path_keeps_no_accumulator_buffer(eight_devices):
    """The scan carries the accumulator inside the program; the engine holds
    no HBM accumulation buffer (that is the memory headroom the fusion buys)."""
    engine = _engine(4, fuse=True)
    train_steps_batch(engine, _full_batch(4), 1)
    assert engine._grad_acc is None
    unfused = _engine(4, fuse=False)
    train_steps_batch(unfused, _full_batch(4), 1)
    assert unfused._grad_acc is not None


def test_micro_protocol_fallback_matches(eight_devices):
    """Driving forward/backward/step per microbatch with fuse on falls back
    to the unfused programs (train_batch is the fused entry point) and still
    produces the same training result."""
    gas = 2
    batch = _full_batch(gas)
    fused = _engine(gas, fuse=True)
    manual = _engine(gas, fuse=True)
    fused_losses = train_steps_batch(fused, batch, STEPS)
    manual_losses = train_steps_micro(manual, batch, STEPS)
    assert manual._grad_acc is not None  # lazily allocated for the fallback
    assert manual.compile_stats()["fused_accum_step"]["dispatches"] == 0
    np.testing.assert_allclose(manual_losses, fused_losses, rtol=1e-5, atol=1e-6)
    fw, mw = master_snapshot(fused), master_snapshot(manual)
    for k in fw:
        np.testing.assert_allclose(mw[k], fw[k], rtol=1e-5, atol=1e-6)


def test_switch_micro_protocol_to_fused_drops_accumulator(eight_devices):
    """A fallback window lazily allocates the accumulator; the next fused
    train_batch must drop it — a kept buffer would pin param-sized HBM and
    hand get_last_grads a stale all-zero tree."""
    gas = 2
    batch = _full_batch(gas)
    engine = _engine(gas, fuse=True)
    train_steps_micro(engine, batch, 1)  # per-microbatch fallback
    assert engine._grad_acc is not None
    engine.train_batch(batch=batch)  # fused single-dispatch step
    assert engine._grad_acc is None
    grads = engine.get_last_grads()
    assert grads is not None
    total = sum(
        float(np.abs(np.asarray(jax.device_get(l))).sum())
        for l in jax.tree_util.tree_leaves(grads)
    )
    assert total > 0, "stale zeroed accumulator returned instead of recomputed grads"


def test_fused_respects_zero_stages(eight_devices):
    """The fused program composes with the ZeRO sharding trees: stages 0-3
    all train and agree with each other (same GSPMD-math contract the
    unfused path keeps)."""
    baseline = None
    for stage in [0, 1, 2, 3]:
        engine = _engine(2, fuse=True, zero_optimization={"stage": stage})
        losses = train_steps_batch(engine, _full_batch(2), STEPS)
        assert losses[-1] < losses[0], f"stage {stage} did not learn: {losses}"
        if baseline is None:
            baseline = losses
        else:
            np.testing.assert_allclose(losses, baseline, rtol=1e-5)


def test_gas_resize_rebuilds_fused_program(eight_devices):
    """set_train_batch_size across gas values keeps the fused path working
    (the resize invalidates and rebuilds the compiled step)."""
    engine = _engine(2, fuse=True)
    train_steps_batch(engine, _full_batch(2), 1)
    engine.set_train_batch_size(32)  # gas 2 -> 4 (micro=1 × dp=8)
    assert engine.gradient_accumulation_steps() == 4
    losses = train_steps_batch(engine, _full_batch(4), 2)
    assert np.isfinite(losses).all()
    # the rebuilt program retraced once; dispatches keep counting up
    stats = engine.compile_stats()["fused_accum_step"]
    assert stats["compiles"] == 2 and stats["dispatches"] == 3, stats
