"""Hybrid engine (RLHF / DS-Chat) tests.

Reference analog: ``tests/hybrid_engine/`` + ``deepspeed/runtime/hybrid_engine.py:32``.
The property under test: one engine alternates generate (inference mode) and
train steps over the SAME weights — rollouts see the latest update, training
resumes untouched.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.runtime.hybrid_engine import DeepSpeedHybridEngine

CFG = dict(
    vocab_size=128,
    hidden_size=32,
    num_layers=2,
    num_heads=4,
    max_seq_len=64,
    dtype="float32",
    flash_attention=False,
)


def _engine(extra=None):
    mesh_mod.reset_topology()
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": 3},
        "hybrid_engine": {"enabled": True, "max_out_tokens": 8},
    }
    if extra:
        config.update(extra)
    engine, *_ = ds.initialize(model=TransformerLM(TransformerConfig(**CFG)), config=config)
    return engine


def _batch(rs, n=8, t=16):
    toks = rs.randint(0, CFG["vocab_size"], size=(n, t)).astype(np.int32)
    return {"input_ids": toks, "labels": toks}


def test_initialize_selects_hybrid_engine():
    engine = _engine()
    assert isinstance(engine, DeepSpeedHybridEngine)


def test_generate_train_loop(eight_devices):
    """The RLHF actor loop: rollout → train → rollout, one engine."""
    engine = _engine()
    rs = np.random.RandomState(0)
    prompts = rs.randint(0, CFG["vocab_size"], size=(8, 4)).astype(np.int32)

    engine.eval()
    out0 = np.asarray(engine.generate(prompts, max_new_tokens=4))
    assert out0.shape == (8, 8)  # prompt 4 + 4 new
    np.testing.assert_array_equal(out0[:, :4], prompts)

    # two train steps move the weights
    engine.train()
    losses = []
    for _ in range(2):
        loss = engine(_batch(rs))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert engine.global_steps == 2

    # rollout again: same weights as training (greedy tokens may change)
    engine.eval()
    out1 = np.asarray(engine.generate(prompts, max_new_tokens=4))
    assert out1.shape == (8, 8)

    # and training continues cleanly after inference mode
    engine.train()
    loss = engine(_batch(rs))
    engine.backward(loss)
    engine.step()
    assert engine.global_steps == 3


def test_generate_uses_current_weights(eight_devices):
    """Generation logits must track the live training params: after a big
    LR step the greedy continuation distribution changes."""
    engine = _engine()
    rs = np.random.RandomState(1)
    batch = _batch(rs)
    engine.init_params(batch)
    prompts = batch["input_ids"][:, :4]
    before = np.asarray(engine.eval().generate(prompts, max_new_tokens=4))
    # snapshot to HOST now: the step programs donate the param buffers, so a
    # live device reference would be deleted by the first training step
    params_before = np.asarray(
        jax.device_get(jax.tree_util.tree_leaves(engine.get_params())[0])
    )

    engine.train()
    for _ in range(3):
        loss = engine(_batch(rs))
        engine.backward(loss)
        engine.step()
    params_after = jax.tree_util.tree_leaves(engine.get_params())[0]
    assert not np.array_equal(params_before, np.asarray(params_after))

    after = np.asarray(engine.eval().generate(prompts, max_new_tokens=4))
    assert after.shape == before.shape


def test_eos_early_stop(eight_devices):
    engine = _engine()
    rs = np.random.RandomState(2)
    prompts = rs.randint(0, CFG["vocab_size"], size=(8, 4)).astype(np.int32)
    engine.init_params({"input_ids": prompts, "labels": prompts})
    engine.eval()
    out = np.asarray(engine.generate(prompts, max_new_tokens=6, eos_token_id=0))
    assert out.shape == (8, 10)
