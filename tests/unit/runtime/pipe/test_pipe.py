"""Pipeline-parallel tests (reference: ``tests/unit/runtime/pipe/``).

The key parity check mirrors the reference's pipe-vs-dense training
comparison (test_pipe.py ``TestPipeCifar10``-style): the same LayerSpec
network trained with pipe=1 and pipe=4 must produce the same losses.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.pipe import LayerSpec, PipelineModule
from deepspeed_tpu.runtime.pipe.schedule import (
    BackwardPass,
    ForwardPass,
    InferenceSchedule,
    LoadMicroBatch,
    OptimizerStep,
    RecvActivation,
    TrainSchedule,
)
from deepspeed_tpu.runtime.pipe.spmd import detect_layout


class InProj:
    """Heterogeneous prologue layer (an 'embedding')."""

    def __init__(self, d_in: int, d: int):
        self.d_in, self.d = d_in, d

    def init(self, rng, x):  # noqa: ARG002
        return {"w": jax.random.normal(rng, (self.d_in, self.d)) * 0.5}

    def apply(self, params, x, train=True):  # noqa: ARG002
        return jnp.tanh(x @ params["w"])


class Block:
    """Homogeneous body layer."""

    def __init__(self, d: int):
        self.d = d

    def init(self, rng, x):  # noqa: ARG002
        return {"w": jax.random.normal(rng, (self.d, self.d)) * 0.3}

    def apply(self, params, x, train=True):  # noqa: ARG002
        return x + jnp.tanh(x @ params["w"])


class OutProj:
    def __init__(self, d: int, d_out: int):
        self.d, self.d_out = d, d_out

    def init(self, rng, x):  # noqa: ARG002
        return {"w": jax.random.normal(rng, (self.d, self.d_out)) * 0.5}

    def apply(self, params, x, train=True):  # noqa: ARG002
        return x @ params["w"]


def _specs(d_in=8, d=16, d_out=4, blocks=4):
    return [
        LayerSpec(InProj, d_in, d),
        *[LayerSpec(Block, d) for _ in range(blocks)],
        LayerSpec(OutProj, d, d_out),
    ]


def _mse(out, labels):
    return jnp.mean((out - labels) ** 2)


def _data(n=8, d_in=8, d_out=4, seed=0):
    rs = np.random.RandomState(seed)
    return (
        rs.randn(n, d_in).astype(np.float32),
        rs.randn(n, d_out).astype(np.float32),
    )


CONFIG = {
    "train_micro_batch_size_per_gpu": 2,
    "gradient_accumulation_steps": 4,
    "optimizer": {"type": "sgd", "params": {"lr": 0.05}},
    "steps_per_print": 100,
}


class TestSchedules:
    def test_train_schedule_covers_all_microbatches(self):
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=0)
        steps = list(sched.steps())
        fwd = [c.buffer_id for s in steps for c in s if isinstance(c, ForwardPass)]
        bwd = [c.buffer_id for s in steps for c in s if isinstance(c, BackwardPass)]
        assert fwd == [0, 1, 2, 3]
        assert bwd == [0, 1, 2, 3]
        # every forward precedes its backward
        flat = [c for s in steps for c in s]
        for m in range(4):
            assert flat.index(ForwardPass(m)) < flat.index(BackwardPass(m))
        assert isinstance(flat[-1], OptimizerStep)

    def test_train_schedule_1f1b_interleaves(self):
        # on the last stage, once warm, forwards and backwards alternate
        sched = TrainSchedule(micro_batches=4, stages=2, stage_id=1)
        kinds = [
            type(c).__name__
            for s in sched.steps()
            for c in s
            if isinstance(c, (ForwardPass, BackwardPass))
        ]
        assert kinds == ["ForwardPass", "BackwardPass"] * 4

    def test_first_stage_loads_microbatches(self):
        sched = TrainSchedule(micro_batches=2, stages=2, stage_id=0)
        flat = [c for s in sched.steps() for c in s]
        assert LoadMicroBatch(0) in flat and LoadMicroBatch(1) in flat
        assert not any(isinstance(c, RecvActivation) for c in flat)

    def test_inference_schedule(self):
        sched = InferenceSchedule(micro_batches=3, stages=2, stage_id=1)
        flat = [c for s in sched.steps() for c in s]
        recvs = [c.buffer_id for c in flat if isinstance(c, RecvActivation)]
        assert recvs == [0, 1, 2]


class TestLayoutDetection:
    def test_detects_homogeneous_body(self):
        layers = [s.build() for s in _specs(blocks=4)]
        x = jax.ShapeDtypeStruct((2, 8), np.float32)
        lo = detect_layout(layers, x, jax.random.PRNGKey(0))
        assert (lo.b0, lo.b1) == (1, 5)

    def test_all_homogeneous(self):
        layers = [Block(16) for _ in range(6)]
        x = jax.ShapeDtypeStruct((2, 16), np.float32)
        lo = detect_layout(layers, x, jax.random.PRNGKey(0))
        assert (lo.b0, lo.b1) == (0, 6)


class TestPipelineModulePartition:
    def test_uniform_partition(self):
        pm = PipelineModule(_specs(blocks=6), num_stages=2, partition_method="uniform")
        parts = pm.partition(2)
        assert parts[0] == 0 and parts[-1] == 8


STEP_BATCH = 32  # fixed per-step global batch so parity runs see identical data


def _step_data(rs, n=STEP_BATCH):
    return rs.randn(n, 8).astype(np.float32), rs.randn(n, 4).astype(np.float32)


def _train(config, blocks, steps=3, seed=0):
    mesh_mod.reset_topology()
    pm = PipelineModule(_specs(blocks=blocks), loss_fn=_mse)
    engine, _, _, _ = ds.initialize(model=pm, config=config, dist_init_required=False)
    losses = []
    rs = np.random.RandomState(seed)
    for step in range(steps):
        x, y = _step_data(rs)
        losses.append(float(engine.train_batch(batch=(x, y))))
    return losses


class TestPipeTraining:
    def test_pipe4_matches_dense(self, eight_devices):  # noqa: ARG002
        dense_cfg = dict(CONFIG, mesh={"data": 8})
        dense = _train(dense_cfg, blocks=4, steps=3)
        pipe_cfg = dict(CONFIG, mesh={"pipe": 4, "data": 2})
        pipe = _train(pipe_cfg, blocks=4, steps=3)
        np.testing.assert_allclose(pipe, dense, rtol=2e-4, atol=2e-5)

    def test_pipe2_with_zero1(self, eight_devices):  # noqa: ARG002
        cfg = dict(
            CONFIG,
            mesh={"pipe": 2, "data": 4},
            zero_optimization={"stage": 1},
            optimizer={"type": "adam", "params": {"lr": 0.01}},
        )
        losses = _train(cfg, blocks=4, steps=3)
        assert all(np.isfinite(l) for l in losses)

    def test_forward_backward_disabled_under_pipe(self, eight_devices):  # noqa: ARG002
        mesh_mod.reset_topology()
        pm = PipelineModule(_specs(blocks=4), loss_fn=_mse)
        cfg = dict(CONFIG, mesh={"pipe": 2, "data": 4})
        engine, _, _, _ = ds.initialize(model=pm, config=cfg, dist_init_required=False)
        with pytest.raises(RuntimeError, match="train_batch"):
            engine.forward((np.zeros((8, 8), np.float32), np.zeros((8, 4), np.float32)))

    def test_eval_batch(self, eight_devices):  # noqa: ARG002
        mesh_mod.reset_topology()
        pm = PipelineModule(_specs(blocks=4), loss_fn=_mse)
        cfg = dict(CONFIG, mesh={"pipe": 2, "data": 4})
        engine, _, _, _ = ds.initialize(model=pm, config=cfg, dist_init_required=False)
        x, y = _data(n=16)
        loss = engine.eval_batch(batch=(x, y))
        assert np.isfinite(float(jax.device_get(loss)))
