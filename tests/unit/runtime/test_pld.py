"""Progressive layer drop (reference runtime/progressive_layer_drop.py:40,
engine.py:1773): schedule math, model semantics, engine wiring."""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.models import TransformerLM, llama_config
from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop


def test_schedule_math():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert pld.get_theta() == 1.0  # before any update
    pld.update_state(0)
    assert pld.get_theta() == pytest.approx(1.0)
    thetas = []
    for t in (10, 100, 1000, 10_000):
        pld.update_state(t)
        thetas.append(pld.get_theta())
    assert all(a > b for a, b in zip(thetas, thetas[1:]))  # monotone decay
    assert thetas[-1] == pytest.approx(0.5, abs=1e-3)  # floor = theta
    assert pld.get_state() == {"progressive_layer_drop": True, "pld_theta": thetas[-1]}
    pld.update_state(5)
    assert pld.get_theta() == pytest.approx(0.5 * math.exp(-0.01 * 5) + 0.5)


def _tiny_model(**over):
    kw = dict(num_layers=2, remat=False, attn_dropout=0.0, hidden_dropout=0.0)
    kw.update(over)
    return TransformerLM(llama_config("tiny", **kw))


def _batch(vocab, B=2, T=16, seed=0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, vocab, (B, T + 1)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


@pytest.mark.parametrize("scan_layers", [True, False])
def test_theta_one_keeps_every_layer(eight_devices, scan_layers):
    model = _tiny_model(scan_layers=scan_layers)
    rng = jax.random.PRNGKey(0)
    batch = _batch(model.config.vocab_size)
    params = model.init(rng, batch)
    base = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True)
    kept = model.apply(
        params, batch, rngs=jax.random.PRNGKey(1), train=True, pld_theta=jnp.float32(1.0)
    )
    # cond changes XLA fusion boundaries: bit-identity is not guaranteed in
    # bf16 compute, semantic identity is
    np.testing.assert_allclose(np.asarray(base), np.asarray(kept), rtol=1e-4)


def test_theta_zero_drops_deepest_layer(eight_devices):
    # L=1, theta=0 -> keep prob 1 - 1/1*(1-0) = 0: the single layer is always
    # bypassed, so the loss must differ from the all-layers forward and match
    # across draws (no randomness left)
    model = _tiny_model(num_layers=1)
    batch = _batch(model.config.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)
    full = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True)
    drop1 = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True, pld_theta=jnp.float32(0.0))
    drop2 = model.apply(params, batch, rngs=jax.random.PRNGKey(2), train=True, pld_theta=jnp.float32(0.0))
    assert not np.allclose(np.asarray(full), np.asarray(drop1))
    np.testing.assert_allclose(np.asarray(drop1), np.asarray(drop2), rtol=1e-6)


def test_eval_ignores_pld(eight_devices):
    model = _tiny_model()
    batch = _batch(model.config.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)
    base = model.apply(params, batch, rngs=None, train=False)
    pld = model.apply(params, batch, rngs=None, train=False, pld_theta=jnp.float32(0.0))
    np.testing.assert_allclose(np.asarray(base), np.asarray(pld), rtol=1e-6)


def test_pld_needs_rng(eight_devices):
    model = _tiny_model()
    batch = _batch(model.config.vocab_size)
    params = model.init(jax.random.PRNGKey(0), batch)
    with pytest.raises(ValueError, match="dropout rng"):
        model.apply(params, batch, rngs=None, train=True, pld_theta=jnp.float32(0.5))


def test_engine_pld_trains_and_decays_theta(eight_devices):
    model = _tiny_model()
    engine, *_ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "progressive_layer_drop": {"enabled": True, "theta": 0.5, "gamma": 0.1},
        },
    )
    assert engine.progressive_layer_drop is not None
    batch = _batch(model.config.vocab_size, B=16)  # micro=2 x dp=8
    for _ in range(3):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        assert np.isfinite(float(jax.device_get(loss)))
    expected = 0.5 * math.exp(-0.1 * engine.global_steps) + 0.5
    assert engine.progressive_layer_drop.get_theta() == pytest.approx(expected)
    state = engine.progressive_layer_drop.get_state()
    assert state["progressive_layer_drop"] is True


def test_theta_restored_on_checkpoint_load(tmp_path, eight_devices):
    import deepspeed_tpu.parallel.mesh as mesh_mod

    cfg = {
        "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "progressive_layer_drop": {"enabled": True, "theta": 0.5, "gamma": 0.1},
    }
    model = _tiny_model()
    batch = _batch(model.config.vocab_size, B=16)
    mesh_mod.reset_topology()
    a, *_ = ds.initialize(model=model, config=cfg)
    for _ in range(3):
        loss = a(batch); a.backward(loss); a.step()
    a.save_checkpoint(str(tmp_path))

    mesh_mod.reset_topology()
    b, *_ = ds.initialize(model=_tiny_model(), config=cfg)
    b.init_params(batch)
    assert b.progressive_layer_drop.get_theta() == 1.0  # fresh engine
    b.load_checkpoint(str(tmp_path))
    # theta is a pure function of global_steps; the first resumed step must
    # drop layers exactly like an uninterrupted run would
    assert b.progressive_layer_drop.get_theta() == pytest.approx(
        a.progressive_layer_drop.get_theta()
    )
    assert b.progressive_layer_drop.get_theta() < 1.0


def test_engine_pld_disabled_by_default(eight_devices):
    from tests.unit.simple_model import SimpleModel

    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
        },
    )
    assert engine.progressive_layer_drop is None
    assert engine._model_kwargs() == {}
