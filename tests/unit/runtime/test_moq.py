"""MoQ in-step weight quantization (reference: deepspeed/runtime/
quantize.py + engine._configure_quantization engine.py:1330): compute
weights re-quantize progressively after each step while the fp32 master
stays full precision."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.runtime.quantize import (
    Quantizer,
    moq_from_compression_config,
    quantize_asymmetric,
    quantize_symmetric,
)
from tests.unit.simple_model import SimpleModel, random_dataloader


class TestQuantizeMath:
    def test_symmetric_roundtrip_levels(self):
        w = jnp.asarray(np.linspace(-1, 1, 64, dtype=np.float32).reshape(8, 8))
        q8 = quantize_symmetric(w, 8)
        assert float(jnp.max(jnp.abs(q8 - w))) < 1.0 / 127  # within one level
        q2 = quantize_symmetric(w, 2)
        assert len(np.unique(np.asarray(q2))) <= 4  # 2-bit: at most 4 levels

    def test_asymmetric_handles_offset_ranges(self):
        w = jnp.asarray(np.linspace(3.0, 5.0, 64, dtype=np.float32).reshape(8, 8))
        q = quantize_asymmetric(w, 8)
        assert float(jnp.max(jnp.abs(q - w))) < (5.0 - 3.0) / 255 + 1e-6
        # symmetric wastes half its range on the unused negative side
        qs = quantize_symmetric(w, 4)
        qa = quantize_asymmetric(w, 4)
        assert float(jnp.max(jnp.abs(qa - w))) < float(jnp.max(jnp.abs(qs - w)))

    def test_grouping(self):
        rs = np.random.RandomState(0)
        w = jnp.asarray(rs.randn(4, 16).astype(np.float32))
        # one outlier per group: per-group scales quantize the rest finer
        w = w.at[0, 0].set(100.0)
        err_g1 = float(jnp.mean(jnp.abs(quantize_symmetric(w, 8, groups=1) - w)))
        err_g4 = float(jnp.mean(jnp.abs(quantize_symmetric(w, 8, groups=4) - w)))
        assert err_g4 < err_g1


class TestSchedule:
    def test_bits_drop_one_per_doubling_threshold(self):
        # reference compute_quantization: start_bits -= 1 per switch, with
        # the switch threshold doubling (period, 2p, 4p, ...)
        q = Quantizer(start_bits=16, target_bits=4, quantize_period=10)
        assert q.current_bits(0) == 16
        assert q.current_bits(9) == 16
        assert q.current_bits(10) == 15
        assert q.current_bits(19) == 15
        assert q.current_bits(20) == 14  # threshold doubled to 20
        assert q.current_bits(40) == 13  # then 40, 80, ...
        assert q.current_bits(10 * 2**11) == 4
        assert q.current_bits(10_000_000) == 4  # floor

    def test_ratio_resets_at_precision_switch(self):
        # reference quantize.py:137: quantize_real_ratio = 1.0 on a switch,
        # so the fp16 blend re-anneals after every bit drop
        q = Quantizer(
            q_mixed_fp16=True, q_change_ratio=0.25,
            start_bits=8, target_bits=4, quantize_period=3,
        )
        params = {"w": jnp.asarray(np.random.RandomState(0).randn(4, 4), jnp.bfloat16)}
        for step in range(3):
            q.quantize_tree(params, step)
        assert q.quantize_real_ratio == pytest.approx(0.25)
        q.quantize_tree(params, 3)  # bits 8 -> 7: reset to 1.0
        assert q.quantize_real_ratio == 1.0
        q.quantize_tree(params, 4)
        assert q.quantize_real_ratio == pytest.approx(0.75)

    def test_mixed_ratio_anneals(self):
        q = Quantizer(q_mixed_fp16=True, q_change_ratio=0.25)
        ratios = [q.update_ratio() for _ in range(5)]
        assert ratios == [0.75, 0.5, 0.25, 0.0, 0.0]
        q2 = Quantizer(q_mixed_fp16=False)
        assert q2.update_ratio() == 0.0

    def test_config_parse(self):
        cfg = {
            "weight_quantization": {
                "shared_parameters": {
                    "enabled": True,
                    "quantize_weight_in_forward": False,
                    "quantize_groups": 4,
                    "quantization_type": "asymmetric",
                    "schedule_offset": 5,
                },
                "different_groups": {
                    "g0": {"params": {"start_bits": 8, "target_bits": 4, "quantize_period": 50}}
                },
            }
        }
        q = moq_from_compression_config(cfg)
        assert q is not None
        assert (q.q_groups, q.q_type, q.schedule_offset) == (4, 1, 5)
        assert (q.start_bits, q.target_bits, q.period) == (8, 4, 50)
        # in-forward (QAT) and disabled configs produce no MoQ quantizer
        cfg["weight_quantization"]["shared_parameters"]["quantize_weight_in_forward"] = True
        assert moq_from_compression_config(cfg) is None
        assert moq_from_compression_config({}) is None


class TestEngineMoQ:
    def _cfg(self, **over):
        base = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "compression_training": {
                "weight_quantization": {
                    "shared_parameters": {
                        "enabled": True,
                        "quantize_weight_in_forward": False,
                        "quantize_groups": 1,
                    },
                    "different_groups": {
                        "g0": {"params": {"start_bits": 8, "target_bits": 8, "quantize_period": 1}}
                    },
                }
            },
        }
        base.update(over)
        return base

    def test_weights_quantized_master_full_precision(self, eight_devices):
        mesh_mod.reset_topology()
        engine, *_ = ds.initialize(model=SimpleModel(), config=self._cfg())
        assert engine.quantizer is not None
        batch = next(random_dataloader(total_samples=8, batch_size=8))
        losses = []
        for _ in range(3):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        assert all(np.isfinite(l) for l in losses)
        w = np.asarray(jax.device_get(engine.get_params()["w0"]), np.float32)
        m = np.asarray(jax.device_get(engine.get_master_params()["w0"]), np.float32)
        # the compute store is 8-bit (few distinct levels); the master is not
        assert len(np.unique(w)) <= 256
        assert len(np.unique(m)) > 250
        # quantized store really is the quantized master
        from deepspeed_tpu.runtime.quantize import quantize_symmetric as qs

        expect = np.asarray(qs(jnp.asarray(m, jnp.bfloat16), 8), np.float32)
        np.testing.assert_allclose(w, expect, atol=2e-2)

    def test_moq_requires_mixed_precision(self, eight_devices):
        mesh_mod.reset_topology()
        with pytest.raises(ValueError, match="mixed precision"):
            ds.initialize(
                model=SimpleModel(),
                config=self._cfg(bf16={"enabled": False}),
            )

    def test_anneal_ratio_survives_resume(self, tmp_path, eight_devices):
        mesh_mod.reset_topology()
        cfg = self._cfg()
        shared = cfg["compression_training"]["weight_quantization"]["shared_parameters"]
        shared["fp16_mixed_quantize"] = {"enabled": True, "quantize_change_ratio": 0.2}
        engine, *_ = ds.initialize(model=SimpleModel(), config=cfg)
        batch = next(random_dataloader(total_samples=8, batch_size=8))
        for _ in range(3):
            loss = engine(batch); engine.backward(loss); engine.step()
        assert engine.quantizer.quantize_real_ratio == pytest.approx(0.4)
        engine.save_checkpoint(str(tmp_path))

        mesh_mod.reset_topology()
        engine2, *_ = ds.initialize(model=SimpleModel(), config=cfg)
        engine2.init_params(batch)
        assert engine2.quantizer.quantize_real_ratio == 1.0  # fresh
        engine2.load_checkpoint(str(tmp_path))
        assert engine2.quantizer.quantize_real_ratio == pytest.approx(0.4)

    def test_training_still_learns(self, eight_devices):
        mesh_mod.reset_topology()
        engine, *_ = ds.initialize(model=SimpleModel(), config=self._cfg())
        batch = next(random_dataloader(total_samples=8, batch_size=8))
        losses = []
        for _ in range(6):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        assert losses[-1] < losses[0], losses
