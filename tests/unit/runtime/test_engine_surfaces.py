"""Engine surface corners the main engine tests don't cover: the
data-iterator train_batch form, eval-mode forwards, wall-clock breakdown
timers, and ZeRO memory estimators (reference engine.py train_batch/eval,
wall_clock_breakdown engine.py:2165, runtime/utils.py estimators)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM, llama_config

VOCAB = 128


def _engine(extra=None, gas=1):
    # (topology reset happens in the autouse conftest fixture)
    cfg = llama_config("tiny", num_layers=2, max_seq_len=32, vocab_size=VOCAB)
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "mesh": {"data": 8},
        "steps_per_print": 10_000,
    }
    config.update(extra or {})
    engine, *_ = ds.initialize(
        model=TransformerLM(cfg), config=config, dist_init_required=False
    )
    return engine


def _batch(rs, n=8):
    toks = rs.randint(0, VOCAB, (n, 33)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


def test_train_batch_with_data_iter(eight_devices):
    """train_batch(data_iter=...) runs a full GAS cycle per call and
    advances global_steps once per cycle (reference train_batch contract)."""
    engine = _engine(gas=2)
    rs = np.random.RandomState(0)
    it = iter([_batch(rs) for _ in range(6)])
    l1 = engine.train_batch(data_iter=it)
    l2 = engine.train_batch(data_iter=it)
    assert np.isfinite(float(l1)) and np.isfinite(float(l2))
    assert engine.global_steps == 2
    assert engine.micro_steps == 4


def test_eval_forward_no_state_change(eight_devices):
    engine = _engine()
    rs = np.random.RandomState(1)
    b = _batch(rs)
    loss = engine(b)
    engine.backward(loss)
    engine.step()
    before = np.asarray(jax.device_get(engine.get_params()["embed"]["tokens"]))
    engine.eval()
    eval_loss = engine(b)
    assert np.isfinite(float(jax.device_get(eval_loss)))
    after = np.asarray(jax.device_get(engine.get_params()["embed"]["tokens"]))
    np.testing.assert_array_equal(before, after)
    assert engine.global_steps == 1
    engine.train()
    loss = engine(b)
    engine.backward(loss)
    engine.step()
    assert engine.global_steps == 2


def test_wall_clock_breakdown_timers(eight_devices):
    engine = _engine(extra={"wall_clock_breakdown": True})
    rs = np.random.RandomState(2)
    for _ in range(2):
        loss = engine(_batch(rs))
        engine.backward(loss)
        engine.step()
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    assert isinstance(engine.timers, SynchronizedWallClockTimer)  # not the Noop stub


def test_memory_estimators():
    from deepspeed_tpu.runtime.utils import estimate_zero_memory

    est1 = estimate_zero_memory(1_000_000, stage=1, dp_size=8)
    est3 = estimate_zero_memory(1_000_000, stage=3, dp_size=8)
    # stage 3 shards params too: strictly less per-chip state than stage 1
    assert est3["total_bytes"] < est1["total_bytes"]
