"""Data-efficiency tests (reference: ``tests/unit/runtime/test_data_efficiency.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.runtime.data_pipeline import (
    CurriculumScheduler,
    DeepSpeedDataSampler,
    DistributedSampler,
    RandomLayerTokenDrop,
    RandomLTDScheduler,
)


class TestCurriculumScheduler:
    CFG = {
        "min_difficulty": 8,
        "max_difficulty": 64,
        "schedule_type": "fixed_linear",
        "schedule_config": {"total_curriculum_step": 10, "difficulty_step": 8},
    }

    def test_linear_ramps(self):
        s = CurriculumScheduler(self.CFG)
        assert s.update_difficulty(0) == 8
        mid = s.update_difficulty(5)
        assert 8 < mid < 64
        assert s.update_difficulty(10) == 64
        assert s.update_difficulty(100) == 64

    def test_difficulty_step_quantized(self):
        s = CurriculumScheduler(self.CFG)
        for step in range(12):
            assert s.update_difficulty(step) % 8 == 0

    def test_fixed_root(self):
        cfg = dict(self.CFG, schedule_type="fixed_root")
        cfg["schedule_config"] = dict(cfg["schedule_config"], root_degree=2)
        s = CurriculumScheduler(cfg)
        # sqrt schedule ramps faster early than linear
        assert s.update_difficulty(3) >= CurriculumScheduler(self.CFG).update_difficulty(3)

    def test_fixed_discrete(self):
        cfg = {
            "min_difficulty": 8,
            "max_difficulty": 64,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [8, 32, 64], "max_step": [5, 10]},
        }
        s = CurriculumScheduler(cfg)
        assert s.update_difficulty(3) == 8
        assert s.update_difficulty(7) == 32
        assert s.update_difficulty(50) == 64

    def test_bad_schedule_raises(self):
        with pytest.raises(RuntimeError):
            CurriculumScheduler(dict(self.CFG, schedule_type="nope"))


class TestEngineCurriculum:
    def test_seq_truncation(self):
        mesh_mod.reset_topology()
        seen_lens = []

        class Probe:
            def init(self, rng, batch):
                return {"w": jnp.ones((1,))}

            def apply(self, params, batch, rngs=None, train=True):  # noqa: ARG002
                seen_lens.append(batch["input_ids"].shape[1])
                return jnp.mean(batch["input_ids"].astype(jnp.float32)) * params["w"][0]

        cfg = {
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "sgd", "params": {"lr": 0.0}},
            "curriculum_learning": {
                "enabled": True,
                "min_difficulty": 8,
                "max_difficulty": 32,
                "schedule_type": "fixed_linear",
                "schedule_config": {"total_curriculum_step": 4, "difficulty_step": 8},
            },
            "steps_per_print": 100,
        }
        engine, _, _, _ = ds.initialize(model=Probe(), config=cfg, dist_init_required=False)
        batch = {"input_ids": np.zeros((8, 32), np.int32)}
        for _ in range(5):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
        assert seen_lens[0] < 32  # truncated early
        assert seen_lens[-1] == 32  # full length at the end


class TestRandomLTD:
    def test_scheduler_ramps(self):
        s = RandomLTDScheduler(start_token_num=16, max_token_num=128, total_steps=10, step_size=16)
        assert s.update(0) == 16
        assert s.update(10) == 128

    def test_token_drop_roundtrip(self):
        from deepspeed_tpu.runtime.data_pipeline.data_routing import (
            gather_tokens,
            random_token_select,
            scatter_tokens,
        )

        x = jnp.arange(2 * 16 * 4, dtype=jnp.float32).reshape(2, 16, 4)
        idx = random_token_select(jax.random.PRNGKey(0), 16, 8, 2)
        assert idx.shape == (2, 8)
        # sorted → causality preserved
        assert (np.diff(np.asarray(idx), axis=1) > 0).all()
        sub = gather_tokens(x, idx)
        back = scatter_tokens(x, sub * 0 + 7.0, idx)
        for b in range(2):
            for t in range(16):
                expect = 7.0 if t in np.asarray(idx)[b] else None
                if expect is not None:
                    assert (np.asarray(back)[b, t] == 7.0).all()
                else:
                    np.testing.assert_array_equal(np.asarray(back)[b, t], np.asarray(x)[b, t])

    def test_layer_wrapper_bypasses_in_eval(self):
        sched = RandomLTDScheduler(4, 16, 10)
        calls = []

        def layer(params, x):  # noqa: ARG001
            calls.append(x.shape[1])
            return x * 2

        wrapped = RandomLayerTokenDrop(layer, sched)
        x = jnp.ones((2, 16, 4))
        wrapped(None, x, jax.random.PRNGKey(0), train=True)
        assert calls[-1] == 4  # subset
        wrapped(None, x, jax.random.PRNGKey(0), train=False)
        assert calls[-1] == 16  # full


class TestSamplers:
    def test_distributed_sampler_partition(self):
        idx0 = list(DistributedSampler(100, num_replicas=4, rank=0, shuffle=False))
        idx1 = list(DistributedSampler(100, num_replicas=4, rank=1, shuffle=False))
        assert len(idx0) == len(idx1) == 25
        assert not set(idx0) & set(idx1)

    def test_curriculum_sampler_respects_difficulty(self):
        cfg = {
            "min_difficulty": 1,
            "max_difficulty": 10,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 1},
        }
        sched = CurriculumScheduler(cfg)
        difficulties = np.arange(100) % 10 + 1
        sampler = DeepSpeedDataSampler(difficulties, sched, global_batch_size=8)
        it = iter(sampler)
        first = [next(it) for _ in range(8)]
        assert all(difficulties[i] <= 2 for i in first)  # early = easy
