"""Multi-output model handling (reference: tests/unit/runtime/
test_multi_output_model.py): models whose apply returns (loss, extras...) —
the engine trains on out[0] and eval forwards surface the full tuple."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from tests.unit.simple_model import random_dataloader


class MultiOutputModel:
    """Returns (loss, per-sample losses, logits-like aux) from apply."""

    def __init__(self, hidden_dim: int = 16):
        self.hidden_dim = hidden_dim

    def init(self, rng, batch):
        return {"w": jax.random.normal(rng, (self.hidden_dim, self.hidden_dim)) * 0.1}

    def apply(self, params, batch, rngs=None, train=True):
        x, y = batch
        h = x @ params["w"]
        per_sample = jnp.mean((h - y) ** 2, axis=-1)
        return jnp.mean(per_sample), per_sample, h


def test_trains_on_first_output(eight_devices):
    engine, *_ = ds.initialize(
        model=MultiOutputModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
        },
    )
    losses = []
    for batch in random_dataloader(total_samples=40, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]  # scalar head of the tuple drove training


def test_eval_returns_full_tuple(eight_devices):
    engine, *_ = ds.initialize(
        model=MultiOutputModel(),
        config={"train_micro_batch_size_per_gpu": 1, "bf16": {"enabled": True}},
    )
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    engine.init_params(batch)
    engine.eval()
    out = engine(batch)
    assert isinstance(out, tuple) and len(out) == 3
    loss, per_sample, h = out
    assert per_sample.shape == (8,)
    assert h.shape == (8, 16)
    assert float(jax.device_get(loss)) == pytest.approx(
        float(np.mean(np.asarray(jax.device_get(per_sample)))), rel=1e-6
    )
