"""FP16/BF16 across ZeRO stages and accumulation dtypes (reference:
tests/unit/runtime/half_precision/test_fp16.py, test_bf16.py,
runtime/test_ds_config_dict grad_accum cases)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel, random_dataloader


def _run(stage, precision, gas=1, grad_accum_dtype=None, steps=4):
    mesh_mod.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
    }
    if precision == "fp16":
        cfg["fp16"] = {"enabled": True, "loss_scale": 128.0}  # static scale
    elif precision == "bf16":
        cfg["bf16"] = {"enabled": True}
    if grad_accum_dtype is not None:
        cfg["data_types"] = {"grad_accum_dtype": grad_accum_dtype}
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg)
    losses = []
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    for _ in range(steps * gas):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


class TestFP16Stages:
    def test_fp16_identical_across_stages(self, eight_devices):
        base = _run(0, "fp16")[1]
        assert base[-1] < base[0]
        for stage in (1, 2, 3):
            assert _run(stage, "fp16")[1] == base, f"stage {stage} diverged"

    def test_fp16_static_scale_consumed(self, eight_devices):
        engine, losses = _run(1, "fp16")
        assert engine.loss_scale == 128.0
        assert all(np.isfinite(l) for l in losses)


class TestGradAccumDtype:
    def test_gas_fp32_accum_default(self, eight_devices):
        engine, losses = _run(1, "bf16", gas=2)
        assert engine._grad_acc is not None
        leaf = jax.tree_util.tree_leaves(engine._grad_acc)[0]
        assert leaf.dtype == np.float32
        assert losses[-1] < losses[0]

    def test_gas_bf16_accum(self, eight_devices):
        import jax.numpy as jnp

        engine, losses = _run(1, "bf16", gas=2, grad_accum_dtype="bf16")
        leaf = jax.tree_util.tree_leaves(engine._grad_acc)[0]
        assert leaf.dtype == jnp.bfloat16
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]

    def test_bf16_accum_close_to_fp32_accum(self, eight_devices):
        _, l32 = _run(1, "bf16", gas=2)
        _, l16 = _run(1, "bf16", gas=2, grad_accum_dtype="bf16")
        # reduced-precision accumulation: same trajectory within bf16 noise
        # (abs floor keeps near-zero late-step losses from flaking the rel check)
        assert l16 == pytest.approx(l32, rel=5e-2, abs=1e-2)

    def test_invalid_dtype_rejected(self, eight_devices):
        with pytest.raises(ValueError, match="grad_accum_dtype"):
            _run(1, "bf16", gas=2, grad_accum_dtype="int8")

    def test_fp16_accum_needs_fp16_mode(self, eight_devices):
        # fp16 accumulation without the fp16 overflow machinery would feed
        # silent infs into the optimizer
        with pytest.raises(ValueError, match="requires fp16.enabled"):
            _run(1, "bf16", gas=2, grad_accum_dtype="fp16")

    def test_fp16_accum_with_fp16_mode_works(self, eight_devices):
        engine, losses = _run(1, "fp16", gas=2, grad_accum_dtype="fp16")
        import jax as _jax
        leaf = _jax.tree_util.tree_leaves(engine._grad_acc)[0]
        assert str(leaf.dtype) == "float16"
        assert all(np.isfinite(l) for l in losses)

    def test_fused_path_ignores_accum_dtype(self, eight_devices):
        # gas=1 fuses grads inside one program: no buffer exists
        engine, _ = _run(1, "bf16", gas=1, grad_accum_dtype="bf16")
        assert engine._grad_acc is None
