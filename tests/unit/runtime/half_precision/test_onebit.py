"""1-bit optimizer tests (reference: ``tests/unit/runtime/half_precision/onebit/``)."""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel


def _train(opt_type, opt_params, steps=6, seed=0):
    mesh_mod.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": opt_type, "params": opt_params},
        "steps_per_print": 100,
    }
    engine, _, _, _ = ds.initialize(
        model=SimpleModel(hidden_dim=16), config=cfg, dist_init_required=False
    )
    rs = np.random.RandomState(seed)
    batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
    losses = []
    for _ in range(steps):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


class TestOnebitAdam:
    def test_warmup_matches_adam(self):
        """Before freeze_step 1-bit Adam IS Adam (reference semantics)."""
        ref, _ = _train("adam", {"lr": 1e-2, "weight_decay": 0.0, "adam_w_mode": False})
        ob, _ = _train("onebitadam", {"lr": 1e-2, "freeze_step": 1000})
        np.testing.assert_allclose(ob, ref, rtol=1e-4)

    def test_compression_stage_trains(self):
        losses, engine = _train("onebitadam", {"lr": 1e-2, "freeze_step": 2}, steps=10)
        assert losses[-1] < losses[0]
        # error-feedback buffer is live after freeze
        import jax

        err = jax.tree_util.tree_leaves(engine._opt_state.worker_error)
        assert any(float(abs(np.asarray(e)).sum()) > 0 for e in err)

    def test_amsgrad_rejected(self):
        from deepspeed_tpu.runtime.fp16.onebit import OnebitAdam

        with pytest.raises(ValueError):
            OnebitAdam(amsgrad=True)


class TestOnebitLamb:
    def test_trains(self):
        losses, _ = _train("onebitlamb", {"lr": 5e-3, "freeze_step": 3}, steps=10)
        assert losses[-1] < losses[0]


class TestZeroOneAdam:
    def test_trains_with_var_schedule(self):
        losses, engine = _train(
            "zerooneadam", {"lr": 1e-2, "var_freeze_step": 4, "var_update_scaler": 4},
            steps=10,
        )
        assert losses[-1] < losses[0]
        assert int(engine._opt_state.step) == 10


class TestMiCS:
    def test_mics_shard_size_shards_within_groups(self, eight_devices):  # noqa: ARG002
        mesh_mod.reset_topology()
        cfg = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3, "mics_shard_size": 2},
            "steps_per_print": 100,
        }
        engine, _, _, _ = ds.initialize(
            model=SimpleModel(hidden_dim=16), config=cfg, dist_init_required=False
        )
        assert engine.topology.config.data == 2
        assert engine.topology.config.data_outer == 4
        assert engine.data_parallel_world_size() == 8
        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        # master shards over the inner axis only (2-way), replicated across groups
        spec = engine._master_specs["w0"]
        flat_axes = [a for e in spec if e for a in (e if isinstance(e, tuple) else (e,))]
        assert "data" in flat_axes and "data_outer" not in flat_axes

    def test_mics_matches_full_zero(self, eight_devices):  # noqa: ARG002
        def run(zero_cfg, seed=0):
            mesh_mod.reset_topology()
            cfg = {
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
                "zero_optimization": zero_cfg,
                "steps_per_print": 100,
            }
            engine, _, _, _ = ds.initialize(
                model=SimpleModel(hidden_dim=16), config=cfg, dist_init_required=False
            )
            rs = np.random.RandomState(seed)
            batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
            losses = []
            for _ in range(3):
                loss = engine(batch)
                engine.backward(loss)
                engine.step()
                losses.append(float(loss))
            return losses

        full = run({"stage": 3})
        mics = run({"stage": 3, "mics_shard_size": 2})
        np.testing.assert_allclose(mics, full, rtol=1e-4)
