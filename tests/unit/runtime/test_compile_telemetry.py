"""Compile telemetry + full-state donation.

Donation is verified two ways: functionally (the step consumes its input
buffers — they are deleted after the call) and structurally (the compiled
step program carries input/output aliases and a nonzero aliased-bytes
figure in ``memory_analysis()``). The retrace guard asserts ≤1 compile of
the step programs across a 5-step loop via the new counters, and the
``invalidate_compiled_step`` test pins the executable-release fix for the
PERF.md mid-suite wedge.
"""

import jax
import numpy as np

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel, step_batch, train_steps_micro


def _cfg(**over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }
    base.update(over)
    return base


def _engine(**over):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg(**over))
    return engine


def test_step_consumes_donated_state(eight_devices):
    """Full-state donation, observed functionally: after an optimizer step,
    every pre-step state buffer (params, master, opt_state, grad_acc,
    scale_state) is deleted — XLA reused it in place instead of
    double-buffering the training state."""
    engine = _engine(gradient_accumulation_steps=2)
    batch = step_batch(batch_size=16)
    train_steps_micro(engine, batch, 1)  # init + first window
    old = {
        "params": jax.tree_util.tree_leaves(engine._params)[0],
        "master": jax.tree_util.tree_leaves(engine._master)[0],
        "opt_state": jax.tree_util.tree_leaves(engine._opt_state)[0],
        "grad_acc": jax.tree_util.tree_leaves(engine._grad_acc)[0],
        "scale": engine._scale_state.scale,
    }
    train_steps_micro(engine, batch, 1)
    for name, buf in old.items():
        assert buf.is_deleted(), f"{name} buffer survived the step (not donated)"


def test_fused_step_consumes_donated_state(eight_devices):
    """Same contract on the gas=1 fused forward+step program."""
    engine = _engine()
    batch = step_batch(batch_size=8)
    train_steps_micro(engine, batch, 1)
    old = {
        "params": jax.tree_util.tree_leaves(engine._params)[0],
        "master": jax.tree_util.tree_leaves(engine._master)[0],
        "opt_state": jax.tree_util.tree_leaves(engine._opt_state)[0],
        "scale": engine._scale_state.scale,
    }
    train_steps_micro(engine, batch, 1)
    for name, buf in old.items():
        assert buf.is_deleted(), f"{name} buffer survived the fused step"


def test_step_program_aliases_donated_inputs(eight_devices):
    """Structural check on the compiled step: donation shows up as
    input/output aliases (in-place update), not as fresh output buffers."""
    engine = _engine(gradient_accumulation_steps=2)
    train_steps_micro(engine, step_batch(batch_size=16), 1)
    compiled = engine._jit_step.lower(
        engine._params,
        engine._master,
        engine._opt_state,
        engine._grad_acc,
        engine._scale_state,
        1e-2,
    ).compile()
    assert "input_output_alias" in compiled.as_text()
    mem = compiled.memory_analysis()
    assert mem is not None and mem.alias_size_in_bytes > 0


def test_retrace_guard_unfused_five_steps(eight_devices):
    """≤1 compile of each hot-loop program across a 5-step train loop: the
    step programs trace exactly once and every later dispatch is warm."""
    engine = _engine(gradient_accumulation_steps=2)
    train_steps_micro(engine, step_batch(batch_size=16), 5)
    stats = engine.compile_stats()
    assert stats["fwd_bwd"]["compiles"] == 1, stats
    assert stats["fwd_bwd"]["dispatches"] == 10, stats  # gas × steps
    assert stats["step"]["compiles"] == 1, stats
    assert stats["step"]["dispatches"] == 5, stats


def test_compile_stats_surface(eight_devices):
    """compile_stats() exposes every instrumented program with the counter
    fields bench.py and the monitor consume."""
    engine = _engine()
    train_steps_micro(engine, step_batch(batch_size=8), 1)
    stats = engine.compile_stats()
    assert {"fwd_bwd", "step", "fused_step", "eval_fwd"} <= set(stats)
    for rec in stats.values():
        assert {"traces", "compiles", "dispatches", "compile_seconds", "invalidations"} <= set(rec)
    totals = engine._telemetry.totals()
    assert totals["compiles"] >= 1 and totals["dispatches"] >= 1


def test_invalidate_releases_stale_executables(eight_devices):
    """invalidate_compiled_step must actually release the old executables
    (the PERF.md wedge: rebinding attributes left them alive in jit's
    cache), then rebuild working programs."""
    engine = _engine()  # gas=1 → fused_step is the hot program
    batch = step_batch(batch_size=8)
    train_steps_micro(engine, batch, 2)
    old = engine._jit_fused_step
    assert old.cache_size() >= 1
    engine.invalidate_compiled_step()
    assert engine._jit_fused_step is not old
    assert old.cache_size() == 0, "stale executable still cached after invalidate"
    stats = engine.compile_stats()["fused_step"]
    assert stats["invalidations"] >= 1
    # the rebuilt program works and its recompile is visible in the counters
    train_steps_micro(engine, batch, 1)
    stats = engine.compile_stats()["fused_step"]
    assert stats["compiles"] == 2 and stats["dispatches"] == 3, stats


def test_micro_batch_resize_bounded_executables(eight_devices):
    """The micro-batch resize loop that reproduced the mid-suite wedge:
    shape changes retrace (expected), and invalidate_compiled_step drops
    the accumulated executables so they cannot pile up."""
    engine = _engine()
    for micro, rows in ((1, 8), (2, 16), (1, 8), (2, 16)):
        engine.set_train_micro_batch_size(micro)
        train_steps_micro(engine, step_batch(batch_size=rows), 1)
    assert engine._jit_fused_step.cache_size() >= 2  # one executable per shape
    engine.invalidate_compiled_step()
    assert engine._jit_fused_step.cache_size() == 0


def test_persistent_cache_opt_in(eight_devices, tmp_path):
    """compile.cache_dir routes jitted programs through JAX's persistent
    compilation cache."""
    cache_dir = str(tmp_path / "xla_cache")
    try:
        engine = _engine(compile={"cache_dir": cache_dir})
        assert jax.config.jax_compilation_cache_dir == cache_dir
        train_steps_micro(engine, step_batch(batch_size=8), 1)
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_monitor_receives_compile_counters(eight_devices, tmp_path):
    """The monitor stream carries the compile counters (wired through
    _write_monitor)."""
    engine = _engine(
        steps_per_print=1,
        csv_monitor={"enabled": True, "output_path": str(tmp_path) + "/", "job_name": "t"},
    )
    train_steps_micro(engine, step_batch(batch_size=8), 1)
    import glob

    files = glob.glob(str(tmp_path) + "/t/*compile_count*.csv")
    assert files, "no compile_count csv written by the monitor"
    body = open(files[0]).read()
    assert body.strip(), body
