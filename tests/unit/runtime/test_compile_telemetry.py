"""Compile telemetry + full-state donation.

Donation is verified through the analysis layer (the ``donation`` pass
checks every declared donated arg is aliased in the compiled module —
``engine.analysis_report()``), with ONE legacy functional cross-check kept:
the step consumes its input buffers, observed via ``is_deleted`` (if the
pass and the runtime ever disagree, the pass is wrong). The retrace guard
asserts ≤1 compile of the step programs across a 5-step loop via the
counters, and the ``invalidate_compiled_step`` test pins the
executable-release fix for the PERF.md mid-suite wedge.
"""

import jax
import numpy as np

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel, step_batch, train_steps_micro


def _cfg(**over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }
    base.update(over)
    return base


def _engine(**over):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg(**over))
    return engine


def test_step_consumes_donated_state(eight_devices):
    """LEGACY functional cross-check for the ``donation`` analysis pass:
    after an optimizer step, every pre-step state buffer (params, master,
    opt_state, grad_acc, scale_state) is deleted — XLA reused it in place
    instead of double-buffering the training state. Kept deliberately
    runtime-observed (is_deleted) so a bug in the static pass cannot
    silently blind both checks."""
    engine = _engine(gradient_accumulation_steps=2)
    batch = step_batch(batch_size=16)
    train_steps_micro(engine, batch, 1)  # init + first window
    old = {
        "params": jax.tree_util.tree_leaves(engine._params)[0],
        "master": jax.tree_util.tree_leaves(engine._master)[0],
        "opt_state": jax.tree_util.tree_leaves(engine._opt_state)[0],
        "grad_acc": jax.tree_util.tree_leaves(engine._grad_acc)[0],
        "scale": engine._scale_state.scale,
    }
    train_steps_micro(engine, batch, 1)
    for name, buf in old.items():
        assert buf.is_deleted(), f"{name} buffer survived the step (not donated)"


def test_fused_step_donation_verified_by_analysis(eight_devices):
    """The gas=1 fused forward+step program's donation contract, checked
    by the ``donation`` analysis pass (replaces the old is_deleted probe:
    the pass reads the compiled module's alias table instead of poking
    runtime buffer state)."""
    engine = _engine()
    train_steps_micro(engine, step_batch(batch_size=8), 1)
    rep = engine.analysis_report(programs=["fused_step"], passes=["donation"])
    don = rep["programs"]["fused_step"]["passes"]["donation"]
    assert don["ok"], don["violations"]
    assert don["summary"]["declared_donations"] >= 4  # params+master+opt+scale
    assert don["summary"].get("unhonored", 0) == 0
    assert rep["totals"]["donation_verified"] is True


def test_step_program_aliases_donated_inputs(eight_devices):
    """Structural check on the compiled unfused step, via the donation
    pass (replaces the hand-rolled lower().compile() + as_text() grep):
    every declared donated arg is aliased, zero bytes double-buffered."""
    engine = _engine(gradient_accumulation_steps=2)
    train_steps_micro(engine, step_batch(batch_size=16), 1)
    rep = engine.analysis_report(programs=["step"], passes=["donation"])
    don = rep["programs"]["step"]["passes"]["donation"]
    assert don["ok"], don["violations"]
    assert don["summary"]["declared_donated_bytes"] > 0
    assert don["summary"].get("double_buffered_bytes", 0) == 0


def test_retrace_guard_unfused_five_steps(eight_devices):
    """≤1 compile of each hot-loop program across a 5-step train loop: the
    step programs trace exactly once and every later dispatch is warm."""
    engine = _engine(gradient_accumulation_steps=2)
    train_steps_micro(engine, step_batch(batch_size=16), 5)
    stats = engine.compile_stats()
    assert stats["fwd_bwd"]["compiles"] == 1, stats
    assert stats["fwd_bwd"]["dispatches"] == 10, stats  # gas × steps
    assert stats["step"]["compiles"] == 1, stats
    assert stats["step"]["dispatches"] == 5, stats


def test_compile_stats_surface(eight_devices):
    """compile_stats() exposes every instrumented program with the counter
    fields bench.py and the monitor consume."""
    engine = _engine()
    train_steps_micro(engine, step_batch(batch_size=8), 1)
    stats = engine.compile_stats()
    assert {"fwd_bwd", "step", "fused_step", "eval_fwd"} <= set(stats)
    for rec in stats.values():
        assert {"traces", "compiles", "dispatches", "compile_seconds", "invalidations"} <= set(rec)
    totals = engine._telemetry.totals()
    assert totals["compiles"] >= 1 and totals["dispatches"] >= 1


def test_invalidate_releases_stale_executables(eight_devices):
    """invalidate_compiled_step must actually release the old executables
    (the PERF.md wedge: rebinding attributes left them alive in jit's
    cache), then rebuild working programs."""
    engine = _engine()  # gas=1 → fused_step is the hot program
    batch = step_batch(batch_size=8)
    train_steps_micro(engine, batch, 2)
    old = engine._jit_fused_step
    assert old.cache_size() >= 1
    engine.invalidate_compiled_step()
    assert engine._jit_fused_step is not old
    assert old.cache_size() == 0, "stale executable still cached after invalidate"
    stats = engine.compile_stats()["fused_step"]
    assert stats["invalidations"] >= 1
    # the rebuilt program works and its recompile is visible in the counters
    train_steps_micro(engine, batch, 1)
    stats = engine.compile_stats()["fused_step"]
    assert stats["compiles"] == 2 and stats["dispatches"] == 3, stats


def test_micro_batch_resize_bounded_executables(eight_devices):
    """The micro-batch resize loop that reproduced the mid-suite wedge:
    shape changes retrace (expected), and invalidate_compiled_step drops
    the accumulated executables so they cannot pile up."""
    engine = _engine()
    for micro, rows in ((1, 8), (2, 16), (1, 8), (2, 16)):
        engine.set_train_micro_batch_size(micro)
        train_steps_micro(engine, step_batch(batch_size=rows), 1)
    assert engine._jit_fused_step.cache_size() >= 2  # one executable per shape
    engine.invalidate_compiled_step()
    assert engine._jit_fused_step.cache_size() == 0


def test_persistent_cache_opt_in(eight_devices, tmp_path):
    """compile.cache_dir routes jitted programs through JAX's persistent
    compilation cache."""
    cache_dir = str(tmp_path / "xla_cache")
    try:
        engine = _engine(compile={"cache_dir": cache_dir})
        assert jax.config.jax_compilation_cache_dir == cache_dir
        train_steps_micro(engine, step_batch(batch_size=8), 1)
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


def test_monitor_receives_compile_counters(eight_devices, tmp_path):
    """The monitor stream carries the compile counters (wired through
    _write_monitor)."""
    engine = _engine(
        steps_per_print=1,
        csv_monitor={"enabled": True, "output_path": str(tmp_path) + "/", "job_name": "t"},
    )
    train_steps_micro(engine, step_batch(batch_size=8), 1)
    import glob

    files = glob.glob(str(tmp_path) + "/t/*compile_count*.csv")
    assert files, "no compile_count csv written by the monitor"
    body = open(files[0]).read()
    assert body.strip(), body
