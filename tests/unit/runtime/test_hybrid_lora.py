"""Hybrid-engine LoRA (reference: tests/unit/hybrid_engine/test_he_lora.py;
containers/features/hybrid_engine.py fuse_lora/unfuse_lora): adapter init,
fuse math, EXACT unfuse, and rollouts on fused views."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM, llama_config
from deepspeed_tpu.module_inject.lora import (
    LoRAConfig,
    fuse_lora_tree,
    init_lora_params,
    lora_delta,
    maybe_get_lora,
    unfuse_lora_tree,
)


def _params(seed=0):
    cfg = llama_config("tiny", num_layers=2, remat=False)
    model = TransformerLM(cfg)
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, cfg.vocab_size, (2, 17)).astype(np.int32)
    batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
    return model, model.init(jax.random.PRNGKey(0), batch), batch


class TestLoRAMath:
    def test_init_shapes_and_identity(self):
        _, params, _ = _params()
        cfg = LoRAConfig(rank=4, alpha=8.0)
        lora = init_lora_params(params, cfg, jax.random.PRNGKey(1))
        assert set(lora["layers"]) == {"wq", "wk", "wv", "wo"}
        L, H, O = params["layers"]["wq"].shape
        assert lora["layers"]["wq"]["right"].shape == (L, H, 4)
        assert lora["layers"]["wq"]["left"].shape == (L, 4, O)
        # left starts at zero: fusing is the identity
        fused = fuse_lora_tree(params, lora, cfg.scaling)
        np.testing.assert_array_equal(
            np.asarray(fused["layers"]["wq"]), np.asarray(params["layers"]["wq"])
        )

    def test_fuse_matches_manual_product(self):
        _, params, _ = _params()
        cfg = LoRAConfig(rank=4, alpha=8.0, target_keys=("wq",))
        lora = init_lora_params(params, cfg, jax.random.PRNGKey(1))
        rs = np.random.RandomState(2)
        lora["layers"]["wq"]["left"] = jnp.asarray(
            rs.randn(*lora["layers"]["wq"]["left"].shape).astype(np.float32) * 0.1
        )
        fused = fuse_lora_tree(params, lora, cfg.scaling)
        manual = np.asarray(params["layers"]["wq"], np.float32) + cfg.scaling * np.einsum(
            "lir,lro->lio",
            np.asarray(lora["layers"]["wq"]["right"], np.float32),
            np.asarray(lora["layers"]["wq"]["left"], np.float32),
        )
        np.testing.assert_allclose(
            np.asarray(fused["layers"]["wq"], np.float32), manual, rtol=1e-4, atol=1e-6
        )
        # untargeted leaves are the SAME buffers, not copies
        assert fused["layers"]["wo"] is params["layers"]["wo"]

    def test_unfuse_inverts_in_fp32(self):
        _, params, _ = _params()
        cfg = LoRAConfig(rank=4, alpha=8.0, target_keys=("wq", "wo"))
        lora = init_lora_params(params, cfg, jax.random.PRNGKey(1))
        for k in ("wq", "wo"):
            rs = np.random.RandomState(hash(k) % 2**31)
            lora["layers"][k]["left"] = jnp.asarray(
                rs.randn(*lora["layers"][k]["left"].shape).astype(np.float32) * 0.1
            )
        restored = unfuse_lora_tree(fuse_lora_tree(params, lora, cfg.scaling), lora, cfg.scaling)
        for k in ("wq", "wo"):
            np.testing.assert_allclose(
                np.asarray(restored["layers"][k]),
                np.asarray(params["layers"][k]),
                atol=1e-6,
            )

    def test_delta_dtype_and_probe(self):
        _, params, _ = _params()
        cfg = LoRAConfig(rank=2)
        lora = init_lora_params(params, cfg, jax.random.PRNGKey(1))
        d = lora_delta(lora["layers"]["wq"], cfg.scaling, dtype=jnp.bfloat16)
        assert d.dtype == jnp.bfloat16
        assert len(maybe_get_lora(lora, "wq")) == 2
        assert maybe_get_lora(lora, "w_gate") == []
        assert maybe_get_lora(None, "wq") == []

    def test_no_targets_raises(self):
        _, params, _ = _params()
        with pytest.raises(ValueError, match="no LoRA targets"):
            init_lora_params(params, LoRAConfig(target_keys=("nope",)), jax.random.PRNGKey(0))


class TestHybridEngineLoRA:
    def _engine(self):
        mesh_mod.reset_topology()
        model, _, batch = _params()
        engine, *_ = ds.initialize(
            model=model,
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 1},
                "hybrid_engine": {"enabled": True, "max_out_tokens": 8},
            },
        )
        full = np.tile(np.asarray(batch["input_ids"]), (4, 1))
        engine.init_params({"input_ids": full, "labels": full})
        return engine, model

    def test_fuse_unfuse_exact(self, eight_devices):
        engine, _ = self._engine()
        lora = engine.configure_lora(rank=4, alpha=8.0)
        # nonzero adapter so fusing actually changes weights
        lora["layers"]["wq"]["left"] = jnp.asarray(
            np.random.RandomState(0).randn(*lora["layers"]["wq"]["left"].shape).astype(np.float32)
        )
        engine.set_lora(lora, 2.0)
        before = np.asarray(jax.device_get(engine.get_params()["layers"]["wq"]))
        engine.fuse_lora_weight()
        assert engine.is_lora_fused
        fused = np.asarray(jax.device_get(engine.get_params()["layers"]["wq"]))
        assert not np.array_equal(fused, before)
        engine.unfuse_lora_weight()
        after = np.asarray(jax.device_get(engine.get_params()["layers"]["wq"]))
        np.testing.assert_array_equal(after, before)  # EXACT, not approximate

    def test_rollout_uses_fused_view_without_state_flip(self, eight_devices):
        engine, model = self._engine()
        prompts = np.zeros((1, 4), np.int32)
        base = np.asarray(engine.generate(prompts, max_new_tokens=6))
        lora = engine.configure_lora(rank=4, alpha=8.0)
        big = np.random.RandomState(1).randn(*lora["layers"]["wq"]["left"].shape)
        lora["layers"]["wq"]["left"] = jnp.asarray(big.astype(np.float32))
        engine.set_lora(lora, 4.0)
        adapted = np.asarray(engine.generate(prompts, max_new_tokens=6))
        assert not engine.is_lora_fused  # view only, no state flip
        assert not np.array_equal(adapted, base)  # adapter changed the rollout
        # detaching restores the base behavior exactly
        engine._lora = None
        again = np.asarray(engine.generate(prompts, max_new_tokens=6))
        np.testing.assert_array_equal(again, base)

    def test_checkpoint_never_persists_fused_weights(self, tmp_path, eight_devices):
        engine, _ = self._engine()
        lora = engine.configure_lora(rank=4, alpha=8.0)
        lora["layers"]["wq"]["left"] = jnp.asarray(
            np.random.RandomState(0).randn(*lora["layers"]["wq"]["left"].shape).astype(np.float32)
        )
        engine.set_lora(lora, 2.0)
        base = np.asarray(jax.device_get(engine.get_params()["layers"]["wq"]))
        engine.fuse_lora_weight()
        engine.save_checkpoint(str(tmp_path))  # must auto-unfuse first
        assert not engine.is_lora_fused
        engine.fuse_lora_weight()
        engine.load_checkpoint(str(tmp_path))  # must reset fuse state
        assert not engine.is_lora_fused
        loaded = np.asarray(jax.device_get(engine.get_params()["layers"]["wq"]))
        np.testing.assert_array_equal(loaded, base)

    def test_fuse_before_init_raises(self, eight_devices):
        mesh_mod.reset_topology()
        model, _, _ = _params()
        engine, *_ = ds.initialize(
            model=model,
            config={
                "train_micro_batch_size_per_gpu": 2,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "hybrid_engine": {"enabled": True, "max_out_tokens": 8},
            },
        )
        engine.set_lora({"layers": {}}, 1.0)
        with pytest.raises(RuntimeError, match="before engine state"):
            engine.fuse_lora_weight()

    def test_fused_view_is_cached_between_rollouts(self, eight_devices):
        engine, _ = self._engine()
        engine.configure_lora(rank=2)
        v1 = engine._fused_view(engine._params)
        v2 = engine._fused_view(engine._params)
        assert v1 is v2  # same params + adapter: no recompute

    def test_training_auto_unfuses(self, eight_devices):
        engine, model = self._engine()
        lora = engine.configure_lora(rank=2)
        engine.set_lora(lora, 1.0)
        engine.fuse_lora_weight()
        assert engine.is_lora_fused
        engine.train()
        assert not engine.is_lora_fused
        rs = np.random.RandomState(0)
        toks = rs.randint(0, model.config.vocab_size, (8, 17)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        assert np.isfinite(float(jax.device_get(loss)))
