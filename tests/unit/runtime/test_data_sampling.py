"""Indexed dataset + data analyzer tests (reference:
``tests/unit/runtime/test_data_efficiency.py`` analysis paths)."""

from __future__ import annotations

import numpy as np
import pytest

from deepspeed_tpu.runtime.data_pipeline.data_sampling import (
    DataAnalyzer,
    MMapIndexedDataset,
    MMapIndexedDatasetBuilder,
    make_builder,
    make_dataset,
)


def _build(tmp_path, seqs, dtype=np.int32, docs=None):
    import os

    os.makedirs(str(tmp_path), exist_ok=True)
    prefix = str(tmp_path / "corpus")
    b = MMapIndexedDatasetBuilder(prefix + ".bin", dtype=dtype)
    for i, s in enumerate(seqs):
        b.add_item(s)
        if docs and i in docs:
            b.end_document()
    if not docs:
        b.end_document()
    b.finalize(prefix + ".idx")
    return prefix


class TestMMapIndexedDataset:
    def test_roundtrip(self, tmp_path):
        seqs = [[1, 2, 3], [4, 5], [6, 7, 8, 9], [10]]
        prefix = _build(tmp_path, seqs)
        ds = MMapIndexedDataset(prefix)
        assert len(ds) == 4
        for i, s in enumerate(seqs):
            np.testing.assert_array_equal(ds[i], np.asarray(s, np.int32))
        np.testing.assert_array_equal(ds.sizes, [3, 2, 4, 1])
        np.testing.assert_array_equal(ds.get(2, offset=1, length=2), [7, 8])

    def test_dtype_uint16(self, tmp_path):
        prefix = _build(tmp_path, [[65535, 1], [7]], dtype=np.uint16)
        ds = MMapIndexedDataset(prefix)
        assert ds.dtype == np.uint16
        np.testing.assert_array_equal(ds[0], np.asarray([65535, 1], np.uint16))

    def test_reference_format_compatibility(self, tmp_path):
        """Byte-level layout check against the documented MMIDIDX header."""
        import struct

        prefix = _build(tmp_path, [[1, 2], [3]])
        raw = open(prefix + ".idx", "rb").read()
        assert raw[:9] == b"MMIDIDX\x00\x00"
        assert struct.unpack("<Q", raw[9:17]) == (1,)
        assert raw[17] == 4  # dtype code for int32
        assert struct.unpack("<Q", raw[18:26]) == (2,)  # n sequences
        bin_raw = np.fromfile(prefix + ".bin", dtype=np.int32)
        np.testing.assert_array_equal(bin_raw, [1, 2, 3])

    def test_merge(self, tmp_path):
        p1 = _build(tmp_path / "a", [[1, 2]])
        p2 = _build(tmp_path / "b", [[3], [4, 5]])
        out = str(tmp_path / "merged")
        b = make_builder(out + ".bin")
        b.merge_file_(p1)
        b.merge_file_(p2)
        b.finalize(out + ".idx")
        ds = make_dataset(out)
        assert len(ds) == 3
        np.testing.assert_array_equal(ds[2], [4, 5])

    def test_exists(self, tmp_path):
        prefix = _build(tmp_path, [[1]])
        assert MMapIndexedDataset.exists(prefix)
        assert not MMapIndexedDataset.exists(str(tmp_path / "nope"))


class TestDataAnalyzer:
    def _dataset(self):
        rs = np.random.RandomState(0)
        return [rs.randint(0, 50, size=rs.randint(2, 10)) for _ in range(23)]

    @pytest.mark.parametrize("workers", [1, 3])
    def test_seqlen_metric(self, tmp_path, workers):
        data = self._dataset()
        an = DataAnalyzer(
            data,
            num_workers=workers,
            metric_names=["seqlen"],
            metric_functions=[len],
            metric_types=["single_value_per_sample"],
            save_path=str(tmp_path),
        )
        an.run()
        s2m = an.load_sample_to_metric("seqlen")
        np.testing.assert_array_equal(s2m, [len(s) for s in data])
        m2s = an.load_metric_to_sample("seqlen")
        values = an.load_metric_values("seqlen")
        # each value's bucket lists exactly the samples with that length
        for vi, v in enumerate(values):
            np.testing.assert_array_equal(
                m2s[vi], np.nonzero(s2m == v)[0].astype(np.int64)
            )

    def test_accumulate_metric(self, tmp_path):
        data = self._dataset()
        an = DataAnalyzer(
            data,
            num_workers=2,
            metric_names=["token_hist"],
            metric_functions=[lambda s: np.bincount(s, minlength=50)],
            metric_types=["accumulate_value_over_samples"],
            save_path=str(tmp_path),
        )
        an.run()
        hist = an.load_accumulate("token_hist")
        expected = np.zeros(50, np.int64)
        for s in data:
            expected += np.bincount(s, minlength=50)
        np.testing.assert_array_equal(hist, expected)

    def test_unknown_metric_type_raises(self, tmp_path):
        with pytest.raises(ValueError, match="metric_type"):
            DataAnalyzer(
                [],
                metric_names=["x"],
                metric_functions=[len],
                metric_types=["bogus"],
                save_path=str(tmp_path),
            )


def test_sampler_from_analysis(tmp_path):
    """Analyzer output feeds the curriculum sampler: early batches draw only
    easy (short) samples."""
    from deepspeed_tpu.runtime.data_pipeline.curriculum_scheduler import (
        CurriculumScheduler,
    )
    from deepspeed_tpu.runtime.data_pipeline.data_sampler import sampler_from_analysis

    rs = np.random.RandomState(0)
    data = [rs.randint(0, 50, size=n) for n in ([2] * 10 + [9] * 10)]
    DataAnalyzer(
        data,
        metric_names=["seqlen"],
        metric_functions=[len],
        metric_types=["single_value_per_sample"],
        save_path=str(tmp_path),
    ).run()

    sched = CurriculumScheduler(
        {
            "enabled": True,
            "curriculum_type": "seqlen",
            "min_difficulty": 2,
            "max_difficulty": 9,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 1},
        }
    )
    sampler = sampler_from_analysis(
        str(tmp_path), "seqlen", sched, global_batch_size=4
    )
    it = iter(sampler)
    first_batch = [next(it) for _ in range(4)]
    # at step 0 the threshold is min_difficulty=2: only the short samples
    assert all(i < 10 for i in first_batch), first_batch
