"""Activation checkpointing tests (reference:
tests/unit/runtime/activation_checkpointing/test_activation_checkpointing.py).

The mechanism here is jax.checkpoint (remat): values and grads must be
identical to the un-checkpointed call; configure()'s knob surface must match
the reference's."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.activation_checkpointing import checkpointing as ckpt
from deepspeed_tpu.models import TransformerLM, llama_config


@pytest.fixture(autouse=True)
def _reset_config():
    yield
    ckpt.reset()


def _fn(x, w):
    return jnp.sum(jnp.tanh(x @ w) ** 2)


def test_checkpoint_value_and_grad_match_direct():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 8), jnp.float32)
    w = jnp.asarray(rs.randn(8, 8), jnp.float32)
    direct_v = _fn(x, w)
    ckpt_v = ckpt.checkpoint(_fn, x, w)
    np.testing.assert_allclose(np.asarray(direct_v), np.asarray(ckpt_v), rtol=1e-6)
    g_direct = jax.grad(_fn, argnums=1)(x, w)
    g_ckpt = jax.grad(lambda x, w: ckpt.checkpoint(_fn, x, w), argnums=1)(x, w)
    np.testing.assert_allclose(np.asarray(g_direct), np.asarray(g_ckpt), rtol=1e-5)


def test_checkpoint_wrapper_and_function_shim():
    x = jnp.ones((2, 4))
    w = jnp.ones((4, 4))
    wrapped = ckpt.checkpoint_wrapper(_fn)
    np.testing.assert_allclose(np.asarray(wrapped(x, w)), np.asarray(_fn(x, w)), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(ckpt.CheckpointFunction.apply(_fn, x, w)), np.asarray(_fn(x, w)), rtol=1e-6
    )


def test_configure_surface():
    assert not ckpt.is_configured()
    ckpt.configure(partition_activations=True, checkpoint_in_cpu=False, num_checkpoints=2)
    assert ckpt.is_configured()
    assert ckpt.get_partition_activations()
    ckpt.reset()
    assert not ckpt.is_configured()


def test_policy_resolution():
    assert ckpt.policy_from_name(None) is None
    assert ckpt.policy_from_name("default") is None
    dots = ckpt.policy_from_name("dots")
    assert callable(dots)
    assert ckpt.policy_from_name("definitely_not_a_policy") is None  # warns, saves nothing


def test_remat_model_matches_stored_activations(eight_devices):
    """TransformerLM remat=True vs remat=False: same loss, same grads —
    recomputation must be semantics-preserving."""
    rs = np.random.RandomState(0)
    batch_toks = rs.randint(0, 128, (2, 17)).astype(np.int32)
    batch = {"input_ids": batch_toks[:, :-1], "labels": batch_toks[:, 1:]}

    losses, grads = [], []
    for remat in (False, True):
        cfg = llama_config("tiny", num_layers=2, remat=remat)
        model = TransformerLM(cfg)
        params = model.init(jax.random.PRNGKey(0), batch)

        def loss_fn(p):
            return model.apply(p, batch, rngs=jax.random.PRNGKey(1), train=True)

        l, g = jax.value_and_grad(loss_fn)(params)
        losses.append(float(l))
        grads.append(g)
    assert losses[0] == pytest.approx(losses[1], rel=1e-5)
    flat0 = jax.tree_util.tree_leaves(grads[0])
    flat1 = jax.tree_util.tree_leaves(grads[1])
    for a, b in zip(flat0, flat1):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6)
