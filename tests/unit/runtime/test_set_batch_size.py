"""Elastic batch resizing (reference: engine.py:403 set_train_batch_size,
:421 set_train_micro_batch_size): gas changes rebuild the fused/accumulating
step structure; micro changes retrace on shape."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import SimpleModel, random_dataloader


def _engine(gas=1, micro=1):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": micro,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
        },
    )
    return engine


def _steps(engine, n, batch):
    losses = []
    for _ in range(n):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


def test_grow_gas_switches_to_accumulating(eight_devices):
    engine = _engine(gas=1)
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    _steps(engine, 2, batch)
    assert engine._fused_step_enabled and engine._grad_acc is None

    engine.set_train_batch_size(16)  # micro(1) x dp(8) x gas(2)
    assert engine.gradient_accumulation_steps() == 2
    assert engine.train_batch_size() == 16
    assert not engine._fused_step_enabled
    assert engine._grad_acc is not None  # buffer allocated on the switch
    losses = _steps(engine, 4, batch)  # two full windows
    assert all(np.isfinite(l) for l in losses)


def test_shrink_gas_back_to_fused(eight_devices):
    engine = _engine(gas=2)
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    _steps(engine, 2, batch)  # one full window
    assert not engine._fused_step_enabled

    engine.set_train_batch_size(8)  # gas -> 1
    assert engine._fused_step_enabled and engine._grad_acc is None
    losses = _steps(engine, 2, batch)
    assert all(np.isfinite(l) for l in losses)


def test_resize_rebases_window_counter(eight_devices):
    """micro_steps=4 then gas 1->3: without re-basing, the first window
    would be short and its grads divided by the wrong divisor."""
    engine = _engine(gas=1)
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    _steps(engine, 4, batch)
    assert engine.micro_steps == 4
    engine.set_train_batch_size(24)  # gas=3
    assert engine.micro_steps == 0  # re-based: windows align with new gas
    for i in range(3):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        boundary_done = engine.micro_steps % 3 == 0
        assert boundary_done == (i == 2)  # exactly one full 3-step window


def test_zero_batch_rejected(eight_devices):
    engine = _engine(gas=1)
    with pytest.raises(ValueError, match="below one micro-batch"):
        engine.set_train_batch_size(0)
    with pytest.raises(ValueError, match="must be >= 1"):
        engine.set_train_micro_batch_size(0)


def test_indivisible_rejected(eight_devices):
    engine = _engine(gas=1)
    with pytest.raises(ValueError, match="divisible"):
        engine.set_train_batch_size(12)  # not a multiple of 8


def test_mid_window_resize_rejected(eight_devices):
    engine = _engine(gas=2)
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    loss = engine(batch)
    engine.backward(loss)
    engine.step()  # half a window
    with pytest.raises(RuntimeError, match="accumulation window"):
        engine.set_train_batch_size(8)


def test_set_micro_batch_size_updates_triad(eight_devices):
    engine = _engine(gas=2)
    engine.set_train_micro_batch_size(2)
    assert engine.train_micro_batch_size_per_gpu() == 2
    assert engine.train_batch_size() == 2 * 2 * 8  # micro x gas x dp
    batch = next(random_dataloader(total_samples=16, batch_size=16))
    losses = _steps(engine, 2, batch)  # shape change -> clean retrace
    assert all(np.isfinite(l) for l in losses)
