"""Tensor-fragment API tests (reference:
``tests/unit/runtime/zero/test_zero_tensor_fragment.py``)."""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.utils.tensor_fragment import (
    parameter_names,
    safe_get_full_fp32_param,
    safe_get_full_grad,
    safe_get_full_optimizer_state,
    safe_set_full_fp32_param,
    safe_set_full_optimizer_state,
)
from tests.unit.simple_model import SimpleModel


def _engine(zero_stage, extra=None):
    mesh_mod.reset_topology()
    cfg = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": dict({"stage": zero_stage}, **(extra or {})),
        "steps_per_print": 100,
    }
    model = SimpleModel(hidden_dim=16)
    engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
    rs = np.random.RandomState(0)
    batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    return engine


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
class TestFragmentGet:
    def test_get_param_and_state(self, stage):
        engine = _engine(stage)
        names = parameter_names(engine)
        assert "w0" in names
        w = safe_get_full_fp32_param(engine, "w0")
        assert w is not None and w.shape == (16, 16) and w.dtype == np.float32
        m = safe_get_full_optimizer_state(engine, "w0", "exp_avg")
        v = safe_get_full_optimizer_state(engine, "w0", "exp_avg_sq")
        assert m is not None and m.shape == (16, 16)
        assert v is not None and (v >= 0).all()

    def test_get_grad(self, stage):
        engine = _engine(stage)
        # after step() the accumulator was zeroed; run a fresh fwd/bwd
        rs = np.random.RandomState(1)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        loss = engine(batch)
        engine.backward(loss)
        g = safe_get_full_grad(engine, "w0")
        assert g is not None and g.shape == (16, 16)
        assert np.abs(g).sum() > 0


class TestFragmentSet:
    @pytest.mark.parametrize("stage", [1, 3])
    def test_set_param_roundtrip(self, stage):
        engine = _engine(stage)
        new = np.full((16, 16), 0.123, np.float32)
        assert safe_set_full_fp32_param(engine, "w0", new)
        got = safe_get_full_fp32_param(engine, "w0")
        np.testing.assert_allclose(got, new)
        # live compute param refreshed too
        live = np.asarray(engine.get_params()["w0"], np.float32)
        np.testing.assert_allclose(live, new, rtol=1e-2)

    def test_set_optimizer_state(self):
        engine = _engine(2)
        new = np.full((16, 16), 0.5, np.float32)
        assert safe_set_full_optimizer_state(engine, "w0", "exp_avg", new)
        got = safe_get_full_optimizer_state(engine, "w0", "exp_avg")
        np.testing.assert_allclose(got, new)

    def test_offload_unsorted_param_names(self):
        """Regression: insertion order != sorted order must still address the
        right leaf (jax tree_flatten sorts dict keys)."""
        from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_available

        if not native_adam_available():
            pytest.skip("no native adam")

        class ReversedModel:
            def init(self, rng, batch):  # noqa: ARG002
                import jax

                k1, k2 = jax.random.split(rng)
                # deliberately inserted in reverse-sorted order
                return {
                    "z_last": jax.random.normal(k1, (16, 16)) * 0.1,
                    "a_first": jax.random.normal(k2, (16, 16)) * 0.1 + 5.0,
                }

            def apply(self, params, batch, rngs=None, train=True):  # noqa: ARG002
                import jax.numpy as jnp

                x, y = batch
                return jnp.mean((x @ params["z_last"] @ params["a_first"] - y) ** 2)

        mesh_mod.reset_topology()
        cfg = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2, "offload_optimizer": {"device": "cpu"}},
            "steps_per_print": 100,
        }
        engine, _, _, _ = ds.initialize(
            model=ReversedModel(), config=cfg, dist_init_required=False
        )
        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        a = safe_get_full_fp32_param(engine, "a_first")
        z = safe_get_full_fp32_param(engine, "z_last")
        # a_first was initialized around +5, z_last around 0 — a swap would flip these
        assert a.mean() > 2.0 and abs(z.mean()) < 1.0

    def test_offload_set_get(self):
        from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_available

        if not native_adam_available():
            pytest.skip("no native adam")
        engine = _engine(2, {"offload_optimizer": {"device": "cpu"}})
        w = safe_get_full_fp32_param(engine, "w0")
        assert w is not None
        new = np.full((16, 16), 0.25, np.float32)
        assert safe_set_full_fp32_param(engine, "w0", new)
        np.testing.assert_allclose(safe_get_full_fp32_param(engine, "w0"), new)
        assert safe_set_full_optimizer_state(engine, "w0", "exp_avg", new)
        np.testing.assert_allclose(
            safe_get_full_optimizer_state(engine, "w0", "exp_avg"), new
        )


class TestZeroToFp32:
    def test_consolidation(self, tmp_path):
        from deepspeed_tpu.utils.zero_to_fp32 import (
            convert_zero_checkpoint_to_fp32_state_dict,
            get_fp32_state_dict_from_zero_checkpoint,
        )

        engine = _engine(2)
        engine.save_checkpoint(str(tmp_path / "ckpt"))
        sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path / "ckpt"))
        assert set(sd.keys()) == {"w0", "w1"}
        ref = safe_get_full_fp32_param(engine, "w0")
        np.testing.assert_allclose(sd["w0"], ref)
        out = str(tmp_path / "consolidated.npz")
        convert_zero_checkpoint_to_fp32_state_dict(str(tmp_path / "ckpt"), out)
        loaded = np.load(out)
        np.testing.assert_allclose(loaded["w0"], ref)
