"""zero.Init context semantics (reference: tests/unit/runtime/zero/
test_zero_context.py, test_zero_nesting_init.py): nesting, enabled=False,
shutdown, and that models built inside the context train normally (under
GSPMD the context is intent-marking — sharded init is the default)."""

import deepspeed_tpu as ds
from deepspeed_tpu import zero
from tests.unit.simple_model import SimpleModel, random_dataloader


def test_init_context_activates_and_deactivates():
    assert not zero.is_init_context_active()
    with zero.Init():
        assert zero.is_init_context_active()
    assert not zero.is_init_context_active()


def test_nested_init_keeps_outer_active():
    with zero.Init():
        with zero.Init():
            assert zero.is_init_context_active()
        # inner exit must NOT deactivate the outer context
        assert zero.is_init_context_active()
    assert not zero.is_init_context_active()


def test_disabled_init_is_inert():
    with zero.Init(enabled=False):
        assert not zero.is_init_context_active()
    # disabled inner context must not deactivate an enabled outer one
    with zero.Init():
        with zero.Init(enabled=False):
            assert zero.is_init_context_active()
        assert zero.is_init_context_active()


def test_shutdown_init_context_force_clears():
    with zero.Init():
        zero.shutdown_init_context()
        assert not zero.is_init_context_active()
    assert not zero.is_init_context_active()


def test_model_built_inside_init_trains(eight_devices):
    with zero.Init():
        model = SimpleModel()
        engine, *_ = ds.initialize(
            model=model,
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3},
            },
        )
        # initialize() PAUSES the context around engine construction and
        # restores it (reference __init__.py:128 + restore): code after it
        # in the same with-block still sees an active context
        assert zero.is_init_context_active()
    assert not zero.is_init_context_active()
    batch = next(random_dataloader(total_samples=8, batch_size=8))
    losses = []
    for _ in range(5):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # memorizing one batch must reduce loss
    # stage 3: master weights sharded over the data axis
    spec = engine.get_master_params()["w0"].sharding.spec
    assert "data" in str(spec)
