"""MiCS (reference ``deepspeed/runtime/zero/mics.py``): shard groups smaller
than the world, state replicated across groups."""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.runtime.zero.mics import MiCS_Init, MiCS_Optimizer
from tests.unit.simple_model import SimpleModel


def _config(mics=4):
    return {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3, "mics_shard_size": mics},
        "steps_per_print": 1000,
    }


class TestMiCSOptimizer:
    def test_reference_shaped_flow(self):
        """The reference example shape: MiCS_Init ctx + MiCS_Optimizer(...)
        returns a working engine with the group-sharded mesh."""
        mesh_mod.reset_topology()
        with MiCS_Init(config_dict_or_path=_config()):
            model = SimpleModel(hidden_dim=16)
        engine = MiCS_Optimizer(model, ds_config=_config(mics=4))
        # 8 virtual devices, shard groups of 4 -> 2 replica groups
        assert engine.topology.mesh.shape["data"] == 4
        assert engine.topology.mesh.shape["data_outer"] == 2

        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        losses = []
        for _ in range(5):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]
        # ZeRO state shards over the inner 'data' axis only: all-gathers stay
        # inside a shard group, replicas ride data_outer
        spec = str(engine.get_master_params()["w0"].sharding.spec)
        assert "data" in spec
        assert "data_outer" not in spec

    def test_requires_config(self):
        with pytest.raises(ValueError, match="ds_config"):
            MiCS_Optimizer(SimpleModel(8))

    def test_missing_shard_size_warns_and_runs(self, caplog):
        mesh_mod.reset_topology()
        cfg = _config()
        del cfg["zero_optimization"]["mics_shard_size"]
        engine = MiCS_Optimizer(SimpleModel(hidden_dim=16), ds_config=cfg)
        assert engine.topology.mesh.shape["data"] == 8  # full-world fallback
