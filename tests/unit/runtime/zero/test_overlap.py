"""Comm/compute overlap for ZeRO training (``runtime/zero/overlap.py``).

The contract under test (ISSUE 5): the pipelined parameter gather and the
bucketed in-scan gradient reduce-scatter are SCHEDULE transforms — they
move where collectives are issued, never what is computed. So:

* parity is ``assert_array_equal`` (bit-identity), not allclose —
  pipelined (``prefetch_layers >= 1``) vs the explicit use-point gather
  (``prefetch_layers: 0``), across ZeRO-1/3 × gas ∈ {1, 2} × fp32/bf16;
* the PR-1 invariants survive the restructuring: one fused dispatch per
  optimizer step, full state donation (checked via the analysis passes);
* the ``overlap`` analysis pass verifies the compiled ZeRO-3 step has
  real compute to hide every loop-body collective behind (green, with
  nonzero hidden bytes — the acceptance criterion), refuses to verify the
  unpipelined raw-scan program, and fails a deliberately serialized
  schedule (red fixture: every dot depends on the loop's gather).

Runs comm-free on the 8-device virtual CPU mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import llama_config

VOCAB = 64
SEQ = 16
STEPS = 2


def _model(num_layers=3, remat=False):
    # einsum attention: flash-attention's CPU interpret-mode Pallas loops
    # contain genuinely-exposed slice gathers the pipeline does not own
    # (pre-existing; the overlap pass would flag them) — the overlap
    # contract is exercised on the XLA attention path
    cfg = llama_config(
        "tiny",
        hidden_size=128,
        num_heads=4,
        num_layers=num_layers,
        max_seq_len=SEQ,
        vocab_size=VOCAB,
        remat=remat,
        attn_dropout=0.0,
        hidden_dropout=0.0,
        flash_attention=False,
        scan_layers=True,
        dtype="float32",
    )
    return TransformerLM(cfg)


def _engine(zover=None, gas=1, precision="fp32", fuse=False, num_layers=3,
            remat=False, extra_cfg=None):
    mesh_mod.reset_topology()
    zero = {
        "stage": 3,
        "overlap_comm": True,
        # hidden-128 leaves all sit under the default persistence threshold
        # (1e5) — zero it so the stack is actually ZeRO-sharded and the
        # pipeline has gathers to own
        "stage3_param_persistence_threshold": 0,
        "reduce_scatter": True,
    }
    zero.update(zover or {})
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": zero,
        "steps_per_print": 10_000,
    }
    if fuse:
        config["compile"] = {"fuse_grad_accum": True}
    if precision == "bf16":
        config["bf16"] = {"enabled": True}
    config.update(extra_cfg or {})
    engine, *_ = ds.initialize(model=_model(num_layers, remat=remat), config=config)
    return engine


def _batches(gas, steps, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        micro = []
        for _ in range(gas):
            toks = rs.randint(0, VOCAB, (8, SEQ + 1)).astype(np.int32)
            micro.append({"input_ids": toks[:, :-1], "labels": toks[:, 1:]})
        out.append(micro)
    return out


def _train(engine, batches):
    return [
        np.asarray(jax.device_get(engine.train_batch(iter(list(micro)))))
        for micro in batches
    ]


def _plan(engine):
    """The overlap plan is built with the jitted programs on the first
    batch (init_params is lazy) — trigger it with one forward."""
    engine(_batches(1, 1)[0][0])
    return engine._overlap_plan


def _masters(engine):
    flat, _ = jax.tree_util.tree_flatten_with_path(engine.get_master_params())
    return [(jax.tree_util.keystr(p), np.asarray(jax.device_get(l))) for p, l in flat]


def _assert_states_identical(ea, eb):
    for (ka, va), (kb, vb) in zip(_masters(ea), _masters(eb)):
        assert ka == kb
        np.testing.assert_array_equal(va, vb, err_msg=f"master leaf {ka} diverged")


# ---------------------------------------------------------------------------
# parity: pipelined vs use-point gather is bit-identical
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("stage", [1, 3])
@pytest.mark.parametrize("gas", [1, 2])
@pytest.mark.parametrize("precision", ["fp32", "bf16"])
def test_overlap_parity_bit_identical(stage, gas, precision, eight_devices):
    """Losses AND the full master tree match exactly (=, not allclose)
    between the pipelined step and the unpipelined (depth-0) step."""
    batches = _batches(gas, STEPS)
    e0 = _engine({"stage": stage, "prefetch_layers": 0}, gas, precision)
    l0 = _train(e0, batches)
    e1 = _engine({"stage": stage, "prefetch_layers": 1}, gas, precision)
    l1 = _train(e1, batches)
    if stage >= 3:
        # guard against vacuous parity: the pipeline must actually engage
        assert e1._overlap_plan is not None and e1._overlap_plan.prefetch_enabled
        assert e1._overlap_plan.depth == 1
        assert e0._overlap_plan is not None and e0._overlap_plan.depth == 0
    else:
        # stage 1 has nothing to prefetch or scatter: the knob must no-op
        assert e1._overlap_plan is None and e0._overlap_plan is None
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    _assert_states_identical(e0, e1)


def test_depth2_and_reduce_off_still_bit_identical(eight_devices):
    """Pipeline depth is schedule-only at every depth, and the bucketed
    reduce-scatter transform is value-preserving on its own."""
    batches = _batches(1, STEPS)
    ref = _engine({"prefetch_layers": 0})
    lref = _train(ref, batches)
    for zover in ({"prefetch_layers": 2}, {"prefetch_layers": 1, "reduce_scatter": False}):
        e = _engine(zover)
        l = _train(e, batches)
        for a, b in zip(lref, l):
            np.testing.assert_array_equal(a, b)
        _assert_states_identical(ref, e)


def test_remat_parity_bit_identical(eight_devices):
    """cfg.remat wraps the pipelined scan body (fresh custom_vjp closures +
    jax.linear_transpose inside jax.checkpoint) — the combination most
    prone to remat/transpose interaction regressions across jax versions.
    The bit-exact contract must hold there too."""
    batches = _batches(1, STEPS)
    e0 = _engine({"prefetch_layers": 0}, remat=True)
    l0 = _train(e0, batches)
    e1 = _engine({"prefetch_layers": 1}, remat=True)
    l1 = _train(e1, batches)
    assert e1._overlap_plan is not None and e1._overlap_plan.prefetch_enabled
    for a, b in zip(l0, l1):
        np.testing.assert_array_equal(a, b)
    _assert_states_identical(e0, e1)


def test_pld_disables_prefetch_visibly(eight_devices):
    """PLD owns the layer loop (cond-skipped layers) — the prefetch
    pipeline does not run there. The plan must SAY so (prefetch_enabled
    False) instead of reporting a pipeline that never engaged; the bucketed
    grad reduction still applies."""
    plan = _plan(_engine(
        {"prefetch_layers": 1},
        extra_cfg={"progressive_layer_drop": {
            "enabled": True, "theta": 0.5, "gamma": 0.001}},
    ))
    assert plan is not None
    assert not plan.prefetch_enabled and plan.depth == 0
    assert plan.reduce_enabled


def test_explicit_gather_matches_raw_scan_allclose(eight_devices):
    """The raw scan (no plan: GSPMD places the gathers itself) reassociates
    the distributed grad sum at the last ulp, so raw-vs-explicit is a tight
    allclose, not = (the bit-exact contract binds the plan's depths to each
    other, not to GSPMD's free choice)."""
    batches = _batches(1, STEPS)
    e0 = _engine({"prefetch_layers": 0})
    l0 = _train(e0, batches)
    eraw = _engine({"overlap_comm": False, "prefetch_layers": None})
    assert eraw._overlap_plan is None
    lraw = _train(eraw, batches)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(lraw), rtol=2e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# plan gating and the in-flight byte budget
# ---------------------------------------------------------------------------
def test_plan_gating(eight_devices):
    # default persistence threshold: every hidden-128 leaf is persistent
    # (replicated), so there is nothing to prefetch — but the bucketed
    # reduce transform still applies
    plan = _plan(_engine({"stage3_param_persistence_threshold": 100_000}))
    assert plan is not None
    assert not plan.prefetch_enabled
    assert plan.reduce_enabled
    # ZeRO++ quantized wire formats own their gather/reduce schedules
    assert _plan(_engine({"zero_quantized_weights": True})) is None


def test_prefetch_bucket_size_caps_depth(eight_devices):
    """stage3_prefetch_bucket_size bounds in-flight prefetched elements:
    a 1-element budget forces the pipeline down to depth 1 (never 0 — one
    layer of lookahead is the floor while prefetch is on)."""
    assert _plan(_engine({"prefetch_layers": 2, "stage3_prefetch_bucket_size": int(5e7)})).depth == 2
    assert _plan(_engine({"prefetch_layers": 2, "stage3_prefetch_bucket_size": 1})).depth == 1


def test_row_coalesced_roundtrip():
    """The [world, C] bucket layout is pure data movement: pack→unpack is
    exact, including the padded not-world-divisible leaf."""
    from deepspeed_tpu.runtime.comm.coalesced_collectives import (
        pack_row_coalesced,
        row_coalesced_layout,
        unpack_row_coalesced,
    )

    world = 8
    shapes = [(16, 3), (8, 2), (5,)]
    tensors = [
        jnp.arange(int(np.prod(s)), dtype=jnp.float32).reshape(s) for s in shapes
    ]
    buf = pack_row_coalesced(tensors, world)
    layout = row_coalesced_layout(shapes, world)
    assert buf.shape == (world, sum(w for _, w in layout))
    out = unpack_row_coalesced(buf, shapes, world)
    for t, o in zip(tensors, out):
        np.testing.assert_array_equal(np.asarray(t), np.asarray(o))


# ---------------------------------------------------------------------------
# PR-1 invariants survive the pipeline
# ---------------------------------------------------------------------------
def test_one_dispatch_and_donation_preserved(eight_devices):
    """Pipelined + bucketed + fused grad-accum still runs ONE jitted program
    per optimizer step, compiles once, and donates-and-aliases the full
    state (via the analysis passes, like PR 3 moved the old runtime
    probes)."""
    e = _engine({"prefetch_layers": 1}, gas=2, precision="bf16", fuse=True)
    _train(e, _batches(2, 3))
    stats = e.compile_stats()
    fused = stats["fused_accum_step"]
    assert fused["dispatches"] == 3, stats
    assert fused["compiles"] == 1, stats
    assert stats["fwd_bwd"]["dispatches"] == 0, stats
    assert stats["step"]["dispatches"] == 0, stats
    rep = e.analysis_report(programs=["fused_accum_step"])
    entry = rep["programs"]["fused_accum_step"]["passes"]
    assert entry["donation"]["ok"], entry["donation"]["violations"]
    assert entry["donation"]["summary"].get("double_buffered_bytes", 0) == 0
    assert entry["host_transfer"]["ok"], entry["host_transfer"]["violations"]


# ---------------------------------------------------------------------------
# the overlap analysis pass: green on the real program, red on serialized
# ---------------------------------------------------------------------------
def test_overlap_pass_green_on_pipelined_zero3_step(eight_devices):
    """Acceptance: the compiled ZeRO-3 pipelined step program verifies —
    every loop-body collective has independent real compute to hide behind,
    with nonzero hidden collective bytes — and the raw (plan-less) scan
    program does NOT, on the same model/mesh (red on a real program, not
    just the fixture)."""
    e = _engine({"prefetch_layers": 1})
    _train(e, _batches(1, 1))
    t = e.analysis_report(passes=["overlap"])["totals"]
    assert t["overlap_verified"] is True, t
    assert t["hidden_collective_bytes"] > 0, t

    eraw = _engine({"overlap_comm": False})
    assert eraw._overlap_plan is None
    _train(eraw, _batches(1, 1))
    traw = eraw.analysis_report(passes=["overlap"])["totals"]
    assert traw["overlap_verified"] is False, traw


def test_overlap_pass_red_serialized_schedule(eight_devices):
    """Red fixture: a scan whose every dot depends on the loop-body param
    gather — the serialized schedule the pipeline exists to prevent. The
    pass must refuse to verify it and name the exposed collective."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.analysis import analyze_program
    from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.asarray(jax.devices()[:8]), ("x",))
    # a stacked, ZeRO-sharded layer stack: the per-iteration slice makes the
    # gather loop-VARIANT, so licm cannot hoist it out of the while body
    # (a loop-invariant gather gets hoisted and stops being a loop finding)
    ws = jax.device_put(
        jnp.stack([jnp.eye(64, dtype=jnp.float32)] * 4),
        NamedSharding(mesh, P(None, "x", None)),
    )
    x = jax.device_put(
        jnp.ones((64, 64), jnp.float32), NamedSharding(mesh, P(None, None))
    )

    def gather(t):
        return shard_map(
            lambda s: jax.lax.all_gather(s, "x", tiled=True),
            mesh=mesh,
            in_specs=P("x", None),
            out_specs=P(None, None),
            check_vma=False,
        )(t)

    def serialized(x, ws):
        def body(c, i):
            w = jax.lax.dynamic_index_in_dim(ws, i, axis=0, keepdims=False)
            g = gather(w)  # use-point gather: the compute below depends on it
            return c @ g, None

        out, _ = jax.lax.scan(body, x, jnp.arange(4, dtype=jnp.int32))
        return out

    tel = CompileTelemetry()
    fn = tel.instrument("serialized", serialized)
    fn(x, ws)
    res = analyze_program(
        "serialized", tel.programs()["serialized"], passes=["overlap"]
    )["overlap"]
    assert res.summary["loop_collectives"] >= 1, res.summary
    assert res.summary["overlap_verified"] is False, res.summary
    assert res.violations and res.violations[0].severity == "warn"
    # require_overlap escalates the finding to error severity (CI gate mode)
    res = analyze_program(
        "serialized",
        tel.programs()["serialized"],
        passes=["overlap"],
        config={"require_overlap": True},
    )["overlap"]
    assert res.violations and res.violations[0].severity == "error"
