"""ZeRO correctness (reference: tests/unit/runtime/zero/test_zero.py).

The key invariant: stage choice changes WHERE state lives, never the math.
Stage 0/1/2/3 must produce identical training trajectories, and stage >= 1
must actually shard master/optimizer state over the data axis.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from tests.unit.simple_model import (
    SimpleModel,
    learnable_dataloader,
    random_dataloader,
    rel_loss_decrease,
)


def _train(stage, steps=5, gas=1, dtype="bf16", hidden=64):
    import deepspeed_tpu.parallel.mesh as mesh_mod

    mesh_mod.reset_topology()
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2, "weight_decay": 0.01}},
        dtype: {"enabled": dtype != "fp32"},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
    }
    if dtype == "fp32":
        config.pop(dtype)
    engine, *_ = ds.initialize(model=SimpleModel(hidden), config=config)
    losses = []
    # deterministic fixed-batch data with a guaranteed gradient (same
    # de-flake as test_zeropp): learning is a property of the optimizer,
    # not of the per-step random targets the old loader drew
    for i, batch in enumerate(learnable_dataloader(hidden, total_samples=steps * gas * 8, batch_size=8)):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stage_trains(stage, eight_devices):
    engine, losses = _train(stage)
    assert rel_loss_decrease(losses) > 0.05, f"stage {stage} did not learn: {losses}"


def test_zero_stages_identical_math(eight_devices):
    baseline = None
    for stage in [0, 1, 2, 3]:
        _, losses = _train(stage)
        if baseline is None:
            baseline = losses
        else:
            np.testing.assert_allclose(losses, baseline, rtol=1e-6)


def test_zero1_shards_optimizer_state(eight_devices):
    engine, _ = _train(1)
    mom = engine._opt_state.exp_avg["w0"]
    assert "data" in str(mom.sharding.spec)
    # bf16 params stay replicated at stage 1
    assert "data" not in str(engine.get_params()["w0"].sharding.spec)


def test_zero3_shards_params(eight_devices):
    import deepspeed_tpu.parallel.mesh as mesh_mod

    mesh_mod.reset_topology()
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
    }
    engine, *_ = ds.initialize(model=SimpleModel(64), config=config)
    batch = next(random_dataloader(64))
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    assert "data" in str(engine.get_params()["w0"].sharding.spec)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_gradient_accumulation_equivalence(stage, eight_devices):
    """gas=2 with half micro-batches matches gas=1 full batch on the SAME
    token stream — i.e. the fused single-program path (gas=1) and the
    accumulating path (gas>1) implement the same math, per stage. Deleting
    either path's numerics (not just its speed) must fail this test."""
    import deepspeed_tpu.parallel.mesh as mesh_mod

    steps = 3
    data = list(random_dataloader(64, total_samples=steps * 16, batch_size=16))

    def run(gas):
        mesh_mod.reset_topology()
        config = {
            "train_micro_batch_size_per_gpu": 2 // gas,
            "gradient_accumulation_steps": gas,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2, "weight_decay": 0.01}},
            "zero_optimization": {"stage": stage},
            "gradient_clipping": 1.0,
        }
        engine, *_ = ds.initialize(model=SimpleModel(64), config=config)
        step_losses = []
        micro = 16 // gas
        for x, y in data:
            micro_losses = []
            for g in range(gas):
                sub = (x[g * micro : (g + 1) * micro], y[g * micro : (g + 1) * micro])
                loss = engine(sub)
                engine.backward(loss)
                engine.step()
                micro_losses.append(float(jax.device_get(loss)))
            step_losses.append(sum(micro_losses) / len(micro_losses))
        assert engine.global_steps == steps
        # confirm the intended code paths actually ran
        assert engine._fused_step_enabled == (gas == 1)
        master = jax.tree_util.tree_map(
            lambda a: np.asarray(jax.device_get(a)), engine.get_master_params()
        )
        return step_losses, master

    losses_gas1, master_gas1 = run(1)
    losses_gas2, master_gas2 = run(2)
    # fp32: summation-order differences only
    np.testing.assert_allclose(losses_gas1, losses_gas2, rtol=1e-4, atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(master_gas1), jax.tree_util.tree_leaves(master_gas2)
    ):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_estimate_zero_memory():
    from deepspeed_tpu.zero import estimate_zero_memory

    est0 = estimate_zero_memory(int(1e9), stage=0, dp_size=8)
    est3 = estimate_zero_memory(int(1e9), stage=3, dp_size=8)
    assert est3["total_bytes"] < est0["total_bytes"] / 6
