"""ZeRO spec-emission unit tests."""

import numpy as np
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.parallel.mesh import initialize_topology
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.runtime.zero.config import DeepSpeedZeroConfig
from deepspeed_tpu.runtime.zero.partition import ZeroPartitioner, shard_over_zero_axes


def _topo(**kw):
    return initialize_topology(MeshConfig(**kw))


def test_shards_largest_divisible_dim(eight_devices):
    topo = _topo()
    spec = shard_over_zero_axes((16, 64), topo)
    assert spec == P(None, "data")


def test_below_threshold_replicated(eight_devices):
    topo = _topo()
    spec = shard_over_zero_axes((16, 64), topo, threshold=10_000)
    assert spec == P(None, None)


def test_indivisible_replicated(eight_devices):
    topo = _topo()
    spec = shard_over_zero_axes((3, 5), topo)
    assert spec == P(None, None)


def test_respects_tp_axes(eight_devices):
    topo = _topo(model=2)
    spec = shard_over_zero_axes((64, 64), topo, base_spec=P(None, "model"))
    assert spec == P("data", "model")


def test_stage_selection(eight_devices):
    topo = _topo()
    params = {"w": np.zeros((64, 64), np.float32)}
    for stage, param_sharded, grad_sharded in [(0, False, False), (1, False, False), (2, False, True), (3, True, True)]:
        part = ZeroPartitioner(DeepSpeedZeroConfig(stage=stage, stage3_param_persistence_threshold=0), topo)
        ps = part.param_specs(params)["w"]
        gs = part.grad_accum_specs(params)["w"]
        ms = part.master_specs(params)["w"]
        assert ("data" in str(ps)) == param_sharded, f"stage {stage} param"
        assert ("data" in str(gs)) == grad_sharded, f"stage {stage} grad"
        assert ("data" in str(ms)) == (stage >= 1), f"stage {stage} master"
