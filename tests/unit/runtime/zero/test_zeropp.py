"""ZeRO++ tests (reference: ``tests/unit/runtime/zero/test_zeropp.py``).

qwZ: stage-3 param gathers carry int8 on the wire; training stays within
quantization tolerance of exact stage 3. qgZ: the explicit quantized grad
reduce matches the exact path within tolerance. hpZ: params shard over the
secondary (intra-group) partition while masters keep the full DP sharding,
with exact numerics.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from tests.unit.simple_model import (
    SimpleModel,
    learnable_dataloader,
    random_dataloader,
    rel_loss_decrease,
)

HIDDEN = 64


def _train(zero_cfg, steps=5, bf16=False):
    mesh_mod.reset_topology()
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        # clipping makes the trajectory sensitive to any grad-scale error
        # (e.g. forgetting the 1/world average of per-chip partials)
        "gradient_clipping": 1.0,
        "zero_optimization": zero_cfg,
    }
    if bf16:
        config["bf16"] = {"enabled": True}
    engine, *_ = ds.initialize(model=SimpleModel(HIDDEN), config=config)
    losses = []
    # deterministic fixed-batch data with a guaranteed gradient: "did the
    # run learn" is then a property of the optimizer, not of the rng draw
    # (the old per-step random targets flaked under the box's jax 0.4.37)
    for batch in learnable_dataloader(HIDDEN, total_samples=steps * 8, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


class TestQwZ:
    def test_trains_within_quant_tolerance(self, eight_devices):
        _, exact = _train({"stage": 3, "stage3_param_persistence_threshold": 0})
        engine, quant = _train(
            {
                "stage": 3,
                "stage3_param_persistence_threshold": 0,
                "zero_quantized_weights": True,
            }
        )
        assert rel_loss_decrease(quant) > 0.05, f"qwZ run did not learn: {quant}"
        np.testing.assert_allclose(quant, exact, rtol=0.05, atol=5e-3)
        # int8 quantization must actually perturb the math (i.e. the flag is
        # consumed, not ignored)
        assert not np.allclose(quant, exact, rtol=1e-12, atol=0)

    def test_int8_on_the_wire(self, eight_devices):
        """The compiled program's param all-gather moves s8, not f32/bf16."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deepspeed_tpu.parallel.mesh import initialize_topology
        from deepspeed_tpu.runtime.zero.zeropp import qwz_gather_tree

        mesh_mod.reset_topology()
        topo = initialize_topology({})
        mesh = topo.mesh
        spec = P("data", None)
        x = jax.device_put(
            np.random.RandomState(0).randn(64, 32).astype(np.float32),
            NamedSharding(mesh, spec),
        )
        fn = jax.jit(lambda p: qwz_gather_tree({"w": p}, {"w": spec}, topo)["w"].sum())
        hlo = fn.lower(x).compile().as_text()
        import re

        # lines where the op itself is an all-gather (not fusions consuming one)
        ag_ops = re.findall(r"= (\S+) all-gather\(", hlo)
        assert any(t.startswith("s8[64,32]") for t in ag_ops), ag_ops
        # the only wide-float gather allowed is the per-group scales
        assert not any(
            t.startswith(("f32[64,32]", "bf16[64,32]")) for t in ag_ops
        ), f"param payload gather still moves wide floats: {ag_ops}"

    def test_requires_stage3(self, eight_devices):
        with pytest.raises(ValueError, match="stage 3"):
            _train({"stage": 1, "zero_quantized_weights": True}, steps=1)


class TestQgZ:
    def test_trains_within_quant_tolerance(self, eight_devices):
        engine_e, exact = _train({"stage": 3, "stage3_param_persistence_threshold": 0})
        engine, quant = _train(
            {
                "stage": 3,
                "stage3_param_persistence_threshold": 0,
                "zero_quantized_gradients": True,
            }
        )
        assert engine._fused_step_enabled is False  # explicit grad path in use
        assert rel_loss_decrease(quant) > 0.05, f"qgZ run did not learn: {quant}"
        np.testing.assert_allclose(quant, exact, rtol=0.05, atol=5e-3)
        assert not np.allclose(quant, exact, rtol=1e-12, atol=0)
        # grad norms must agree in scale (catches missing 1/world averaging)
        n_exact = engine_e.get_global_grad_norm()
        n_quant = engine.get_global_grad_norm()
        assert abs(n_quant - n_exact) / n_exact < 0.05, (n_quant, n_exact)

    def test_combined_with_qwz(self, eight_devices):
        _, exact = _train({"stage": 3, "stage3_param_persistence_threshold": 0})
        _, quant = _train(
            {
                "stage": 3,
                "stage3_param_persistence_threshold": 0,
                "zero_quantized_weights": True,
                "zero_quantized_gradients": True,
            }
        )
        np.testing.assert_allclose(quant, exact, rtol=0.08, atol=1e-2)

    def test_rejects_nondata_mesh(self):
        mesh_mod.reset_topology()
        config = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "mesh": {"model": 2},
            "zero_optimization": {"stage": 3, "zero_quantized_gradients": True},
        }
        engine, *_ = ds.initialize(model=SimpleModel(HIDDEN), config=config)
        batch = next(random_dataloader(HIDDEN, total_samples=8, batch_size=8))
        with pytest.raises(ValueError, match="pure data-axis"):
            engine(batch)


class TestHpZ:
    def test_secondary_partition_shardings(self, eight_devices):
        engine, losses = _train(
            {
                "stage": 3,
                "stage3_param_persistence_threshold": 0,
                "zero_hpz_partition_size": 4,
            },
            bf16=True,
        )
        assert losses[-1] < losses[0]
        # mesh split 8 = data(4) × data_outer(2)
        assert engine.topology.axis_size("data") == 4
        assert engine.topology.axis_size("data_outer") == 2
        p_spec = str(engine.get_params()["w0"].sharding.spec)
        m_spec = str(engine.get_master_params()["w0"].sharding.spec)
        # the bf16 store shards within the group only (gathers stay local);
        # the fp32 master shards over the full DP world
        assert "data" in p_spec and "data_outer" not in p_spec, p_spec
        assert "data_outer" in m_spec, m_spec

    def test_matches_plain_stage3(self, eight_devices):
        _, exact = _train(
            {"stage": 3, "stage3_param_persistence_threshold": 0}, bf16=True
        )
        _, hpz = _train(
            {
                "stage": 3,
                "stage3_param_persistence_threshold": 0,
                "zero_hpz_partition_size": 4,
            },
            bf16=True,
        )
        # hpZ changes placement only, never math (bf16 reduction-order noise)
        np.testing.assert_allclose(hpz, exact, rtol=1e-2, atol=1e-3)

    def test_requires_mixed_precision(self):
        with pytest.raises(ValueError, match="bf16/fp16"):
            _train({"stage": 3, "zero_hpz_partition_size": 4}, steps=1)

    def test_conflicts_with_mics(self):
        with pytest.raises(ValueError, match="mics"):
            _train(
                {"stage": 3, "zero_hpz_partition_size": 4, "mics_shard_size": 4},
                steps=1,
                bf16=True,
            )


def test_unwired_nontrainable_key_raises():
    with pytest.raises(NotImplementedError, match="nontrainable"):
        _train({"stage": 3, "zero_quantized_nontrainable_weights": True}, steps=1)
