"""ZeRO-Offload / Infinity tests.

Reference analogs: ``tests/unit/runtime/zero/test_zero.py`` offload
parametrizations + ``tests/unit/ops/aio/`` + CPUAdam numerics
(``tests/perf/adam_test.py``). The key check: offloaded training must match
the in-device optimizer step.
"""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_available
from tests.unit.simple_model import SimpleModel

pytestmark = pytest.mark.skipif(
    not native_adam_available(), reason="native cpu_adam unavailable"
)


def _losses(config, steps=4, seed=0):
    mesh_mod.reset_topology()
    model = SimpleModel(hidden_dim=32, nlayers=2)
    engine, _, _, _ = ds.initialize(model=model, config=config, dist_init_required=False)
    rs = np.random.RandomState(seed)
    losses = []
    for _ in range(steps):
        x = rs.randn(8, 32).astype(np.float32)
        y = rs.randn(8, 32).astype(np.float32)
        loss = engine((x, y))
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


BASE = {
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "adam", "params": {"lr": 1e-2, "weight_decay": 0.01}},
    "steps_per_print": 100,
}


class TestCpuOffload:
    @pytest.mark.parametrize("stage", [1, 2, 3])
    def test_matches_device_optimizer(self, stage):
        device_cfg = dict(BASE, zero_optimization={"stage": stage})
        dev_losses, _ = _losses(device_cfg)
        offload_cfg = dict(
            BASE,
            zero_optimization={"stage": stage, "offload_optimizer": {"device": "cpu"}},
        )
        off_losses, _ = _losses(offload_cfg)
        np.testing.assert_allclose(off_losses, dev_losses, rtol=3e-4, atol=1e-5)

    def test_bf16_offload_trains(self):
        cfg = dict(
            BASE,
            bf16={"enabled": True},
            zero_optimization={"stage": 2, "offload_optimizer": {"device": "cpu"}},
        )
        losses, engine = _losses(cfg, steps=6)
        assert losses[-1] < losses[0]
        assert engine._host_offload is not None
        assert engine._opt_state is None  # no moments on device

    def test_checkpoint_roundtrip(self, tmp_path):
        cfg = dict(
            BASE,
            zero_optimization={"stage": 2, "offload_optimizer": {"device": "cpu"}},
        )
        losses, engine = _losses(cfg, steps=2)
        engine.save_checkpoint(str(tmp_path))

        mesh_mod.reset_topology()
        model = SimpleModel(hidden_dim=32, nlayers=2)
        engine2, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        rs = np.random.RandomState(9)
        batch = (rs.randn(8, 32).astype(np.float32), rs.randn(8, 32).astype(np.float32))
        engine2.init_params(batch)
        engine2.load_checkpoint(str(tmp_path))
        assert engine2.global_steps == engine.global_steps
        m1 = engine.get_master_params()
        m2 = engine2.get_master_params()
        import jax

        for a, b in zip(jax.tree_util.tree_leaves(m1), jax.tree_util.tree_leaves(m2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestNvmeOffload:
    def test_matches_device_optimizer(self, tmp_path):
        device_cfg = dict(BASE, zero_optimization={"stage": 2})
        dev_losses, _ = _losses(device_cfg)
        nvme_cfg = dict(
            BASE,
            zero_optimization={
                "stage": 2,
                "offload_optimizer": {"device": "nvme", "nvme_path": str(tmp_path)},
            },
        )
        off_losses, engine = _losses(nvme_cfg)
        np.testing.assert_allclose(off_losses, dev_losses, rtol=3e-4, atol=1e-5)
        assert engine._host_offload.swapper is not None
        # moment arrays actually live on disk, not DRAM
        import os

        files = []
        for root, _, fnames in os.walk(str(tmp_path)):
            files += [f for f in fnames if f.endswith(".swp")]
        assert files, "no swap files created"
