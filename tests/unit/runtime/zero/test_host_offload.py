"""ZeRO-Infinity streamed host offload (ISSUE 16): fp32 master + Adam
moments live in pinned host buffers and stream device-ward bucket by
bucket through a depth-2 double-buffered pipeline, hidden behind compute.

The bit-identity contract pinned here:

* sequential streamed training bit-matches the on-device path — losses,
  master tree, moments, fp16 scale trajectory — across
  zero{1,3} x {fp32,bf16,fp16} x gas{1,2};
* under ``compile.multi_step`` the window program is the SAME trace on
  both engines, so a fully-windowed run (params pre-initialized — the
  lazy-init step would otherwise run as a sequential step) is bitwise
  end to end, overflow-in-window included;
* a checkpoint roundtrip and a ``train.mid_offload_stream`` chaos kill
  both resume bit-identically — torn host buffers are never trusted,
  they are rebuilt from the last committed checkpoint.

Plus the stream accounting (declared schedule == measured bytes, zero
exposed ms with both pipeline knobs on, red when a knob is off), the
bucket splitter edges, the config-hygiene red tests, and the bench
bisection-probe helper.
"""

from __future__ import annotations

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.runtime.zero.host_offload import split_offload_buckets
from deepspeed_tpu.utils import chaos
from tests.unit.simple_model import SimpleModel, master_snapshot, step_batch, train_steps_batch

# 300-element buckets split SimpleModel's 512 params into 2 buckets, so
# every test exercises real bucket boundaries and the double-buffer depth
STREAM = {
    "device": "cpu",
    "pin_memory": True,
    "pipeline_read": True,
    "pipeline_write": True,
    "bucket_size": 300,
}


def _cfg(offload, gas=1, stage=1, prec="bf16", multi_step=False, horizon=2, **over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "gradient_clipping": 1.0,
    }
    if prec == "bf16":
        base["bf16"] = {"enabled": True}
    elif prec == "fp16":
        base["fp16"] = {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}
    if gas > 1 and (offload or multi_step):
        base["compile"] = {"fuse_grad_accum": True}
    if multi_step:
        base.setdefault("compile", {})["multi_step"] = {
            "enable": True, "horizon": horizon,
        }
    if offload:
        base["zero_optimization"]["offload_optimizer"] = dict(STREAM)
    base.update(over)
    return base


def _engine(offload, **kw):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg(offload, **kw))
    return engine


def _batches(gas, steps, seed=0, bad_step=None):
    bad = set() if bad_step is None else {bad_step}
    rs = np.random.RandomState(seed)
    out = []
    for s in range(steps):
        for g in range(gas):
            x = rs.randn(8, 16).astype(np.float32)
            y = rs.randn(8, 16).astype(np.float32)
            if s in bad and g == 0:
                x = x.copy()
                x[0, 0] = np.inf
            out.append((x, y))
    return out


def _drive(engine, data, steps):
    it = iter(list(data))
    return [float(engine.train_batch(data_iter=it)) for _ in range(steps)]


def _assert_same_master(a, b):
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


# ---------------------------------------------------------------------------
# bucket splitter unit edges
# ---------------------------------------------------------------------------
def test_split_buckets_groups_whole_leaves_in_order():
    assert split_offload_buckets([100, 100, 100], 200) == [[0, 1], [2]]
    assert split_offload_buckets([100, 100, 100], 300) == [[0, 1, 2]]
    assert split_offload_buckets([100, 100], 1) == [[0], [1]]


def test_split_buckets_oversized_leaf_gets_own_bucket():
    # a leaf bigger than bucket_size never splits (whole-leaf streaming);
    # it closes the open bucket and rides alone
    assert split_offload_buckets([50, 500, 50], 100) == [[0], [1], [2]]
    assert split_offload_buckets([500], 100) == [[0]]


def test_split_buckets_exact_fit_and_empty():
    assert split_offload_buckets([100, 100, 100, 100], 200) == [[0, 1], [2, 3]]
    assert split_offload_buckets([], 100) == []


# ---------------------------------------------------------------------------
# bit-identity: sequential streamed vs on-device
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("stage", [1, 3])
@pytest.mark.parametrize("prec", ["fp32", "bf16", "fp16"])
@pytest.mark.parametrize("gas", [1, 2])
def test_streamed_bit_identical_to_on_device(eight_devices, stage, prec, gas):
    """Losses AND fp32 master bit-match the on-device engine over 3 steps
    for every zero-stage x precision x gas combination. The streamed step
    (fwd_bwd + offload_stats + per-bucket donated updates) mirrors the
    on-device update math op for op; at gas>1 the on-device arm runs the
    unfused micro path — the program family the streamed grads share."""
    batch = step_batch(batch_size=8 * gas, seed=0)
    ref = _engine(False, gas=gas, stage=stage, prec=prec)
    ref_losses = train_steps_batch(ref, batch, 3)
    ref_master = master_snapshot(ref)
    off = _engine(True, gas=gas, stage=stage, prec=prec)
    off_losses = train_steps_batch(off, batch, 3)
    assert off._streamed_offload, "streamed engine not selected"
    assert off._host_offload.num_buckets >= 2  # real bucket boundaries
    np.testing.assert_array_equal(np.asarray(off_losses), np.asarray(ref_losses))
    _assert_same_master(master_snapshot(off), ref_master)


def test_fp16_overflow_reverts_bitwise_and_tracks_scale(eight_devices):
    """An overflow micro-batch must leave the offloaded master bitwise
    untouched (the donated bucket programs revert via jnp.where, the host
    discards the staged buckets) and walk the loss scale exactly like the
    on-device engine."""
    batch = step_batch(batch_size=8, seed=0)
    x, y = batch
    xbad = x.copy()
    xbad[0, 0] = np.inf
    for offload in (False, True):
        engine = _engine(offload, prec="fp16")
        train_steps_batch(engine, batch, 1)
        before = master_snapshot(engine)
        engine.train_batch(batch=(xbad, y))
        assert engine.skipped_steps == 1, f"offload={offload}"
        assert engine.loss_scale == 8.0, f"offload={offload}"
        _assert_same_master(master_snapshot(engine), before)


# ---------------------------------------------------------------------------
# bit-identity under multi_step windows
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("prec", ["bf16", "fp16"])
@pytest.mark.parametrize("gas", [1, 2])
def test_windowed_run_bit_identical(eight_devices, prec, gas):
    """Fully-windowed streamed run vs fully-windowed on-device run: the
    window program is the identical trace on both engines (the streamed
    arm gathers master/moments device-ward, runs the SAME window, streams
    the result back), so losses, master, skipped steps and the loss-scale
    trajectory are bitwise. Params are pre-initialized so the lazy-init
    step doesn't fall back to a sequential (different-program) step; the
    fp16 arm puts an overflow INSIDE a window."""
    steps, horizon = 6, 2
    bad = 2 if prec == "fp16" else None
    data = _batches(gas, steps, bad_step=bad)
    runs = {}
    for offload in (False, True):
        engine = _engine(offload, gas=gas, prec=prec, multi_step=True, horizon=horizon)
        engine.init_params(data[0])
        losses = _drive(engine, data, steps)
        ws = engine.window_stats()
        assert ws["window_steps"] == steps // horizon, (offload, ws)
        runs[offload] = (
            losses, master_snapshot(engine), engine.skipped_steps, engine.loss_scale,
        )
    ref_losses, ref_master, ref_skip, ref_scale = runs[False]
    off_losses, off_master, off_skip, off_scale = runs[True]
    assert off_losses == ref_losses
    assert (off_skip, off_scale) == (ref_skip, ref_scale)
    _assert_same_master(off_master, ref_master)


def test_window_gather_scatter_roundtrip_lossless(eight_devices):
    """gather_device_state -> scatter_device_state with zero steps taken
    must leave the host buffers bit-identical: the window path's framing
    adds nothing to the state."""
    engine = _engine(True)
    batch = step_batch(batch_size=8, seed=0)
    train_steps_batch(engine, batch, 1)
    ho = engine._host_offload
    ho.drain_writes()
    before = (
        [m.copy() for m in ho._master],
        [m.copy() for m in ho._exp_avg],
        [m.copy() for m in ho._exp_avg_sq],
    )
    masters, ms, vs = ho.gather_device_state()
    ho.scatter_device_state(masters, ms, vs, steps_taken=0)
    ho.drain_writes()
    for got, want in zip((ho._master, ho._exp_avg, ho._exp_avg_sq), before):
        for g, w in zip(got, want):
            np.testing.assert_array_equal(g, w)
    assert ho.step_count == 1


# ---------------------------------------------------------------------------
# stream accounting: declared schedule vs measured transfers
# ---------------------------------------------------------------------------
def test_stream_schedule_matches_measured_bytes(eight_devices):
    engine = _engine(True)
    batch = step_batch(batch_size=8, seed=0)
    train_steps_batch(engine, batch, 3)
    ho = engine._host_offload
    sched = ho.stream_schedule()
    assert sched["anchor"] == "offload_stats"
    declared_h2d = sum(t["bytes"] for t in sched["transfers"] if t["direction"] == "h2d")
    declared_d2h = sum(t["bytes"] for t in sched["transfers"] if t["direction"] == "d2h")
    compute = set(sched["compute_programs"])
    assert all(t["hide_behind"] in compute for t in sched["transfers"])
    stats = engine.offload_stream_stats()
    assert stats["steps"] == 3
    assert stats["h2d_bytes"] == 3 * declared_h2d
    assert stats["d2h_bytes"] == 3 * declared_d2h
    # both pipeline knobs on: every copy is issued async and lands behind
    # compute — zero blocking wait on the stream
    assert stats["exposed_ms"] == 0.0


def test_stream_exposed_when_pipeline_write_off(eight_devices):
    """pipeline_write=False is the red arm of the overlap story: writes
    block at the end of each bucket (measured exposed_ms > 0 once timing
    is observable) and the DECLARED schedule stops claiming a hiding
    program, which the overlap pass turns into exposed stream bytes."""
    over = dict(STREAM)
    over["pipeline_write"] = False
    engine = _engine(True, **{"zero_optimization": {
        "stage": 1, "offload_optimizer": over}})
    batch = step_batch(batch_size=8, seed=0)
    train_steps_batch(engine, batch, 2)
    assert engine._streamed_offload
    sched = engine._host_offload.stream_schedule()
    d2h = [t for t in sched["transfers"] if t["direction"] == "d2h"]
    assert d2h and all(t["hide_behind"] is None for t in d2h)
    rep = engine.analysis_report(programs=["offload_stats"], passes=["overlap"])
    t = rep["totals"]
    assert t["stream_verified"] is False
    assert t["exposed_stream_bytes"] == sum(x["bytes"] for x in d2h)


# ---------------------------------------------------------------------------
# checkpoints: host-resident snapshot, roundtrip, format guards
# ---------------------------------------------------------------------------
def test_checkpoint_roundtrip_bit_identical(eight_devices, tmp_path):
    steps = 4
    data = _batches(1, steps)
    ref = _engine(True, prec="fp16")
    ref_losses = _drive(ref, data, steps)
    ref_master = master_snapshot(ref)

    engine = _engine(True, prec="fp16")
    _drive(engine, data[:2], 2)
    engine.save_checkpoint(str(tmp_path), tag="mid")
    engine.wait_pending_checkpoint()

    resumed = _engine(True, prec="fp16")
    resumed.init_params(data[0])
    path, _ = resumed.load_checkpoint(str(tmp_path), tag="mid")
    assert path is not None
    out = _drive(resumed, data[2:], steps - 2)
    assert out == ref_losses[2:]
    _assert_same_master(master_snapshot(resumed), ref_master)


def test_state_dict_is_host_resident_numpy(eight_devices):
    """The checkpoint snapshot must come straight from the pinned host
    buffers — plain numpy, no device round-trip for the async writer to
    stall on — and must drain the in-flight write fence first."""
    engine = _engine(True)
    batch = step_batch(batch_size=8, seed=0)
    train_steps_batch(engine, batch, 2)
    state = engine._host_offload.state_dict()
    assert state["format"] == "streamed"
    assert state["step"] == 2
    for rec in state["leaves"]:
        for key in ("master", "exp_avg", "exp_avg_sq"):
            assert type(rec[key]) is np.ndarray, key
    # copies, not views of the live buffers: training must not mutate a
    # snapshot the async writer is still draining
    engine._host_offload._master[0][...] = 0.0
    assert np.any(state["leaves"][0]["master"] != 0.0)


def test_streamed_rejects_legacy_checkpoint_and_vice_versa(eight_devices):
    batch = step_batch(batch_size=8, seed=0)
    streamed = _engine(True)
    train_steps_batch(streamed, batch, 1)
    streamed_state = streamed._host_offload.state_dict()

    legacy_cfg = dict(STREAM)
    legacy_cfg["pipeline_read"] = legacy_cfg["pipeline_write"] = False
    legacy = _engine(True, **{"zero_optimization": {
        "stage": 1, "offload_optimizer": legacy_cfg}})
    train_steps_batch(legacy, batch, 1)
    assert not legacy._streamed_offload  # the legacy host-Adam engine
    legacy_state = legacy._host_offload.state_dict()

    with pytest.raises(ValueError, match="(?i)streamed"):
        legacy._host_offload.load_state_dict(streamed_state)
    with pytest.raises(ValueError, match="legacy"):
        streamed._host_offload.load_state_dict(legacy_state)


# ---------------------------------------------------------------------------
# chaos: kill mid-stream, resume from the last committed checkpoint
# ---------------------------------------------------------------------------
def test_mid_stream_chaos_kill_resumes_bit_identical(eight_devices, tmp_path):
    """``train.mid_offload_stream`` fires between bucket dispatches: the
    kill lands with staged H2D buckets live, in-flight D2H writes pending,
    and the host buffers torn mid-step. The resumed engine never trusts
    them — it rebuilds from the last interval autosave — and the continued
    run is bit-identical to an uninterrupted one. fp16: scale state rides
    the checkpoint too."""
    steps = 6
    data = _batches(1, steps, seed=7)

    def build():
        return _engine(True, prec="fp16", **{
            "checkpoint": {"interval_steps": 2, "save_dir": str(tmp_path)},
        })

    ref = build()
    ref_losses = _drive(ref, data, steps)
    ref_master = master_snapshot(ref)
    import shutil

    shutil.rmtree(str(tmp_path))
    tmp_path.mkdir()

    engine = build()
    it = iter(list(data))
    committed = []
    # 2 buckets -> the point fires twice per step; hit=5 kills step 3
    # (0-indexed step 2) on its FIRST bucket — a genuinely torn stream
    chaos.install(chaos.ChaosSchedule([
        chaos.ChaosRule("train.mid_offload_stream", hit=5),
    ]))
    try:
        for _ in range(steps):
            committed.append(float(engine.train_batch(data_iter=it)))
        raise AssertionError("chaos never fired")
    except chaos.ChaosKilled:
        pass
    finally:
        chaos.uninstall()
    assert committed == ref_losses[: len(committed)]

    resumed = build()
    resumed.init_params(data[0])
    path, _ = resumed.load_checkpoint(str(tmp_path), auto_resume=True)
    assert path is not None
    start = resumed.global_steps
    assert start % 2 == 0 and start >= len(committed) - 1
    it2 = iter(list(data[start:]))
    out = [float(resumed.train_batch(data_iter=it2)) for _ in range(steps - start)]
    assert out == ref_losses[start:]
    _assert_same_master(master_snapshot(resumed), ref_master)


# ---------------------------------------------------------------------------
# config hygiene (red tests)
# ---------------------------------------------------------------------------
def test_config_red_orphan_pin_memory_knob():
    """The silently-popped knob: cpu_offload_use_pin_memory without any
    offloaded optimizer used to parse and then vanish. Now it's a clear
    error."""
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    with pytest.raises(Exception, match="cpu_offload_use_pin_memory"):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "zero_optimization": {"stage": 1, "cpu_offload_use_pin_memory": True},
        })


def test_config_legacy_cpu_offload_routes():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {
            "stage": 1,
            "cpu_offload": True,
            "cpu_offload_use_pin_memory": True,
            "cpu_offload_param": True,
        },
    })
    off = cfg.zero_config.offload_optimizer
    assert off is not None and str(off.device.value) == "cpu"
    assert off.pin_memory is True
    assert cfg.zero_config.offload_param is not None
    assert str(cfg.zero_config.offload_param.device.value) == "cpu"


def test_config_red_streamed_buffer_count_too_small(eight_devices):
    over = dict(STREAM)
    over["buffer_count"] = 1
    engine = _engine(True, **{"zero_optimization": {
        "stage": 1, "offload_optimizer": over}})
    with pytest.raises(ValueError, match="buffer_count"):
        engine.train_batch(batch=step_batch(batch_size=8, seed=0))


def test_config_red_streamed_partial_ratio(eight_devices):
    over = dict(STREAM)
    over["ratio"] = 0.5
    engine = _engine(True, **{"zero_optimization": {
        "stage": 1, "offload_optimizer": over}})
    with pytest.raises(ValueError, match="ratio"):
        engine.train_batch(batch=step_batch(batch_size=8, seed=0))


def test_red_multistep_rejects_legacy_offload_and_offload_param(eight_devices):
    # legacy (non-pipelined) host offload cannot window: the message must
    # point at the streamed path
    legacy = dict(STREAM)
    legacy["pipeline_read"] = legacy["pipeline_write"] = False
    cfg = _cfg(False, multi_step=True)
    cfg["zero_optimization"]["offload_optimizer"] = legacy
    mesh_mod.reset_topology()
    with pytest.raises(ValueError, match="pipeline"):
        ds.initialize(model=SimpleModel(), config=cfg)

    cfg = _cfg(False, multi_step=True, stage=3)
    cfg["zero_optimization"]["offload_param"] = {"device": "cpu"}
    mesh_mod.reset_topology()
    with pytest.raises(ValueError, match="offload_param"):
        ds.initialize(model=SimpleModel(), config=cfg)


# ---------------------------------------------------------------------------
# the bench probe's pure bisection helper
# ---------------------------------------------------------------------------
def test_max_params_under_budget_bisection():
    import sys

    sys.path.insert(0, str(__import__("pathlib").Path(__file__).parents[4]))
    from bench import _max_params_under_budget

    calls = []

    def fits(i):
        calls.append(i)
        return i <= 11

    assert _max_params_under_budget(fits, 0, 31) == 11
    assert len(calls) <= 7  # log2(32) + the lo probe: bisection, not a sweep
    assert _max_params_under_budget(lambda i: True, 0, 9) == 9
    assert _max_params_under_budget(lambda i: False, 0, 9) == -1
    assert _max_params_under_budget(lambda i: i == 0, 0, 0) == 0
