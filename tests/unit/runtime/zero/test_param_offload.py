"""ZeRO-Infinity *parameter* offload (layer streaming) tests.

Reference analogs: ``tests/unit/runtime/zero/test_zero.py`` offload-param
parametrizations + ``partitioned_param_swapper`` behavior
(``deepspeed/runtime/swap_tensor/partitioned_param_swapper.py:36``). The key
checks: training with params in host DRAM / on disk matches in-HBM ZeRO-3
training, and the streamed state checkpoints round-trip.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.models.transformer import TransformerLM
from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_available

pytestmark = pytest.mark.skipif(
    not native_adam_available(), reason="native cpu_adam unavailable"
)

CFG = dict(
    vocab_size=128,
    hidden_size=32,
    num_layers=3,
    num_heads=4,
    max_seq_len=32,
    dtype="float32",
    flash_attention=False,
)

BASE = {
    "train_micro_batch_size_per_gpu": 1,
    "optimizer": {"type": "adam", "params": {"lr": 1e-2, "weight_decay": 0.01}},
    "steps_per_print": 100,
}


def _batches(n, steps, seed=0):
    rs = np.random.RandomState(seed)
    out = []
    for _ in range(steps):
        toks = rs.randint(0, CFG["vocab_size"], size=(n, 16)).astype(np.int32)
        out.append({"input_ids": toks, "labels": toks})
    return out


def _train(config, steps=4, gas=1):
    mesh_mod.reset_topology()
    model = TransformerLM(TransformerConfig(**CFG))
    cfg = dict(config, gradient_accumulation_steps=gas)
    engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
    losses = []
    for batch in _batches(8, steps * gas):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    return losses, engine


class TestParamOffloadCpu:
    def test_matches_in_hbm_zero3(self):
        dev_losses, _ = _train(dict(BASE, zero_optimization={"stage": 3}))
        off_losses, engine = _train(
            dict(
                BASE,
                zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}},
            )
        )
        assert engine._param_stream is not None
        assert engine._param_stream.store.device == "cpu"
        # no monolithic jitted step was ever built on the stream path
        assert engine._jit_fused_step is None and engine._jit_step is None
        np.testing.assert_allclose(off_losses, dev_losses, rtol=3e-4, atol=1e-5)

    def test_gas_accumulation(self):
        """gas=2 offload matches gas=2 in-HBM (window accumulation on host)."""
        dev_losses, _ = _train(dict(BASE, zero_optimization={"stage": 3}), steps=2, gas=2)
        off_losses, _ = _train(
            dict(
                BASE,
                zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}},
            ),
            steps=2,
            gas=2,
        )
        np.testing.assert_allclose(off_losses, dev_losses, rtol=3e-4, atol=1e-5)

    def test_requires_stage3(self):
        with pytest.raises(ValueError, match="stage 3"):
            _train(
                dict(
                    BASE,
                    zero_optimization={"stage": 2, "offload_param": {"device": "cpu"}},
                ),
                steps=1,
            )

    def test_rejects_unstreamable_model(self):
        from tests.unit.simple_model import SimpleModel

        mesh_mod.reset_topology()
        engine, _, _, _ = ds.initialize(
            model=SimpleModel(hidden_dim=16, nlayers=2),
            config=dict(
                BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
            ),
            dist_init_required=False,
        )
        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        with pytest.raises(ValueError, match="stream_fns"):
            engine(batch)

    def test_checkpoint_roundtrip(self, tmp_path):
        cfg = dict(
            BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
        )
        losses, engine = _train(cfg, steps=2)
        engine.save_checkpoint(str(tmp_path))

        mesh_mod.reset_topology()
        model = TransformerLM(TransformerConfig(**CFG))
        engine2, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        engine2.init_params(_batches(8, 1, seed=7)[0])
        engine2.load_checkpoint(str(tmp_path))
        assert engine2.global_steps == engine.global_steps

        import jax

        for a, b in zip(
            jax.tree_util.tree_leaves(engine.get_master_params()),
            jax.tree_util.tree_leaves(engine2.get_master_params()),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # both continue identically
        batch = _batches(8, 1, seed=11)[0]
        for e in (engine, engine2):
            l = e(batch)
            e.backward(l)
            e.step()
        np.testing.assert_allclose(
            float(engine._last_loss), float(engine2._last_loss), rtol=1e-6
        )

    def test_module_only_load_resets_moments(self, tmp_path):
        cfg = dict(
            BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
        )
        _, engine = _train(cfg, steps=2)
        engine.save_checkpoint(str(tmp_path))

        # mid-run module-only load: trained moments/step must be discarded
        _, engine2 = _train(cfg, steps=2)
        engine2.load_checkpoint(str(tmp_path), load_module_only=True)
        stream = engine2._param_stream
        assert stream.step_count == 0
        assert all(
            st.exp_avg is None or np.all(st.exp_avg == 0)
            for st in stream._layer_state
        )

        import jax

        for a, b in zip(
            jax.tree_util.tree_leaves(engine.get_master_params()),
            jax.tree_util.tree_leaves(engine2.get_master_params()),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_rejects_moe_family(self):
        from deepspeed_tpu.models.moe_transformer import (
            MoETransformerConfig,
            MoETransformerLM,
        )

        mesh_mod.reset_topology()
        model = MoETransformerLM(
            MoETransformerConfig(
                vocab_size=64,
                hidden_size=16,
                num_layers=2,
                num_heads=2,
                num_experts=2,
                dtype="float32",
            )
        )
        engine, _, _, _ = ds.initialize(
            model=model,
            config=dict(
                BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
            ),
            dist_init_required=False,
        )
        with pytest.raises(NotImplementedError, match="MoE"):
            engine(_batches(8, 1)[0])

    def test_eval_deterministic_under_dropout(self):
        cfg_m = dict(CFG, hidden_dropout=0.1, attn_dropout=0.1)
        mesh_mod.reset_topology()
        model = TransformerLM(TransformerConfig(**cfg_m))
        engine, _, _, _ = ds.initialize(
            model=model,
            config=dict(
                BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
            ),
            dist_init_required=False,
        )
        batch = _batches(8, 1)[0]
        engine.init_params(batch)
        engine.eval()
        l1 = float(engine(batch))
        l2 = float(engine(batch))
        assert l1 == l2, "eval loss must be dropout-free and deterministic"

    def test_double_forward_raises(self):
        cfg = dict(
            BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
        )
        mesh_mod.reset_topology()
        model = TransformerLM(TransformerConfig(**CFG))
        engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        batches = _batches(8, 2)
        engine(batches[0])
        with pytest.raises(RuntimeError, match="backward"):
            engine(batches[1])

    def test_eval_logits_inference(self):
        cfg = dict(
            BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
        )
        mesh_mod.reset_topology()
        model = TransformerLM(TransformerConfig(**CFG))
        engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        batch = _batches(8, 1)[0]
        engine.init_params(batch)
        engine.eval()
        logits = engine(batch["input_ids"])  # labels-less batch → logits
        assert logits.shape == (8, 16, CFG["vocab_size"])

    def test_eval_does_not_disturb_training(self):
        cfg = dict(
            BASE, zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}}
        )
        mesh_mod.reset_topology()
        model = TransformerLM(TransformerConfig(**CFG))
        engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        batches = _batches(8, 3)
        l0 = engine(batches[0])
        engine.backward(l0)
        engine.step()
        engine.eval()
        eval_loss = engine(batches[1])
        assert np.isfinite(float(eval_loss))
        engine.train()
        l1 = engine(batches[2])
        engine.backward(l1)
        engine.step()
        assert engine.global_steps == 2


class TestParamOffloadNvme:
    def test_matches_cpu_store(self, tmp_path):
        cpu_losses, _ = _train(
            dict(
                BASE,
                zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}},
            )
        )
        nvme_losses, engine = _train(
            dict(
                BASE,
                zero_optimization={
                    "stage": 3,
                    # buffer_count=2 < num_layers=3 forces staging-slot reuse
                    "offload_param": {
                        "device": "nvme",
                        "nvme_path": str(tmp_path),
                        "buffer_count": 2,
                    },
                },
            )
        )
        # identical math: the nvme store round-trips the same compute-dtype bytes
        np.testing.assert_allclose(nvme_losses, cpu_losses, rtol=1e-6, atol=0)
        swap_dir = os.path.join(str(tmp_path), "ds_tpu_param_swap")
        files = [f for f in os.listdir(swap_dir) if f.startswith("layer_")]
        assert len(files) == CFG["num_layers"]
        # gathered layers must be distinct copies, not aliased staging views
        # (n_layers=3 > buffer_count=2 reuses staging slots)
        gathered = engine.get_params()["layers"]
        leaf = next(iter(gathered.values()))
        assert not np.array_equal(leaf[0], leaf[2]), "staging-buffer aliasing"


class TestParamOffloadFp16:
    def test_overflow_skip_and_rescale(self):
        """fp16 + dynamic loss scale on the stream path: early steps overflow
        at the huge initial scale, get skipped (reference overflow-skip
        semantics), the scale backs off, training proceeds."""
        mesh_mod.reset_topology()
        cfg_m = dict(CFG, dtype="float16")
        model = TransformerLM(TransformerConfig(**cfg_m))
        engine, _, _, _ = ds.initialize(
            model=model,
            config=dict(
                BASE,
                fp16={"enabled": True, "initial_scale_power": 20},
                zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}},
            ),
            dist_init_required=False,
        )
        scales = []
        losses = []
        for batch in _batches(8, 8):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
            scales.append(engine.loss_scale)
        assert engine.skipped_steps > 0, "expected early overflow skips at 2^20"
        assert engine.skipped_steps < 8, "every step skipped: scale never recovered"
        assert scales[-1] < scales[0], "dynamic scale never backed off"
        assert np.isfinite(losses[-1])
        # weights moved (some step applied) but the step counter counts all
        init_master = np.concatenate(
            [st.master for st in engine._param_stream._layer_state]
        )
        mesh_mod.reset_topology()
        fresh = TransformerLM(TransformerConfig(**cfg_m))
        e2, _, _, _ = ds.initialize(
            model=fresh,
            config=dict(
                BASE,
                fp16={"enabled": True, "initial_scale_power": 20},
                zero_optimization={"stage": 3, "offload_param": {"device": "cpu"}},
            ),
            dist_init_required=False,
        )
        e2.init_params(_batches(8, 1)[0])
        fresh_master = np.concatenate(
            [st.master for st in e2._param_stream._layer_state]
        )
        assert not np.array_equal(init_master, fresh_master), "no step ever applied"
        assert engine.global_steps == 8
