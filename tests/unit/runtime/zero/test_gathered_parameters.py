"""zero.GatheredParameters write-back + rejected dead flags.

Reference: ``partition_parameters.py:1938`` (GatheredParameters re-partitions
modified params transparently on exit), ``tests/unit/runtime/zero/test_zero_context*``.
"""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.zero import GatheredParameters
from tests.unit.simple_model import SimpleModel, random_dataloader

HIDDEN = 32


def _engine(zero_cfg=None, bf16=False):
    mesh_mod.reset_topology()
    config = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": zero_cfg or {"stage": 3},
    }
    if bf16:
        config["bf16"] = {"enabled": True}
    engine, *_ = ds.initialize(model=SimpleModel(HIDDEN), config=config)
    batch = next(random_dataloader(HIDDEN, total_samples=8, batch_size=8))
    engine.init_params(batch)
    return engine, batch


@pytest.mark.parametrize("bf16", [False, True])
def test_write_back_sticks_through_step(eight_devices, bf16):
    engine, batch = _engine(bf16=bf16)
    with GatheredParameters(engine=engine, modifier_rank=0) as params:
        params["w0"][:] = 0.5  # user surgery on the gathered host view
    # surgery must be visible in BOTH stores...
    np.testing.assert_allclose(np.asarray(engine.get_params()["w0"]), 0.5, rtol=1e-2)
    np.testing.assert_allclose(np.asarray(engine.get_master_params()["w0"]), 0.5)
    # ...and survive an optimizer step (master was refreshed, not just params)
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    w0 = np.asarray(engine.get_params()["w0"], dtype=np.float32)
    assert np.abs(w0 - 0.5).max() < 0.1, "step clobbered the surgery"


def test_write_back_host_offload(eight_devices):
    engine, batch = _engine(
        zero_cfg={"stage": 2, "offload_optimizer": {"device": "cpu"}}
    )
    with GatheredParameters(engine=engine, modifier_rank=0) as params:
        params["w0"][:] = 0.25
    np.testing.assert_allclose(np.asarray(engine.get_master_params()["w0"]), 0.25)
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    w0 = np.asarray(engine.get_params()["w0"], dtype=np.float32)
    assert np.abs(w0 - 0.25).max() < 0.1


def test_write_back_param_stream(eight_devices):
    from deepspeed_tpu.models.config import TransformerConfig
    from deepspeed_tpu.models.transformer import TransformerLM
    from deepspeed_tpu.ops.adam.cpu_adam_native import native_adam_available

    if not native_adam_available():
        pytest.skip("native cpu_adam unavailable")
    mesh_mod.reset_topology()
    model = TransformerLM(
        TransformerConfig(
            vocab_size=64,
            hidden_size=16,
            num_layers=2,
            num_heads=2,
            dtype="float32",
            flash_attention=False,
        )
    )
    engine, *_ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "zero_optimization": {"stage": 3, "offload_param": {"device": "cpu"}},
        },
    )
    toks = np.random.RandomState(0).randint(0, 64, size=(8, 8)).astype(np.int32)
    engine.init_params({"input_ids": toks, "labels": toks})
    with GatheredParameters(engine=engine, modifier_rank=0) as params:
        params["final_norm_scale"][:] = 2.0
        params["layers"]["wq"][:] = 0.125
    np.testing.assert_allclose(np.asarray(engine.get_params()["final_norm_scale"]), 2.0)
    np.testing.assert_allclose(
        np.asarray(engine.get_master_params()["layers"]["wq"]), 0.125
    )


def test_partial_tree_without_write_back_raises(eight_devices):
    engine, _ = _engine()
    sub = {"w0": engine.get_params()["w0"]}  # partial tree
    with pytest.raises(ValueError, match="write-back"):
        GatheredParameters(sub, modifier_rank=0, engine=engine)


def test_no_modifier_rank_reads_only(eight_devices):
    engine, _ = _engine()
    before = np.asarray(engine.get_params()["w0"]).copy()
    with GatheredParameters(engine=engine) as params:
        params["w0"][:] = 99.0  # read-only context: mutation is dropped
    np.testing.assert_array_equal(np.asarray(engine.get_params()["w0"]), before)


def test_sparse_gradients_rejected():
    mesh_mod.reset_topology()
    with pytest.raises(NotImplementedError, match="sparse_gradients"):
        ds.initialize(
            model=SimpleModel(HIDDEN),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "sparse_gradients": True,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            },
        )


def test_structure_check_does_not_materialize_params(eight_devices):
    """The full-tree structure check must use the engine's treedef, not
    get_params(): on the offload path gathered_params copies the whole model
    to host just to compare shapes (round-3 advisory)."""
    engine, _ = _engine()
    real = engine.get_params()
    calls = []
    orig = engine.get_params
    engine.get_params = lambda: calls.append(1) or orig()
    with GatheredParameters(params=real, modifier_rank=0, engine=engine) as p:
        pass
    assert not calls, "structure check materialized the full param tree"
