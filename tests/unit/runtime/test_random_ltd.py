"""Random-LTD end to end (reference: data_routing engine hooks
engine.py:340-344, basic_layer.py RandomLayerTokenDrop, csrc/random_ltd/):
per-layer token subsets in the model, schedule-driven kept counts in the
engine, checkpointed scheduler state."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM, llama_config
from deepspeed_tpu.runtime.data_pipeline.data_routing import (
    RandomLTDScheduler,
    sample_layer_token_indices,
)


def _model(**over):
    kw = dict(num_layers=4, remat=False, attn_dropout=0.0, hidden_dropout=0.0,
              flash_attention=False, max_seq_len=64)
    kw.update(over)
    return TransformerLM(llama_config("tiny", **kw))


def _batch(vocab, B=2, T=64, seed=0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, vocab, (B, T + 1)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


class TestSampler:
    def test_shapes_sorted_unique(self):
        idx = sample_layer_token_indices(jax.random.PRNGKey(0), 3, 2, 64, 16)
        assert idx.shape == (3, 2, 16)
        a = np.asarray(idx)
        assert (np.diff(a, axis=-1) > 0).all()  # sorted, no duplicates
        assert a.min() >= 0 and a.max() < 64
        # layers draw different subsets
        assert not np.array_equal(a[0], a[1])

    def test_scheduler_ramp(self):
        s = RandomLTDScheduler(start_token_num=16, max_token_num=64, total_steps=10, step_size=16)
        assert s.current == 16
        s.update(5)
        assert 16 <= s.current <= 64
        s.update(10)
        assert s.current == 64
        sd = s.state_dict()
        s2 = RandomLTDScheduler(16, 64, 10)
        s2.load_state_dict(sd)
        assert s2.current == s.current


class TestModelLTD:
    @pytest.mark.parametrize("scan_layers", [True, False])
    def test_full_idx_matches_dense(self, eight_devices, scan_layers):
        """kept == T with the identity permutation: every layer sees every
        token — must equal the plain forward."""
        model = _model(scan_layers=scan_layers)
        batch = _batch(model.config.vocab_size)
        params = model.init(jax.random.PRNGKey(0), batch)
        T = 64
        idx = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (2, 2, T))
        base = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True)
        ltd = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True, ltd_idx=idx)
        np.testing.assert_allclose(np.asarray(base), np.asarray(ltd), rtol=2e-4, atol=1e-5)

    def test_subset_changes_output_and_grads_flow(self, eight_devices):
        model = _model()
        batch = _batch(model.config.vocab_size)
        params = model.init(jax.random.PRNGKey(0), batch)
        idx = sample_layer_token_indices(jax.random.PRNGKey(2), 2, 2, 64, 16)
        base = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True)
        ltd = model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True, ltd_idx=idx)
        assert not np.allclose(np.asarray(base), np.asarray(ltd))

        def loss_fn(p):
            return model.apply(p, batch, rngs=jax.random.PRNGKey(1), train=True, ltd_idx=idx)

        grads = jax.grad(loss_fn)(params)
        gnorm = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree_util.tree_leaves(grads))
        assert np.isfinite(gnorm) and gnorm > 0

    def test_eval_ignores_ltd(self, eight_devices):
        model = _model()
        batch = _batch(model.config.vocab_size)
        params = model.init(jax.random.PRNGKey(0), batch)
        idx = sample_layer_token_indices(jax.random.PRNGKey(2), 2, 2, 64, 16)
        base = model.apply(params, batch, rngs=None, train=False)
        ltd = model.apply(params, batch, rngs=None, train=False, ltd_idx=idx)
        np.testing.assert_allclose(np.asarray(base), np.asarray(ltd), rtol=1e-6)

    def test_too_many_ltd_layers_rejected(self, eight_devices):
        model = _model(num_layers=3)
        batch = _batch(model.config.vocab_size)
        params = model.init(jax.random.PRNGKey(0), batch)
        idx = sample_layer_token_indices(jax.random.PRNGKey(2), 2, 2, 64, 16)
        with pytest.raises(ValueError, match="middle"):
            model.apply(params, batch, rngs=jax.random.PRNGKey(1), train=True, ltd_idx=idx)


def _ltd_config(min_v=16, max_v=64, steps=4, layers=2):
    return {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
        "data_efficiency": {
            "enabled": True,
            "data_routing": {
                "enabled": True,
                "random_ltd": {
                    "enabled": True,
                    "random_ltd_layer_num": layers,
                    "random_ltd_schedule": {
                        "min_value": min_v,
                        "max_value": max_v,
                        "schedule_config": {"require_steps": steps, "seq_per_step": 16},
                    },
                },
            },
        },
    }


class TestEngineLTD:
    def test_trains_and_ramps_to_full(self, eight_devices):
        mesh_mod.reset_topology()
        model = _model()
        engine, *_ = ds.initialize(model=model, config=_ltd_config())
        assert engine.random_ltd_scheduler is not None
        batch = _batch(model.config.vocab_size, B=8)
        losses = []
        for _ in range(5):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        assert all(np.isfinite(l) for l in losses), losses
        assert engine.random_ltd_scheduler.current == 64  # ramped to full

    def test_scheduler_state_survives_checkpoint(self, tmp_path, eight_devices):
        mesh_mod.reset_topology()
        model = _model()
        engine, *_ = ds.initialize(model=model, config=_ltd_config(steps=100))
        batch = _batch(model.config.vocab_size, B=8)
        for _ in range(2):
            loss = engine(batch); engine.backward(loss); engine.step()
        engine.save_checkpoint(str(tmp_path))
        cur = engine.random_ltd_scheduler.current

        mesh_mod.reset_topology()
        engine2, *_ = ds.initialize(model=_model(), config=_ltd_config(steps=100))
        engine2.init_params(batch)
        engine2.load_checkpoint(str(tmp_path))
        assert engine2.random_ltd_scheduler.current == cur

    def test_pld_combo_rejected(self, eight_devices):
        mesh_mod.reset_topology()
        cfg = _ltd_config()
        cfg["progressive_layer_drop"] = {"enabled": True}
        with pytest.raises(ValueError, match="cannot be combined"):
            ds.initialize(model=_model(), config=cfg)
