"""LR schedule tests (reference: tests/unit/runtime/test_lr_schedulers.py)."""

import math

import pytest

from deepspeed_tpu.ops import FusedAdam
from deepspeed_tpu.runtime.lr_schedules import (
    LRRangeTest,
    OneCycle,
    WarmupCosineLR,
    WarmupDecayLR,
    WarmupLR,
    get_lr_scheduler,
)


def _opt(lr=0.1):
    return FusedAdam(lr=lr)


class TestWarmupLR:
    def test_linear_warmup(self):
        opt = _opt()
        s = WarmupLR(opt, warmup_min_lr=0.0, warmup_max_lr=0.1, warmup_num_steps=10, warmup_type="linear")
        lrs = []
        for _ in range(15):
            s.step()
            lrs.append(opt.lr)
        assert lrs[0] == pytest.approx(0.0)
        assert lrs[4] == pytest.approx(0.04)
        assert lrs[-1] == pytest.approx(0.1)

    def test_log_warmup_reaches_max(self):
        opt = _opt()
        s = WarmupLR(opt, warmup_max_lr=0.1, warmup_num_steps=10)
        for _ in range(12):
            s.step()
        assert opt.lr == pytest.approx(0.1)


class TestWarmupDecayLR:
    def test_decays_to_zero(self):
        opt = _opt()
        s = WarmupDecayLR(opt, total_num_steps=20, warmup_max_lr=0.1, warmup_num_steps=5, warmup_type="linear")
        for _ in range(21):
            s.step()
        assert opt.lr == pytest.approx(0.0, abs=1e-9)

    def test_peak_at_warmup_end(self):
        opt = _opt()
        s = WarmupDecayLR(opt, total_num_steps=20, warmup_max_lr=0.1, warmup_num_steps=5, warmup_type="linear")
        peak = 0
        for _ in range(20):
            s.step()
            peak = max(peak, opt.lr)
        assert peak == pytest.approx(0.1, rel=0.01)


class TestWarmupCosineLR:
    def test_cosine_floor(self):
        opt = _opt(lr=0.1)
        s = WarmupCosineLR(opt, total_num_steps=20, warmup_num_steps=5, cos_min_ratio=0.1)
        for _ in range(25):
            s.step()
        assert opt.lr == pytest.approx(0.1 * 0.1, rel=1e-3)


class TestOneCycle:
    def test_triangle(self):
        opt = _opt()
        s = OneCycle(opt, cycle_min_lr=0.01, cycle_max_lr=0.1, cycle_first_step_size=10)
        lrs = []
        for _ in range(21):
            s.step()
            lrs.append(opt.lr)
        assert max(lrs) == pytest.approx(0.1, rel=0.05)
        assert lrs[-1] == pytest.approx(0.01, rel=0.3)


class TestLRRangeTest:
    def test_growth(self):
        opt = _opt()
        s = LRRangeTest(opt, lr_range_test_min_lr=0.01, lr_range_test_step_size=5, lr_range_test_step_rate=1.0)
        s.step()
        first = opt.lr
        for _ in range(10):
            s.step()
        assert opt.lr > first


class TestRegistry:
    def test_get_by_name(self):
        s = get_lr_scheduler("WarmupLR", _opt(), warmup_max_lr=0.5)
        assert isinstance(s, WarmupLR)

    def test_unknown_raises(self):
        with pytest.raises(ValueError):
            get_lr_scheduler("Nope", _opt())

    def test_state_dict_roundtrip(self):
        opt = _opt()
        s = WarmupLR(opt, warmup_max_lr=0.1, warmup_num_steps=10)
        for _ in range(5):
            s.step()
        sd = s.state_dict()
        s2 = WarmupLR(_opt(), warmup_max_lr=0.1, warmup_num_steps=10)
        s2.load_state_dict(sd)
        assert s2.last_batch_iteration == s.last_batch_iteration
