"""Eigenvalue (MoQ) tests: power iteration must recover the largest |eig| of
a known Hessian (reference deepspeed/runtime/eigenvalue.py; engine hook
engine.py:2103-2116)."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.runtime.eigenvalue import Eigenvalue


def _quadratic(A):
    A = jnp.asarray(A, jnp.float32)

    def loss(x):
        return 0.5 * x @ A @ x

    return loss


def test_known_hessian_eigenvalue():
    # symmetric with eigenvalues {1, 3, 7}
    rs = np.random.RandomState(0)
    Q, _ = np.linalg.qr(rs.randn(3, 3))
    A = Q @ np.diag([1.0, 3.0, 7.0]) @ Q.T
    eig = Eigenvalue(max_iter=200, tol=1e-5).compute_eigenvalue(
        _quadratic(A), jnp.ones((3,), jnp.float32)
    )
    assert eig == pytest.approx(7.0, rel=1e-2)


def test_negative_dominant_eigenvalue_abs():
    A = np.diag([-9.0, 2.0, 1.0])
    eig = Eigenvalue(max_iter=200, tol=1e-5).compute_eigenvalue(
        _quadratic(A), jnp.ones((3,), jnp.float32)
    )
    assert eig == pytest.approx(9.0, rel=1e-2)


def test_per_block_eigenvalues():
    A1 = np.diag([5.0, 1.0])
    A2 = np.diag([2.0, 11.0])

    def loss(params):
        return 0.5 * (params["a"] @ jnp.asarray(A1, jnp.float32) @ params["a"]) + 0.5 * (
            params["b"] @ jnp.asarray(A2, jnp.float32) @ params["b"]
        )

    params = {"a": jnp.ones((2,), jnp.float32), "b": jnp.ones((2,), jnp.float32)}
    out = Eigenvalue(max_iter=200, tol=1e-5).compute_eigenvalue_per_block(loss, params)
    assert out["a"] == pytest.approx(5.0, rel=1e-2)
    assert out["b"] == pytest.approx(11.0, rel=1e-2)


def test_nan_to_zero_guards_unstable_hvp():
    ev = Eigenvalue()
    arr = jnp.asarray([1.0, np.nan, np.inf, -np.inf])
    out = np.asarray(ev.nan_to_zero(arr))
    assert np.array_equal(out, [1.0, 0.0, 0.0, 0.0])
