"""Config tests (reference: tests/unit/runtime/test_ds_config_dict.py etc.)."""

import pytest

from deepspeed_tpu.runtime.config import DeepSpeedConfig, DeepSpeedConfigError, MeshConfig


class TestBatchTriad:
    def test_all_given_consistent(self):
        c = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2})
        c.resolve_batch_triad(dp_world_size=8)
        assert (c.train_batch_size, c.train_micro_batch_size_per_gpu, c.gradient_accumulation_steps) == (32, 2, 2)

    def test_all_given_inconsistent(self):
        c = DeepSpeedConfig({"train_batch_size": 33, "train_micro_batch_size_per_gpu": 2, "gradient_accumulation_steps": 2})
        with pytest.raises(DeepSpeedConfigError):
            c.resolve_batch_triad(dp_world_size=8)

    def test_derive_gas(self):
        c = DeepSpeedConfig({"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2})
        c.resolve_batch_triad(dp_world_size=8)
        assert c.gradient_accumulation_steps == 2

    def test_derive_micro(self):
        c = DeepSpeedConfig({"train_batch_size": 32, "gradient_accumulation_steps": 2})
        c.resolve_batch_triad(dp_world_size=8)
        assert c.train_micro_batch_size_per_gpu == 2

    def test_derive_total(self):
        c = DeepSpeedConfig({"train_micro_batch_size_per_gpu": 4})
        c.resolve_batch_triad(dp_world_size=2)
        assert c.train_batch_size == 8
        assert c.gradient_accumulation_steps == 1

    def test_none_given(self):
        c = DeepSpeedConfig({})
        with pytest.raises(DeepSpeedConfigError):
            c.resolve_batch_triad(dp_world_size=2)


class TestPrecisionConfig:
    def test_fp16_and_bf16_conflict(self):
        with pytest.raises(DeepSpeedConfigError):
            DeepSpeedConfig({"fp16": {"enabled": True}, "bf16": {"enabled": True}})

    def test_auto_values_filtered(self):
        c = DeepSpeedConfig({"train_batch_size": "auto", "train_micro_batch_size_per_gpu": 4})
        c.resolve_batch_triad(dp_world_size=1)
        assert c.train_batch_size == 4

    def test_dynamic_loss_scale_args(self):
        c = DeepSpeedConfig({"fp16": {"enabled": True, "initial_scale_power": 8, "hysteresis": 3}})
        assert c.dynamic_loss_scale_args["init_scale"] == 256
        assert c.dynamic_loss_scale_args["delayed_shift"] == 3

    def test_bfloat16_old_key(self):
        c = DeepSpeedConfig({"bfloat16": {"enabled": True}})
        assert c.bfloat16_enabled


class TestZeroConfig:
    def test_stage_parse(self):
        c = DeepSpeedConfig({"zero_optimization": {"stage": 3}})
        assert c.zero_enabled and c.zero_optimization_stage == 3

    def test_stage_aliases(self):
        c = DeepSpeedConfig({"zero_optimization": {"stage": 3, "stage3_max_live_parameters": 123}})
        assert int(c.zero_config.max_live_parameters) == 123

    def test_legacy_cpu_offload(self):
        c = DeepSpeedConfig({"zero_optimization": {"stage": 2, "cpu_offload": True}})
        assert c.zero_config.offload_optimizer is not None
        assert c.zero_config.offload_optimizer.device == "cpu"

    def test_overlap_comm_default(self):
        assert DeepSpeedConfig({"zero_optimization": {"stage": 3}}).zero_config.overlap_comm
        assert not DeepSpeedConfig({"zero_optimization": {"stage": 1}}).zero_config.overlap_comm


class TestMeshConfig:
    def test_resolve_data_axis(self):
        m = MeshConfig(model=2).resolve(8)
        assert m.data == 4

    def test_indivisible(self):
        with pytest.raises(DeepSpeedConfigError):
            MeshConfig(model=3).resolve(8)

    def test_duplicate_key_rejected(self, tmp_path):
        p = tmp_path / "cfg.json"
        p.write_text('{"train_batch_size": 1, "train_batch_size": 2}')
        with pytest.raises(ValueError):
            DeepSpeedConfig(str(p))
