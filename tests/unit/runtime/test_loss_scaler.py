"""Loss scaler tests (reference: tests/unit/runtime/half_precision/)."""

import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.runtime.fp16.loss_scaler import (
    CreateLossScaler,
    DynamicLossScaler,
    LossScaler,
    has_inf_or_nan,
)


def _step(scaler, state, overflow: bool):
    return scaler.update(state, jnp.asarray(overflow))


def test_static_scaler_constant():
    s = LossScaler(scale=128.0)
    st = s.init_state()
    st = _step(s, st, True)
    assert float(st.scale) == 128.0


def test_dynamic_shrinks_on_overflow():
    s = DynamicLossScaler(init_scale=16.0, delayed_shift=1)
    st = s.init_state()
    st = _step(s, st, True)
    assert float(st.scale) == 8.0


def test_hysteresis_tolerates_first_overflow():
    s = DynamicLossScaler(init_scale=16.0, delayed_shift=2)
    st = s.init_state()
    st = _step(s, st, True)
    assert float(st.scale) == 16.0
    st = _step(s, st, True)
    assert float(st.scale) == 8.0


def test_hysteresis_resets_on_good_step():
    s = DynamicLossScaler(init_scale=16.0, delayed_shift=2)
    st = s.init_state()
    st = _step(s, st, True)  # hysteresis 2 -> 1
    st = _step(s, st, False)  # resets to 2
    st = _step(s, st, True)  # 2 -> 1, no shrink
    assert float(st.scale) == 16.0


def test_growth_after_window():
    s = DynamicLossScaler(init_scale=16.0, scale_window=3, delayed_shift=1)
    st = s.init_state()
    for _ in range(3):
        st = _step(s, st, False)
    assert float(st.scale) == 32.0


def test_min_scale_floor():
    s = DynamicLossScaler(init_scale=2.0, min_scale=1.0, delayed_shift=1)
    st = s.init_state()
    for _ in range(5):
        st = _step(s, st, True)
    assert float(st.scale) == 1.0


def test_factory_selection():
    assert CreateLossScaler(jnp.float16, 0, True, {}).dynamic
    assert not CreateLossScaler(jnp.float16, 128, False, {}).dynamic
    assert CreateLossScaler(jnp.bfloat16, 0, True, {}).init_scale == 1.0


def test_has_inf_or_nan():
    clean = {"a": jnp.ones((3,)), "b": jnp.zeros((2,))}
    dirty = {"a": jnp.array([1.0, np.inf]), "b": jnp.zeros((2,))}
    nan = {"a": jnp.array([np.nan]), "b": jnp.zeros((2,))}
    assert not bool(has_inf_or_nan(clean))
    assert bool(has_inf_or_nan(dirty))
    assert bool(has_inf_or_nan(nan))
