"""Legacy sharded state-dict loading (reference
``runtime/state_dict_factory.py`` + ``weight_quantizer.py``): Megatron
SplitCheckpoint merge/split with optional quantize-on-load."""

from __future__ import annotations

import numpy as np
import pytest

from deepspeed_tpu.runtime.state_dict_factory import (
    AUTO_MODULE_KEY,
    MegatronSDLoader,
    SDLoaderFactory,
)
from deepspeed_tpu.runtime.weight_quantizer import WeightQuantization, dequantize_weight
from tests.unit.inference.test_containers import _megatron_sd, _MegatronCfg

QKV_OR_COL = ("attention.query_key_value", "mlp.dense_h_to_4h", "word_embeddings.weight")
ROW = ("attention.dense.weight", "mlp.dense_4h_to_h.weight")


def _shard_megatron_sd(sd, mp):
    """Split a full Megatron sd into mp shard dicts (checkpoint v2.0:
    qkv is a plain axis-0 split)."""
    shards = [dict() for _ in range(mp)]
    for key, value in sd.items():
        if any(h in key for h in QKV_OR_COL):
            parts = np.split(value, mp, axis=0)
        elif any(h in key for h in ROW):
            parts = np.split(value, mp, axis=1)
        else:
            parts = [value] * mp
        for r in range(mp):
            shards[r][key] = np.ascontiguousarray(parts[r])
    return shards


def _save_shards(tmp_path, shards, version=2.0):
    import torch

    files = []
    for r, shard in enumerate(shards):
        path = str(tmp_path / f"mp_rank_{r:02d}_model_states.pt")
        torch.save(
            {
                "module": {k: torch.from_numpy(v) for k, v in shard.items()},
                "checkpoint_version": version,
                "mp_world_size": len(shards),
            },
            path,
        )
        files.append(path)
    return files


class TestMegatronSDLoader:
    def test_merge_to_full(self, tmp_path):
        full = _megatron_sd()
        files = _save_shards(tmp_path, _shard_megatron_sd(full, 2))
        loader = SDLoaderFactory.get_sd_loader(files, sd_type="Megatron", version=2.0)
        path, sd, (scales, merge_count) = loader.load(mp_world_size=1, mp_rank=0)
        assert merge_count == 2 and scales is None
        merged = loader.get_module(sd)
        assert sorted(merged) == sorted(full)
        for key in full:
            np.testing.assert_allclose(merged[key], full[key], rtol=1e-6, err_msg=key)

    def test_split_further(self, tmp_path):
        full = _megatron_sd()
        files = _save_shards(tmp_path, _shard_megatron_sd(full, 2))
        loader = MegatronSDLoader(files, 2.0, None)
        # 2 files -> 4 ranks: each rank gets half of one file's shard
        ranks = [loader.load(mp_world_size=4, mp_rank=r)[1] for r in range(4)]
        key = "language_model.transformer.layers.0.mlp.dense_h_to_4h.weight"
        stacked = np.concatenate([loader.get_module(r)[key] for r in ranks], axis=0)
        np.testing.assert_allclose(stacked, full[key], rtol=1e-6)
        row_key = "language_model.transformer.layers.0.attention.dense.weight"
        stacked_row = np.concatenate([loader.get_module(r)[row_key] for r in ranks], axis=1)
        np.testing.assert_allclose(stacked_row, full[row_key], rtol=1e-6)

    def test_qkv_version0_interleave(self):
        loader = MegatronSDLoader.__new__(MegatronSDLoader)
        loader.version = 0
        rs = np.random.RandomState(0)
        full_q, full_k, full_v = rs.randn(3, 8, 4).astype(np.float32)
        # v0 shard format: [(3 * np * hn), h] — each shard holds its q,k,v
        shards = [
            np.concatenate([full_q[i * 4 : (i + 1) * 4], full_k[i * 4 : (i + 1) * 4], full_v[i * 4 : (i + 1) * 4]])
            for i in range(2)
        ]
        merged = loader.merge_query_key_value(shards, 0)
        np.testing.assert_array_equal(merged, np.concatenate([full_q, full_k, full_v]))
        # split inverts merge
        for off in range(2):
            np.testing.assert_array_equal(
                loader.split_query_key_value(merged, 2, off, 0), shards[off]
            )

    def test_descriptor_json(self, tmp_path):
        full = _megatron_sd()
        files = _save_shards(tmp_path, _shard_megatron_sd(full, 2))
        loader = SDLoaderFactory.get_sd_loader_json(
            {"type": "Megatron", "checkpoints": files, "version": 2.0}
        )
        assert isinstance(loader, MegatronSDLoader)
        # bloom/ds_model descriptors pass through untouched
        data = SDLoaderFactory.get_sd_loader_json(
            {"type": "bloom", "checkpoints": files, "version": 1}
        )
        assert isinstance(data, dict)

    def test_mp_world_size_mismatch_asserts(self, tmp_path):
        files = _save_shards(tmp_path, _shard_megatron_sd(_megatron_sd(), 2))
        with pytest.raises(AssertionError, match="mp_world_size"):
            MegatronSDLoader(files[:1], 2.0, None)


class TestQuantizeOnLoad:
    def test_merge_quantized_close_to_original(self, tmp_path):
        full = _megatron_sd()
        files = _save_shards(tmp_path, _shard_megatron_sd(full, 2))
        loader = MegatronSDLoader(files, 2.0, None)
        _, sd, (scales, _) = loader.load(
            mp_world_size=1, mp_rank=0, quantize=True, quantize_bits=8, quantize_groups=4
        )
        merged = loader.get_module(sd)
        key = "language_model.transformer.layers.0.attention.query_key_value.weight"
        assert merged[key].dtype == np.int8
        assert scales is not None and scales.ndim >= 2
        # norms and biases stay exact
        np.testing.assert_array_equal(
            merged["language_model.transformer.final_layernorm.weight"],
            full["language_model.transformer.final_layernorm.weight"],
        )

    def test_quantize_dequantize_roundtrip(self):
        rs = np.random.RandomState(0)
        w = rs.randn(16, 8).astype(np.float32)
        wq = WeightQuantization()
        q, scale = wq.quantize_data(w, quantize_bits=8, groups=4)
        assert q.dtype == np.int8
        back = dequantize_weight(q, scale, groups=4)
        # int8 group quantization: worst-case error is half a step
        step = (2.0 * np.abs(w).max() + 1e-5) / 256
        assert np.max(np.abs(back - w)) <= step

    def test_sd_quantize_megatron(self):
        full = _megatron_sd()
        wq = WeightQuantization(mp_size=1)
        sd, scales = wq.sd_quantize_megatron(dict(full), 8, 4)
        key = "language_model.transformer.layers.0.mlp.dense_h_to_4h.weight"
        assert sd[key].dtype == np.int8
        assert scales.shape[0] == 2  # one scale row per layer


class TestInferenceDescriptorWiring:
    def test_init_inference_with_descriptor(self, tmp_path):
        import jax
        import jax.numpy as jnp

        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod
        from deepspeed_tpu.models.transformer import TransformerLM
        from deepspeed_tpu.module_inject.containers import policy_for

        policy = policy_for("megatron_gpt")
        cfg = policy.build_config(_MegatronCfg())
        cfg.dtype = "float32"
        full = _megatron_sd()
        files = _save_shards(tmp_path, _shard_megatron_sd(full, 2))

        mesh_mod.reset_topology()
        engine = ds.init_inference(
            TransformerLM(cfg),
            dtype="fp32",
            checkpoint={"type": "Megatron", "checkpoints": files, "version": 2.0},
        )
        toks = np.random.RandomState(5).randint(0, 128, (2, 10)).astype(np.int32)
        got = np.asarray(engine(toks))

        ref_params = policy.convert_weights(full, cfg)
        ref = np.asarray(
            TransformerLM(cfg).apply(
                jax.tree_util.tree_map(jnp.asarray, ref_params), toks, train=False
            )
        )
        np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-5)

    def test_single_file_descriptor_numpy_boundary(self, tmp_path):
        """A one-file list takes the equal-count branch, which must still
        hand numpy (not torch) leaves to the policy — torch bf16 tensors
        crash np.asarray (round-5 review finding)."""
        import torch

        full = _megatron_sd()
        files = _save_shards(tmp_path, [full])
        # rewrite as bf16 torch tensors
        sd = torch.load(files[0], weights_only=False)
        sd["module"] = {k: v.to(torch.bfloat16) for k, v in sd["module"].items()}
        torch.save(sd, files[0])
        loader = MegatronSDLoader(files, 2.0, None)
        _, out, _ = loader.load(mp_world_size=1, mp_rank=0)
        merged = loader.get_module(out)
        for v in merged.values():
            assert isinstance(v, np.ndarray)

    def test_mp_manifest_json_still_routes_to_mp_loader(self, tmp_path):
        """checkpoint='<...>.json' pointing at an mp-checkpoint manifest must
        keep loading via the mp path (round-5 review finding)."""
        import jax
        import jax.numpy as jnp

        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod
        from deepspeed_tpu.models.transformer import TransformerLM
        from deepspeed_tpu.module_inject.containers import policy_for

        policy = policy_for("megatron_gpt")
        cfg = policy.build_config(_MegatronCfg())
        cfg.dtype = "float32"
        params = policy.convert_weights(_megatron_sd(), cfg)

        mesh_mod.reset_topology()
        engine = ds.init_inference(TransformerLM(cfg), dtype="fp32")
        engine.set_params(jax.tree_util.tree_map(jnp.asarray, params))
        manifest = engine.save_mp_checkpoint(str(tmp_path / "mp"))
        assert manifest.endswith(".json")
        toks = np.random.RandomState(5).randint(0, 128, (2, 10)).astype(np.int32)
        ref = np.asarray(engine(toks))

        mesh_mod.reset_topology()
        engine2 = ds.init_inference(TransformerLM(cfg), dtype="fp32", checkpoint=manifest)
        np.testing.assert_allclose(np.asarray(engine2(toks)), ref, rtol=1e-5, atol=1e-5)
