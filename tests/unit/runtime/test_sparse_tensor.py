"""Sparse embedding gradients (reference engine.py:2398-2465 +
runtime/sparse_tensor.py:68): math parity with the dense path, the compact
pair collective in the compiled program, and the engine's validation gates."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM, llama_config
from deepspeed_tpu.runtime.sparse_tensor import SparseTensor, sparse_embedding_lookup

VOCAB, HIDDEN = 512, 64


class TestSparseTensor:
    def test_roundtrip(self):
        dense = np.zeros((16, 4), np.float32)
        dense[3] = 1.5
        dense[11] = -2.0
        st = SparseTensor.from_dense(jnp.asarray(dense))
        assert st.sparse_size() < dense.size
        np.testing.assert_array_equal(np.asarray(st.to_dense()), dense)


class TestSparseLookupMath:
    def test_grad_matches_dense_single_shard(self):
        rs = np.random.RandomState(0)
        table = jnp.asarray(rs.randn(VOCAB, HIDDEN).astype(np.float32))
        tokens = jnp.asarray(rs.randint(0, VOCAB, (4, 16)).astype(np.int32))
        w = jnp.asarray(rs.randn(HIDDEN).astype(np.float32))

        def loss_sparse(t):
            return jnp.sum(sparse_embedding_lookup(t, tokens, None) * w)

        def loss_dense(t):
            return jnp.sum(t[tokens] * w)

        gs = jax.grad(loss_sparse)(table)
        gd = jax.grad(loss_dense)(table)
        np.testing.assert_allclose(np.asarray(gs), np.asarray(gd), rtol=1e-6)

    def test_grad_matches_dense_dp8(self, eight_devices):
        """Sharded batch over data=8: the shard_map pair-gather reduction
        must equal the dense psum reduction."""
        from deepspeed_tpu.parallel.mesh import MeshConfig

        mesh_mod.reset_topology()
        topo = mesh_mod.initialize_topology(MeshConfig(data=8))
        rs = np.random.RandomState(1)
        table = jnp.asarray(rs.randn(VOCAB, HIDDEN).astype(np.float32))
        tokens_np = rs.randint(0, VOCAB, (8, 16)).astype(np.int32)
        w = jnp.asarray(rs.randn(HIDDEN).astype(np.float32))
        from jax.sharding import NamedSharding, PartitionSpec as P

        tokens = jax.device_put(tokens_np, NamedSharding(topo.mesh, P("data", None)))

        @jax.jit
        def g_sparse(t):
            return jax.grad(lambda tt: jnp.sum(sparse_embedding_lookup(tt, tokens, ("data",)) * w))(t)

        @jax.jit
        def g_dense(t):
            return jax.grad(lambda tt: jnp.sum(tt[tokens] * w))(t)

        np.testing.assert_allclose(
            np.asarray(g_sparse(table)), np.asarray(g_dense(table)), rtol=1e-6
        )


class TestEngineSparseGradients:
    def _config(self, stage=1):
        return {
            "train_micro_batch_size_per_gpu": 1,
            "sparse_gradients": True,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": stage},
            "mesh": {"data": 8},
            "steps_per_print": 10_000,
        }

    def _model(self, **over):
        cfg = llama_config(
            "tiny", num_layers=2, max_seq_len=32, vocab_size=VOCAB, **over
        )
        return TransformerLM(cfg)

    def test_trains_and_matches_dense(self, eight_devices):
        rs = np.random.RandomState(2)
        toks = rs.randint(0, VOCAB, (8, 33)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        finals = []
        for sparse in (True, False):
            mesh_mod.reset_topology()
            cfg = dict(self._config())
            if not sparse:
                cfg.pop("sparse_gradients")
            engine, _, _, _ = ds.initialize(
                model=self._model(), config=cfg, dist_init_required=False
            )
            for _ in range(3):
                loss = engine(batch)
                engine.backward(loss)
                engine.step()
            finals.append(
                (
                    float(jax.device_get(loss)),
                    np.asarray(jax.device_get(engine.get_params()["embed"]["tokens"])),
                )
            )
        # the pair-gather scatter-adds in a different order than the dense
        # psum; fp32 rounding noise passes through Adam's sign-like early
        # updates, so per-element drift is bounded by ~a few lr — exact grad
        # equality is asserted at dp8 in test_grad_matches_dense_dp8
        assert abs(finals[0][0] - finals[1][0]) < 5e-3
        np.testing.assert_allclose(finals[0][1], finals[1][1], rtol=2e-2, atol=5e-3)

    def test_pair_gather_in_compiled_program(self, eight_devices):
        """The sparse path's compiled step carries the compact pair
        all-gather; the dense table is never all-reduced."""
        mesh_mod.reset_topology()
        engine, _, _, _ = ds.initialize(
            model=self._model(), config=self._config(), dist_init_required=False
        )
        rs = np.random.RandomState(3)
        toks = rs.randint(0, VOCAB, (8, 33)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        placed = engine._place_batch(batch)
        lr = engine.optimizer.param_groups[0]["lr"]
        args = (engine._master, engine._opt_state, engine._scale_state, lr, engine._rng, placed, {})
        txt = engine._jit_fused_step.lower(*args).compile().as_text()
        assert "all-gather" in txt

    def test_stage2_rejected(self):
        mesh_mod.reset_topology()
        with pytest.raises(ValueError, match="stage <= 1"):
            ds.initialize(
                model=self._model(), config=self._config(stage=2), dist_init_required=False
            )

    def test_tied_embeddings_rejected(self):
        mesh_mod.reset_topology()
        with pytest.raises(ValueError, match="untied"):
            ds.initialize(
                model=self._model(tie_embeddings=True),
                config=self._config(),
                dist_init_required=False,
            )
