"""Runtime utils, tiled linear, contiguous allocator, elastic agent tests.

Reference analogs: ``tests/unit/runtime/test_runtime_utils.py`` (clip/norm/
CheckOverflow), ``tests/unit/runtime/zero/test_tiling.py``, the allocator's
in-file sanity harness, and ``deepspeed/elasticity/elastic_agent.py``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.elasticity.elastic_agent import DSElasticAgent, WorkerSpec
from deepspeed_tpu.runtime.utils import (
    CheckOverflow,
    call_to_str,
    clip_grad_norm_,
    global_grad_norm,
    see_memory_usage,
)
from deepspeed_tpu.runtime.zero.contiguous_memory_allocator import (
    ContiguousMemoryAllocator,
)
from deepspeed_tpu.runtime.zero.tiling import TiledLinear, TiledLinearReturnBias


class TestRuntimeUtils:
    def test_clip_grad_norm(self):
        grads = {"a": jnp.full((4,), 3.0), "b": jnp.full((4,), 4.0)}
        clipped, norm = clip_grad_norm_(grads, max_norm=1.0)
        np.testing.assert_allclose(float(norm), 10.0, rtol=1e-6)
        np.testing.assert_allclose(float(global_grad_norm(clipped)), 1.0, rtol=1e-4)
        # under the max: untouched
        same, _ = clip_grad_norm_(grads, max_norm=100.0)
        np.testing.assert_allclose(np.asarray(same["a"]), 3.0)

    def test_check_overflow(self):
        ok = {"w": jnp.ones((4,))}
        bad = {"w": jnp.array([1.0, jnp.nan, 2.0, 3.0])}
        assert CheckOverflow.has_overflow(ok) is False
        assert CheckOverflow.has_overflow(bad) is True
        assert CheckOverflow.check_using_norm([1.0, 2.0]) is False
        assert CheckOverflow.check_using_norm([1.0, -1]) is True
        assert CheckOverflow.check_using_norm([float("nan")]) is True

    def test_see_memory_usage(self):
        assert see_memory_usage("quiet") is None  # not forced: no-op
        stats = see_memory_usage("forced", force=True)
        assert stats is not None and stats["bytes_in_use"] >= 0

    def test_call_to_str(self):
        assert call_to_str("f", 1, "x", k=2) == "f(1, x, k=2)"


class TestTiledLinear:
    @pytest.mark.parametrize("in_splits,out_splits", [(1, 1), (2, 3), (4, 2)])
    def test_matches_dense(self, in_splits, out_splits):
        tl = TiledLinear(24, 36, in_splits=in_splits, out_splits=out_splits)
        rs = np.random.RandomState(0)
        w = rs.randn(24, 36).astype(np.float32)
        b = rs.randn(36).astype(np.float32)
        params = tl.from_full(w, b)
        x = jnp.asarray(rs.randn(5, 24).astype(np.float32))
        out = tl.apply(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(x) @ w + b, rtol=1e-5, atol=1e-5)

    def test_uneven_splits(self):
        tl = TiledLinear(10, 7, in_splits=3, out_splits=2)  # non-divisible dims
        rs = np.random.RandomState(1)
        w = rs.randn(10, 7).astype(np.float32)
        params = tl.from_full(w)
        x = jnp.asarray(rs.randn(2, 10).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(tl.apply(params, x)), np.asarray(x) @ w + 0.0, rtol=1e-5, atol=1e-5
        )

    def test_return_bias_variant(self):
        tl = TiledLinearReturnBias(8, 8, in_splits=2, out_splits=2)
        params = tl.init(jax.random.PRNGKey(0))
        x = jnp.ones((2, 8))
        out, bias = tl.apply(params, x)
        assert out.shape == (2, 8) and bias.shape == (8,)

    def test_grad_flows(self):
        tl = TiledLinear(8, 8, in_splits=2, out_splits=2)
        params = tl.init(jax.random.PRNGKey(0))
        g = jax.grad(lambda p, x: jnp.sum(tl.apply(p, x) ** 2))(params, jnp.ones((2, 8)))
        assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree_util.tree_leaves(g))


class TestContiguousMemoryAllocator:
    def test_allocate_release(self):
        al = ContiguousMemoryAllocator(100)
        a = al.allocate_tensor(40)
        b = al.allocate_tensor(30)
        assert a.size == 40 and b.size == 30
        assert al.available_memory == 30
        al.release_tensor(a)
        assert al.available_memory == 70

    def test_oom_raises(self):
        al = ContiguousMemoryAllocator(10)
        al.allocate_tensor(8)
        with pytest.raises(RuntimeError, match="out of memory"):
            al.allocate_tensor(4)

    def test_defragment_preserves_contents(self):
        al = ContiguousMemoryAllocator(100)
        a = al.allocate_tensor(40)
        b = al.allocate_tensor(30)
        a_id, b_id = al.tensor_id(a), al.tensor_id(b)
        b[:] = 7.0
        al.release_tensor(a)  # hole [0:40), free tail [70:100)
        # 60 won't fit any hole but fits total free: triggers defragment
        c = al.allocate_tensor(60)
        assert c.size == 60
        np.testing.assert_array_equal(al.get_tensor(b_id), 7.0)  # moved, intact
        assert al.available_memory == 10


ELASTIC_CFG = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 64,
        "micro_batch_sizes": [2, 4],
        "min_gpus": 1,
        "max_gpus": 16,
        "min_time": 0,
        "version": 0.1,
    }
}


class TestDSElasticAgent:
    def _agent(self):
        spawned, killed = [], []
        agent = DSElasticAgent(
            WorkerSpec(entrypoint=["python", "train.py"], max_restarts=3),
            ELASTIC_CFG,
            env={"BASE": "1"},
            spawn_fn=lambda cmd, env: spawned.append((cmd, env)) or len(spawned),
            kill_fn=lambda h: killed.append(h),
        )
        return agent, spawned, killed

    def test_start_spawns_world(self):
        agent, spawned, _ = self._agent()
        sched = agent.start(4)
        assert len(spawned) == 4
        env0 = spawned[0][1]
        assert env0["RANK"] == "0" and env0["WORLD_SIZE"] == "4"
        assert int(env0["DS_ELASTIC_TRAIN_BATCH_SIZE"]) == sched["train_batch_size"]
        # schedule consistency: batch = micro x gas x world
        assert (
            sched["train_batch_size"]
            == sched["train_micro_batch_size_per_gpu"]
            * sched["gradient_accumulation_steps"]
            * 4
        )

    def test_resize_restarts_with_new_schedule(self):
        agent, spawned, killed = self._agent()
        agent.start(4)
        sched = agent.on_membership_change(8)
        assert len(killed) == 4  # old workers stopped
        assert len(spawned) == 12  # 4 old + 8 new
        assert agent.restart_count == 1
        assert spawned[-1][1]["WORLD_SIZE"] == "8"
        # global batch preserved across the resize
        first = agent.schedule_for(4)
        assert sched["train_batch_size"] == first["train_batch_size"]

    def test_invalid_world_does_not_kill_job(self):
        agent, spawned, killed = self._agent()
        agent.start(4)
        with pytest.raises(Exception):
            agent.on_membership_change(5)  # 5 not in the compatible set
        assert len(killed) == 0, "running workers must survive a bad resize"

    def test_max_restarts(self):
        agent, _, _ = self._agent()
        agent.start(2)
        agent.spec.max_restarts = 0
        with pytest.raises(RuntimeError, match="max_restarts"):
            agent.on_membership_change(4)

    def test_requires_elasticity_enabled(self):
        with pytest.raises(ValueError, match="elasticity"):
            DSElasticAgent(WorkerSpec(["x"]), {"elasticity": {"enabled": False}})


class TestSave16BitModel:
    def test_consolidated_save(self, tmp_path, eight_devices):
        import deepspeed_tpu as ds
        import deepspeed_tpu.parallel.mesh as mesh_mod
        from tests.unit.simple_model import SimpleModel, random_dataloader

        mesh_mod.reset_topology()
        engine, *_ = ds.initialize(
            model=SimpleModel(32),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 0},
            },
        )
        batch = next(random_dataloader(32, total_samples=8, batch_size=8))
        loss = engine(batch)
        engine.backward(loss)
        engine.step()

        engine.save_16bit_model(str(tmp_path), "model.bin")
        import torch

        sd = torch.load(str(tmp_path / "model.bin"), weights_only=True)
        assert "w0" in sd
        np.testing.assert_allclose(
            sd["w0"].numpy(),
            np.asarray(engine.get_params()["w0"], dtype=np.float32),
            rtol=1e-6,
        )

        engine.save_16bit_model(str(tmp_path), "model.npz")
        loaded = np.load(str(tmp_path / "model.npz"))
        assert "w0" in loaded
