"""Multi-step fused training windows (``compile.multi_step``; ISSUE 14).

The acceptance contract: with windows armed, ``train_batch(data_iter)`` is
BIT-identical to the unwindowed run — same per-step losses, same master
param tree, same loss-scale trajectory (including a forced fp16
overflow-skip INSIDE a window), same lr schedule — across
zero ∈ {1, 3} × {bf16, fp16-with-forced-overflow} × gas ∈ {1, 2} and
horizons {2, 4}; the host gap amortizes (steady-state
``dispatches_per_opt_step`` ≤ 1/N via compile telemetry, one compiled
window program per armed horizon, no retrace after the first wave);
windows break — counted in ``window_break_reasons`` — on checkpoint
intervals, monitor flushes, the flops-profiler step, and dataloader
exhaustion, and never straddle a checkpoint boundary (the
``train.mid_window`` chaos kill resumes bit-identically from the last
committed checkpoint); and the prefetching input pipeline preserves the
PR-8 exact-resume data-cursor semantics.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, PrefetchingLoader
from deepspeed_tpu.utils import chaos
from tests.unit.simple_model import SimpleModel, master_snapshot

STEPS = 6


def _cfg(multi_step, gas=1, horizon=2, precision="bf16", stage=1, prefetch=True, **over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "gradient_accumulation_steps": gas,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "zero_optimization": {"stage": stage},
        "compile": {
            # the window scans the fused grad-accum body at gas>1, so the
            # sequential comparison arm runs the same program family
            "fuse_grad_accum": gas > 1,
            "multi_step": {"enable": multi_step, "horizon": horizon, "prefetch": prefetch},
        },
        "gradient_clipping": 1.0,
        "scheduler": {
            "type": "WarmupLR",
            "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 10},
        },
    }
    if precision == "bf16":
        base["bf16"] = {"enabled": True}
    elif precision == "fp16":
        base["fp16"] = {"enabled": True, "initial_scale_power": 4, "hysteresis": 1}
    base.update(over)
    return base


def _engine(multi_step, **kw):
    mesh_mod.reset_topology()
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg(multi_step, **kw))
    return engine


def _batches(gas, steps, seed=0, bad_step=None):
    """Deterministic microbatch stream; ``bad_step`` (an int or a set of
    step indices) injects an inf into that step's first microbatch (the
    fp16 forced-overflow probe)."""
    bad = (
        set() if bad_step is None
        else ({bad_step} if isinstance(bad_step, int) else set(bad_step))
    )
    rs = np.random.RandomState(seed)
    out = []
    for s in range(steps):
        for g in range(gas):
            x = rs.randn(8, 16).astype(np.float32)
            y = rs.randn(8, 16).astype(np.float32)
            if s in bad and g == 0:
                x = x.copy()
                x[0, 0] = np.inf
            out.append((x, y))
    return out


def _drive(engine, data, steps):
    it = iter(list(data))
    return [float(engine.train_batch(data_iter=it)) for _ in range(steps)]


def _assert_same_master(a, b):
    wa, wb = master_snapshot(a), master_snapshot(b)
    assert set(wa) == set(wb)
    for k in wa:
        np.testing.assert_array_equal(wa[k], wb[k])


# ---------------------------------------------------------------------------
# bit-identity matrix
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("gas", [1, 2])
@pytest.mark.parametrize("precision", ["bf16", "fp16"])
@pytest.mark.parametrize("stage", [1, 3])
def test_window_vs_sequential_bit_identical(stage, precision, gas, eight_devices):
    """The core acceptance sweep: windowed losses, master trees, loss-scale
    trajectory, skip counters, and the lr schedule all bit-match N
    sequential ``train_batch`` calls. fp16 runs force an overflow INSIDE a
    window (step 3 of 6: mid-window at horizon 2 after the sequential init
    step) so the in-program skip/rescale + lr-cursor freeze is exercised."""
    bad = 3 if precision == "fp16" else None
    data = _batches(gas, STEPS, bad_step=bad)
    ref = _engine(False, gas=gas, precision=precision, stage=stage)
    ref_losses = _drive(ref, data, STEPS)
    win = _engine(True, gas=gas, precision=precision, stage=stage, horizon=2)
    win_losses = _drive(win, data, STEPS)
    assert win_losses == ref_losses
    assert win.window_stats()["window_steps"] >= 2, win.window_stats()
    _assert_same_master(ref, win)
    assert win.skipped_steps == ref.skipped_steps
    assert win.loss_scale == ref.loss_scale
    assert float(win.optimizer.param_groups[0]["lr"]) == float(
        ref.optimizer.param_groups[0]["lr"]
    )
    if precision == "fp16":
        assert win.skipped_steps == 1  # the forced overflow actually fired


def test_window_horizon4_bit_identical(eight_devices):
    """Horizon 4 (the second acceptance horizon), fp16 with the overflow on
    the LAST step of a window — the lr cursor freeze at the window edge."""
    steps = 9
    data = _batches(1, steps, bad_step=4)  # step idx 4 = last step of window 1..4
    ref = _engine(False, precision="fp16")
    ref_losses = _drive(ref, data, steps)
    win = _engine(True, precision="fp16", horizon=4)
    win_losses = _drive(win, data, steps)
    assert win_losses == ref_losses
    assert win.skipped_steps == ref.skipped_steps == 1
    assert win.loss_scale == ref.loss_scale
    _assert_same_master(ref, win)
    ws = win.window_stats()
    assert ws["window_steps"] == 2 and ws["windowed_opt_steps"] == 8, ws


# ---------------------------------------------------------------------------
# horizon edge cases + break accounting
# ---------------------------------------------------------------------------
def test_tail_and_exhaustion_fall_back_single_step(eight_devices):
    """steps % N != 0: the tail that cannot fill a window runs sequentially
    (no new program, counted under the 'data' break) and still bit-matches;
    a fully exhausted iterator raises StopIteration like the sequential
    path always did."""
    steps = 6  # 1 sequential init + window(2) + window(2) + 1 tail
    data = _batches(1, steps)
    ref = _engine(False)
    ref_losses = _drive(ref, data, steps)
    win = _engine(True, horizon=2)
    it = iter(list(data))
    win_losses = [float(win.train_batch(data_iter=it)) for _ in range(steps)]
    assert win_losses == ref_losses
    ws = win.window_stats()
    assert ws["window_steps"] == 2
    assert ws["window_break_reasons"]["data"] >= 1, ws
    # only the armed horizon's program compiled — the tail reused the
    # single-step fused program, no tail-sized window variant exists
    window_programs = [
        n for n in win.compile_stats() if n.startswith("fused_window_step")
    ]
    assert window_programs == ["fused_window_step_n2"]
    with pytest.raises(StopIteration):
        win.train_batch(data_iter=it)


def test_checkpoint_interval_breaks_window(eight_devices, tmp_path):
    """A checkpoint-interval boundary inside the horizon breaks the window
    BEFORE dispatch: every auto-save lands exactly on its boundary with the
    counters caught up (windows never straddle), and the broken steps are
    counted under 'checkpoint'."""
    steps = 9
    data = _batches(1, steps)
    win = _engine(
        True, horizon=3,
        checkpoint={"interval_steps": 4, "save_dir": str(tmp_path)},
    )
    saved_at = []
    orig = win.save_checkpoint

    def spy(*a, **k):
        saved_at.append(win.global_steps)
        assert not win._window_stash, "auto-save fired mid-window"
        return orig(*a, **k)

    win.save_checkpoint = spy
    losses = _drive(win, data, steps)
    ref = _engine(False)
    assert losses == _drive(ref, data, steps)
    assert saved_at == [4, 8], saved_at
    ws = win.window_stats()
    # step 1 is the sequential init; windows cover 2-4 and 5-7 (each ends
    # exactly ON or before a boundary); step 8 sits 1 step from the
    # boundary at 8 — less than the horizon — so it breaks on 'checkpoint'
    # and runs sequentially; step 9 has only 1 step of data left ('data')
    assert ws["window_break_reasons"]["checkpoint"] == 1, ws
    assert ws["window_break_reasons"]["data"] == 1, ws
    assert ws["window_steps"] == 2, ws


def test_monitor_flush_breaks_window(eight_devices, tmp_path):
    """An armed monitor flushes every interval_steps — the window must end
    there (the flush device_gets the step's loss), counted under 'monitor'."""
    win = _engine(
        True, horizon=4,
        monitor={"enabled": True, "interval_steps": 2,
                 "jsonl": {"enabled": True, "output_path": str(tmp_path)}},
    )
    data = _batches(1, 5)
    _drive(win, data, 5)
    ws = win.window_stats()
    assert ws["window_break_reasons"]["monitor"] >= 1, ws
    assert ws["window_steps"] == 0  # horizon 4 never fits inside interval 2


# ---------------------------------------------------------------------------
# prefetching input pipeline
# ---------------------------------------------------------------------------
def test_prefetcher_cursor_exact_resume_roundtrip():
    """PrefetchingLoader reports the cursor of the first UNDELIVERED batch
    (not the source's pulled-ahead one), and load_state_dict resumes the
    exact sequence — over a RE-ITERABLE source; a bare-iterator source
    refuses to 'restore' (a running generator cannot rewind, and silently
    continuing would skip the staged batches)."""
    data = [np.full((4,), i, np.float32) for i in range(12)]
    loader = DeepSpeedDataLoader(data, batch_size=2)
    pf = PrefetchingLoader(iter(loader), depth=3, state_source=loader)
    first = next(pf)
    second = next(pf)
    assert float(first[0, 0]) == 0.0 and float(second[0, 0]) == 2.0
    # 2 delivered; up to 3 more staged — the source cursor is ahead, the
    # wrapper's is not
    assert loader.state_dict()["cursor"] > 2
    sd = pf.state_dict()
    assert sd == {"epoch": 0, "cursor": 2}
    # resume via a re-iterable source: the sequence continues at 2
    loader_b = DeepSpeedDataLoader(data, batch_size=2)
    pf_b = PrefetchingLoader(loader_b, depth=3)
    pf_b.load_state_dict(sd)
    np.testing.assert_array_equal(next(pf_b), next(pf))
    np.testing.assert_array_equal(next(pf_b), next(pf))
    # a bare-iterator source cannot rewind — restoring must refuse, not
    # silently skip the staged batches
    loader_c = DeepSpeedDataLoader(data, batch_size=2)
    pf_c = PrefetchingLoader(iter(loader_c), depth=3, state_source=loader_c)
    with pytest.raises(ValueError, match="re-iterable"):
        pf_c.load_state_dict(sd)


def test_prefetcher_place_fn_and_exhaustion():
    """place_fn applies at PULL time (the staged device_put), fill() reports
    data availability without consuming, and exhaustion is latched."""
    placed = []

    def place(b):
        placed.append(len(placed))
        return jax.numpy.asarray(b)

    pf = PrefetchingLoader(iter([np.ones(2)] * 3), place_fn=place, depth=2)
    assert pf.fill(3) == 3  # only 3 exist
    assert len(placed) == 3  # all were placed at pull time, ahead of use
    out = [next(pf) for _ in range(3)]
    assert all(isinstance(o, jax.Array) for o in out)
    with pytest.raises(StopIteration):
        next(pf)
    assert pf.fill(1) == 0


def test_engine_checkpoint_cursor_ignores_prefetched_batches(eight_devices, tmp_path):
    """A checkpoint cut while the engine's prefetcher has staged batches
    ahead must carry the cursor of the first UNDELIVERED batch — the PR-8
    mid-epoch exact-resume contract under the double-buffered pipeline."""
    data = [(np.random.RandomState(i).randn(16).astype(np.float32),
             np.zeros(16, np.float32)) for i in range(80)]

    def build():
        mesh_mod.reset_topology()
        return ds.initialize(
            model=SimpleModel(),
            config=_cfg(True, horizon=2),
            training_data=data,
        )

    a, _, loader_a, _ = build()
    it = iter(loader_a)
    for _ in range(3):  # 1 sequential init step + one window of 2
        a.train_batch(data_iter=it)
    # the window's top-up pulled ahead: source cursor > 3 delivered
    assert a._active_prefetcher is not None
    assert loader_a.state_dict()["cursor"] > 3
    a.save_checkpoint(str(tmp_path))
    b, _, loader_b, _ = build()
    b.init_params(data[0])
    b.load_checkpoint(str(tmp_path))
    assert loader_b.state_dict() == {"epoch": 0, "cursor": 3}
    # resumed run consumes batch 3 next — identical to an unpaused one
    # (batch_size is micro×dp = 8, so batch 3 starts at sample 24)
    nxt = next(iter(loader_b))
    np.testing.assert_array_equal(np.asarray(nxt[0])[0], data[24][0])


# ---------------------------------------------------------------------------
# dispatch amortization + retrace guards (compile telemetry)
# ---------------------------------------------------------------------------
def test_steady_state_dispatches_per_opt_step(eight_devices):
    """THE perf gate: after the init step, every window is ONE dispatch of
    the fused program covering H steps — steady-state dispatches/opt-step
    ≤ 1/H, measured through compile telemetry, with telemetry and the
    engine's window_stats reconciling exactly."""
    H = 4
    win = _engine(True, horizon=H)
    steps = 1 + 3 * H  # sequential init + exactly 3 full windows
    data = _batches(1, steps)
    _drive(win, data, steps)
    stats = win.compile_stats()
    wrec = stats["fused_window_step_n4"]
    ws = win.window_stats()
    assert wrec["dispatches"] == ws["window_steps"] == 3
    # steady-state bound: ignore the single init step, the windowed
    # segment is exactly 1/H
    windowed = ws["windowed_opt_steps"]
    assert windowed == 3 * H
    assert wrec["dispatches"] / windowed == 1.0 / H
    # whole-run form (init step included) stays under the sequential cost
    assert ws["dispatches_per_opt_step"] <= (1.0 / H) + (1.0 / ws["opt_steps"])
    assert ws["dispatches"] == wrec["dispatches"] + stats["fused_step"]["dispatches"]


def test_three_wave_retrace_guard(eight_devices):
    """Three waves of windows with varying data: everything compiles in
    wave 1 and NOTHING retraces after — one compiled window program per
    armed horizon, ≤1 compile per program."""
    H = 2
    win = _engine(True, horizon=H)
    compiles_after = []
    for wave in range(3):
        data = _batches(1, 1 + 2 * H, seed=wave)
        _drive(win, data, 1 + 2 * H)
        compiles_after.append(
            sum(r["compiles"] for r in win.compile_stats().values())
        )
    assert compiles_after[1] == compiles_after[0], compiles_after
    assert compiles_after[2] == compiles_after[0], compiles_after
    for name, rec in win.compile_stats().items():
        assert rec["compiles"] <= 1, (name, rec)
    assert (
        sum(1 for n in win.compile_stats() if n.startswith("fused_window_step")) == 1
    )


def test_drained_losses_match_returned(eight_devices):
    """The deferred drain delivers the SAME values a per-step device_get
    would have — only later. Every windowed step shows up exactly once, in
    step order, after flush."""
    H = 2
    win = _engine(True, horizon=H)
    steps = 1 + 2 * H
    data = _batches(1, steps)
    losses = _drive(win, data, steps)
    assert win.window_stats()["pending_loss_drains"] >= 1  # deferral is real
    win.flush_loss_drain()
    drained = win.drained_losses()
    assert [d["step"] for d in drained] == [2, 3, 4, 5]
    for d in drained:
        assert d["loss"] == losses[d["step"] - 1]
        assert d["overflow"] is False


# ---------------------------------------------------------------------------
# chaos: kill mid-window, resume bit-identically (satellite 2)
# ---------------------------------------------------------------------------
def test_mid_window_chaos_kill_resumes_bit_identical(eight_devices, tmp_path):
    """``train.mid_window`` fires between the window dispatch and the loss
    drain: the donated state is already N steps ahead but NOTHING was
    committed. A fresh engine auto-resumes from the last committed
    checkpoint (window-aligned by the formation clamp) and the continued
    run is bit-identical — losses AND master tree — to an uninterrupted
    one. fp16 + interval autosave: the hardest variant."""
    steps = 9
    data = _batches(1, steps, seed=7)
    over = {
        "checkpoint": {"interval_steps": 2, "save_dir": str(tmp_path)},
        "scheduler": None,
    }

    def build():
        mesh_mod.reset_topology()
        cfg = _cfg(True, horizon=2, precision="fp16")
        cfg["checkpoint"] = over["checkpoint"]
        engine, *_ = ds.initialize(model=SimpleModel(), config=cfg)
        return engine

    ref = build()
    # reference consumes the autosave dir too: rebuild it clean after
    ref_losses = _drive(ref, data, steps)
    ref_master = master_snapshot(ref)
    import shutil

    shutil.rmtree(str(tmp_path))
    tmp_path.mkdir()

    e = build()
    it = iter(list(data))
    committed = []
    chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("train.mid_window", hit=2)]))
    try:
        for _ in range(steps):
            committed.append(float(e.train_batch(data_iter=it)))
        raise AssertionError("chaos never fired")
    except chaos.ChaosKilled:
        pass
    finally:
        chaos.uninstall()
    assert committed == ref_losses[: len(committed)]

    e2 = build()
    e2.init_params(data[0])
    path, _ = e2.load_checkpoint(str(tmp_path), auto_resume=True)
    assert path is not None
    resumed_from = e2.global_steps
    assert resumed_from % 2 == 0  # a committed interval boundary
    assert resumed_from >= len(committed) - 1  # at most the in-flight window lost
    it2 = iter(list(data[resumed_from:]))
    resumed = [
        float(e2.train_batch(data_iter=it2)) for _ in range(steps - resumed_from)
    ]
    assert resumed == ref_losses[resumed_from:]
    e2_master = master_snapshot(e2)
    for k in ref_master:
        np.testing.assert_array_equal(ref_master[k], e2_master[k])


# ---------------------------------------------------------------------------
# config + protocol red tests
# ---------------------------------------------------------------------------
def test_config_red_horizon_too_small():
    from deepspeed_tpu.runtime.config import DeepSpeedConfig

    with pytest.raises(Exception, match="horizon"):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "compile": {"multi_step": {"enable": True, "horizon": 1}},
        })


def test_config_red_gas_without_fuse(eight_devices):
    with pytest.raises(ValueError, match="fuse_grad_accum"):
        cfg = _cfg(True, gas=2, horizon=2)
        cfg["compile"]["fuse_grad_accum"] = False
        mesh_mod.reset_topology()
        ds.initialize(model=SimpleModel(), config=cfg)


def test_config_red_incompatible_features(eight_devices):
    for key, val, pat in [
        ("curriculum_learning", {"enabled": True, "min_difficulty": 8,
                                 "max_difficulty": 16, "schedule_type": "fixed_linear",
                                 "schedule_config": {"total_curriculum_step": 10,
                                                     "difficulty_step": 8}},
         "curriculum"),
        ("progressive_layer_drop", {"enabled": True}, "progressive_layer_drop"),
    ]:
        cfg = _cfg(True, horizon=2)
        cfg[key] = val
        mesh_mod.reset_topology()
        with pytest.raises(ValueError, match=pat):
            ds.initialize(model=SimpleModel(), config=cfg)


def test_mid_window_protocol_guards(eight_devices, tmp_path):
    """With computed-but-uncommitted steps stashed, every state-touching
    surface refuses loudly: save/load checkpoint, eval(), forward(), batch
    resize, and train_batch(batch=...)."""
    win = _engine(True, horizon=3)
    data = _batches(1, 1 + 3)
    it = iter(list(data))
    win.train_batch(data_iter=it)  # sequential init
    win.train_batch(data_iter=it)  # window dispatch: 2 steps stashed
    assert len(win._window_stash) == 2
    with pytest.raises(RuntimeError, match="mid-window"):
        win.save_checkpoint(str(tmp_path))
    with pytest.raises(RuntimeError, match="mid-window"):
        win.load_checkpoint(str(tmp_path))
    with pytest.raises(RuntimeError, match="mid-flight"):
        win.eval()
    with pytest.raises(RuntimeError, match="mid-flight"):
        win.forward(data[0])
    with pytest.raises(RuntimeError, match="mid-window"):
        win.set_train_batch_size(16)
    with pytest.raises(RuntimeError, match="mid-flight"):
        win.train_batch(batch=data[0])
    # draining the stash restores every surface
    win.train_batch(data_iter=it)
    win.train_batch(data_iter=it)
    assert not win._window_stash
    win.save_checkpoint(str(tmp_path))


def test_all_overflow_first_window_keeps_lr_exact(eight_devices):
    """The fp16 scale-settling phase: every step up to and including the
    whole first window overflows, so the lr scheduler NEVER steps before
    the second window forms. The lr pre-evaluation's snapshot→replay→
    restore must not leak the replayed warmup value into the live param
    groups (_LRSchedulerBase.load_state_dict only re-applies lr for a
    stepped scheduler) — the run must stay bit-identical to sequential."""
    steps = 7
    bad = {0, 1, 2}  # the sequential init step AND both steps of window 1
    data = _batches(1, steps, bad_step=bad)
    ref = _engine(False, precision="fp16")
    ref_losses = _drive(ref, data, steps)
    win = _engine(True, precision="fp16", horizon=2)
    win_losses = _drive(win, data, steps)
    assert win_losses == ref_losses
    assert win.skipped_steps == ref.skipped_steps == 3
    assert float(win.optimizer.param_groups[0]["lr"]) == float(
        ref.optimizer.param_groups[0]["lr"]
    )
    _assert_same_master(ref, win)
    assert win.window_stats()["window_steps"] >= 2  # windows really formed


def test_resize_cannot_silently_disarm_windows(eight_devices):
    """A live gas resize must honor the same multi_step contract the
    constructor validates: raising gas past 1 without fuse_grad_accum
    would rebuild with windows silently disarmed — it raises instead."""
    win = _engine(True, horizon=2)  # gas=1, fuse_grad_accum off
    _drive(win, _batches(1, 3), 3)
    with pytest.raises(ValueError, match="fuse_grad_accum"):
        win.set_train_batch_size(16)  # gas 1 -> 2
    assert win.window_stats()["multi_step_enabled"] is True  # untouched


def test_window_stats_block_and_observability(eight_devices):
    """window_stats rides engine.observability() as the train_window
    source, and the tracer timeline carries train.window spans."""
    win = _engine(True, horizon=2)
    data = _batches(1, 5)
    _drive(win, data, 5)
    rep = win.observability(analysis=False)
    assert rep["train_window"]["window_steps"] >= 2
    assert rep["train_window"]["multi_step_enabled"] is True
    phases = win.tracer.phase_summary()
    assert "train.window" in phases
    assert phases["train.window"]["count"] == rep["train_window"]["window_steps"]
    assert "train.loss_drain" in phases
