"""Dataloader tests."""

import numpy as np

from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader


class _ListDataset:
    def __init__(self, n=20, dim=4):
        rs = np.random.RandomState(0)
        self.data = [(rs.randn(dim).astype(np.float32), rs.randn(1).astype(np.float32)) for _ in range(n)]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


def test_batching_and_len():
    loader = DeepSpeedDataLoader(_ListDataset(20), batch_size=8)
    assert len(loader) == 2
    batches = list(loader)
    assert len(batches) == 2
    x, y = batches[0]
    assert x.shape == (8, 4) and y.shape == (8, 1)


def test_no_drop_last():
    loader = DeepSpeedDataLoader(_ListDataset(20), batch_size=8, drop_last=False)
    assert len(loader) == 3
    assert list(loader)[-1][0].shape == (4, 4)


def test_shuffle_deterministic_per_epoch():
    l1 = DeepSpeedDataLoader(_ListDataset(16), batch_size=4, shuffle=True, seed=3)
    l2 = DeepSpeedDataLoader(_ListDataset(16), batch_size=4, shuffle=True, seed=3)
    b1, b2 = next(iter(l1)), next(iter(l2))
    np.testing.assert_array_equal(b1[0], b2[0])
    l1.set_epoch(1)
    b3 = next(iter(l1))
    assert not np.array_equal(b1[0], b3[0])


def test_dict_collate():
    class DictDS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return {"x": np.full(3, i, np.float32), "y": np.int32(i)}

    loader = DeepSpeedDataLoader(DictDS(), batch_size=4)
    b = next(iter(loader))
    assert set(b) == {"x", "y"}
    assert b["x"].shape == (4, 3)


def test_repeating_loader():
    loader = DeepSpeedDataLoader(_ListDataset(8), batch_size=4)
    rep = RepeatingLoader(loader)
    batches = [next(rep) for _ in range(5)]
    assert len(batches) == 5


def test_iterable_dataset():
    def gen():
        for i in range(10):
            yield np.full(2, i, np.float32)

    loader = DeepSpeedDataLoader(gen(), batch_size=4)
    batches = list(loader)
    assert len(batches) == 2
    assert batches[0].shape == (4, 2)
