"""Dataloader tests."""

import numpy as np

from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader, RepeatingLoader


class _ListDataset:
    def __init__(self, n=20, dim=4):
        rs = np.random.RandomState(0)
        self.data = [(rs.randn(dim).astype(np.float32), rs.randn(1).astype(np.float32)) for _ in range(n)]

    def __len__(self):
        return len(self.data)

    def __getitem__(self, i):
        return self.data[i]


def test_batching_and_len():
    loader = DeepSpeedDataLoader(_ListDataset(20), batch_size=8)
    assert len(loader) == 2
    batches = list(loader)
    assert len(batches) == 2
    x, y = batches[0]
    assert x.shape == (8, 4) and y.shape == (8, 1)


def test_no_drop_last():
    loader = DeepSpeedDataLoader(_ListDataset(20), batch_size=8, drop_last=False)
    assert len(loader) == 3
    assert list(loader)[-1][0].shape == (4, 4)


def test_shuffle_deterministic_per_epoch():
    l1 = DeepSpeedDataLoader(_ListDataset(16), batch_size=4, shuffle=True, seed=3)
    l2 = DeepSpeedDataLoader(_ListDataset(16), batch_size=4, shuffle=True, seed=3)
    b1, b2 = next(iter(l1)), next(iter(l2))
    np.testing.assert_array_equal(b1[0], b2[0])
    l1.set_epoch(1)
    b3 = next(iter(l1))
    assert not np.array_equal(b1[0], b3[0])


def test_dict_collate():
    class DictDS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return {"x": np.full(3, i, np.float32), "y": np.int32(i)}

    loader = DeepSpeedDataLoader(DictDS(), batch_size=4)
    b = next(iter(loader))
    assert set(b) == {"x", "y"}
    assert b["x"].shape == (4, 3)


def test_repeating_loader():
    loader = DeepSpeedDataLoader(_ListDataset(8), batch_size=4)
    rep = RepeatingLoader(loader)
    batches = [next(rep) for _ in range(5)]
    assert len(batches) == 5


def test_iterable_dataset():
    def gen():
        for i in range(10):
            yield np.full(2, i, np.float32)

    loader = DeepSpeedDataLoader(gen(), batch_size=4)
    batches = list(loader)
    assert len(batches) == 2
    assert batches[0].shape == (4, 2)


def test_post_process_func_applied():
    """reference engine.set_data_post_process_func: the hook transforms
    every emitted batch."""
    from deepspeed_tpu.runtime.dataloader import DeepSpeedDataLoader

    data = [(np.full((2,), float(i)), np.zeros((2,))) for i in range(8)]
    loader = DeepSpeedDataLoader(data, batch_size=4, shuffle=False)
    loader.post_process_func = lambda batch: (batch[0] + 100.0, batch[1])
    xs = [b[0] for b in loader]
    assert all((x >= 100.0).all() for x in xs)


def test_engine_data_efficiency_hooks(eight_devices):
    """engine.set_data_post_process_func + set_custom_curriculum_learning_schedule
    (reference engine.py:433,437)."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from tests.unit.simple_model import SimpleModel

    mesh_mod.reset_topology()
    data = [(np.random.RandomState(i).randn(16).astype(np.float32),
             np.zeros(16, np.float32)) for i in range(16)]
    engine, _, loader, _ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "curriculum_learning": {
                "enabled": True, "curriculum_type": "seqlen",
                "min_difficulty": 8, "max_difficulty": 16,
                # reference semantics: the custom function installed via
                # set_custom_curriculum_learning_schedule only drives the
                # "custom" schedule type
                "schedule_type": "custom",
            },
        },
        training_data=data,
    )
    marks = []
    engine.set_data_post_process_func(lambda b: (marks.append(1), b)[1])
    batch = next(iter(loader))
    assert marks, "post-process hook did not run"

    seen = []
    engine.set_custom_curriculum_learning_schedule(lambda step: seen.append(step) or 16)
    assert engine.curriculum_scheduler.update_difficulty(3) == 16
    assert seen == [3]
