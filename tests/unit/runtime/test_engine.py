"""Engine behavior tests (reference: tests/unit/runtime/test_ds_initialize.py)."""

import os
import tempfile

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
from tests.unit.simple_model import SimpleModel, random_dataloader


def _cfg(**over):
    base = {
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 1},
    }
    base.update(over)
    return base


def test_initialize_returns_tuple(eight_devices):
    engine, opt, loader, sched = ds.initialize(model=SimpleModel(), config=_cfg())
    assert opt is engine.optimizer
    assert loader is None and sched is None


def test_client_optimizer(eight_devices):
    from deepspeed_tpu.ops import FusedAdam

    opt = FusedAdam(lr=5e-3)
    engine, returned, *_ = ds.initialize(model=SimpleModel(), config={"train_micro_batch_size_per_gpu": 1, "bf16": {"enabled": True}}, optimizer=opt)
    assert returned is opt
    assert engine.get_lr() == [5e-3]


def test_lr_scheduler_from_config(eight_devices):
    cfg = _cfg(scheduler={"type": "WarmupLR", "params": {"warmup_min_lr": 0.0, "warmup_max_lr": 1e-2, "warmup_num_steps": 4}})
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg)
    batch = next(random_dataloader())
    lrs = []
    for _ in range(5):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        lrs.append(engine.get_lr()[0])
    assert lrs[-1] == pytest.approx(1e-2, rel=1e-3)
    assert lrs[0] < lrs[1] < lrs[2]


def test_checkpoint_roundtrip(eight_devices):
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg())
    batch = next(random_dataloader())
    for _ in range(3):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    with tempfile.TemporaryDirectory() as d:
        engine.save_checkpoint(d, client_state={"k": 1})
        assert os.path.isfile(os.path.join(d, "latest"))
        w_before = jax.device_get(engine.get_master_params()["w0"])

        import deepspeed_tpu.parallel.mesh as mesh_mod

        mesh_mod.reset_topology()
        engine2, *_ = ds.initialize(model=SimpleModel(), config=_cfg())
        engine2.init_params(batch, rng=jax.random.PRNGKey(123))
        path, client = engine2.load_checkpoint(d)
        assert client == {"k": 1}
        assert engine2.global_steps == 3
        np.testing.assert_array_equal(jax.device_get(engine2.get_master_params()["w0"]), w_before)


def test_checkpoint_load_without_latest(eight_devices, tmp_path):
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg())
    batch = next(random_dataloader())
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    path, client = engine.load_checkpoint(str(tmp_path))
    assert path is None and client == {}


def test_eval_mode_no_grad_side_effects(eight_devices):
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg())
    batch = next(random_dataloader())
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    master_before = jax.device_get(engine.get_master_params()["w0"])
    steps_before = engine.global_steps
    engine.eval()
    _ = engine(batch)
    with pytest.raises(RuntimeError):
        engine.backward(loss)
    np.testing.assert_array_equal(
        jax.device_get(engine.get_master_params()["w0"]), master_before
    )
    assert engine.global_steps == steps_before
    if engine._grad_acc is not None:
        np.testing.assert_array_equal(jax.device_get(engine._grad_acc["w0"]), 0.0)
    engine.train()


def test_model_parameters_passthrough(eight_devices):
    model = SimpleModel(16)
    params = model.init(jax.random.PRNGKey(7), None)
    engine, *_ = ds.initialize(model=model, config=_cfg(), model_parameters=params)
    batch = next(random_dataloader(16))
    engine.init_params(batch)
    np.testing.assert_allclose(
        jax.device_get(engine.get_master_params()["w0"]),
        np.asarray(params["w0"], dtype=np.float32),
        rtol=1e-6,
    )


def test_fp16_overflow_skips_step(eight_devices):
    cfg = _cfg(bf16={"enabled": False}, fp16={"enabled": True, "initial_scale_power": 4, "hysteresis": 1})
    engine, *_ = ds.initialize(model=SimpleModel(), config=cfg)
    x, y = next(random_dataloader())
    loss = engine((x, y))
    engine.backward(loss)
    engine.step()
    w_after_good = jax.device_get(engine.get_master_params()["w0"])
    xn = x.copy()
    xn[0, 0] = np.inf
    loss = engine((xn, y))
    engine.backward(loss)
    engine.step()
    assert engine.skipped_steps == 1
    assert engine.loss_scale == 8.0  # 16 / 2 after overflow with hysteresis=1
    np.testing.assert_array_equal(jax.device_get(engine.get_master_params()["w0"]), w_after_good)


def test_gradient_clipping_applied(eight_devices):
    engine, *_ = ds.initialize(model=SimpleModel(), config=_cfg(gradient_clipping=1e-8))
    batch = next(random_dataloader())
    w_before = jax.device_get(engine.get_master_params()["w0"]) if engine._initialized else None
    loss = engine(batch)
    engine.backward(loss)
    engine.step()
    norm = engine.get_global_grad_norm()
    assert norm is not None and norm > 0
    # with a tiny clip threshold the update must be tiny
    w_after = jax.device_get(engine.get_master_params()["w0"])
    assert np.abs(w_after).max() < 1.0
