"""Compression tests (reference: ``tests/unit/compression/``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.compression import (
    init_compression,
    redundancy_clean,
    row_pruning_mask,
    sparse_pruning_mask,
)
from tests.unit.simple_model import SimpleModel


class TestMasks:
    def test_sparse_mask_ratio(self):
        rs = np.random.RandomState(0)
        w = jnp.asarray(rs.randn(32, 32).astype(np.float32))
        mask = sparse_pruning_mask(w, ratio=0.75)
        kept = float(np.asarray(mask).sum()) / mask.size
        assert abs(kept - 0.25) < 0.02
        # the kept entries are the largest-magnitude ones
        thresh = np.sort(np.abs(np.asarray(w)).ravel())[-int(0.25 * w.size)]
        assert (np.abs(np.asarray(w))[np.asarray(mask) > 0] >= thresh).all()

    def test_row_mask_structured(self):
        rs = np.random.RandomState(1)
        w = jnp.asarray(rs.randn(16, 8).astype(np.float32))
        mask = np.asarray(row_pruning_mask(w, ratio=0.5))
        col_live = mask.all(axis=0)
        assert set(np.unique(mask)) <= {0.0, 1.0}
        assert col_live.sum() == 4  # half the output features survive
        # each column is fully on or fully off
        assert ((mask.sum(axis=0) == 16) | (mask.sum(axis=0) == 0)).all()


COMPRESSION_CONFIG = {
    "weight_quantization": {
        "shared_parameters": {"enabled": True},
        "different_groups": {
            "wq1": {"params": {"start_bits": 8, "quantize_groups": 1}, "modules": ["w0"]}
        },
    },
    "sparse_pruning": {
        "shared_parameters": {"enabled": True},
        "different_groups": {
            "sp1": {"params": {"dense_ratio": 0.5}, "modules": ["w1"]}
        },
    },
}


class TestInitCompression:
    def test_forward_uses_compressed_weights(self):
        mesh_mod.reset_topology()
        model = init_compression(SimpleModel(hidden_dim=16), COMPRESSION_CONFIG)
        cfg = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "steps_per_print": 100,
        }
        engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        losses = []
        for _ in range(5):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # QAT still trains (straight-through)

    def test_redundancy_clean_bakes_masks(self):
        rs = np.random.RandomState(0)
        params = {
            "w0": jnp.asarray(rs.randn(16, 16).astype(np.float32)),
            "w1": jnp.asarray(rs.randn(16, 16).astype(np.float32)),
        }
        cleaned = redundancy_clean(params, COMPRESSION_CONFIG)
        # w1 pruned to ~50%
        zeros = float((np.asarray(cleaned["w1"]) == 0).mean())
        assert abs(zeros - 0.5) < 0.05
        # w0 quantized: at most 256 distinct values
        assert len(np.unique(np.asarray(cleaned["w0"]))) <= 256


class TestEigenvalue:
    def test_quadratic_eigenvalue(self):
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        # loss = 0.5 x^T A x with known top eigenvalue
        rs = np.random.RandomState(0)
        q, _ = np.linalg.qr(rs.randn(8, 8))
        eigs = np.array([5.0, 3, 2, 1, 0.5, 0.2, 0.1, 0.05])
        A = jnp.asarray((q * eigs) @ q.T, dtype=jnp.float32)

        def loss(p):
            x = p["x"]
            return 0.5 * x @ A @ x

        ev = Eigenvalue(max_iter=200, tol=1e-4)
        est = ev.compute_eigenvalue(loss, {"x": jnp.ones(8)})
        assert abs(est - 5.0) < 0.1

    def test_per_block(self):
        from deepspeed_tpu.runtime.eigenvalue import Eigenvalue

        def loss(p):
            return 2.0 * jnp.sum(p["a"] ** 2) + 0.5 * jnp.sum(p["b"] ** 2)

        ev = Eigenvalue(max_iter=50)
        out = ev.compute_eigenvalue_per_block(loss, {"a": jnp.ones(4), "b": jnp.ones(4)})
        assert abs(out["a"] - 4.0) < 0.1  # Hessian of 2x² is 4I
        assert abs(out["b"] - 1.0) < 0.1


class TestProgressiveLayerDrop:
    def test_theta_schedule(self):
        from deepspeed_tpu.runtime.progressive_layer_drop import ProgressiveLayerDrop

        pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
        pld.update_state(0)
        assert pld.get_theta() == 1.0
        pld.update_state(10**6)
        assert abs(pld.get_theta() - 0.5) < 1e-6
        assert pld.get_state()["progressive_layer_drop"]


class TestStaging:
    def _module(self, offset=5, end=0):
        from deepspeed_tpu.compression import CompressionScheduler, init_compression
        from tests.unit.simple_model import SimpleModel

        cfg = {
            "compression_training": {
                "weight_quantization": {
                    "shared_parameters": {
                        "enabled": True,
                        "schedule_offset": offset,
                        **({"schedule_offset_end": end} if end else {}),
                    },
                    "different_groups": {
                        "wq1": {"params": {"start_bits": 8}, "modules": ["*"]}
                    },
                }
            }
        }
        module = init_compression(SimpleModel(8), cfg)
        return module, CompressionScheduler(module)

    def test_method_activates_at_offset(self):
        module, sched = self._module(offset=5)
        sched.step(0)
        assert sched.active_methods() == []
        sched.step(5)
        assert sched.active_methods() == ["weight_quantization"]

    def test_method_deactivates_after_end(self):
        module, sched = self._module(offset=2, end=4)
        sched.step(3)
        assert sched.active_methods() == ["weight_quantization"]
        sched.step(5)
        assert sched.active_methods() == []

    def test_inactive_stage_is_identity(self):
        import jax.numpy as jnp
        import numpy as np

        module, sched = self._module(offset=100)
        w = {"w0": jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)}
        sched.step(0)
        np.testing.assert_array_equal(np.asarray(module._compress(w)["w0"]), np.asarray(w["w0"]))
        sched.step(100)
        assert not np.array_equal(np.asarray(module._compress(w)["w0"]), np.asarray(w["w0"]))


class TestLayerReductionDistillation:
    def test_student_from_teacher_layers(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from deepspeed_tpu.compression import student_initialization

        rs = np.random.RandomState(0)
        teacher = {
            "embed": {"tokens": jnp.asarray(rs.randn(16, 4), jnp.float32)},
            "layers": {"w": jnp.asarray(rs.randn(8, 4, 4), jnp.float32)},
            "head": jnp.asarray(rs.randn(4, 16), jnp.float32),
        }
        student = {
            "embed": {"tokens": jnp.zeros((16, 4))},
            "layers": {"w": jnp.zeros((4, 4, 4))},
            "head": jnp.zeros((4, 16)),
        }
        cfg = {
            "compression_training": {
                "layer_reduction": {
                    "enabled": True,
                    "teacher_layer": [1, 3, 5, 7],
                    "module_name_prefix": "layers",
                    "other_module_name": ["embed", "head"],
                }
            }
        }
        out = student_initialization(student, teacher, cfg)
        np.testing.assert_array_equal(
            np.asarray(out["layers"]["w"]), np.asarray(teacher["layers"]["w"])[[1, 3, 5, 7]]
        )
        np.testing.assert_array_equal(np.asarray(out["embed"]["tokens"]), np.asarray(teacher["embed"]["tokens"]))
        np.testing.assert_array_equal(np.asarray(out["head"]), np.asarray(teacher["head"]))

    def test_mismatched_selection_raises(self):
        import jax.numpy as jnp
        import pytest

        from deepspeed_tpu.compression import student_initialization

        teacher = {"layers": {"w": jnp.zeros((8, 4))}}
        student = {"layers": {"w": jnp.zeros((4, 4))}}
        cfg = {"layer_reduction": {"enabled": True, "teacher_layer": [0, 2, 4]}}
        with pytest.raises(ValueError, match="teacher_layer"):
            student_initialization(student, teacher, cfg)


ACT_QUANT_CONFIG = {
    "activation_quantization": {
        "shared_parameters": {"enabled": True},
        "different_groups": {
            "aq1": {"params": {"bits": 8}, "modules": ["*"]}
        },
    },
}


class TestActivationQuantization:
    """activation_quantization flows from config through the forward
    (reference compress.py:100 + basic_layer quantize-activation path)."""

    def _tiny_lm(self):
        from deepspeed_tpu.models import TransformerLM
        from deepspeed_tpu.models.config import TransformerConfig

        cfg = TransformerConfig(
            vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
            max_seq_len=16, use_bias=False, tie_embeddings=True,
        )
        return TransformerLM(cfg)

    def test_forward_differs_from_unquantized(self):
        mesh_mod.reset_topology()
        model = self._tiny_lm()
        wrapped = init_compression(model, ACT_QUANT_CONFIG)
        rng = jax.random.PRNGKey(0)
        toks = np.random.RandomState(0).randint(0, 64, (2, 17)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        params = wrapped.init(rng, batch)
        loss_q = float(wrapped.apply(params, batch, train=False))
        loss_plain = float(model.apply(params, batch, train=False))
        assert np.isfinite(loss_q)
        # 8-bit activations perturb the forward, but not catastrophically
        assert loss_q != loss_plain
        assert abs(loss_q - loss_plain) < 0.5 * abs(loss_plain)

    def test_site_patterns_select_hooks(self):
        from deepspeed_tpu.compression.act_quant import (
            activation_quantization_scope,
            maybe_quantize,
        )

        x = jnp.asarray(np.random.RandomState(0).randn(4, 8), jnp.float32)
        with activation_quantization_scope([(8, ["layers/mlp_input"])]):
            np.testing.assert_array_equal(
                np.asarray(maybe_quantize(x, "layers/attn_input")), np.asarray(x)
            )
            assert not np.array_equal(
                np.asarray(maybe_quantize(x, "layers/mlp_input")), np.asarray(x)
            )
        # scope exited: everything is identity again
        np.testing.assert_array_equal(
            np.asarray(maybe_quantize(x, "layers/mlp_input")), np.asarray(x)
        )

    def test_trains_with_straight_through(self):
        mesh_mod.reset_topology()
        wrapped = init_compression(self._tiny_lm(), ACT_QUANT_CONFIG)
        cfg = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "steps_per_print": 100,
        }
        engine, _, _, _ = ds.initialize(model=wrapped, config=cfg, dist_init_required=False)
        rs = np.random.RandomState(0)
        toks = rs.randint(0, 64, (8, 17)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        losses = []
        for _ in range(10):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_inactive_before_schedule_offset(self):
        cfg = {
            "activation_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 50},
                "different_groups": {"aq1": {"params": {"bits": 8}, "modules": ["*"]}},
            }
        }
        mesh_mod.reset_topology()
        model = self._tiny_lm()
        wrapped = init_compression(model, cfg)
        rng = jax.random.PRNGKey(0)
        toks = np.random.RandomState(0).randint(0, 64, (2, 17)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        params = wrapped.init(rng, batch)
        # step 0 < offset: forward identical to the plain model
        assert float(wrapped.apply(params, batch, train=False)) == float(
            model.apply(params, batch, train=False)
        )
        wrapped.set_step(50)
        assert float(wrapped.apply(params, batch, train=False)) != float(
            model.apply(params, batch, train=False)
        )


class TestStagingThroughEngine:
    """A schedule_offset flip must reach the ENGINE's compiled step: the
    step programs are traced once, so the scheduler (given the engine)
    rebuilds them on the activation edge."""

    def test_midtraining_activation_changes_compiled_forward(self):
        from deepspeed_tpu.compression import CompressionScheduler

        mesh_mod.reset_topology()
        cfg = {
            "weight_quantization": {
                "shared_parameters": {"enabled": True, "schedule_offset": 3},
                "different_groups": {
                    "wq1": {"params": {"start_bits": 4}, "modules": ["*"]}
                },
            }
        }
        wrapped = init_compression(SimpleModel(hidden_dim=16), cfg)
        engine, _, _, _ = ds.initialize(
            model=wrapped,
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 0.0}},  # frozen
                "steps_per_print": 1000,
            },
            dist_init_required=False,
        )
        sched = CompressionScheduler(wrapped, engine=engine)
        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))

        def step_loss(global_step):
            sched.step(global_step)
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            return float(loss)

        rebuilds = []
        original = engine.invalidate_compiled_step

        def counting_invalidate():
            rebuilds.append(True)
            original()

        engine.invalidate_compiled_step = counting_invalidate

        pre = [step_loss(s) for s in range(3)]
        post = step_loss(3)
        # lr=0: params never change, so any loss difference is the compiled
        # forward changing — 4-bit weight quantization kicking in at step 3
        assert pre[0] == pre[1] == pre[2]
        assert post != pre[0]
        assert step_loss(4) == post
        # edge-triggered: exactly ONE rebuild (at the step-3 activation),
        # not one per step
        assert len(rebuilds) == 1
