"""Telemetry-is-free guard (ISSUE 10 hard constraint).

Tracing must add ZERO host↔device transfers and ZERO new compiled programs
on the hot path, and its measured overhead must stay under 2% of a
bench-like step. Checks here:

* program-set guard — a traced training run compiles exactly the same
  program set as an untraced one, and continued traced stepping triggers
  no new compiles (compile telemetry is the witness);
* host-transfer guard — the analysis pass over the dispatched step
  programs stays clean with tracing on (spans are host-side bookkeeping;
  nothing it does can appear inside compiled HLO — ``tracer.py`` never
  imports jax — but the pass proves the programs themselves are unchanged);
* overhead guard — the measured per-span cost times a generous
  spans-per-step budget is under 2% of a measured bench-like step (the
  wall-clock A/B rides in ``bench.py`` as ``trace_overhead_pct``; here the
  bound is computed from stable minima so the fast tier never flakes);
* the merged ``observability()`` report + Perfetto trace for a training
  run (the serving-run counterparts live in test_request_spans.py).
"""

from __future__ import annotations

import json
import time

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.profiling.tracer import Tracer
from tests.unit.simple_model import SimpleModel, random_dataloader


def _engine(tracing_enabled=True, **extra):
    cfg = {
        "train_micro_batch_size_per_gpu": 8,
        "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
        "bf16": {"enabled": True},
        "tracing": {"enabled": tracing_enabled},
    }
    cfg.update(extra)
    engine, *_ = ds.initialize(
        model=SimpleModel(), config=cfg, dist_init_required=False
    )
    return engine


def _run_steps(engine, n):
    for i, batch in enumerate(random_dataloader(total_samples=8 * n, batch_size=8)):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()


def test_tracing_compiles_zero_new_programs(eight_devices):
    """Same program set traced vs untraced; further traced steps add zero
    compiles (the tracer cannot retrace anything — it never touches jax)."""
    on = _engine(tracing_enabled=True)
    _run_steps(on, 2)
    traced_programs = {
        name: rec["compiles"] for name, rec in on.compile_stats().items()
    }
    off = _engine(tracing_enabled=False)
    _run_steps(off, 2)
    untraced_programs = {
        name: rec["compiles"] for name, rec in off.compile_stats().items()
    }
    assert traced_programs == untraced_programs
    # tracing actually ran
    assert on.tracer.phase_summary()["train.dispatch"]["count"] >= 2
    assert off.tracer.spans() == []
    # steady state: more traced steps, not one more compile anywhere
    _run_steps(on, 4)
    after = {name: rec["compiles"] for name, rec in on.compile_stats().items()}
    assert after == traced_programs


def test_tracing_adds_zero_host_transfers(eight_devices):
    """The analysis host-transfer pass over the dispatched step programs is
    clean with tracing on, via the MERGED observability report (which also
    proves the acceptance surface: timeline + metrics + compile + analysis
    + checkpoint in one call)."""
    engine = _engine(tracing_enabled=True)
    _run_steps(engine, 2)
    rep = engine.observability()  # analysis included
    assert set(rep) >= {"timeline", "metrics", "compile", "analysis", "checkpoint"}
    an = rep["analysis"]
    assert "error" not in an, an
    assert an["totals"]["violations"] == 0
    for name, prog in an["programs"].items():
        ht = prog["passes"].get("host_transfer")
        if ht is not None:
            assert ht["violations"] == [], (name, ht)
    # the timeline saw the run; metrics counted the steps
    assert rep["timeline"]["phases"]["train.step_commit"]["count"] >= 1
    assert rep["metrics"]["counters"]["train.steps"] >= 2


def test_trace_overhead_under_2pct_of_bench_step():
    """Deterministic overhead bound: measured per-span cost × a generous
    spans-per-step budget (16 — the engines place ~6 training / ~10
    serving spans per step) must be under 2% of a measured bench-like
    step (~10 ms of host compute). Minima over repeats make this stable
    where a raw wall-clock A/B flakes on a noisy box."""
    tr = Tracer(max_spans=50_000)
    N = 20_000
    per_span = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(N):
            with tr.span("x"):
                pass
        per_span = min(per_span, (time.perf_counter() - t0) / N)
        tr.clear()
    a = np.random.rand(384, 384)
    b = np.random.rand(384, 384)
    step_s = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        for _ in range(8):
            c = a @ b
            c = c @ b
            c = c @ b
            c = c @ b
        step_s = min(step_s, (time.perf_counter() - t0) / 8)
    overhead_pct = 16 * per_span / step_s * 100.0
    assert overhead_pct < 2.0, (
        f"per_span={per_span * 1e6:.2f}us step={step_s * 1e3:.2f}ms "
        f"-> {overhead_pct:.3f}%"
    )


def test_disabled_tracer_is_nanoscale():
    """tracing.enabled=False must cost one attribute read + one call —
    bound it at 1µs/span with a huge margin so a regression to 'always
    allocate' is caught."""
    tr = Tracer(enabled=False)
    N = 100_000
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(N):
            with tr.span("x"):
                pass
        best = min(best, (time.perf_counter() - t0) / N)
    assert best < 1e-6, f"{best * 1e9:.0f}ns per disabled span"


def test_training_chrome_trace_perfetto_loadable(eight_devices, tmp_path):
    """Acceptance: a Perfetto-loadable trace JSON for a training run —
    well-formed Trace Event Format with the step phases present."""
    engine = _engine(tracing_enabled=True)
    _run_steps(engine, 3)
    path = engine.observability_hub.export_chrome_trace(str(tmp_path / "train.json"))
    obj = json.load(open(path))
    evs = obj["traceEvents"]
    assert isinstance(evs, list) and evs[0]["ph"] == "M"
    names = {e["name"] for e in evs}
    assert {"train.h2d", "train.dispatch", "train.step_commit"} <= names
    for e in evs:
        assert "ph" in e and "name" in e
        if e["ph"] == "X":
            assert e["dur"] >= 0 and "ts" in e and "tid" in e
    assert obj["otherData"]["metrics"]["counters"]["train.steps"] == 3.0


def test_fused_accum_step_phase_breakdown(eight_devices):
    """gas>1 with fuse_grad_accum: the fused train_batch records the full
    phase chain (h2d → dispatch → loss_fetch inside train.step) and the
    step-time histogram."""
    engine = _engine(
        tracing_enabled=True,
        gradient_accumulation_steps=2,
        compile={"fuse_grad_accum": True},
    )
    data = random_dataloader(total_samples=32, batch_size=8)
    it = iter(data)
    for _ in range(2):
        engine.train_batch(data_iter=it)
    phases = engine.tracer.phase_summary()
    for name in ("train.step", "train.h2d", "train.dispatch", "train.loss_fetch",
                 "train.data_fetch"):
        assert phases[name]["count"] == 2, (name, phases.get(name))
    hist = engine.metrics.snapshot()["histograms"]["train.step_ms"]
    assert hist["count"] == 2 and hist["p50"] > 0


def test_ckpt_d2h_stall_span_and_writer_spans(eight_devices, tmp_path):
    """The async save's only step-loop cost (the D2H snapshot) is a span;
    the background writer's stage/commit land on the same timeline from
    its own thread."""
    engine = _engine(
        tracing_enabled=True,
        checkpoint={"async_snapshot": True},
    )
    _run_steps(engine, 1)
    engine.save_checkpoint(str(tmp_path))
    engine.wait_pending_checkpoint()
    phases = engine.tracer.phase_summary()
    assert phases["ckpt.d2h_stall"]["count"] == 1
    assert phases["ckpt.stage"]["count"] == 1
    assert phases["ckpt.commit"]["count"] == 1
    assert engine.metrics.snapshot()["histograms"]["ckpt.stall_ms"]["count"] == 1
