"""Flight-recorder tests (ISSUE 10).

The crash postmortem: a chaos fault injection (the PR-8 kill harness) must
leave a parseable dump whose last timeline entry names the armed injection
point — in-process via the ChaosKilled simulation, and (``-m slow``) in a
real subprocess that dies via ``os._exit(137)``, proving the dump happens
BEFORE the no-atexit death."""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.tracer import (
    FlightRecorder,
    MetricsRegistry,
    Tracer,
)
from deepspeed_tpu.utils import chaos

CFG = dict(
    vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
    num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
    activation="swiglu", use_bias=False, tie_embeddings=False,
    flash_attention=False, dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def test_manual_dump_shape(tmp_path):
    tr = Tracer()
    m = MetricsRegistry()
    m.counter("tok").inc(3)
    with tr.span("phase"):
        pass
    fr = FlightRecorder(tr, m, path=str(tmp_path / "fr.json"), last_spans=128)
    path = fr.dump(reason="manual")
    obj = json.load(open(path))
    assert obj["reason"] == "manual" and obj["pid"] == os.getpid()
    assert obj["spans"][-1]["name"] == "phase"
    assert obj["metrics"]["counters"]["tok"] == 3.0
    assert obj["open_spans"] == []


def test_dump_respects_last_spans_cap(tmp_path):
    tr = Tracer(max_spans=4096)
    for i in range(500):
        with tr.span(f"s{i}"):
            pass
    fr = FlightRecorder(tr, path=str(tmp_path / "fr.json"), last_spans=16)
    obj = json.load(open(fr.dump()))
    assert len(obj["spans"]) == 16
    assert obj["spans"][-1]["name"] == "s499"  # the NEWEST window


def test_chaos_kill_leaves_postmortem_with_armed_point(tmp_path):
    """The in-process simulation: an armed ChaosKilled fires the kill hook
    before the raise — the dump exists, names the point, and the timeline's
    last entry is the chaos event."""
    tr = Tracer()
    fr = FlightRecorder(tr, path=str(tmp_path / "fr.json")).install(on_exit=False)
    try:
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("journal.append")]))
        with pytest.raises(chaos.ChaosKilled):
            with tr.span("serve.step"):
                chaos.point("journal.append")
    finally:
        chaos.uninstall()
        fr.uninstall()
    obj = json.load(open(str(tmp_path / "fr.json")))
    assert obj["reason"] == "chaos" and obj["point"] == "journal.append"
    assert obj["spans"][-1]["name"] == "chaos.journal.append"
    assert obj["chaos_fired"] == ["journal.append#1:raise"]
    # the in-flight span at death is visible — "what was it doing"
    assert [s["name"] for s in obj["open_spans"]] == ["serve.step"]


def test_uninstalled_recorder_stops_dumping(tmp_path):
    tr = Tracer()
    fr = FlightRecorder(tr, path=str(tmp_path / "fr.json")).install(on_exit=False)
    fr.uninstall()
    try:
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("journal.append")]))
        with pytest.raises(chaos.ChaosKilled):
            chaos.point("journal.append")
    finally:
        chaos.uninstall()
    assert not os.path.exists(str(tmp_path / "fr.json"))


def test_serving_chaos_kill_dumps_mid_step(tmp_path, model_and_params):
    """The real serving loop: a chaos kill at serve.mid_step (inside the
    scheduler's step span, before the journal flush) leaves a dump whose
    last entry names the point and whose open spans show the step in
    flight."""
    cfg, _, params = model_and_params
    tr = Tracer()
    server = PagedServer(
        cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32, tracer=tr,
    )
    fr = FlightRecorder(tr, path=str(tmp_path / "fr.json")).install(on_exit=False)
    server.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=6)
    try:
        chaos.install(
            chaos.ChaosSchedule([chaos.ChaosRule("serve.mid_step", hit=3)])
        )
        with pytest.raises(chaos.ChaosKilled):
            server.run()
    finally:
        chaos.uninstall()
        fr.uninstall()
    obj = json.load(open(str(tmp_path / "fr.json")))
    assert obj["point"] == "serve.mid_step"
    assert obj["spans"][-1]["name"] == "chaos.serve.mid_step"
    assert "serve.step" in [s["name"] for s in obj["open_spans"]]
    # the two completed scheduler rounds are on the timeline
    names = [s["name"] for s in obj["spans"]]
    assert names.count("serve.step") == 2


# ---------------------------------------------------------------------------
# the real death: a subprocess os._exit(137) kill still leaves the dump
# ---------------------------------------------------------------------------
_CHILD = r"""
import sys, numpy as np, jax, jax.numpy as jnp
from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.tracer import FlightRecorder, MetricsRegistry, Tracer
from deepspeed_tpu.utils import chaos

dump_path = sys.argv[1]
cfg = TransformerConfig(
    vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
    num_kv_heads=2, max_seq_len=64, norm="rmsnorm", position="rope",
    activation="swiglu", use_bias=False, tie_embeddings=False,
    flash_attention=False, dtype="float32",
)
model = TransformerLM(cfg)
toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
params = model.init(jax.random.PRNGKey(0), toks)
tracer = Tracer()
metrics = MetricsRegistry()
FlightRecorder(tracer, metrics, path=dump_path).install(on_exit=False)
server = PagedServer(cfg, params, page_size=8, max_slots=4, prefill_chunk=8,
                     attn_impl="xla", dtype=jnp.float32, tracer=tracer,
                     metrics=metrics)
server.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=8)
chaos.install(chaos.ChaosSchedule(
    [chaos.ChaosRule("serve.mid_step", hit=4, action="exit")]
))
server.run()
print("UNREACHABLE")  # the kill must fire before the serve completes
sys.exit(3)
"""


@pytest.mark.slow
def test_subprocess_exit_kill_leaves_parseable_postmortem(tmp_path):
    """A REAL abrupt death (os._exit(137): no atexit, no flushing, nothing
    downstream) — the kill hook runs before the exit, so the postmortem
    file exists, parses, and its last span matches the armed injection
    point."""
    dump = str(tmp_path / "postmortem.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD, dump],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 137, proc.stderr[-2000:]
    assert "UNREACHABLE" not in proc.stdout
    obj = json.load(open(dump))
    assert obj["reason"] == "chaos" and obj["point"] == "serve.mid_step"
    assert obj["spans"][-1]["name"] == "chaos.serve.mid_step"
    assert obj["spans"][-1]["attrs"] == {"action": "exit"}
    assert "serve.step" in [s["name"] for s in obj["open_spans"]]
    assert obj["chaos_fired"] == ["serve.mid_step#4:exit"]
    # three completed rounds before the fourth died mid-step
    assert [s["name"] for s in obj["spans"]].count("serve.step") == 3
