"""Flops profiler tests (reference: ``tests/unit/profiling/``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.profiling.flops_profiler import get_model_profile
from deepspeed_tpu.profiling.flops_profiler.profiler import get_compiled_cost
from tests.unit.simple_model import SimpleModel


class TestCostAnalysis:
    def test_matmul_flops(self):
        n = 128

        def f(a, b):
            return a @ b

        a = jnp.ones((n, n), jnp.float32)
        b = jnp.ones((n, n), jnp.float32)
        cost = get_compiled_cost(jax.jit(f), a, b)
        # 2*n^3 fma flops, allow fusion slack
        assert cost["flops"] >= 2 * n**3 * 0.9

    def test_get_model_profile(self, capsys):
        def f(x):
            return jnp.tanh(x @ x.T).sum()

        flops, macs, params = get_model_profile(
            f, input_shape=(64, 64), print_profile=True, as_string=False
        )
        assert flops > 0
        out = capsys.readouterr().out
        assert "flops=" in out


class TestEngineProfiler:
    def test_profile_step_prints(self, capsys):
        mesh_mod.reset_topology()
        cfg = {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "sgd", "params": {"lr": 0.01}},
            "flops_profiler": {"enabled": True, "profile_step": 1},
            "steps_per_print": 100,
        }
        model = SimpleModel(hidden_dim=16)
        engine, _, _, _ = ds.initialize(model=model, config=cfg, dist_init_required=False)
        rs = np.random.RandomState(0)
        batch = (rs.randn(8, 16).astype(np.float32), rs.randn(8, 16).astype(np.float32))
        for _ in range(3):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
        out = capsys.readouterr().out
        assert "DeepSpeed Flops Profiler" in out
        assert "Compiled step flops" in out


class TestActivationCheckpointing:
    def test_checkpoint_matches_uncheckpointed(self):
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing

        def f(w, x):
            return jnp.sum(jnp.tanh(x @ w) ** 2)

        w = jnp.ones((8, 8)) * 0.3
        x = jnp.ones((4, 8))
        g_plain = jax.grad(f)(w, x)
        g_remat = jax.grad(lambda w, x: checkpointing.checkpoint(f, w, x))(w, x)
        np.testing.assert_allclose(np.asarray(g_plain), np.asarray(g_remat), rtol=1e-6)

    def test_configure_roundtrip(self):
        from deepspeed_tpu.runtime.activation_checkpointing import checkpointing

        checkpointing.reset()
        assert not checkpointing.is_configured()
        checkpointing.configure(partition_activations=True, checkpoint_in_cpu=False)
        assert checkpointing.is_configured()
        assert checkpointing.get_partition_activations()
        checkpointing.reset()

    def test_checkpoint_function_shim(self):
        from deepspeed_tpu.runtime.activation_checkpointing.checkpointing import (
            CheckpointFunction,
        )

        out = CheckpointFunction.apply(lambda a, b: a + b, jnp.ones(3), jnp.ones(3))
        np.testing.assert_array_equal(np.asarray(out), np.full(3, 2.0))


class TestModuleProfileTree:
    """Per-module tree (reference profiler.py:85-130): depth-indented rows
    with params/MACs/latency/% per module, layer-by-layer."""

    def _model(self):
        from deepspeed_tpu.models import TransformerLM
        from deepspeed_tpu.models.config import TransformerConfig

        cfg = TransformerConfig(
            vocab_size=128,
            hidden_size=32,
            num_layers=4,
            num_heads=4,
            max_seq_len=32,
            dtype="float32",
            flash_attention=False,
        )
        model = TransformerLM(cfg)
        toks = jnp.asarray(np.random.RandomState(0).randint(0, 128, (2, 16)), jnp.int32)
        params = model.init(jax.random.PRNGKey(0), toks)
        return model, params, toks

    def test_per_layer_rows(self):
        from deepspeed_tpu.profiling.flops_profiler.profiler import (
            get_module_profile,
            render_module_tree,
        )

        model, params, toks = self._model()
        root = get_module_profile(model, params, toks, runs=1)
        names = [c.name for c in root.children]
        assert names == ["embed", "layers", "head"]
        layer_rows = root.children[1].children
        assert [r.name for r in layer_rows] == [f"layers.{i}" for i in range(4)]
        assert all(r.macs > 0 and r.params > 0 and r.latency > 0 for r in layer_rows)
        # totals are consistent: children sum to the root
        child_flops = sum(c.flops for c in root.children)
        assert abs(child_flops - root.flops) < 1e-6 * max(root.flops, 1)
        text = render_module_tree(root)
        assert "layers.3" in text and "MACs" in text and "%" in text

    def test_engine_wired_tree_in_printout(self, capsys, eight_devices):
        from deepspeed_tpu.models import TransformerLM
        from deepspeed_tpu.models.config import TransformerConfig

        mesh_mod.reset_topology()
        cfg = TransformerConfig(
            vocab_size=128, hidden_size=32, num_layers=4, num_heads=4,
            max_seq_len=32, dtype="float32", flash_attention=False,
        )
        engine, *_ = ds.initialize(
            model=TransformerLM(cfg),
            config={
                "train_micro_batch_size_per_gpu": 1,
                "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "flops_profiler": {"enabled": True, "profile_step": 1},
            },
        )
        toks = np.random.RandomState(0).randint(0, 128, (8, 17)).astype(np.int32)
        batch = {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}
        for _ in range(2):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
        prof = engine.flops_profiler
        tree = prof.get_module_profile()
        assert tree is not None and len(tree.children[1].children) == 4
        prof.print_model_profile(detailed=True)
        out = capsys.readouterr().out
        assert "layers.2" in out
