"""Tracer / metrics core tests (ISSUE 10).

Load-bearing checks: span nesting depth and ordering, ring-buffer
wraparound with an exact dropped count, histogram percentiles against a
known distribution, thread-safety driven by the REAL async checkpoint
writer (spans recorded from its background thread while the main thread
traces), the Chrome-trace (Perfetto) export shape, the timer→tracer
routing (and the flipped ``stop(sync=...)`` default), and the hub's
monitor-event feed."""

from __future__ import annotations

import json
import threading

import pytest

from deepspeed_tpu.profiling.tracer import (
    Histogram,
    MetricsRegistry,
    ObservabilityHub,
    Tracer,
)


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
def test_span_nesting_depth_and_order():
    tr = Tracer()
    with tr.span("outer", step=1):
        with tr.span("mid"):
            with tr.span("inner"):
                pass
    recs = tr.spans()
    # children complete (and land) before their parents
    assert [r["name"] for r in recs] == ["inner", "mid", "outer"]
    assert [r["depth"] for r in recs] == [2, 1, 0]
    outer = recs[-1]
    assert outer["attrs"] == {"step": 1}
    assert outer["t1"] >= outer["t0"]
    # parents fully contain their children in time
    inner = recs[0]
    assert outer["t0"] <= inner["t0"] and inner["t1"] <= outer["t1"]


def test_span_set_attrs_mid_flight_and_duration():
    tr = Tracer()
    with tr.span("pack") as sp:
        sp.set(rows=7)
    assert tr.spans()[-1]["attrs"] == {"rows": 7}
    assert sp.duration_ms >= 0.0


def test_ring_buffer_wraparound_exact_drop_count():
    tr = Tracer(max_spans=16)
    for i in range(100):
        with tr.span(f"s{i}"):
            pass
    recs = tr.spans()
    assert len(recs) == 16
    assert tr.dropped() == 84
    # the ring holds the NEWEST spans
    assert recs[-1]["name"] == "s99" and recs[0]["name"] == "s84"


def test_disabled_tracer_records_nothing():
    tr = Tracer(enabled=False)
    with tr.span("x"):
        pass
    tr.event("e")
    tr.begin_async("request", 1, "r")
    tr.add_span("y", 0.0, 1.0)
    assert tr.spans() == []
    assert tr.phase_summary() == {}


def test_phase_summary_aggregates():
    t = [0.0]

    def clock():
        t[0] += 0.001  # every call advances 1 ms
        return t[0]

    tr = Tracer(clock=clock)
    for _ in range(3):
        with tr.span("phase"):
            pass
    agg = tr.phase_summary()["phase"]
    assert agg["count"] == 3
    assert agg["mean_ms"] == pytest.approx(1.0)
    assert agg["total_ms"] == pytest.approx(3.0)


def test_async_lifecycle_events_keep_id_and_category():
    tr = Tracer()
    tr.begin_async("request", 42, "req42", tenant="a")
    tr.instant_async("request", 42, "first_token")
    tr.end_async("request", 42, "req42", tokens=9)
    phs = [(r["ph"], r["name"], r["id"]) for r in tr.spans()]
    assert phs == [("b", "req42", 42), ("n", "first_token", 42), ("e", "req42", 42)]


def test_open_spans_visible_across_threads():
    tr = Tracer()
    entered = threading.Event()
    release = threading.Event()

    def worker():
        with tr.span("bg.work"):
            entered.set()
            release.wait(5)

    t = threading.Thread(target=worker)
    t.start()
    entered.wait(5)
    names = [s["name"] for s in tr.open_spans()]
    assert "bg.work" in names  # the flight recorder's "what was it doing"
    release.set()
    t.join()
    assert tr.open_spans() == []


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
def test_counter_gauge_roundtrip():
    m = MetricsRegistry()
    m.counter("c").inc()
    m.counter("c").inc(2)
    m.gauge("g").set(3.5)
    snap = m.snapshot()
    assert snap["counters"]["c"] == 3.0
    assert snap["gauges"]["g"] == 3.5
    # same name, different kind → loud failure, not a shadowed series
    with pytest.raises(TypeError):
        m.gauge("c")


def test_histogram_percentiles_uniform():
    h = Histogram("h", buckets=[float(b) for b in range(0, 110, 10)])
    for v in range(1, 101):  # 1..100 uniform
        h.observe(float(v))
    assert h.percentile(50) == pytest.approx(50.0, abs=5.0)
    assert h.percentile(99) == pytest.approx(99.0, abs=5.0)
    snap = h.snapshot()
    assert snap["count"] == 100
    assert snap["min"] == 1.0 and snap["max"] == 100.0
    assert snap["mean"] == pytest.approx(50.5)


def test_histogram_percentile_clamped_to_observed_range():
    h = Histogram("h", buckets=[10.0, 1000.0])
    for _ in range(10):
        h.observe(42.0)
    # all mass in one wide bucket: interpolation must stay within [42, 42]
    assert h.percentile(50) == pytest.approx(42.0)
    assert h.percentile(99) == pytest.approx(42.0)
    assert Histogram("e", buckets=[1.0]).percentile(50) == 0.0


# ---------------------------------------------------------------------------
# thread safety — with the REAL async checkpoint writer
# ---------------------------------------------------------------------------
def test_thread_safety_with_async_ckpt_writer(tmp_path):
    """The engine's tracer is shared between the step loop and the async
    checkpoint writer thread (ckpt.stage/ckpt.commit spans). Drive the real
    AsyncCheckpointWriter with a tracing fake engine while the main thread
    traces concurrently: every span lands, no corruption, no deadlock."""
    from deepspeed_tpu.runtime.checkpoint_engine.async_snapshot import (
        AsyncCheckpointWriter,
    )

    tr = Tracer(max_spans=100_000)

    class FakeEngine:
        def save(self, state, path):
            with tr.span("ckpt.fake_save"):
                pass

        def commit(self, tag):
            pass

    writer = AsyncCheckpointWriter(FakeEngine(), max_inflight=2, tracer=tr)
    N = 50
    for i in range(N):
        with tr.span("train.step"):
            writer.submit({"i": i}, str(tmp_path / f"ck{i}"), f"ck{i}", None)
    writer.wait()
    summary = tr.phase_summary()
    assert summary["train.step"]["count"] == N
    assert summary["ckpt.stage"]["count"] == N
    assert summary["ckpt.commit"]["count"] == N
    assert summary["ckpt.fake_save"]["count"] == N
    # nesting stayed per-thread: stage spans wrap fake_save on the writer
    # thread, at depth 1 under ckpt.stage
    fake = [r for r in tr.spans() if r["name"] == "ckpt.fake_save"]
    assert all(r["depth"] == 1 for r in fake)
    assert tr.open_spans() == []


def test_many_threads_exact_span_count():
    tr = Tracer(max_spans=100_000)

    def work():
        for _ in range(500):
            with tr.span("t"):
                pass

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(tr.spans()) == 8 * 500
    assert tr.dropped() == 0


# ---------------------------------------------------------------------------
# Chrome-trace export
# ---------------------------------------------------------------------------
def test_chrome_trace_export_shape(tmp_path):
    tr = Tracer()
    m = MetricsRegistry()
    m.counter("tokens").inc(5)
    with tr.span("serve.step", rows=2):
        pass
    tr.begin_async("request", 7, "req7")
    tr.end_async("request", 7, "req7", tokens=3)
    tr.event("chaos.serve.mid_step")
    path = tr.export_chrome_trace(str(tmp_path / "trace.json"), metrics=m)
    obj = json.load(open(path))
    evs = obj["traceEvents"]
    assert evs[0]["ph"] == "M"  # process_name metadata
    by_ph = {}
    for e in evs[1:]:
        by_ph.setdefault(e["ph"], []).append(e)
    x = by_ph["X"][0]
    assert x["name"] == "serve.step" and "dur" in x and "ts" in x
    assert x["args"] == {"rows": 2}
    b, e = by_ph["b"][0], by_ph["e"][0]
    assert b["id"] == e["id"] == "7" and b["cat"] == "request"
    assert by_ph["i"][0]["name"] == "chaos.serve.mid_step"
    assert obj["otherData"]["metrics"]["counters"]["tokens"] == 5.0


# ---------------------------------------------------------------------------
# timer routing + flipped sync default (satellite 2)
# ---------------------------------------------------------------------------
def test_timer_stop_default_no_device_sync(monkeypatch):
    """The hot-path hazard: Timer.stop used to default sync=True (a full
    async-dispatch drain per stop). The default is now off; explicit
    sync=True still syncs."""
    import deepspeed_tpu.utils.timer as timer_mod

    calls = {"n": 0}
    monkeypatch.setattr(timer_mod, "_sync", lambda: calls.__setitem__("n", calls["n"] + 1))
    t = timer_mod.SynchronizedWallClockTimer()("x")
    t.start()
    t.stop()
    assert calls["n"] == 0
    t.start()
    t.stop(sync=True)
    assert calls["n"] == 1


def test_timer_routes_spans_into_tracer():
    from deepspeed_tpu.utils.timer import SynchronizedWallClockTimer

    tr = Tracer()
    timers = SynchronizedWallClockTimer(tracer=tr)
    timers("fwd").start()
    timers("fwd").stop()
    timers("fwd").start()
    timers("fwd").stop()
    agg = tr.phase_summary()
    assert agg["timer.fwd"]["count"] == 2


# ---------------------------------------------------------------------------
# hub
# ---------------------------------------------------------------------------
def test_hub_report_merges_and_guards_sources():
    tr = Tracer()
    m = MetricsRegistry()
    hub = ObservabilityHub(tr, m)
    hub.add_source("compile", lambda: {"ok": 1})
    hub.add_source("broken", lambda: 1 / 0)
    with tr.span("p"):
        pass
    rep = hub.report()
    assert rep["compile"] == {"ok": 1}
    assert "error" in rep["broken"]  # one failing source never hides the rest
    assert rep["timeline"]["phases"]["p"]["count"] == 1
    assert hub.report(exclude=("compile",)).get("compile") is None


def test_hub_monitor_events_feed():
    tr = Tracer()
    m = MetricsRegistry()
    hub = ObservabilityHub(tr, m)
    with tr.span("serve.step"):
        pass
    m.counter("serve.tokens").inc(12)
    m.gauge("pool.util").set(0.5)
    h = m.histogram("ttft")
    h.observe(3.0)
    events = dict((name, val) for name, val, step in hub.monitor_events(step=7))
    assert "Trace/serve.step/mean_ms" in events
    assert events["Metrics/serve.tokens"] == 12.0
    assert events["Metrics/pool.util"] == 0.5
    assert "Metrics/ttft/p50" in events and "Metrics/ttft/p99" in events
    assert all(step == 7 for _, _, step in hub.monitor_events(step=7))
