"""Accelerator abstraction tests (reference: tests/accelerator/)."""

import jax.numpy as jnp

from deepspeed_tpu.accelerator import get_accelerator


def test_singleton_and_name():
    acc = get_accelerator()
    assert acc is get_accelerator()
    assert acc._name in ("tpu", "cpu")


def test_device_api():
    acc = get_accelerator()
    assert acc.device_count() >= 1
    assert acc.is_available()
    acc.set_device(0)
    assert acc.current_device() == 0
    assert str(acc.current_device_name()).endswith(":0")


def test_streams_and_events():
    acc = get_accelerator()
    s = acc.Stream()
    with acc.stream(s):
        pass
    e1, e2 = acc.Event(enable_timing=True), acc.Event(enable_timing=True)
    e1.record()
    e2.record()
    assert e1.elapsed_time(e2) >= 0
    acc.synchronize()


def test_dtype_support():
    acc = get_accelerator()
    assert acc.is_bf16_supported()
    assert jnp.bfloat16 in acc.supported_dtypes()


def test_comm_backend_name():
    assert get_accelerator().communication_backend_name() == "xla"


def test_op_builder_dispatch():
    acc = get_accelerator()
    builder = acc.create_op_builder("fused_adam")
    assert builder is not None and builder.is_compatible()
    mod = builder.load()
    assert hasattr(mod, "FusedAdam")


def test_rng_api():
    acc = get_accelerator()
    acc.manual_seed(7)
    assert acc.initial_seed() == 7
    k = acc.get_rng_state()
    acc.set_rng_state(k)
