"""Elasticity tests (reference: ``tests/unit/elasticity/test_elastic.py``)."""

from __future__ import annotations

import pytest

from deepspeed_tpu.elasticity import (
    compute_elastic_config,
    elasticity_enabled,
    get_compatible_gpus_v01,
)
from deepspeed_tpu.elasticity.config import ElasticityConfigError
from deepspeed_tpu.elasticity.elasticity import ElasticityIncompatibleWorldSize


BASE_CONFIG = {
    "elasticity": {
        "enabled": True,
        "max_train_batch_size": 10000,
        "micro_batch_sizes": [8, 12, 16, 17],
        "min_gpus": 32,
        "max_gpus": 1500,
        "min_time": 20,
        "version": 0.1,
    }
}


class TestV01:
    def test_basic(self):
        final_batch, valid_gpus = compute_elastic_config(BASE_CONFIG, "0.1.0")
        assert final_batch <= 10000
        assert valid_gpus
        # every valid chip count divides the batch with some micro size
        micro = BASE_CONFIG["elasticity"]["micro_batch_sizes"]
        for g in valid_gpus:
            assert any(final_batch % (m * g) == 0 for m in micro)
            assert 32 <= g <= 1500

    def test_compatible_world_size(self):
        final_batch, valid_gpus = compute_elastic_config(BASE_CONFIG, "0.1.0")
        ws = valid_gpus[0]
        fb, vg, mb = compute_elastic_config(BASE_CONFIG, "0.1.0", world_size=ws, return_microbatch=True)
        assert fb == final_batch
        assert mb in BASE_CONFIG["elasticity"]["micro_batch_sizes"]
        assert fb % (mb * ws) == 0

    def test_incompatible_world_size(self):
        _, valid_gpus = compute_elastic_config(BASE_CONFIG, "0.1.0")
        bad = max(valid_gpus) + 1
        while bad in valid_gpus:
            bad += 1
        with pytest.raises(ElasticityIncompatibleWorldSize):
            compute_elastic_config(BASE_CONFIG, "0.1.0", world_size=bad)

    def test_disabled_raises(self):
        cfg = {"elasticity": dict(BASE_CONFIG["elasticity"], enabled=False)}
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config(cfg, "0.1.0")

    def test_missing_section_raises(self):
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config({}, "0.1.0")

    def test_enabled_probe(self):
        assert elasticity_enabled(BASE_CONFIG)
        assert not elasticity_enabled({})

    def test_invalid_micro_batches(self):
        cfg = {"elasticity": dict(BASE_CONFIG["elasticity"], micro_batch_sizes=[8, -1])}
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config(cfg, "0.1.0")


class TestV01Math:
    def test_prefer_larger(self):
        fb_large, _ = get_compatible_gpus_v01([2, 4], 128, prefer_larger=True)
        assert fb_large <= 128
        assert fb_large > 0

    def test_valid_gpu_divisibility(self):
        fb, gpus = get_compatible_gpus_v01([2, 3], 60, min_gpus=1, max_gpus=100)
        for g in gpus:
            assert fb % (2 * g) == 0 or fb % (3 * g) == 0


class TestV02:
    @staticmethod
    def _cfg(**over):
        base = {
            "enabled": True,
            "max_train_batch_size": 2048,
            "micro_batch_sizes": [2, 4],
            "min_gpus": 1,
            "max_gpus": 1024,
            "version": 0.2,
            "model_parallel_size": 4,
            "num_gpus_per_node": 4,
        }
        base.update(over)
        return {"elasticity": base}

    def test_model_parallel(self):
        # mp == chips/node → one dp replica per node; valid counts are node counts
        fb, valid_gpus, mb = compute_elastic_config(
            self._cfg(), "0.1.0", world_size=8, return_microbatch=True
        )
        assert fb > 0 and fb <= 2048
        assert 8 in valid_gpus
        assert mb in (2, 4)
        assert (fb // 8) % mb == 0

    def test_mp_smaller_than_node(self):
        # mp=2 on 8-chip nodes: 4 dp replicas per node (the reference node-level
        # contract ADVICE flagged) — must NOT raise, and valid dp sizes scale by 4
        fb, valid_gpus, mb = compute_elastic_config(
            self._cfg(model_parallel_size=2, num_gpus_per_node=8, max_gpus=256),
            "0.1.0",
            world_size=8,
            return_microbatch=True,
        )
        assert fb > 0
        assert all(g % 4 == 0 for g in valid_gpus)  # whole nodes → multiples of dp/node
        assert mb in (2, 4)

    def test_mp_not_dividing_node_raises(self):
        from deepspeed_tpu.elasticity.elasticity import ElasticityError

        with pytest.raises(ElasticityError):
            compute_elastic_config(
                self._cfg(model_parallel_size=3, num_gpus_per_node=8),
                "0.1.0",
                world_size=8,
            )

    def test_two_tuple_without_return_microbatch(self):
        out = compute_elastic_config(self._cfg(), "0.1.0", world_size=8)
        assert len(out) == 2

    def test_world_size_required(self):
        import os

        old = os.environ.pop("WORLD_SIZE", None)
        try:
            with pytest.raises(ElasticityConfigError):
                compute_elastic_config(self._cfg(), "0.1.0", world_size=0)
        finally:
            if old is not None:
                os.environ["WORLD_SIZE"] = old

    def test_v01_rejects_model_parallel(self):
        cfg = {
            "elasticity": {
                "enabled": True,
                "max_train_batch_size": 2048,
                "micro_batch_sizes": [2, 4],
                "version": 0.1,
                "model_parallel_size": 4,
            }
        }
        with pytest.raises(ElasticityConfigError):
            compute_elastic_config(cfg, "0.1.0")
