"""AutoTP tests (reference: ``tests/unit/model_parallelism/``)."""

from __future__ import annotations

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from deepspeed_tpu.module_inject import (
    AutoTP,
    Classification,
    ReplaceWithTensorSlicing,
    classify_param,
    spec_for_param,
)


class TestClassification:
    @pytest.mark.parametrize(
        "name,expected",
        [
            ("layers/wq", Classification.COLUMN),
            ("layers/q_proj", Classification.COLUMN),
            ("layers/gate_proj", Classification.COLUMN),
            ("layers/c_fc", Classification.COLUMN),
            ("layers/dense_h_to_4h", Classification.COLUMN),
            ("layers/wo", Classification.ROW),
            ("layers/o_proj", Classification.ROW),
            ("layers/down_proj", Classification.ROW),
            ("layers/c_proj", Classification.ROW),
            ("layers/dense_4h_to_h", Classification.ROW),
            ("embed/tokens", Classification.VOCAB),
            ("lm_head", Classification.VOCAB),
            ("layers/attn_norm_scale", Classification.REPLICATE),
            ("final_norm_bias", Classification.REPLICATE),
        ],
    )
    def test_classify(self, name, expected):
        assert classify_param(name) == expected


class TestSpecs:
    def test_column_2d(self):
        assert spec_for_param("wq", (64, 128)) == P(None, "model")

    def test_column_stacked(self):
        assert spec_for_param("layers/wq", (4, 64, 128)) == P(None, None, "model")

    def test_row_2d(self):
        assert spec_for_param("wo", (128, 64)) == P("model", None)

    def test_row_bias_replicated(self):
        assert spec_for_param("bo", (64,)) == P(None)

    def test_vocab_embedding(self):
        assert spec_for_param("embed/tokens", (50257, 768)) == P("model", None)

    def test_lm_head(self):
        assert spec_for_param("lm_head", (768, 50257)) == P(None, "model")


class TestAutoTPTree:
    def test_partition_specs_tree(self):
        shapes = {
            "embed": {"tokens": np.zeros((100, 16))},
            "layers": {
                "wq": np.zeros((2, 16, 32)),
                "wo": np.zeros((2, 32, 16)),
                "attn_norm_scale": np.zeros((2, 16)),
            },
        }
        specs = AutoTP().partition_specs(shapes)
        assert specs["layers"]["wq"] == P(None, None, "model")
        assert specs["layers"]["wo"] == P(None, "model", None)
        assert specs["layers"]["attn_norm_scale"] == P(None, None)
        assert specs["embed"]["tokens"] == P("model", None)

    def test_validate_divisibility(self):
        shapes = {"wq": np.zeros((16, 30))}  # 30 % 4 != 0
        tp = AutoTP()
        specs = tp.partition_specs(shapes)
        problems = tp.validate(shapes, specs, mp_size=4)
        assert problems and "wq" in problems[0]

    def test_overrides(self):
        shapes = {"custom": np.zeros((8, 8))}
        specs = AutoTP(overrides={"/custom": P("model", None)}).partition_specs(shapes)
        assert specs["custom"] == P("model", None)


class TestTensorSlicing:
    def test_column_shard(self):
        w = np.arange(32).reshape(4, 8).astype(np.float32)
        slicer = ReplaceWithTensorSlicing(mp_rank=1, mp_size=2)
        out = slicer.shard("wq", w)
        np.testing.assert_array_equal(out, w[:, 4:])

    def test_row_shard(self):
        w = np.arange(32).reshape(8, 4).astype(np.float32)
        slicer = ReplaceWithTensorSlicing(mp_rank=0, mp_size=2)
        out = slicer.shard("wo", w)
        np.testing.assert_array_equal(out, w[:4, :])

    def test_replicated_passthrough(self):
        w = np.ones((6,), np.float32)
        out = ReplaceWithTensorSlicing(0, 2).shard("norm_scale", w)
        np.testing.assert_array_equal(out, w)
