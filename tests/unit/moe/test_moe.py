"""MoE tests (reference: tests/unit/moe/test_moe.py).

Covers gating properties (capacity, load-balance loss, top-2 normalization),
dispatch/combine round-trip, PR-MoE residual, expert-axis sharding, and
end-to-end training of the MoE model family through the engine on the
8-device mesh with a real expert axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.moe import (
    MoE,
    combine,
    dispatch,
    top1gating,
    top2gating,
)


class TestGating:
    def test_top1_shapes_and_capacity(self):
        S, E = 64, 4
        logits = jax.random.normal(jax.random.PRNGKey(0), (S, E))
        l_aux, cw, dm, counts = top1gating(logits, capacity_factor=1.0, min_capacity=4, use_rts=False)
        C = S // E  # capacity_factor 1.0
        assert cw.shape == (S, E, C)
        assert dm.shape == (S, E, C)
        assert counts.shape == (E,)
        # no expert slot is double-booked
        per_slot = jnp.sum(dm.astype(jnp.int32), axis=0)
        assert int(per_slot.max()) <= 1
        # each token goes to at most one slot
        per_token = jnp.sum(dm.astype(jnp.int32), axis=(1, 2))
        assert int(per_token.max()) <= 1

    def test_top1_balanced_aux_loss(self):
        # perfectly uniform gates → l_aux == 1.0 (E * sum(1/E * 1/E) * E = 1)
        S, E = 32, 4
        logits = jnp.zeros((S, E))
        l_aux, *_ = top1gating(logits, 1.0, 4, use_rts=False)
        assert float(l_aux) == pytest.approx(1.0, rel=1e-5)

    def test_top1_drop_tokens_off_keeps_all(self):
        S, E = 64, 4
        logits = jax.random.normal(jax.random.PRNGKey(1), (S, E)) * 5  # skewed
        _, cw, dm, _ = top1gating(logits, 1.0, 4, drop_tokens=False, use_rts=False)
        per_token = jnp.sum(dm.astype(jnp.int32), axis=(1, 2))
        assert int(per_token.min()) == 1  # nothing dropped

    def test_top2_gate_normalization(self):
        S, E = 64, 8
        logits = jax.random.normal(jax.random.PRNGKey(2), (S, E))
        _, cw, dm, _ = top2gating(logits, 4.0, 4, top2_2nd_expert_sampling=False)
        # combine weights of an undropped token sum to ~1 over its 2 experts
        token_w = jnp.sum(cw, axis=(1, 2))
        kept = jnp.sum(dm.astype(jnp.int32), axis=(1, 2)) == 2
        np.testing.assert_allclose(np.asarray(token_w)[np.asarray(kept)], 1.0, rtol=1e-5)

    def test_rts_is_permutation_invariant_in_count(self):
        S, E = 128, 4
        logits = jax.random.normal(jax.random.PRNGKey(3), (S, E)) * 3
        _, _, dm_rts, _ = top1gating(logits, 0.5, 4, use_rts=True, rng=jax.random.PRNGKey(9))
        _, _, dm_seq, _ = top1gating(logits, 0.5, 4, use_rts=False)
        # same number of tokens kept either way (capacity binds identically)
        assert int(dm_rts.sum()) == int(dm_seq.sum())


class TestDispatchCombine:
    def test_round_trip_identity_experts(self):
        S, E, H = 32, 4, 16
        x = jax.random.normal(jax.random.PRNGKey(0), (S, H))
        logits = jax.random.normal(jax.random.PRNGKey(1), (S, E))
        _, cw, dm, _ = top1gating(logits, 2.0, 4, use_rts=False)
        sent = dispatch(x, dm)
        back = combine(sent, dm.astype(x.dtype))  # weights=mask → identity for kept
        kept = jnp.sum(dm.astype(jnp.int32), axis=(1, 2)) == 1
        np.testing.assert_allclose(
            np.asarray(back)[np.asarray(kept)], np.asarray(x)[np.asarray(kept)], rtol=1e-5
        )


class TestMoELayer:
    def test_forward_shapes(self):
        layer = MoE(hidden_size=32, num_experts=4, k=1, capacity_factor=2.0)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 32))
        out, l_aux, counts = layer.apply(params, x, train=True, rng=jax.random.PRNGKey(2))
        assert out.shape == x.shape
        assert l_aux.shape == ()
        assert counts.shape == (4,)

    def test_prmoe_residual(self):
        layer = MoE(hidden_size=32, num_experts=4, k=1, use_residual=True)
        params = layer.init(jax.random.PRNGKey(0))
        assert "mlp" in params and "coefficient" in params
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
        out, _, _ = layer.apply(params, x, train=False)
        assert out.shape == x.shape

    def test_prmoe_residual_swiglu_matches_experts(self):
        # residual branch must use the same gated activation as the experts
        layer = MoE(hidden_size=32, num_experts=2, k=1, use_residual=True, activation="swiglu", use_bias=False)
        params = layer.init(jax.random.PRNGKey(0))
        assert "w_gate" in params["mlp"] and "w_up" in params["mlp"]
        x = jax.random.normal(jax.random.PRNGKey(1), (4, 32))
        out, _, _ = layer.apply(params, x, train=False)
        assert out.shape == x.shape

    def test_top2_layer(self):
        layer = MoE(hidden_size=32, num_experts=4, k=2, capacity_factor=2.0)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (16, 32))
        out, l_aux, _ = layer.apply(params, x, train=True, rng=jax.random.PRNGKey(2))
        assert out.shape == x.shape

    def test_gradients_flow_to_experts_and_gate(self):
        layer = MoE(hidden_size=16, num_experts=2, k=1, capacity_factor=2.0)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (8, 16))

        def loss_fn(p):
            out, l_aux, _ = layer.apply(p, x, train=True, rng=jax.random.PRNGKey(2))
            return jnp.sum(out**2) + 0.01 * l_aux

        grads = jax.grad(loss_fn)(params)
        gate_g = np.abs(np.asarray(grads["gate"]["wg"])).sum()
        exp_g = np.abs(np.asarray(grads["experts"]["w_in"])).sum()
        assert gate_g > 0, "gate got no gradient"
        assert exp_g > 0, "experts got no gradient"


class TestMoEEngine:
    def _config(self, stage=1):
        return {
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": stage},
            "gradient_clipping": 1.0,
            "mesh": {"data": 4, "expert": 2},
        }

    def _batch(self, vocab, dp, seq=32, seed=0):
        rs = np.random.RandomState(seed)
        toks = rs.randint(0, vocab, (dp, seq + 1)).astype(np.int32)
        return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}

    def test_moe_model_trains_on_expert_mesh(self, eight_devices):
        from deepspeed_tpu.models import MoETransformerLM, moe_llama_config

        cfg = moe_llama_config(
            "tiny", num_layers=2, num_experts=2, capacity_factor=2.0, max_seq_len=64, flash_attention=False
        )
        model = MoETransformerLM(cfg)
        engine, *_ = ds.initialize(model=model, config=self._config())
        batch = self._batch(cfg.vocab_size, engine.data_parallel_world_size())
        losses = []
        for i in range(5):
            loss = engine(batch)
            engine.backward(loss)
            engine.step()
            losses.append(float(jax.device_get(loss)))
        assert all(np.isfinite(l) for l in losses), losses
        # memorizing one batch must drive the loss down hard
        assert losses[-1] < losses[0] - 0.5, f"did not learn: {losses}"

    def test_expert_params_sharded_over_expert_axis(self, eight_devices):
        from deepspeed_tpu.models import MoETransformerLM, moe_llama_config

        cfg = moe_llama_config("tiny", num_layers=2, num_experts=2, max_seq_len=64, flash_attention=False)
        model = MoETransformerLM(cfg)
        engine, *_ = ds.initialize(model=model, config=self._config())
        batch = self._batch(cfg.vocab_size, engine.data_parallel_world_size())
        engine.init_params(batch)
        expert_w = engine._params["layers"]["moe"]["experts"]["w_gate"]
        assert "expert" in str(expert_w.sharding.spec), expert_w.sharding.spec
        # router weights stay fp32 in the bf16 compute store (keep_fp32_params)
        assert engine._params["layers"]["moe"]["gate"]["wg"].dtype == jnp.float32
        assert expert_w.dtype == jnp.bfloat16

    def test_moe_interleaved_dense_layers(self, eight_devices):
        from deepspeed_tpu.models import MoETransformerLM, moe_llama_config

        cfg = moe_llama_config(
            "tiny", num_layers=2, num_experts=2, moe_layer_freq=2, max_seq_len=64, flash_attention=False
        )
        model = MoETransformerLM(cfg)
        engine, *_ = ds.initialize(model=model, config=self._config())
        batch = self._batch(cfg.vocab_size, engine.data_parallel_world_size())
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        assert np.isfinite(float(jax.device_get(loss)))
        # MoE layers carry no dead dense-FFN weights: with 2 layers and
        # freq=2, exactly one layer is dense → dense_mlp stacks have L=1
        assert engine._params["dense_mlp"]["w_gate"].shape[0] == 1
        assert "w_gate" not in engine._params["layers"]


def test_mixtral_preset_trains(eight_devices):
    """Mixtral family (BASELINE config 5): tiny preset, top-2 routing,
    expert-parallel mesh."""
    import deepspeed_tpu as ds
    import deepspeed_tpu.parallel.mesh as mesh_mod
    from deepspeed_tpu.models import MoETransformerLM, mixtral_config

    mesh_mod.reset_topology()
    cfg = mixtral_config("tiny", num_layers=2, max_seq_len=64, dtype="float32", flash_attention=False)
    assert cfg.moe_top_k == 2 and cfg.num_experts == 8
    engine, *_ = ds.initialize(
        model=MoETransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 1,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 2},
            "mesh": {"expert": 2, "data": 4},
        },
    )
    import numpy as np

    rs = np.random.RandomState(0)
    losses = []
    for _ in range(3):
        toks = rs.randint(0, cfg.vocab_size, (8, 16)).astype(np.int32)
        loss = engine({"input_ids": toks, "labels": toks})
        engine.backward(loss)
        engine.step()
        losses.append(float(loss))
    assert np.isfinite(losses).all() if hasattr(np, "isfinite") else True
    assert losses[-1] < losses[0]
