"""MoE param utilities (reference: deepspeed/moe/utils.py — here expert-ness
is a path property of the param pytree, not a tensor tag)."""

import jax
import numpy as np
import pytest

from deepspeed_tpu.moe.utils import (
    has_moe_layers,
    is_moe_param_path,
    split_params_into_different_moe_groups_for_optimizer,
    split_params_into_shared_and_expert_params,
)


def _tree():
    return {
        "embed": {"tokens": np.zeros((8, 4))},
        "layers": {
            "wq": np.zeros((2, 4, 4)),
            "moe": {
                "gate": np.zeros((2, 4, 2)),
                "experts": {"w1": np.zeros((2, 2, 4, 8))},
            },
        },
    }


def test_is_moe_param_path():
    assert is_moe_param_path("layers/moe/experts/w1")
    assert is_moe_param_path("expert_3/w")
    # the gate lives under "moe" but is REPLICATED — not an expert param
    assert not is_moe_param_path(["layers", "moe", "gate"])
    assert not is_moe_param_path("layers/wq")
    assert not is_moe_param_path(["embed", "tokens"])


def test_array_argument_rejected_clearly():
    with pytest.raises(TypeError, match="tree path"):
        is_moe_param_path([np.zeros((2, 2))])


def test_split_shared_and_expert():
    tree = _tree()
    shared, expert = split_params_into_shared_and_expert_params(tree)
    # shared: embed.tokens, layers.wq, AND the replicated gate
    assert len(jax.tree_util.tree_leaves(shared)) == 3
    assert len(jax.tree_util.tree_leaves(expert)) == 1  # experts.w1 only
    # leaves keep identity — no copies
    assert shared["embed"]["tokens"] is tree["embed"]["tokens"]
    assert shared["layers"]["moe"]["gate"] is tree["layers"]["moe"]["gate"]
    assert expert["layers"]["moe"]["experts"]["w1"] is tree["layers"]["moe"]["experts"]["w1"]
    # non-expert positions are None holes in the expert tree
    assert expert["layers"]["wq"] is None
    assert expert["layers"]["moe"]["gate"] is None


def test_has_moe_layers_from_tree_and_model():
    assert has_moe_layers(_tree())[0]
    assert not has_moe_layers({"layers": {"wq": np.zeros((2, 2))}})[0]

    from deepspeed_tpu.models import MoETransformerLM, TransformerLM, llama_config, moe_llama_config

    moe_model = MoETransformerLM(moe_llama_config("tiny", num_layers=2, num_experts=2, max_seq_len=32))
    dense = TransformerLM(llama_config("tiny", num_layers=2))
    has, n = has_moe_layers(moe_model)
    assert has and n == 2
    assert not has_moe_layers(dense)[0]
    # one-expert MoE is still an MoE family
    one = MoETransformerLM(moe_llama_config("tiny", num_layers=2, num_experts=1, max_seq_len=32))
    assert has_moe_layers(one) == (True, 1)


def test_optimizer_group_split():
    groups = split_params_into_different_moe_groups_for_optimizer(
        {"name": "g0", "params": _tree(), "lr": 1e-3}
    )
    assert len(groups) == 2
    shared_g, moe_g = groups
    assert shared_g["moe"] is False and moe_g["moe"] is True
    assert moe_g["name"] == "g0_moe"
    assert moe_g["lr"] == 1e-3  # hyperparameters copied
    assert len(jax.tree_util.tree_leaves(moe_g["params"])) == 1
    assert len(jax.tree_util.tree_leaves(shared_g["params"])) == 3


def test_optimizer_group_split_no_experts_passthrough():
    groups = split_params_into_different_moe_groups_for_optimizer(
        [{"params": {"w": np.zeros((2, 2))}, "lr": 1.0}]
    )
    assert len(groups) == 1
    assert groups[0]["moe"] is False


def test_group_without_params_raises():
    with pytest.raises(ValueError, match="params"):
        split_params_into_different_moe_groups_for_optimizer({"lr": 1.0})
