"""MoE composed with tensor parallelism (reference: tests/unit/moe/
test_moe_tp.py): experts shard over the expert axis while attention/dense
blocks shard over the model axis, on one mesh, in one training program."""

import jax
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import MoETransformerLM, moe_llama_config


def _batch(vocab, B, T=32, seed=0):
    rs = np.random.RandomState(seed)
    toks = rs.randint(0, vocab, (B, T + 1)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


GLOBAL_BATCH = 8  # constant across meshes so trajectories are comparable


def _train(mesh, steps=4, seed=0):
    mesh_mod.reset_topology()
    cfg = moe_llama_config(
        "tiny", num_layers=2, num_experts=2, capacity_factor=2.0,
        max_seq_len=32, flash_attention=False,
    )
    model = MoETransformerLM(cfg)
    dp = mesh.get("data", 1)
    engine, *_ = ds.initialize(
        model=model,
        config={
            "train_micro_batch_size_per_gpu": GLOBAL_BATCH // dp,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 0},
            "mesh": mesh,
        },
    )
    batch = _batch(cfg.vocab_size, GLOBAL_BATCH, seed=seed)
    losses = []
    for _ in range(steps):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return engine, losses


def test_moe_trains_on_expert_by_model_mesh(eight_devices):
    engine, losses = _train({"data": 2, "expert": 2, "model": 2})
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], f"did not learn: {losses}"


def test_expert_and_model_axes_both_shard(eight_devices):
    engine, _ = _train({"data": 2, "expert": 2, "model": 2}, steps=1)
    params = engine.get_params()
    expert_leaf = jax.tree_util.tree_leaves(params["layers"]["moe"]["experts"])[0]
    assert "expert" in str(expert_leaf.sharding.spec), expert_leaf.sharding.spec
    # attention projections shard over the model axis
    attn_spec = str(params["layers"]["wq"].sharding.spec)
    assert "model" in attn_spec, attn_spec


def test_moe_tp_matches_ep_only_math(eight_devices):
    """The mesh layout must not change the math: ep2×tp2×dp2 and ep2×dp4
    trajectories agree on the same data and seed."""
    _, l_tp = _train({"data": 2, "expert": 2, "model": 2})
    _, l_ep = _train({"data": 4, "expert": 2})
    assert l_tp == pytest.approx(l_ep, rel=2e-2), (l_tp, l_ep)
