"""Expert-parallel MoE fast-path correctness (ISSUE 20 satellites).

The shard_map fast path (``moe/a2a.py``) re-derives the reference's explicit
per-rank dataflow — local gating, local capacity, explicit dispatch/combine
all-to-alls — so its MATH must stay pinned to the dense-dispatch reference
(``sharded_moe.topkgating`` + einsum dispatch/combine with no mesh):

* top-1/top-2 gating parity: with capacity generous enough that nothing
  drops, the fast path and the dense reference agree per token;
* capacity-overflow drops are deterministic and shard-local: the same
  tokens produce the same drop pattern bit-for-bit, and one shard's drops
  never depend on another shard's tokens;
* the expert-sharded param tree (two mesh axes) checkpoints and restores
  bit-identically through the atomic engine;
* a ``train.mid_step`` chaos kill on the MoE config resumes bit-identically
  from the last committed checkpoint — the fault-tolerance contract does
  not care that the state spans a ``data × expert`` mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.moe import a2a
from deepspeed_tpu.moe.layer import MoE
from deepspeed_tpu.runtime.config import MeshConfig
from deepspeed_tpu.utils import chaos


@pytest.fixture(autouse=True)
def _clean_topology_and_chaos():
    mesh_mod.reset_topology()
    yield
    chaos.uninstall()
    mesh_mod.reset_topology()


# ---------------------------------------------------------------------------
# gating parity: fast path vs dense-dispatch reference
# ---------------------------------------------------------------------------
class TestFastPathParity:
    S, E, H = 64, 4, 32

    def _layer(self, k):
        # capacity_factor = E keeps every per-shard expert queue under
        # capacity even if all 8 local tokens pick the same expert, so the
        # two paths differ by dataflow only, never by drops
        return MoE(
            hidden_size=self.H, num_experts=self.E, k=k,
            capacity_factor=float(self.E), eval_capacity_factor=float(self.E),
            min_capacity=4, use_bias=False, activation="gelu",
        )

    @pytest.mark.parametrize("k", [1, 2])
    def test_topk_output_matches_dense_reference(self, eight_devices, k):
        layer = self._layer(k)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (self.S, self.H), jnp.float32)

        # dense-dispatch reference: no topology → the GSPMD/einsum path
        mesh_mod.reset_topology()
        ref, ref_aux, ref_counts = layer.apply(params, x, train=False)

        # fast path: data×expert mesh → per-shard gating + explicit a2as
        topo = mesh_mod.initialize_topology(MeshConfig(data=4, expert=2))
        assert a2a.ep_fast_path(topo, self.E, self.S)
        out, _aux, counts = layer.apply(params, x, train=False)

        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )
        # routing decisions are per token, so global counts agree exactly
        np.testing.assert_array_equal(np.asarray(counts), np.asarray(ref_counts))
        # the reference kept every token (capacity never bound)
        expected = self.S * k if k == 1 else self.S * 2
        assert int(np.asarray(ref_counts).sum()) == expected

    def test_quantized_a2a_stays_close_to_fp(self, eight_devices):
        """The int8 wire format is lossy by contract but must not distort
        the routed output beyond quantization noise."""
        layer = self._layer(1)
        params = layer.init(jax.random.PRNGKey(0))
        x = jax.random.normal(jax.random.PRNGKey(1), (self.S, self.H), jnp.float32)
        mesh_mod.initialize_topology(MeshConfig(data=4, expert=2))
        fp, *_ = layer.apply(params, x, train=False)
        layer_q = self._layer(1)
        layer_q.quantized_a2a = True
        q, *_ = layer_q.apply(params, x, train=False)
        # per-chunk symmetric int8: relative error bounded by ~1/127 per hop
        err = np.abs(np.asarray(q) - np.asarray(fp)).max()
        ref = np.abs(np.asarray(fp)).max()
        assert err < 0.05 * ref, (err, ref)
        assert err > 0.0  # the quantized wire really was in the loop


# ---------------------------------------------------------------------------
# capacity-overflow drop determinism
# ---------------------------------------------------------------------------
class TestDropDeterminism:
    S, E, H = 64, 4, 16

    def _skewed(self, seed=3):
        # strongly skewed logits: most tokens want expert 0 → capacity binds
        rs = np.random.RandomState(seed)
        logits = rs.randn(self.S, self.E).astype(np.float32)
        logits[:, 0] += 4.0
        tokens = rs.randn(self.S, self.H).astype(np.float32)
        return jnp.asarray(tokens), jnp.asarray(logits)

    def test_fast_path_drops_are_bit_deterministic(self, eight_devices):
        tokens, logits = self._skewed()
        topo = mesh_mod.initialize_topology(MeshConfig(data=4, expert=2))

        def run():
            d, cw, _aux, counts = a2a.ep_gate_dispatch(
                tokens, logits, topo, k=1, capacity_factor=1.0,
                min_capacity=1, drop_tokens=True, use_rts=True, rng=None,
            )
            return np.asarray(d), np.asarray(cw), np.asarray(counts)

        d1, cw1, c1 = run()
        d2, cw2, c2 = run()
        np.testing.assert_array_equal(d1, d2)
        np.testing.assert_array_equal(cw1, cw2)
        np.testing.assert_array_equal(c1, c2)
        # the overflow really happened: some routed tokens lost their slot
        kept = int((cw1.sum(axis=(1, 2)) > 0).sum())
        assert kept < self.S, "capacity never bound; the test is vacuous"

    def test_drops_are_shard_local(self, eight_devices):
        """Per-shard gating means one shard's keep/drop pattern is a pure
        function of its own tokens: perturbing shard 0 must not move any
        other shard's drops (the GSPMD global-cumsum formulation could)."""
        tokens, logits = self._skewed()
        topo = mesh_mod.initialize_topology(MeshConfig(data=4, expert=2))
        n = 8  # data 4 × expert 2 token shards
        shard = self.S // n

        def combine_w(lg):
            _d, cw, _aux, _c = a2a.ep_gate_dispatch(
                tokens, lg, topo, k=1, capacity_factor=1.0,
                min_capacity=1, drop_tokens=True, use_rts=True, rng=None,
            )
            return np.asarray(cw)

        base = combine_w(logits)
        # push shard 0's tokens toward expert 1 (a uniform bump would be
        # softmax-invariant and route nothing differently)
        delta = np.zeros_like(np.asarray(logits))
        delta[:shard, 1] = 6.0
        moved = combine_w(jnp.asarray(np.asarray(logits) + delta))
        # shard 0 re-routed...
        assert not np.array_equal(base[:shard], moved[:shard])
        # ...every other shard's routing is untouched, bit for bit
        np.testing.assert_array_equal(base[shard:], moved[shard:])

    def test_dense_reference_eval_drops_deterministic(self):
        """Eval mode (rng=None): RTS degrades to cumsum priority, so the
        reference path's overflow drops are position-deterministic too —
        the property the serving engine's retrace-free routing leans on."""
        from deepspeed_tpu.moe import sharded_moe

        _tokens, logits = self._skewed()
        one = sharded_moe.topkgating(logits, 1, 1.0, 1, drop_tokens=True,
                                     rng=None, use_rts=True)
        two = sharded_moe.topkgating(logits, 1, 1.0, 1, drop_tokens=True,
                                     rng=None, use_rts=True)
        np.testing.assert_array_equal(np.asarray(one[2]), np.asarray(two[2]))
        assert int(np.asarray(one[2]).sum()) < self.S


# ---------------------------------------------------------------------------
# expert-sharded checkpoint roundtrip + chaos resume
# ---------------------------------------------------------------------------
def _moe_batch(step, vocab=256, B=8, T=16):
    rs = np.random.RandomState(1000 + step)
    toks = rs.randint(0, vocab, (B, T + 1)).astype(np.int32)
    return {"input_ids": toks[:, :-1], "labels": toks[:, 1:]}


def _fresh_moe_engine():
    from deepspeed_tpu.models import MoETransformerLM, moe_llama_config

    mesh_mod.reset_topology()
    cfg = moe_llama_config(
        "tiny", num_layers=2, num_experts=2, capacity_factor=2.0,
        max_seq_len=32, flash_attention=False,
    )
    engine, *_ = ds.initialize(
        model=MoETransformerLM(cfg),
        config={
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adam", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "zero_optimization": {"stage": 1},
            "mesh": {"data": 4, "expert": 2},
        },
    )
    engine.init_params(_moe_batch(0, vocab=cfg.vocab_size))
    return engine, cfg


def _moe_steps(engine, vocab, n):
    losses = []
    for _ in range(n):
        loss = engine(_moe_batch(engine.global_steps, vocab=vocab))
        engine.backward(loss)
        engine.step()
        losses.append(float(jax.device_get(loss)))
    return losses


class TestExpertShardedCheckpoint:
    def test_roundtrip_bit_identical(self, tmp_path, eight_devices):
        a, cfg = _fresh_moe_engine()
        _moe_steps(a, cfg.vocab_size, 2)
        # the tree under test really spans the expert axis
        expert_leaf = a._params["layers"]["moe"]["experts"]["w_gate"]
        assert "expert" in str(expert_leaf.sharding.spec)
        a.save_checkpoint(str(tmp_path))

        b, _ = _fresh_moe_engine()
        path, _client = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path is not None and b.global_steps == 2

        flat_a = jax.tree_util.tree_leaves_with_path(a._params)
        flat_b = jax.tree_util.tree_leaves_with_path(b._params)
        assert [p for p, _ in flat_a] == [p for p, _ in flat_b]
        for (pa, la), (_pb, lb) in zip(flat_a, flat_b):
            np.testing.assert_array_equal(
                np.asarray(jax.device_get(la)), np.asarray(jax.device_get(lb)),
                err_msg=str(pa),
            )
            assert la.dtype == lb.dtype, pa
        # restored shardings keep the expert axis (not de-sharded on load)
        rb = b._params["layers"]["moe"]["experts"]["w_gate"]
        assert "expert" in str(rb.sharding.spec)
        # and the restored engine's next step matches the original's exactly
        la = _moe_steps(a, cfg.vocab_size, 1)
        lb = _moe_steps(b, cfg.vocab_size, 1)
        assert la == lb, (la, lb)

    def test_mid_step_chaos_kill_resumes_bit_identical(self, tmp_path, eight_devices):
        ref, cfg = _fresh_moe_engine()
        ref_losses = _moe_steps(ref, cfg.vocab_size, 6)

        a, _ = _fresh_moe_engine()
        _moe_steps(a, cfg.vocab_size, 3)
        a.save_checkpoint(str(tmp_path))
        # die inside step 4: state adopted on device, nothing committed
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("train.mid_step", hit=1)]))
        with pytest.raises(chaos.ChaosKilled):
            _moe_steps(a, cfg.vocab_size, 1)
        chaos.uninstall()

        b, _ = _fresh_moe_engine()
        path, _client = b.load_checkpoint(str(tmp_path), auto_resume=True)
        assert path is not None and b.global_steps == 3
        resumed = _moe_steps(b, cfg.vocab_size, 3)
        assert resumed == ref_losses[3:], (resumed, ref_losses[3:])
