"""MoE TP token mappings (reference: deepspeed/moe/mappings.py): drop
shards a dim over the model axis, gather replicates, values survive the
round trip and gradients flow through both."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu.parallel.mesh as mesh_mod
import pytest
from deepspeed_tpu.moe.mappings import drop_tokens, gather_tokens
from deepspeed_tpu.parallel.mesh import MeshConfig


@pytest.fixture(autouse=True)
def _tp_mesh(eight_devices):
    mesh_mod.reset_topology()
    mesh_mod.initialize_topology(MeshConfig(model=2, data=4))
    yield
    mesh_mod.reset_topology()


def test_drop_shards_and_gather_replicates():
    x = jnp.arange(4 * 6, dtype=jnp.float32).reshape(4, 6)

    @jax.jit
    def f(x):
        dropped = drop_tokens(x, dim=0)
        gathered = gather_tokens(dropped, dim=0)
        return dropped, gathered

    dropped, gathered = f(x)
    assert "model" in str(dropped.sharding.spec)
    np.testing.assert_array_equal(np.asarray(gathered), np.asarray(x))


def test_indivisible_drop_raises():
    x = jnp.ones((3, 4))
    with pytest.raises(ValueError, match="not divisible"):
        drop_tokens(x, dim=0)


def test_gradients_flow():
    x = jnp.ones((4, 6), jnp.float32)

    def loss(x):
        return jnp.sum(gather_tokens(drop_tokens(x, dim=0)) ** 2)

    g = jax.grad(loss)(x)
    np.testing.assert_allclose(np.asarray(g), 2 * np.ones((4, 6)), rtol=1e-6)


def test_other_dims_keep_their_sharding():
    """drop/gather must not disturb a data-sharded batch dim (the review
    hazard: all-None specs would all-gather the batch over DP)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    topo = mesh_mod.get_topology()
    x = jnp.ones((8, 4, 6), jnp.float32)
    x = jax.device_put(x, NamedSharding(topo.mesh, P("data", None, None)))

    @jax.jit
    def f(x):
        return drop_tokens(x, dim=1)

    out = f(x)
    spec = out.sharding.spec
    assert "model" in str(spec[1] if len(spec) > 1 else spec)
    assert "data" in str(spec[0])  # batch sharding preserved


def test_identity_without_topology():
    mesh_mod.reset_topology()
    x = jnp.ones((4, 4))
    assert drop_tokens(x, dim=0) is x
    assert gather_tokens(x, dim=0) is x
    # no topology was created as a side effect
    assert mesh_mod._TOPOLOGY is None
