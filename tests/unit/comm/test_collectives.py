"""In-mesh collective tests (reference: tests/unit/comm/test_dist.py)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from deepspeed_tpu.comm import collectives
from deepspeed_tpu import comm as dist
import os


@pytest.fixture
def mesh(eight_devices):
    return Mesh(np.asarray(eight_devices), ("data",))


def _smap(mesh, fn, in_specs, out_specs):
    return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs, check_rep=False, out_specs=out_specs))


def test_psum(mesh):
    x = jnp.arange(8.0)
    out = _smap(mesh, lambda v: collectives.psum(v, "data"), P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.full(8, 28.0))


def test_all_gather(mesh):
    x = jnp.arange(8.0)
    out = _smap(mesh, lambda v: collectives.all_gather(v, "data"), P("data"), P())(x)
    # each shard gathers the full array; out_specs=P() verifies replication
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0))


def test_reduce_scatter(mesh):
    # every shard holds the full vector [0..7]; each ends with its 1/8 slice
    # of the 8-way sum
    x = jnp.tile(jnp.arange(8.0), 8)  # [64] sharded -> local [8] = 0..7
    out = _smap(mesh, lambda v: collectives.reduce_scatter(v, "data"), P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.arange(8.0) * 8)


def test_all_to_all_is_resharding(mesh):
    # all_to_all moves a row-sharded matrix to column-sharded WITHOUT
    # changing its content (this is exactly the Ulysses seq<->head swap)
    x = jnp.arange(64.0).reshape(8, 8)
    fn = _smap(
        mesh,
        lambda v: collectives.all_to_all(v, "data", split_axis=1, concat_axis=0),
        P("data", None),
        P(None, "data"),
    )
    out = fn(x)
    assert out.shape == (8, 8)
    np.testing.assert_allclose(np.asarray(out), np.arange(64.0).reshape(8, 8))
    # and the output really is column-sharded now
    assert "data" in str(out.sharding.spec[1])


def test_ring_shift(mesh):
    x = jnp.arange(8.0)
    out = _smap(mesh, lambda v: collectives.ring_shift(v, "data", shift=1), P("data"), P("data"))(x)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_quantized_reduce_scatter_close_to_exact(mesh):
    rs = np.random.RandomState(0)
    data = rs.randn(8, 1024).astype(np.float32)

    def body(v):
        return collectives.quantized_reduce_scatter(v[0], "data", n_shards=8, block=128)

    fn = _smap(mesh, body, P("data", None), P("data"))
    out = np.asarray(fn(jnp.asarray(data)))  # global [8 * 128]
    exact = data.sum(axis=0)  # [1024]; shard s holds slice s of the reduction
    rel_rms = np.sqrt(np.mean((out - exact) ** 2)) / np.sqrt(np.mean(exact**2))
    assert rel_rms < 0.02, f"quantization error too large: {rel_rms}"


def test_eager_control_plane_single_process():
    from deepspeed_tpu import comm as dist

    assert dist.get_world_size() == 1
    out = dist.all_reduce(np.array([1.0, 2.0]))
    np.testing.assert_allclose(out, [1.0, 2.0])
    gathered = dist.all_gather_object({"rank": dist.get_rank()})
    assert gathered == [{"rank": 0}]
    dist.barrier()  # no-op, must not raise


class TestFacadeSurface:
    """The full torch.distributed-shaped surface (reference comm/comm.py) —
    single-process semantics; the multi-process rendezvous is exercised by
    test_multiprocess.py."""

    def test_reduce_gather_single(self):
        out = dist.reduce(np.arange(4.0), dst=0)
        np.testing.assert_array_equal(out, np.arange(4.0))
        lst = []
        g = dist.gather(np.arange(3), gather_list=lst, dst=0)
        assert g.shape == (1, 3)
        assert len(lst) == 1

    def test_into_tensor_forms(self):
        x = np.arange(6.0)
        out = dist.all_gather_into_tensor(np.zeros(6), x)
        np.testing.assert_array_equal(out, x)
        rs = dist.reduce_scatter_tensor(np.zeros(6), x)
        np.testing.assert_array_equal(rs, x)
        np.testing.assert_array_equal(dist.allgather_fn(np.zeros(6), x), x)
        np.testing.assert_array_equal(dist.reduce_scatter_fn(np.zeros(6), x), x)

    def test_all_to_all_single_identity_at_world1(self):
        x = np.arange(8.0).reshape(4, 2)
        out = dist.all_to_all_single(None, x)
        np.testing.assert_array_equal(out, x)
        outs = dist.all_to_all([], [x])
        np.testing.assert_array_equal(outs[0], x)

    def test_coalesced(self):
        a, b = np.arange(3.0), np.ones((2, 2))
        ra, rb = dist.all_reduce_coalesced([a, b])
        np.testing.assert_array_equal(ra, a)
        np.testing.assert_array_equal(rb, b)
        per = dist.all_gather_coalesced([a, b])
        assert len(per) == 2 and len(per[0]) == 1
        np.testing.assert_array_equal(per[0][0], a)

    def test_p2p_cooperative_single(self):
        got = dist.recv(None, src=0)
        assert got is None or isinstance(got, np.ndarray)
        w = dist.isend(np.arange(2), dst=0)
        assert w.is_completed()
        w2 = dist.irecv(None, src=0)
        w2.wait()

    def test_misc_probes(self):
        assert dist.is_available()
        assert dist.get_world_group().size == dist.get_world_size()
        dist.monitored_barrier(timeout=1.0)
        assert dist.in_aml() in (True, False)
        np.testing.assert_array_equal(
            dist.inference_all_reduce(np.arange(3.0)), np.arange(3.0)
        )

    def test_env_patches(self, monkeypatch):
        monkeypatch.setenv("OMPI_COMM_WORLD_RANK", "0")
        monkeypatch.setenv("OMPI_COMM_WORLD_SIZE", "1")
        monkeypatch.setenv("OMPI_COMM_WORLD_LOCAL_SIZE", "1")
        for var in ("RANK", "WORLD_SIZE", "LOCAL_RANK", "MASTER_ADDR", "MASTER_PORT"):
            monkeypatch.delenv(var, raising=False)
        dist.patch_aml_env_for_torch_nccl_backend(verbose=False)
        assert os.environ["RANK"] == "0"
        assert "MASTER_ADDR" in os.environ
        dist.patch_aws_sm_env_for_torch_nccl_backend(verbose=False)
        assert os.environ["WORLD_SIZE"] == "1"
