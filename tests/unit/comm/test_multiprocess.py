"""True multi-process distributed runtime test.

The rest of the suite exercises multi-CHIP semantics on a virtual mesh in
one process; this is the multi-HOST leg — the reference's
distributed-in-one-box strategy applied to the actual rendezvous
(``deepspeed.init_distributed`` → ``jax.distributed.initialize``) and a
cross-process collective, with 2 real OS processes coordinating over TCP
(SURVEY §4; reference ``tests/unit/common.py`` ``DistributedExec``).
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = r"""
import os, sys
os.environ["PALLAS_AXON_POOL_IPS"] = ""
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")

import deepspeed_tpu as ds
from deepspeed_tpu import comm as dist

ds.init_distributed()  # rendezvous from MASTER_ADDR/RANK/WORLD_SIZE envs
assert dist.is_initialized()
rank, world = dist.get_rank(), dist.get_world_size()
assert world == 2, world

import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
import numpy as np

# one device per process; a global psum must cross the process boundary
mesh = Mesh(np.asarray(jax.devices()).reshape(-1), ("data",))
local = jnp.full((4,), float(rank + 1))
arr = jax.make_array_from_single_device_arrays(
    (2 * 4,), NamedSharding(mesh, P("data")),
    [jax.device_put(local, jax.local_devices()[0])],
)
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
expected = 4.0 * 1 + 4.0 * 2
got = float(jax.device_get(total.addressable_shards[0].data))
assert got == expected, (got, expected)

# facade control-plane ops across the real process boundary -----------------
# all_to_all_single: rank r sends chunk i to rank i
x = np.arange(4.0) + 10.0 * rank  # rank0: [0..3], rank1: [10..13]
out = dist.all_to_all_single(None, x)
exp = np.concatenate([np.arange(2.0) + 10.0 * s for s in range(2)]) + 2.0 * rank
np.testing.assert_array_equal(out, exp)

# dtype-preserving coalesced all-reduce (f32 + int64 flag together)
ra, rb = dist.all_reduce_coalesced([np.arange(3, dtype=np.float32), np.array([rank], np.int64)])
np.testing.assert_array_equal(ra, 2 * np.arange(3, dtype=np.float32))
assert rb.dtype == np.int64 and int(rb[0]) == 1

# cooperative p2p: both ranks isend then irecv (the torch nonblocking order)
peer = 1 - rank
dist.isend(np.full((2,), float(rank)), dst=peer)
w = dist.irecv(None, src=peer)
got_p2p = w.wait()
np.testing.assert_array_equal(got_p2p, np.full((2,), float(peer)))

print(f"RANK{rank} OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_psum(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            MASTER_ADDR="127.0.0.1",
            MASTER_PORT=str(port),
            RANK=str(rank),
            WORLD_SIZE="2",
            JAX_PLATFORMS="cpu",
            PALLAS_AXON_POOL_IPS="",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
        )
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))),
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK{rank} OK" in out
