"""Pallas block-sparse attention kernel tests (interpret mode on CPU).

Reference analog: ``tests/unit/ops/sparse_attention/`` — numerics of the
block kernel vs a dense masked-softmax oracle, forward and backward, over
the SparsityConfig layout family.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention.pallas_block_sparse import (
    build_block_tables,
    pallas_block_sparse_attention,
)
from deepspeed_tpu.ops.sparse_attention.sparsity_config import (
    BigBirdSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
)

B, NH, D = 2, 2, 64
BLOCK = 16


def _qkv(T, seed=0):
    rs = np.random.RandomState(seed)
    shape = (B, NH, T, D)
    return (
        jnp.asarray(rs.randn(*shape), jnp.float32),
        jnp.asarray(rs.randn(*shape), jnp.float32),
        jnp.asarray(rs.randn(*shape), jnp.float32),
    )


def _dense_oracle(q, k, v, layout, block, causal):
    """Dense masked softmax with the same live-pair semantics."""
    T = q.shape[2]
    nb = T // block
    lay = np.asarray(layout, bool)
    if lay.shape[0] == 1:
        lay = np.repeat(lay, NH, axis=0)
    elem = np.kron(lay, np.ones((block, block), bool))  # [NH, T, T]
    if causal:
        elem &= np.tril(np.ones((T, T), bool))[None]
    mask = jnp.asarray(elem)[None]  # [1, NH, T, T]
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(D)
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(mask.any(-1, keepdims=True), probs, 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _layout(cfg_cls, T, **kw):
    cfg = cfg_cls(num_heads=NH, block=BLOCK, **kw)
    return cfg.make_layout(T)


CASES = [
    ("fixed", lambda T: _layout(FixedSparsityConfig, T), True),
    ("bigbird", lambda T: _layout(BigBirdSparsityConfig, T), False),
    ("local", lambda T: _layout(LocalSlidingWindowSparsityConfig, T), True),
]


@pytest.mark.parametrize("name,layout_fn,causal", CASES)
def test_forward_matches_dense_oracle(name, layout_fn, causal):
    T = 128
    q, k, v = _qkv(T)
    layout = layout_fn(T)
    out = pallas_block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
    ref = _dense_oracle(q, k, v, layout, BLOCK, causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("name,layout_fn,causal", CASES[:2])
def test_backward_matches_dense_oracle(name, layout_fn, causal):
    T = 64
    q, k, v = _qkv(T, seed=3)
    layout = layout_fn(T)

    def sparse_loss(q, k, v):
        o = pallas_block_sparse_attention(q, k, v, layout, BLOCK, causal=causal)
        return jnp.sum(o * jnp.cos(o))

    def dense_loss(q, k, v):
        o = _dense_oracle(q, k, v, layout, BLOCK, causal)
        return jnp.sum(o * jnp.cos(o))

    gs = jax.grad(sparse_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, label in zip(gs, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5, err_msg=f"d{label}"
        )


def test_matches_xla_emulation():
    """The Pallas kernel and the XLA dense-gather emulation are two
    implementations of the same op; they must agree."""
    from deepspeed_tpu.ops.sparse_attention.sparse_self_attention import (
        block_sparse_attention,
    )

    T = 128
    q, k, v = _qkv(T, seed=5)
    layout = _layout(FixedSparsityConfig, T)
    a = pallas_block_sparse_attention(q, k, v, layout, BLOCK, causal=True)
    b = block_sparse_attention(q, k, v, layout, BLOCK, causal=True)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-5)


def test_block_tables():
    lay = np.zeros((4, 4), bool)
    lay[0, 0] = lay[1, 0] = lay[1, 1] = lay[3, 2] = True
    row_idx, row_cnt, col_idx, col_cnt = build_block_tables(lay)
    assert row_cnt.tolist() == [1, 2, 0, 1]
    assert row_idx.shape == (4, 2)
    assert col_cnt.tolist() == [2, 1, 1, 0]
    np.testing.assert_array_equal(row_idx[1], [0, 1])


def test_work_scales_with_live_blocks():
    """The grid is nq x max_live, not nq x nk — the FLOP-skipping the
    kernel exists for."""
    T = 512
    layout = _layout(LocalSlidingWindowSparsityConfig, T)  # narrow band
    row_idx, row_cnt, _, _ = build_block_tables(layout[0])
    nb = T // BLOCK
    assert row_idx.shape[1] < nb / 2, (row_idx.shape, nb)


def test_causally_dead_rows_zero_fwd_and_bwd():
    """A custom layout whose q-block 0 only lists a strictly-future kv block
    (causal): those rows have no live scores, so the forward must emit 0 (not
    mean(v) — NEG_INF is finite, exp(s-m)=1 without explicit zeroing) and all
    gradients flowing through them must be 0, not garbage."""
    T = 64  # 4 blocks of BLOCK=16
    q, k, v = _qkv(T, seed=7)
    layout = np.zeros((1, 4, 4), bool)
    layout[0, 0, 3] = True  # q-block 0 → only future kv-block 3: fully dead
    layout[0, 1, 1] = True
    layout[0, 2, 2] = True
    layout[0, 2, 0] = True
    layout[0, 3, 3] = True

    out = pallas_block_sparse_attention(q, k, v, layout, BLOCK, causal=True)
    ref = _dense_oracle(q, k, v, layout, BLOCK, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    assert np.all(np.asarray(out)[:, :, :BLOCK, :] == 0.0), "dead rows must output 0"

    def sparse_loss(q, k, v):
        o = pallas_block_sparse_attention(q, k, v, layout, BLOCK, causal=True)
        return jnp.sum(o * jnp.cos(o))

    def dense_loss(q, k, v):
        o = _dense_oracle(q, k, v, layout, BLOCK, True)
        return jnp.sum(o * jnp.cos(o))

    gs = jax.grad(sparse_loss, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b, label in zip(gs, gd, "qkv"):
        assert np.all(np.isfinite(np.asarray(a))), f"d{label} not finite"
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5, err_msg=f"d{label}"
        )
    # dead q rows get zero dq
    assert np.all(np.asarray(gs[0])[:, :, :BLOCK, :] == 0.0)
