"""Paged attention front-end tests (``ops/transformer/paged_attention.py``).

The serving layer depends on three invariants: the XLA gather fallback and
the Pallas page-table kernel agree, sentinel/garbage table entries past the
live length never leak into outputs, and GQA is computed by grouping —
never by materializing an NH-wide cache copy.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.paged_attention import (
    paged_decode_attention,
    paged_decode_attention_xla,
    paged_prefill_attention,
    ragged_paged_attention,
)


def _rand_pool(rs, NP, NKV, P, D):
    k = rs.randn(NP, NKV, P, D).astype(np.float32)
    v = rs.randn(NP, NKV, P, D).astype(np.float32)
    return jnp.asarray(k), jnp.asarray(v)


def _dense_from_pages(k_pages, page_table, P):
    """[B, S, NKV, D] linear cache equivalent of a page table (numpy ref)."""
    kp = np.asarray(k_pages)
    pt = np.asarray(page_table)
    B, maxp = pt.shape
    _, NKV, _, D = kp.shape
    out = np.zeros((B, maxp * P, NKV, D), np.float32)
    for b in range(B):
        for i, pid in enumerate(pt[b]):
            if pid >= 0:
                out[b, i * P : (i + 1) * P] = kp[pid].transpose(1, 0, 2)
    return out


def _ref_decode(q, k_lin, v_lin, lens, scale):
    B, NH, D = q.shape
    NKV = k_lin.shape[2]
    G = NH // NKV
    out = np.zeros((B, NH, D), np.float32)
    for b in range(B):
        L = int(lens[b])
        if L == 0:
            continue
        for h in range(NH):
            kv = h // G
            s = (k_lin[b, :L, kv] @ q[b, h]) * scale
            p = np.exp(s - s.max())
            p /= p.sum()
            out[b, h] = p @ v_lin[b, :L, kv]
    return out


@pytest.mark.parametrize("nkv", [4, 2, 1])  # MHA, GQA, MQA
def test_xla_fallback_matches_reference(nkv):
    B, NH, D, P, NP, maxp = 3, 4, 16, 8, 12, 4
    rs = np.random.RandomState(0)
    q = rs.randn(B, NH, D).astype(np.float32)
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    # ragged tables: unused tail entries are -1 sentinels
    pt = np.full((B, maxp), -1, np.int32)
    pt[0, :3] = [3, 7, 1]
    pt[1, :1] = [5]
    pt[2, :4] = [2, 9, 4, 8]
    lens = np.array([20, 8, 32], np.int32)
    out = paged_decode_attention_xla(jnp.asarray(q), kp, vp, jnp.asarray(pt), lens)
    ref = _ref_decode(
        q, _dense_from_pages(kp, pt, P), _dense_from_pages(vp, pt, P),
        lens, 1.0 / np.sqrt(D),
    )
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_xla_matches_pallas_interpret():
    B, NH, nkv, D, P, NP, maxp = 2, 4, 2, 16, 8, 10, 3
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(B, NH, D).astype(np.float32))
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    pt = np.full((B, maxp), -1, np.int32)
    pt[0, :2] = [4, 2]
    pt[1, :3] = [7, 1, 9]
    lens = np.array([13, 24], np.int32)
    out_x = paged_decode_attention(q, kp, vp, jnp.asarray(pt), lens, impl="xla")
    out_p = paged_decode_attention(q, kp, vp, jnp.asarray(pt), lens, impl="pallas")
    np.testing.assert_allclose(np.asarray(out_x), np.asarray(out_p), rtol=2e-5, atol=2e-5)


def test_zero_length_rows_and_garbage_pages_are_inert():
    B, NH, nkv, D, P, NP, maxp = 2, 2, 2, 8, 4, 6, 2
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(B, NH, D).astype(np.float32))
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    pt = np.array([[3, -1], [-1, -1]], np.int32)
    lens = np.array([4, 0], np.int32)
    out = np.asarray(paged_decode_attention_xla(q, kp, vp, jnp.asarray(pt), lens))
    assert (out[1] == 0).all()  # dead row: exact zeros (kernel contract)
    # garbage in pages past the live length must not move the output
    kp2 = kp.at[5].set(1e6)
    vp2 = vp.at[5].set(-1e6)
    out2 = np.asarray(paged_decode_attention_xla(q, kp2, vp2, jnp.asarray(pt), lens))
    np.testing.assert_allclose(out, out2, rtol=1e-6)


def test_prefill_chunk_matches_causal_reference():
    B, T, NH, nkv, D, P, NP, maxp = 1, 6, 4, 2, 8, 4, 8, 4
    rs = np.random.RandomState(3)
    q = rs.randn(B, T, NH, D).astype(np.float32)
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    pt = np.array([[2, 5, 1, -1]], np.int32)
    start = 3  # chunk positions 3..8: prefix 0..2 already in the pages
    q_pos = np.arange(start, start + T, dtype=np.int32)[None]
    out = paged_prefill_attention(
        jnp.asarray(q), kp, vp, jnp.asarray(pt), jnp.asarray(q_pos)
    )
    k_lin = _dense_from_pages(kp, pt, P)
    v_lin = _dense_from_pages(vp, pt, P)
    scale = 1.0 / np.sqrt(D)
    for t in range(T):
        ref = _ref_decode(
            q[:, t], k_lin, v_lin, np.array([start + t + 1]), scale
        )
        np.testing.assert_allclose(
            np.asarray(out[:, t]), ref, rtol=2e-5, atol=2e-5,
            err_msg=f"chunk offset {t}",
        )


# --- ragged mixed-row attention (ISSUE 8) -----------------------------------
def _ragged_fixture(rs, R=3, W=6, NH=4, nkv=2, D=16, P=8, NP=12, maxp=4):
    """A genuinely mixed window: row 0 decodes (q_len 1), row 1 runs a
    prefill chunk filling its window (q_len W), row 2 is dead padding."""
    q = jnp.asarray(rs.randn(R, W, NH, D).astype(np.float32))
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    pt = np.full((R, maxp), -1, np.int32)
    pt[0, :3] = [3, 7, 1]
    pt[1, :1] = [5]
    kv_lens = np.array([18, W, 0], np.int32)  # INCLUDING this step's tokens
    q_lens = np.array([1, W, 0], np.int32)
    return q, kp, vp, jnp.asarray(pt), jnp.asarray(kv_lens), jnp.asarray(q_lens)


def test_ragged_matches_per_mode_reference():
    """Each row of a mixed window must equal its single-mode computation:
    the decode row matches masked decode attention at its length, the
    chunk row matches the causal per-position reference, and the dead row
    is exact zeros."""
    rs = np.random.RandomState(4)
    q, kp, vp, pt, kv_lens, q_lens = _ragged_fixture(rs)
    W, D, P = q.shape[1], q.shape[3], kp.shape[2]
    out = np.asarray(ragged_paged_attention(q, kp, vp, pt, kv_lens, q_lens, impl="xla"))
    k_lin = _dense_from_pages(kp, pt, P)
    v_lin = _dense_from_pages(vp, pt, P)
    scale = 1.0 / np.sqrt(D)
    # decode row: one token at position kv_len-1 sees the whole prefix
    ref0 = _ref_decode(np.asarray(q[0:1, 0]), k_lin[0:1], v_lin[0:1],
                       np.array([18]), scale)
    np.testing.assert_allclose(out[0:1, 0], ref0, rtol=2e-5, atol=2e-5)
    # chunk row: causal per position (start 0: kv_len == q_len)
    for t in range(W):
        ref1 = _ref_decode(np.asarray(q[1:2, t]), k_lin[1:2], v_lin[1:2],
                           np.array([t + 1]), scale)
        np.testing.assert_allclose(out[1:2, t], ref1, rtol=2e-5, atol=2e-5,
                                   err_msg=f"chunk offset {t}")
    assert (out[2] == 0).all()  # dead row: exact zeros


def test_ragged_xla_matches_pallas_interpret():
    """The Pallas ragged kernel (scalar-prefetched page table + per-row
    (kv_len, q_len) metadata) agrees with the XLA gather fallback on every
    LIVE window slot; dead rows are zeros in both."""
    rs = np.random.RandomState(5)
    q, kp, vp, pt, kv_lens, q_lens = _ragged_fixture(rs)
    out_x = np.asarray(ragged_paged_attention(q, kp, vp, pt, kv_lens, q_lens, impl="xla"))
    out_p = np.asarray(ragged_paged_attention(q, kp, vp, pt, kv_lens, q_lens, impl="pallas"))
    for r, ql in enumerate(np.asarray(q_lens)):
        np.testing.assert_allclose(
            out_x[r, :ql], out_p[r, :ql], rtol=2e-5, atol=2e-5, err_msg=f"row {r}"
        )
    assert (out_p[2] == 0).all()


def test_ragged_mid_sequence_verify_row():
    """A verify-shaped row (q_len 3 starting mid-sequence) must score each
    slot causally against prefix + earlier slots — the accepted-prefix
    computation depends on it."""
    rs = np.random.RandomState(6)
    R, W, NH, nkv, D, P, NP, maxp = 1, 4, 4, 2, 8, 4, 8, 4
    q = jnp.asarray(rs.randn(R, W, NH, D).astype(np.float32))
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    pt = np.array([[2, 5, 1, -1]], np.int32)
    start, ql = 5, 3  # tokens at positions 5, 6, 7; slot 3 is pad garbage
    kv_lens = np.array([start + ql], np.int32)
    q_lens = np.array([ql], np.int32)
    out = np.asarray(ragged_paged_attention(
        q, kp, vp, jnp.asarray(pt), jnp.asarray(kv_lens), jnp.asarray(q_lens),
        impl="xla",
    ))
    k_lin = _dense_from_pages(kp, pt, P)
    v_lin = _dense_from_pages(vp, pt, P)
    for t in range(ql):
        ref = _ref_decode(np.asarray(q[:, t]), k_lin, v_lin,
                          np.array([start + t + 1]), 1.0 / np.sqrt(D))
        np.testing.assert_allclose(out[:, t], ref, rtol=2e-5, atol=2e-5,
                                   err_msg=f"verify slot {t}")
    # garbage k/v in the tabled page past the live length (table slot 2 =
    # positions 8..11, all >= kv_len 8) never leak in
    kp2 = kp.at[1].set(1e6)
    vp2 = vp.at[1].set(-1e6)
    out2 = np.asarray(ragged_paged_attention(
        q, kp2, vp2, jnp.asarray(pt), jnp.asarray(kv_lens), jnp.asarray(q_lens),
        impl="xla",
    ))
    np.testing.assert_allclose(out[:, :ql], out2[:, :ql], rtol=1e-6)


def test_gqa_grouped_equals_repeat_expansion():
    """The grouped-einsum GQA math must equal the (banned) NH-wide repeat."""
    B, NH, nkv, D, P, NP, maxp = 2, 8, 2, 16, 8, 8, 2
    rs = np.random.RandomState(4)
    q = rs.randn(B, NH, D).astype(np.float32)
    kp, vp = _rand_pool(rs, NP, nkv, P, D)
    pt = np.array([[1, 4], [6, -1]], np.int32)
    lens = np.array([12, 5], np.int32)
    out = paged_decode_attention_xla(jnp.asarray(q), kp, vp, jnp.asarray(pt), lens)
    # reference: expand kv to NH heads, per-head attention
    k_lin = _dense_from_pages(kp, pt, P).repeat(NH // nkv, axis=2)
    v_lin = _dense_from_pages(vp, pt, P).repeat(NH // nkv, axis=2)
    ref = np.zeros((B, NH, D), np.float32)
    scale = 1.0 / np.sqrt(D)
    for b in range(B):
        for h in range(NH):
            s = (k_lin[b, : lens[b], h] @ q[b, h]) * scale
            p = np.exp(s - s.max())
            p /= p.sum()
            ref[b, h] = p @ v_lin[b, : lens[b], h]
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_dense_fallback_gqa_has_no_repeat():
    """decode.py's dense GQA fallback: grouped einsum matches the repeat
    reference, and the lowered HLO contains no NH-wide cache broadcast
    (satellite guard for the jnp.repeat blowup fix)."""
    from deepspeed_tpu.inference.decode import _cached_attention
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=64, num_layers=1, num_heads=8,
        num_kv_heads=2, max_seq_len=32, flash_attention=False, dtype="float32",
    )
    B, T, S = 2, 3, 17  # S deliberately not a multiple of 256 (dense path)
    rs = np.random.RandomState(5)
    q = jnp.asarray(rs.randn(B, T, 8, 8).astype(np.float32))
    k = jnp.asarray(rs.randn(B, S, 2, 8).astype(np.float32))
    v = jnp.asarray(rs.randn(B, S, 2, 8).astype(np.float32))
    q_pos = jnp.asarray(np.tile(np.arange(5, 5 + T, dtype=np.int32), (B, 1)))
    mask = jnp.asarray(np.arange(S) < 8)
    out = _cached_attention(cfg, q, k, v, q_pos, mask)
    # repeat-based reference
    kr = jnp.repeat(k, 4, axis=2)
    vr = jnp.repeat(v, 4, axis=2)
    scores = jnp.einsum("btnd,bsnd->bnts", q, kr).astype(jnp.float32) / np.sqrt(8)
    causal = q_pos[:, None, :, None] >= jnp.arange(S)[None, None, None, :]
    scores = jnp.where(causal & mask[None, None, None, :], scores, -1e30)
    ref = jnp.einsum("bnts,bsnd->btnd", jax.nn.softmax(scores, -1), vr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
    # structural guard: no intermediate may materialize an NH-wide cache
    # copy [B, S, NH, D] (what jnp.repeat(k_cache, G, axis=2) produced) —
    # checked by the analysis layer's recursive shape scan (sees through
    # scan/pjit bodies, unlike the old top-level eqn loop)
    from deepspeed_tpu.analysis import find_aval_shapes

    jaxpr = jax.make_jaxpr(
        lambda q, k, v: _cached_attention(cfg, q, k, v, q_pos, mask)
    )(q, k, v)
    banned = (B, S, 8, 8)
    hits = find_aval_shapes(jaxpr, banned)
    assert not hits, f"decode fallback materializes an NH-wide cache: {hits}"
    # legacy cross-check (top-level eqns only): keeps the analysis helper
    # honest against a hand-rolled scan of the same jaxpr
    for eqn in jaxpr.jaxpr.eqns:
        for var in eqn.outvars:
            assert tuple(getattr(var.aval, "shape", ())) != banned, (
                f"decode fallback materializes an NH-wide cache: {eqn.primitive}"
            )


def test_training_gqa_attention_has_no_repeat():
    """Satellite guard for the training-side GQA fix: the grouped einsum
    path in ``_local_full_attention`` must not materialize NH-wide k/v
    copies (what ``_expand_gqa``'s jnp.repeat produced). With grouping,
    the ONLY [B, T, NH, D] tensor in the attention body is the final
    output reshape; an expansion-based path adds NH-wide k and v too."""
    from deepspeed_tpu.analysis import find_aval_shapes
    from deepspeed_tpu.models import TransformerLM
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=64, hidden_size=64, num_layers=1, num_heads=8,
        num_kv_heads=2, max_seq_len=16, flash_attention=False, dtype="float32",
    )
    model = TransformerLM(cfg)
    B, T, NH, NKV, D = 2, 16, 8, 2, 8
    rs = np.random.RandomState(7)
    q = jnp.asarray(rs.randn(B, T, NH, D).astype(np.float32))
    k = jnp.asarray(rs.randn(B, T, NKV, D).astype(np.float32))
    v = jnp.asarray(rs.randn(B, T, NKV, D).astype(np.float32))
    pos = jnp.asarray(np.tile(np.arange(T, dtype=np.int32), (B, 1)))
    jaxpr = jax.make_jaxpr(
        lambda q, k, v: model._local_full_attention(q, k, v, pos, 1.0 / np.sqrt(D))
    )(q, k, v)
    nh_wide = find_aval_shapes(jaxpr, (B, T, NH, D))
    assert len(nh_wide) <= 1, (
        f"NH-wide tensors materialized in GQA attention (expansion?): {nh_wide}"
    )
    grouped = find_aval_shapes(jaxpr, (B, T, NKV, NH // NKV, D))
    assert grouped, "grouped [B,T,NKV,G,D] factoring missing — GQA regressed"
    # numerics: grouped math equals the repeat-expansion reference
    out = model._local_full_attention(q, k, v, pos, 1.0 / np.sqrt(D))
    kr, vr = jnp.repeat(k, NH // NKV, axis=2), jnp.repeat(v, NH // NKV, axis=2)
    ref = model._local_full_attention(q, kr, vr, pos, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5)
