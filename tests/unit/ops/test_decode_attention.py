"""Ragged decode-attention kernel tests (interpret mode on CPU).

Reference analog: ``tests/unit/ops/transformer/inference`` softmax_context
numerics — the fused single-token cache attention must match the dense
masked computation, including ragged per-batch lengths and GQA grouping.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.decode_attention import decode_attention


def _dense_ref(q, k_cache, v_cache, kv_len, scale):
    B, NH, D = q.shape
    S, NKV = k_cache.shape[1], k_cache.shape[2]
    if NKV != NH:
        k_cache = np.repeat(k_cache, NH // NKV, axis=2)
        v_cache = np.repeat(v_cache, NH // NKV, axis=2)
    scores = np.einsum("bnd,bsnd->bns", q, k_cache).astype(np.float64) * scale
    lens = np.broadcast_to(np.asarray(kv_len), (B,))
    for b in range(B):
        scores[b, :, lens[b] :] = -1e30
    probs = np.exp(scores - scores.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    return np.einsum("bns,bsnd->bnd", probs, v_cache)


@pytest.mark.parametrize("nkv", [8, 2])  # MHA and GQA grouping
def test_matches_dense(nkv):
    B, NH, D, S = 3, 8, 64, 512
    rs = np.random.RandomState(0)
    q = rs.randn(B, NH, D).astype(np.float32)
    k = rs.randn(B, S, nkv, D).astype(np.float32)
    v = rs.randn(B, S, nkv, D).astype(np.float32)
    lens = np.array([1, 200, 512], np.int32)  # ragged, incl. edges
    out = decode_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), lens, block_k=128)
    ref = _dense_ref(q, k, v, lens, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_scalar_length_and_custom_scale():
    B, NH, D, S = 2, 4, 32, 256
    rs = np.random.RandomState(1)
    q = rs.randn(B, NH, D).astype(np.float32)
    k = rs.randn(B, S, NH, D).astype(np.float32)
    v = rs.randn(B, S, NH, D).astype(np.float32)
    out = decode_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), 77, scale=1.0)
    ref = _dense_ref(q, k, v, 77, 1.0)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_dead_blocks_are_skipped_semantics():
    """Values in cache slots past kv_len must not affect the output."""
    B, NH, D, S = 1, 4, 32, 512
    rs = np.random.RandomState(2)
    q = rs.randn(B, NH, D).astype(np.float32)
    k = rs.randn(B, S, NH, D).astype(np.float32)
    v = rs.randn(B, S, NH, D).astype(np.float32)
    out1 = decode_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), 100, block_k=128)
    k2, v2 = k.copy(), v.copy()
    k2[:, 100:] = 1e6  # garbage beyond the live prefix
    v2[:, 100:] = -1e6
    out2 = decode_attention(jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2), 100, block_k=128)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)


def test_rejects_bad_shapes():
    q = jnp.zeros((1, 6, 8))
    kv = jnp.zeros((1, 256, 4, 8))
    with pytest.raises(ValueError, match="multiple"):
        decode_attention(q, kv, kv, 10)


class TestDeepSpeedTransformerLayer:
    def test_layer_runs_and_matches_model_family(self):
        import deepspeed_tpu as ds
        import jax
        import jax.numpy as jnp

        cfg = ds.DeepSpeedTransformerConfig(hidden_size=32, heads=4, pre_layer_norm=True)
        layer = ds.DeepSpeedTransformerLayer(cfg)
        params = layer.init(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 32), jnp.float32)
        out = layer(params, x, train=False)
        assert out.shape == (2, 8, 32)
        assert np.isfinite(np.asarray(out)).all()
        # post-LN (BERT) variant
        cfg2 = ds.DeepSpeedTransformerConfig(hidden_size=32, heads=4, pre_layer_norm=False)
        layer2 = ds.DeepSpeedTransformerLayer(cfg2)
        params2 = layer2.init(jax.random.PRNGKey(1))
        out2 = layer2(params2, x, train=False)
        assert out2.shape == (2, 8, 32)
        assert not np.allclose(np.asarray(out), np.asarray(out2))

    def test_mask_rejected(self):
        import deepspeed_tpu as ds
        import jax
        import jax.numpy as jnp
        import pytest

        layer = ds.DeepSpeedTransformerLayer(
            ds.DeepSpeedTransformerConfig(hidden_size=16, heads=2)
        )
        params = layer.init(jax.random.PRNGKey(0))
        with pytest.raises(NotImplementedError, match="mask"):
            layer(params, jnp.zeros((1, 4, 16)), attention_mask=jnp.ones((1, 4)))

    def test_on_device_context(self):
        import deepspeed_tpu as ds
        import jax
        import jax.numpy as jnp

        with ds.OnDevice(device="cpu"):
            x = jnp.ones((2, 2))
        assert x.devices()  # placed somewhere valid
        with ds.OnDevice(device="meta"):
            shapes = jax.eval_shape(lambda: jnp.zeros((4, 4)))
        assert shapes.shape == (4, 4)


class TestPagedDecodeAttention:
    def _pages_from_contiguous(self, k, v, page):
        """Scatter a contiguous [B,S,NKV,D] cache into a shared page pool
        with a per-sequence page table."""
        B, S, NKV, D = k.shape
        per = S // page
        pool_k = np.zeros((B * per + 1, NKV, page, D), np.float32)
        pool_v = np.zeros_like(pool_k)
        table = np.zeros((B, per), np.int32)
        nxt = 1  # page 0 stays unused (garbage detector)
        for b in range(B):
            for pi in range(per):
                pool_k[nxt] = k[b, pi * page : (pi + 1) * page].transpose(1, 0, 2)
                pool_v[nxt] = v[b, pi * page : (pi + 1) * page].transpose(1, 0, 2)
                table[b, pi] = nxt
                nxt += 1
        return pool_k, pool_v, table

    @pytest.mark.parametrize("nkv", [4, 2])
    def test_matches_contiguous_kernel(self, nkv):
        from deepspeed_tpu.ops.transformer.decode_attention import (
            paged_decode_attention,
        )

        B, NH, D, S, page = 2, 4, 32, 512, 128
        rs = np.random.RandomState(0)
        q = rs.randn(B, NH, D).astype(np.float32)
        k = rs.randn(B, S, nkv, D).astype(np.float32)
        v = rs.randn(B, S, nkv, D).astype(np.float32)
        lens = np.array([130, 512], np.int32)
        pool_k, pool_v, table = self._pages_from_contiguous(k, v, page)
        out = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v), table, lens
        )
        ref = _dense_ref(q, k, v, lens, 1.0 / np.sqrt(D))
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_shared_prefix_pages(self):
        """Two sequences sharing their first page (prefix sharing — the
        memory win paging exists for) must read identical prefix content."""
        from deepspeed_tpu.ops.transformer.decode_attention import (
            paged_decode_attention,
        )

        NH, D, page = 4, 32, 128
        rs = np.random.RandomState(1)
        pool_k = rs.randn(4, NH, page, D).astype(np.float32)
        pool_v = rs.randn(4, NH, page, D).astype(np.float32)
        q = rs.randn(2, NH, D).astype(np.float32)
        # both sequences point at page 1 first, then diverge (2 vs 3)
        table = np.array([[1, 2], [1, 3]], np.int32)
        lens = np.array([256, 256], np.int32)
        out = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v), table, lens
        )
        # dense reference: reconstruct each sequence's contiguous cache
        for b in range(2):
            kb = np.concatenate(
                [pool_k[table[b, i]].transpose(1, 0, 2) for i in range(2)], axis=0
            )[None]
            vb = np.concatenate(
                [pool_v[table[b, i]].transpose(1, 0, 2) for i in range(2)], axis=0
            )[None]
            ref = _dense_ref(q[b : b + 1], kb, vb, np.array([256]), 1.0 / np.sqrt(D))
            np.testing.assert_allclose(np.asarray(out)[b : b + 1], ref, rtol=2e-5, atol=2e-5)

    def test_unused_pool_pages_ignored(self):
        from deepspeed_tpu.ops.transformer.decode_attention import (
            paged_decode_attention,
        )

        NH, D, page = 2, 32, 128
        rs = np.random.RandomState(2)
        pool_k = rs.randn(3, NH, page, D).astype(np.float32)
        pool_v = rs.randn(3, NH, page, D).astype(np.float32)
        q = rs.randn(1, NH, D).astype(np.float32)
        table = np.array([[1, 2]], np.int32)
        out1 = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v), table, np.array([200])
        )
        pool_k2 = pool_k.copy()
        pool_k2[0] = 1e6  # garbage in the unused page
        # and garbage past len inside the last live page's tail
        out2 = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k2), jnp.asarray(pool_v), table, np.array([200])
        )
        np.testing.assert_allclose(np.asarray(out1), np.asarray(out2), rtol=1e-6)

    def test_padding_slots_with_sentinel_ids(self):
        """Serving stacks pad page tables with -1 (or ids >= NP) past the
        live length; the index map must clamp those fetches in-range rather
        than read out of bounds, and their scores are masked anyway."""
        from deepspeed_tpu.ops.transformer.decode_attention import (
            paged_decode_attention,
        )

        NH, D, page = 2, 32, 128
        rs = np.random.RandomState(3)
        pool_k = rs.randn(3, NH, page, D).astype(np.float32)
        pool_v = rs.randn(3, NH, page, D).astype(np.float32)
        q = rs.randn(2, NH, D).astype(np.float32)
        lens = np.array([130, 256], np.int32)
        valid = np.array([[1, 2, 0, 0], [2, 0, 0, 0]], np.int32)
        padded = np.array([[1, 2, -1, 99], [2, 0, -1, -1]], np.int32)
        out_valid = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v), valid, lens
        )
        out_padded = paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v), padded, lens
        )
        np.testing.assert_allclose(
            np.asarray(out_valid), np.asarray(out_padded), rtol=1e-6
        )
