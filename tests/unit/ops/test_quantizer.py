"""Quantizer + quantized collectives tests (reference:
``tests/unit/ops/quantizer/`` + ``tests/unit/runtime/zero/test_zeropp.py``)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.ops.quantizer import (
    dequantize,
    dequantize_asymmetric,
    fake_quantize,
    quantize,
    quantize_asymmetric,
)


class TestQuantize:
    @pytest.mark.parametrize("num_bits", [4, 8])
    def test_roundtrip_error_bounded(self, num_bits):
        rs = np.random.RandomState(0)
        x = rs.randn(64, 32).astype(np.float32)
        q, s = quantize(jnp.asarray(x), num_groups=16, num_bits=num_bits)
        out = np.asarray(dequantize(q, s, shape=x.shape))
        qmax = 2 ** (num_bits - 1) - 1
        # per-group max-abs / qmax bounds the rounding error
        bound = np.abs(x).max() / qmax
        assert np.abs(out - x).max() <= bound + 1e-6

    def test_zeros_stable(self):
        q, s = quantize(jnp.zeros(64), num_groups=4)
        assert np.all(np.asarray(q) == 0)
        np.testing.assert_array_equal(np.asarray(dequantize(q, s)), np.zeros((4, 16)))

    def test_asymmetric_roundtrip(self):
        rs = np.random.RandomState(1)
        x = (rs.rand(128) * 5 + 3).astype(np.float32)  # strictly positive range
        q, s, m = quantize_asymmetric(jnp.asarray(x), num_groups=8)
        out = np.asarray(dequantize_asymmetric(q, s, m, shape=x.shape))
        assert np.abs(out - x).max() <= (x.max() - x.min()) / 255 + 1e-6

    def test_fake_quantize_straight_through(self):
        x = jnp.linspace(-1, 1, 64)
        g = jax.grad(lambda x: jnp.sum(fake_quantize(x, num_groups=4) ** 2))(x)
        # STE: gradient flows as if identity → d/dx sum(fq(x)^2) ≈ 2*fq(x)
        fq = fake_quantize(x, num_groups=4)
        np.testing.assert_allclose(np.asarray(g), 2 * np.asarray(fq), rtol=1e-5)


class TestQuantizedCollectives:
    def _mesh(self):
        devs = jax.devices()
        return Mesh(np.array(devs).reshape(len(devs)), ("data",))

    def test_quantized_reduce_scatter_close_to_exact(self, eight_devices):  # noqa: ARG002
        from deepspeed_tpu.runtime.comm.coalesced_collectives import (
            quantized_reduce_scatter,
        )

        mesh = self._mesh()
        rs = np.random.RandomState(0)
        x = rs.randn(1024).astype(np.float32)
        out = np.asarray(jax.device_get(quantized_reduce_scatter(jnp.asarray(x), mesh)))
        # every chip contributed the same replicated x → exact = world * x
        exact = len(jax.devices()) * x
        err = np.abs(out - exact).max()
        assert err <= np.abs(x).max() * len(jax.devices()) / 127 + 1e-5

    def test_quantized_all_gather(self, eight_devices):  # noqa: ARG002
        from deepspeed_tpu.runtime.comm.coalesced_collectives import quantized_all_gather

        mesh = self._mesh()
        rs = np.random.RandomState(1)
        x = rs.randn(64, 16).astype(np.float32)
        sharded = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("data")))
        out = np.asarray(jax.device_get(quantized_all_gather(sharded, mesh)))
        assert out.shape == x.shape
        assert np.abs(out - x).max() <= np.abs(x).max() / 127 + 1e-5

    def test_reduce_scatter_coalesced_exact(self, eight_devices):  # noqa: ARG002
        from deepspeed_tpu.runtime.comm.coalesced_collectives import (
            reduce_scatter_coalesced,
        )

        mesh = self._mesh()
        rs = np.random.RandomState(2)
        a = rs.randn(128).astype(np.float32)
        b = rs.randn(72).astype(np.float32)
        outs = reduce_scatter_coalesced([jnp.asarray(a), jnp.asarray(b)], mesh)
        n = len(jax.devices())
        np.testing.assert_allclose(np.asarray(outs[0]), n * a, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(outs[1]), n * b, rtol=1e-5)
