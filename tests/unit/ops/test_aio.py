"""Native async-IO op tests (reference: ``tests/unit/ops/aio/test_aio.py``)."""

from __future__ import annotations

import numpy as np
import pytest

from deepspeed_tpu.ops.aio import AsyncIOBuilder

pytestmark = pytest.mark.skipif(
    not AsyncIOBuilder().is_compatible(), reason="native aio unavailable"
)


@pytest.fixture
def handle():
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    return AsyncIOHandle(block_size=1 << 16, queue_depth=4, thread_count=2)


class TestAio:
    @pytest.mark.parametrize("numel", [255, 1 << 12, (1 << 18) + 31])
    def test_write_read_roundtrip(self, handle, tmp_path, numel):
        buf = np.random.RandomState(0).randn(numel).astype(np.float32)
        path = str(tmp_path / "t.swp")
        assert handle.sync_pwrite(buf, path) == buf.nbytes
        out = np.empty_like(buf)
        assert handle.sync_pread(out, path) == buf.nbytes
        np.testing.assert_array_equal(buf, out)

    def test_async_overlap(self, handle, tmp_path):
        bufs = [np.full(1 << 14, i, np.float32) for i in range(8)]
        for i, b in enumerate(bufs):
            handle.async_pwrite(b, str(tmp_path / f"{i}.swp"))
        assert handle.wait() == 8
        outs = [np.empty_like(b) for b in bufs]
        for i, o in enumerate(outs):
            handle.async_pread(o, str(tmp_path / f"{i}.swp"))
        handle.wait()
        for b, o in zip(bufs, outs):
            np.testing.assert_array_equal(b, o)

    def test_read_missing_file_raises(self, handle, tmp_path):
        out = np.empty(16, np.float32)
        with pytest.raises(IOError):
            handle.async_pread(out, str(tmp_path / "missing.swp"))
            handle.wait()


class TestSwapBuffers:
    def test_buffer_pack_unpack(self):
        from deepspeed_tpu.runtime.swap_tensor.utils import SwapBuffer

        buf = SwapBuffer(np.zeros(1024, np.float32))
        t1 = np.arange(100, dtype=np.float32)
        swap, compute = buf.insert_tensor(t1, "/tmp/a.swp", 128)
        assert swap.size == 128 and compute.size == 100
        np.testing.assert_array_equal(compute, t1)
        assert buf.get_swap_paths() == ["/tmp/a.swp"]
        assert not buf.has_space(1024 - 128 + 1)

    def test_manager_alloc_free(self):
        from deepspeed_tpu.runtime.swap_tensor.utils import SwapBufferManager

        mgr = SwapBufferManager(num_elems=256, count=4)
        bufs = mgr.allocate(num_elems=200, count=2)
        assert len(bufs) == 2
        assert mgr.allocate(200, 3) is None  # only 2 free left
        mgr.free(bufs)
        assert mgr.allocate(200, 4) is not None

    def test_async_swapper(self, tmp_path):
        from deepspeed_tpu.ops.aio import AsyncIOHandle
        from deepspeed_tpu.runtime.swap_tensor.async_swapper import AsyncTensorSwapper

        h = AsyncIOHandle(block_size=1 << 16, thread_count=2)
        swapper = AsyncTensorSwapper(h, numel_alignment=256)
        swapper.add_buffers([np.zeros(1 << 12, np.float32) for _ in range(2)])
        tensors = [np.full(1000, i, np.float32) for i in range(6)]
        paths = [str(tmp_path / f"s{i}.swp") for i in range(6)]
        swapper.swap_out_tensors(tensors, paths)
        swapper.release_buffers()
        for i, p in enumerate(paths):
            out = np.empty(1024, np.float32)  # aligned numel
            h.async_pread(out, p)
            h.wait()
            np.testing.assert_array_equal(out[:1000], tensors[i])


class TestNativeAdam:
    def test_adam_vs_numpy(self):
        from deepspeed_tpu.ops.adam.cpu_adam_native import NativeCPUAdam, native_adam_available

        if not native_adam_available():
            pytest.skip("no native adam")
        rs = np.random.RandomState(1)
        n = 10007
        p = rs.randn(n).astype(np.float32)
        g = rs.randn(n).astype(np.float32)
        m = np.zeros(n, np.float32)
        v = np.zeros(n, np.float32)
        p_ref, m_ref, v_ref = p.copy(), m.copy(), v.copy()
        opt = NativeCPUAdam(lr=1e-3, weight_decay=0.01, adamw_mode=True)
        for step in range(1, 5):
            opt.step(p, g, m, v, step=step)
            m_ref = 0.9 * m_ref + 0.1 * g
            v_ref = 0.999 * v_ref + 0.001 * g * g
            bc1, bc2 = 1 - 0.9**step, 1 - 0.999**step
            # torch-AdamW: decoupled decay lr*wd*p, unscaled by bias correction
            p_ref = p_ref - 1e-3 * 0.01 * p_ref
            p_ref = p_ref - 1e-3 / bc1 * (m_ref / (np.sqrt(v_ref) / np.sqrt(bc2) + 1e-8))
        assert np.abs(p - p_ref).max() < 1e-5

    def test_plain_adam_mode(self):
        from deepspeed_tpu.ops.adam.cpu_adam_native import NativeCPUAdam, native_adam_available

        if not native_adam_available():
            pytest.skip("no native adam")
        rs = np.random.RandomState(2)
        n = 4096
        p = rs.randn(n).astype(np.float32)
        g = rs.randn(n).astype(np.float32)
        m = np.zeros(n, np.float32)
        v = np.zeros(n, np.float32)
        p_ref = p.copy()
        opt = NativeCPUAdam(lr=1e-2, weight_decay=0.1, adamw_mode=False)
        opt.step(p, g, m, v, step=1)
        # L2-style decay folds into the gradient
        g_ref = g + 0.1 * p_ref
        m_ref = 0.1 * g_ref
        v_ref = 0.001 * g_ref * g_ref
        upd = m_ref / (np.sqrt(v_ref) / np.sqrt(1 - 0.999) + 1e-8)
        p_ref -= 1e-2 / (1 - 0.9) * upd
        assert np.abs(p - p_ref).max() < 1e-5
