"""Optimizer numerics vs torch reference (reference: tests/unit/ops/adam/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops import SGD, DeepSpeedCPUAdagrad, FusedAdam, FusedLamb
from deepspeed_tpu.ops.adam.fused_adam import Adam


def _run_ds(opt, params, grads_list, lr):
    state = opt.init_state(params)
    p = params
    for g in grads_list:
        p, state = jax.jit(opt.apply)(g, state, p, jnp.float32(lr))
    return p


def _torch_params_grads(shape=(7, 9), steps=5, seed=0):
    rs = np.random.RandomState(seed)
    p0 = rs.randn(*shape).astype(np.float32)
    grads = [rs.randn(*shape).astype(np.float32) for _ in range(steps)]
    return p0, grads


@pytest.mark.parametrize("adam_w_mode,weight_decay", [(True, 0.01), (False, 0.01), (True, 0.0)])
def test_fused_adam_matches_torch(adam_w_mode, weight_decay):
    torch = pytest.importorskip("torch")
    p0, grads = _torch_params_grads()
    lr = 1e-2

    ds_opt = FusedAdam(lr=lr, adam_w_mode=adam_w_mode, weight_decay=weight_decay)
    ds_final = _run_ds(ds_opt, {"p": jnp.asarray(p0)}, [{"p": jnp.asarray(g)} for g in grads], lr)

    tp = torch.nn.Parameter(torch.tensor(p0))
    cls = torch.optim.AdamW if adam_w_mode else torch.optim.Adam
    topt = cls([tp], lr=lr, betas=(0.9, 0.999), eps=1e-8, weight_decay=weight_decay)
    for g in grads:
        tp.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(np.asarray(ds_final["p"]), tp.detach().numpy(), rtol=2e-5, atol=2e-6)


def test_adagrad_matches_torch():
    torch = pytest.importorskip("torch")
    p0, grads = _torch_params_grads()
    lr = 1e-2
    ds_opt = DeepSpeedCPUAdagrad(lr=lr, eps=1e-10)
    ds_final = _run_ds(ds_opt, {"p": jnp.asarray(p0)}, [{"p": jnp.asarray(g)} for g in grads], lr)
    tp = torch.nn.Parameter(torch.tensor(p0))
    topt = torch.optim.Adagrad([tp], lr=lr, eps=1e-10)
    for g in grads:
        tp.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(np.asarray(ds_final["p"]), tp.detach().numpy(), rtol=2e-5, atol=2e-6)


def test_sgd_momentum_matches_torch():
    torch = pytest.importorskip("torch")
    p0, grads = _torch_params_grads()
    lr, mom = 1e-2, 0.9
    ds_final = _run_ds(SGD(lr=lr, momentum=mom), {"p": jnp.asarray(p0)}, [{"p": jnp.asarray(g)} for g in grads], lr)
    tp = torch.nn.Parameter(torch.tensor(p0))
    topt = torch.optim.SGD([tp], lr=lr, momentum=mom)
    for g in grads:
        tp.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(np.asarray(ds_final["p"]), tp.detach().numpy(), rtol=2e-5, atol=2e-6)


def test_lamb_trust_ratio_bounds():
    p0, grads = _torch_params_grads()
    lr = 1e-2
    opt = FusedLamb(lr=lr, max_coeff=10.0, min_coeff=0.01)
    final = _run_ds(opt, {"p": jnp.asarray(p0)}, [{"p": jnp.asarray(g)} for g in grads], lr)
    assert np.isfinite(np.asarray(final["p"])).all()
    assert not np.allclose(np.asarray(final["p"]), p0)


def test_state_specs_congruent():
    from jax.sharding import PartitionSpec as P

    opt = FusedAdam(lr=1e-3)
    params = {"a": jnp.zeros((4, 4)), "b": jnp.zeros((2,))}
    spec_tree = {"a": P("data", None), "b": P(None)}
    ss = opt.state_specs(spec_tree)
    assert ss.exp_avg["a"] == P("data", None)
    assert ss.step == P()
