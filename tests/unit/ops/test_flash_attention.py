"""Flash-attention numerics vs einsum reference (reference analog:
tests/unit/ops/transformer/). Runs the Pallas kernel in interpret mode on the
CPU mesh; the same code lowers to Mosaic on TPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.transformer.flash_attention import flash_attention


def ref_attn(q, k, v, causal=True):
    D = q.shape[-1]
    s = jnp.einsum("btnd,bsnd->bnts", q, k).astype(jnp.float32) / np.sqrt(D)
    if causal:
        T = q.shape[1]
        m = jnp.tril(jnp.ones((T, T), bool))
        s = jnp.where(m[None, None], s, -1e30)
    return jnp.einsum("bnts,bsnd->btnd", jax.nn.softmax(s, axis=-1).astype(v.dtype), v)


def _qkv(B=2, T=256, N=4, D=64, seed=0):
    rs = np.random.RandomState(seed)
    return [jnp.asarray(rs.randn(B, T, N, D), jnp.float32) for _ in range(3)]


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _qkv()
    o1 = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    o2 = ref_attn(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=2e-5)


def test_gradients_match_reference():
    q, k, v = _qkv()

    def l_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, block_q=128, block_k=128) ** 2)

    def l_ref(q, k, v):
        return jnp.sum(ref_attn(q, k, v) ** 2)

    g1 = jax.grad(l_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(l_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_padded_sequence():
    q, k, v = _qkv(T=200)  # not a multiple of the block
    o1 = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    o2 = ref_attn(q, k, v)
    assert o1.shape == q.shape
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=2e-5)


def test_single_block():
    q, k, v = _qkv(T=64)
    o1 = flash_attention(q, k, v, causal=True)
    o2 = ref_attn(q, k, v)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=2e-5)


def test_uneven_blocks():
    q, k, v = _qkv(T=384)
    o1 = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    o2 = ref_attn(q, k, v)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o2), atol=2e-5)


def test_model_uses_flash_when_enabled():
    from deepspeed_tpu.models import TransformerLM, llama_config

    cfg_on = llama_config("tiny", num_layers=2, flash_attention=True, remat=False)
    cfg_off = llama_config("tiny", num_layers=2, flash_attention=False, remat=False)
    rs = np.random.RandomState(0)
    toks = rs.randint(0, cfg_on.vocab_size, (2, 128)).astype(np.int32)
    m_on, m_off = TransformerLM(cfg_on), TransformerLM(cfg_off)
    params = m_on.init(jax.random.PRNGKey(0), toks)
    l_on = m_on.apply(params, (toks, toks), train=True)
    l_off = m_off.apply(params, (toks, toks), train=True)
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=1e-3)
