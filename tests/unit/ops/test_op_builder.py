"""Every registered op builder must be loadable.

Reference contract: ``op_builder/builder.py:102`` — ``OpBuilder.load()``
returns the op module; ``is_compatible()`` gates it. Round-4 verdict found 5
of 12 registry entries pointing at modules that did not exist (passing
``is_compatible`` then crashing on ``load``); this test pins the contract
for every entry in ``ALL_OPS``.
"""

import pytest

from deepspeed_tpu.ops.op_builder import ALL_OPS, get_builder


@pytest.mark.parametrize("name", sorted(ALL_OPS))
def test_builder_load(name):
    builder = ALL_OPS[name]()
    assert builder.name == name
    if not builder.is_compatible(verbose=False):
        # only the two host-native builders may legitimately report
        # incompatible (missing toolchain) — and then load() must raise,
        # not silently succeed
        assert name in ("cpu_adam", "async_io")
        with pytest.raises(Exception):
            builder.load(verbose=False)
        return
    module = builder.load(verbose=False)
    assert module is not None


def test_get_builder_lookup():
    assert get_builder("fused_adam") is ALL_OPS["fused_adam"]
    assert get_builder("definitely_not_an_op") is None


def test_utils_builder_flatten_roundtrip():
    import jax.numpy as jnp
    import numpy as np

    mod = ALL_OPS["utils"]().load(verbose=False)
    tensors = [jnp.arange(6.0).reshape(2, 3), jnp.ones((4,)), jnp.zeros((1, 1, 2))]
    flat = mod.flatten(tensors)
    assert flat.shape == (12,)
    out = mod.unflatten(flat, tensors)
    for a, b in zip(tensors, out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
