"""Block-sparse attention tests (reference: ``tests/unit/ops/sparse_attention/``)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    LocalSlidingWindowSparsityConfig,
    SparseSelfAttention,
    VariableSparsityConfig,
    block_sparse_attention,
)


def _dense_reference(q, k, v, mask=None, causal=False, scale=None):
    scale = scale or 1.0 / np.sqrt(q.shape[-1])
    scores = np.einsum("bhtd,bhsd->bhts", q, k).astype(np.float64) * scale
    T = q.shape[2]
    if causal:
        cm = np.tril(np.ones((T, T), bool))
        scores = np.where(cm, scores, -1e30)
    if mask is not None:
        scores = np.where(mask[:, None, None, :], scores, -1e30)
    p = np.exp(scores - scores.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhts,bhsd->bhtd", p, v)


class TestLayouts:
    def test_dense_all_ones(self):
        layout = DenseSparsityConfig(num_heads=2, block=8).make_layout(64)
        assert layout.shape == (2, 8, 8)
        assert layout.all()

    def test_fixed_local_blocks(self):
        cfg = FixedSparsityConfig(num_heads=2, block=8, num_local_blocks=2, attention="unidirectional")
        layout = cfg.make_layout(64)
        # diagonal always live; nothing above diagonal in causal mode
        for r in range(8):
            assert layout[0, r, r] == 1
        assert np.triu(layout[0], k=1).sum() == 0

    def test_bigbird_window_and_global(self):
        cfg = BigBirdSparsityConfig(num_heads=1, block=8, num_sliding_window_blocks=3, num_global_blocks=1)
        layout = cfg.make_layout(64)
        assert layout[0, 0].all()  # global row
        assert layout[0, :, 0].all()  # global col
        for r in range(1, 8):
            assert layout[0, r, r] == 1

    def test_longformer(self):
        cfg = BSLongformerSparsityConfig(num_heads=1, block=8, num_sliding_window_blocks=3)
        layout = cfg.make_layout(64)
        assert layout[0, :, 0].all() and layout[0, 0, :].all()

    def test_variable(self):
        cfg = VariableSparsityConfig(num_heads=1, block=8, local_window_blocks=[1, 2])
        layout = cfg.make_layout(64)
        assert layout[0].sum() > 0

    def test_local_sliding(self):
        cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=8, num_sliding_window_blocks=3)
        layout = cfg.make_layout(64)
        assert np.triu(layout[0], k=1).sum() == 0

    def test_indivisible_raises(self):
        with pytest.raises(ValueError):
            DenseSparsityConfig(num_heads=1, block=16).make_layout(70)


class TestBlockSparseAttention:
    def _qkv(self, B=2, NH=2, T=64, D=16, seed=0):
        rs = np.random.RandomState(seed)
        return (
            rs.randn(B, NH, T, D).astype(np.float32),
            rs.randn(B, NH, T, D).astype(np.float32),
            rs.randn(B, NH, T, D).astype(np.float32),
        )

    def test_dense_layout_matches_full_attention(self):
        q, k, v = self._qkv()
        layout = DenseSparsityConfig(num_heads=1, block=16).make_layout(64)[:1]
        out = np.asarray(
            block_sparse_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), layout, 16)
        )
        ref = _dense_reference(q, k, v)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)

    def test_causal_dense_matches(self):
        q, k, v = self._qkv()
        layout = DenseSparsityConfig(num_heads=1, block=16).make_layout(64)[:1]
        out = np.asarray(
            block_sparse_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), layout, 16, causal=True
            )
        )
        ref = _dense_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)

    def test_sparse_masks_dead_blocks(self):
        """Keys in dead blocks must not influence the output."""
        q, k, v = self._qkv(NH=1)
        cfg = LocalSlidingWindowSparsityConfig(num_heads=1, block=16, num_sliding_window_blocks=1)
        layout = cfg.make_layout(64)
        out1 = np.asarray(
            block_sparse_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), layout, 16, causal=True)
        )
        # perturb keys/values OUTSIDE each row's own block: no effect
        k2, v2 = k.copy(), v.copy()
        k2[:, :, :16] += 100.0
        v2[:, :, :16] -= 55.0
        out2 = np.asarray(
            block_sparse_attention(jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2), layout, 16, causal=True)
        )
        # rows in blocks >= 1 never see block 0 under a width-1 window
        np.testing.assert_allclose(out1[:, :, 16:], out2[:, :, 16:], rtol=1e-5)

    def test_module_surface(self):
        q, k, v = self._qkv(NH=4)
        attn = SparseSelfAttention(
            FixedSparsityConfig(num_heads=4, block=16, num_local_blocks=2, attention="unidirectional")
        )
        out = attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert out.shape == q.shape
        assert np.isfinite(np.asarray(out)).all()

    def test_key_padding_mask(self):
        q, k, v = self._qkv(NH=1)
        mask = np.ones((2, 64), bool)
        mask[:, 48:] = False  # padded tail
        layout = DenseSparsityConfig(num_heads=1, block=16).make_layout(64)[:1]
        out = np.asarray(
            block_sparse_attention(
                jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), layout, 16,
                key_padding_mask=jnp.asarray(mask),
            )
        )
        ref = _dense_reference(q, k, v, mask=mask)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)
