"""Monitor backend tests (reference: tests/unit/monitor/test_monitor.py).

csvMonitor writes per-metric files; MonitorMaster fans out; the engine emits
lr/train_loss events at steps_per_print boundaries.
"""

import csv
import os

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.monitor.monitor import MonitorMaster, TensorBoardMonitor, WandbMonitor, csvMonitor
from deepspeed_tpu.runtime.config import CSVConfig, MonitorConfig, TensorBoardConfig, WandbConfig
from tests.unit.simple_model import SimpleModel, random_dataloader


def _read_csv(path):
    with open(path, newline="") as f:
        return list(csv.reader(f))


def test_csv_monitor_writes_rows(tmp_path):
    mon = csvMonitor(CSVConfig(enabled=True, output_path=str(tmp_path), job_name="job"))
    assert mon.enabled
    mon.write_events([("Train/loss", 1.5, 0), ("Train/loss", 1.2, 1), ("Train/lr", 0.1, 0)])
    loss = _read_csv(tmp_path / "job" / "Train_loss.csv")
    assert loss[0] == ["step", "Train_loss"]
    assert [r[0] for r in loss[1:]] == ["0", "1"]
    assert float(loss[1][1]) == 1.5
    lr = _read_csv(tmp_path / "job" / "Train_lr.csv")
    assert len(lr) == 2


def test_csv_monitor_disabled_writes_nothing(tmp_path):
    mon = csvMonitor(CSVConfig(enabled=False, output_path=str(tmp_path), job_name="job"))
    assert not mon.enabled
    mon.write_events([("a", 1.0, 0)])
    assert not (tmp_path / "job").exists()


def test_master_fans_out_to_enabled_backends(tmp_path):
    cfg = MonitorConfig(
        tensorboard=TensorBoardConfig(enabled=False),
        wandb=WandbConfig(enabled=False),
        csv_monitor=CSVConfig(enabled=True, output_path=str(tmp_path), job_name="m"),
    )
    master = MonitorMaster(cfg)
    assert master.enabled
    master.write_events([("x", 2.0, 7)])
    rows = _read_csv(tmp_path / "m" / "x.csv")
    assert rows[1] == ["7", "2.0"]


def test_tensorboard_monitor_degrades_without_package(tmp_path):
    mon = TensorBoardMonitor(
        TensorBoardConfig(enabled=True, output_path=str(tmp_path), job_name="tb")
    )
    try:
        import torch.utils.tensorboard  # noqa: F401

        assert mon.enabled
        mon.write_events([("a/b", 1.0, 0)])
        assert any((tmp_path / "tb").iterdir())
    except ImportError:
        assert not mon.enabled  # warned and disabled, no crash
        mon.write_events([("a/b", 1.0, 0)])


def test_wandb_monitor_degrades_without_package():
    mon = WandbMonitor(WandbConfig(enabled=True, project="p"))
    try:
        import wandb  # noqa: F401
    except ImportError:
        assert not mon.enabled
        mon.write_events([("a", 1.0, 0)])


def test_engine_writes_monitor_events(tmp_path, eight_devices):
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "steps_per_print": 1,
            "csv_monitor": {"enabled": True, "output_path": str(tmp_path), "job_name": "run"},
        },
    )
    assert engine.monitor is not None and engine.monitor.enabled
    for batch in random_dataloader(total_samples=16, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    lr_rows = _read_csv(tmp_path / "run" / "Train_Samples_lr.csv")
    loss_rows = _read_csv(tmp_path / "run" / "Train_Samples_train_loss.csv")
    # one event per step (steps_per_print=1), keyed by global sample count
    assert len(lr_rows) == 3 and len(loss_rows) == 3
    assert float(lr_rows[1][1]) == pytest.approx(1e-2)
    assert np.isfinite(float(loss_rows[1][1]))
