"""Monitor backend tests (reference: tests/unit/monitor/test_monitor.py).

csvMonitor writes per-metric files; the torch-free JSONL backend is
default-on behind the ``monitor`` block's master switch; MonitorMaster fans
out; the engine emits lr/train_loss events — plus the observability hub's
periodic metric feed — at the configured cadence.
"""

import csv
import json
import os

import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.monitor.monitor import (
    JSONLMonitor,
    MonitorMaster,
    TensorBoardMonitor,
    WandbMonitor,
    csvMonitor,
)
from deepspeed_tpu.runtime.config import (
    CSVConfig,
    DeepSpeedConfig,
    JSONLConfig,
    MonitorConfig,
    TensorBoardConfig,
    WandbConfig,
)
from tests.unit.simple_model import SimpleModel, random_dataloader


def _read_csv(path):
    with open(path, newline="") as f:
        return list(csv.reader(f))


def test_csv_monitor_writes_rows(tmp_path):
    mon = csvMonitor(CSVConfig(enabled=True, output_path=str(tmp_path), job_name="job"))
    assert mon.enabled
    mon.write_events([("Train/loss", 1.5, 0), ("Train/loss", 1.2, 1), ("Train/lr", 0.1, 0)])
    loss = _read_csv(tmp_path / "job" / "Train_loss.csv")
    assert loss[0] == ["step", "Train_loss"]
    assert [r[0] for r in loss[1:]] == ["0", "1"]
    assert float(loss[1][1]) == 1.5
    lr = _read_csv(tmp_path / "job" / "Train_lr.csv")
    assert len(lr) == 2


def test_csv_monitor_disabled_writes_nothing(tmp_path):
    mon = csvMonitor(CSVConfig(enabled=False, output_path=str(tmp_path), job_name="job"))
    assert not mon.enabled
    mon.write_events([("a", 1.0, 0)])
    assert not (tmp_path / "job").exists()


def test_master_fans_out_to_enabled_backends(tmp_path):
    cfg = MonitorConfig(
        tensorboard=TensorBoardConfig(enabled=False),
        wandb=WandbConfig(enabled=False),
        csv_monitor=CSVConfig(enabled=True, output_path=str(tmp_path), job_name="m"),
    )
    master = MonitorMaster(cfg)
    assert master.enabled
    master.write_events([("x", 2.0, 7)])
    rows = _read_csv(tmp_path / "m" / "x.csv")
    assert rows[1] == ["7", "2.0"]


def test_tensorboard_monitor_degrades_without_package(tmp_path):
    mon = TensorBoardMonitor(
        TensorBoardConfig(enabled=True, output_path=str(tmp_path), job_name="tb")
    )
    try:
        import torch.utils.tensorboard  # noqa: F401

        assert mon.enabled
        mon.write_events([("a/b", 1.0, 0)])
        assert any((tmp_path / "tb").iterdir())
    except ImportError:
        assert not mon.enabled  # warned and disabled, no crash
        mon.write_events([("a/b", 1.0, 0)])


def test_wandb_monitor_degrades_without_package():
    mon = WandbMonitor(WandbConfig(enabled=True, project="p"))
    try:
        import wandb  # noqa: F401
    except ImportError:
        assert not mon.enabled
        mon.write_events([("a", 1.0, 0)])


def _read_jsonl(path):
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def test_jsonl_monitor_writes_parseable_lines(tmp_path):
    mon = JSONLMonitor(
        JSONLConfig(enabled=True, output_path=str(tmp_path), job_name="job"),
        master_enabled=True,
    )
    assert mon.enabled
    mon.write_events([("Train/loss", 1.5, 0), ("Train/lr", 0.1, 4)])
    mon.write_events([("Train/loss", 1.2, 8)])
    recs = _read_jsonl(tmp_path / "job" / "events.jsonl")
    assert [r["name"] for r in recs] == ["Train/loss", "Train/lr", "Train/loss"]
    assert recs[0] == {"name": "Train/loss", "value": 1.5, "step": 0, "t": recs[0]["t"]}
    assert all("t" in r for r in recs)


def test_jsonl_gated_on_master_switch(tmp_path):
    """jsonl.enabled defaults True but the backend only activates with the
    monitor block's master switch (or force=True) — legacy configs that
    never mention `monitor` keep writing nothing new."""
    cfg = JSONLConfig(enabled=True, output_path=str(tmp_path), job_name="j")
    assert not JSONLMonitor(cfg, master_enabled=False).enabled
    assert JSONLMonitor(cfg, master_enabled=False, force=True).enabled


def test_monitor_block_parses_and_defaults_jsonl_on(tmp_path):
    cfg = DeepSpeedConfig(
        {
            "train_micro_batch_size_per_gpu": 1,
            "monitor": {"enabled": True, "interval_steps": 3,
                        "jsonl": {"output_path": str(tmp_path)}},
        }
    )
    mc = cfg.monitor_config
    assert mc.enabled and mc.active and mc.interval_steps == 3
    assert mc.jsonl.enabled  # default-on behind the master switch
    master = MonitorMaster(mc)
    assert master.enabled and master.jsonl_monitor.enabled
    assert not master.csv_monitor.enabled
    # legacy top-level keys still reach the same config object
    legacy = DeepSpeedConfig(
        {
            "train_micro_batch_size_per_gpu": 1,
            "csv_monitor": {"enabled": True, "output_path": str(tmp_path)},
        }
    )
    assert legacy.monitor_config.active and not legacy.monitor_config.enabled


def test_monitor_block_rejects_typoed_keys(tmp_path):
    """The block is validated whole by pydantic: a typo'd key fails loudly
    instead of silently doing nothing."""
    with pytest.raises(Exception):
        DeepSpeedConfig(
            {"train_micro_batch_size_per_gpu": 1, "monitor": {"enable": True}}
        )
    # the `csv` alias inside the block is sanctioned
    cfg = DeepSpeedConfig(
        {
            "train_micro_batch_size_per_gpu": 1,
            "monitor": {"enabled": True,
                        "csv": {"enabled": True, "output_path": str(tmp_path)}},
        }
    )
    assert cfg.monitor_config.csv_monitor.enabled


def test_engine_monitor_block_jsonl_with_hub_feed(tmp_path, eight_devices):
    """The satellite acceptance: the `monitor` block alone (no legacy keys)
    wires the engine → MonitorMaster → JSONL, and the events include the
    observability hub's periodic metric feed (trace phase means + metric
    counters), every interval_steps optimizer steps."""
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "monitor": {"enabled": True, "interval_steps": 1,
                        "jsonl": {"output_path": str(tmp_path), "job_name": "run"}},
        },
    )
    assert engine.monitor is not None and engine.monitor.jsonl_monitor.enabled
    for batch in random_dataloader(total_samples=16, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    recs = _read_jsonl(tmp_path / "run" / "events.jsonl")
    names = {r["name"] for r in recs}
    assert "Train/Samples/train_loss" in names
    assert "Metrics/train.steps" in names  # the hub's metric feed
    assert any(n.startswith("Trace/train.dispatch") for n in names)
    steps_feed = [r["value"] for r in recs if r["name"] == "Metrics/train.steps"]
    assert steps_feed == [1.0, 2.0]  # interval_steps=1 → once per step


def test_engine_writes_monitor_events(tmp_path, eight_devices):
    engine, *_ = ds.initialize(
        model=SimpleModel(),
        config={
            "train_micro_batch_size_per_gpu": 8,
            "optimizer": {"type": "adam", "params": {"lr": 1e-2}},
            "bf16": {"enabled": True},
            "steps_per_print": 1,
            "csv_monitor": {"enabled": True, "output_path": str(tmp_path), "job_name": "run"},
        },
    )
    assert engine.monitor is not None and engine.monitor.enabled
    for batch in random_dataloader(total_samples=16, batch_size=8):
        loss = engine(batch)
        engine.backward(loss)
        engine.step()
    lr_rows = _read_csv(tmp_path / "run" / "Train_Samples_lr.csv")
    loss_rows = _read_csv(tmp_path / "run" / "Train_Samples_train_loss.csv")
    # one event per step (steps_per_print=1), keyed by global sample count
    assert len(lr_rows) == 3 and len(loss_rows) == 3
    assert float(lr_rows[1][1]) == pytest.approx(1e-2)
    assert np.isfinite(float(loss_rows[1][1]))
