"""Inference config surface (reference: tests/unit/inference/
test_inference_config.py): alias handling, legacy mp_size remap, dtype
parsing, and that init_inference accepts both kwargs and a config dict."""

import numpy as np
import pytest
from pydantic import ValidationError

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig, DtypeEnum


class TestConfigModel:
    def test_defaults(self):
        cfg = DeepSpeedInferenceConfig()
        assert cfg.dtype == DtypeEnum.bf16
        assert cfg.tensor_parallel.tp_size == 1
        assert cfg.max_out_tokens == 1024
        assert not cfg.replace_with_kernel_inject

    def test_aliases(self):
        cfg = DeepSpeedInferenceConfig(
            kernel_inject=True, tp={"tp_size": 4}, max_tokens=2048
        )
        assert cfg.replace_with_kernel_inject
        assert cfg.tensor_parallel.tp_size == 4
        assert cfg.max_out_tokens == 2048

    def test_legacy_mp_size_maps_to_tp(self):
        cfg = DeepSpeedInferenceConfig(mp_size=2)
        assert cfg.tensor_parallel.tp_size == 2

    def test_explicit_tp_wins_over_mp_size(self):
        cfg = DeepSpeedInferenceConfig(mp_size=2, tensor_parallel={"tp_size": 8})
        assert cfg.tensor_parallel.tp_size == 8

    def test_dtype_strings(self):
        for name in ("fp32", "fp16", "bf16", "int8"):
            assert DeepSpeedInferenceConfig(dtype=name).dtype == DtypeEnum(name)
        with pytest.raises(ValidationError):
            DeepSpeedInferenceConfig(dtype="fp64")

    def test_unknown_key_rejected(self):
        with pytest.raises(ValidationError):
            DeepSpeedInferenceConfig(definitely_not_a_key=1)


class TestInitInference:
    def _model(self):
        from deepspeed_tpu.models import TransformerLM, llama_config

        return TransformerLM(llama_config("tiny", num_layers=2, remat=False))

    def test_config_dict(self, eight_devices):
        mesh_mod.reset_topology()
        model = self._model()
        engine = ds.init_inference(model, config={"dtype": "bf16", "max_tokens": 128})
        toks = np.random.RandomState(0).randint(0, model.config.vocab_size, (2, 16)).astype(np.int32)
        engine.init_params(toks)
        out = engine(toks)
        assert out.shape == (2, 16, model.config.vocab_size)

    def test_kwargs_equiv(self, eight_devices):
        mesh_mod.reset_topology()
        model = self._model()
        engine = ds.init_inference(model, dtype="bf16", max_tokens=128)
        assert engine._config.max_out_tokens == 128
