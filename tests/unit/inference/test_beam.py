"""Beam search on the KV-cached decode path (reference reaches beams via HF
``generate``, deepspeed/inference/engine.py:578; here the whole search is one
compiled loop with on-device cache reordering — decode.py beam_generate)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.inference.decode import beam_generate, generate
from deepspeed_tpu.models import TransformerLM, llama_config

NEW = 8


@pytest.fixture(scope="module")
def model_and_params():
    mesh_mod.reset_topology()
    cfg = llama_config("tiny", num_layers=2, max_seq_len=64, vocab_size=128)
    model = TransformerLM(cfg)
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 128, (2, 6)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)
    return cfg, model, params, prompt


def _seq_logprob(model, params, seq, prompt_len):
    """Σ log p(token | prefix) over the generated part, full forward."""
    logits = model.apply(params, jnp.asarray(seq), train=False)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    total = 0.0
    out = []
    for b in range(seq.shape[0]):
        s = 0.0
        for t in range(prompt_len, seq.shape[1]):
            s += float(logp[b, t - 1, int(seq[b, t])])
        out.append(s)
    return out


def test_beam1_equals_greedy(model_and_params):
    cfg, model, params, prompt = model_and_params
    greedy = np.asarray(generate(cfg, params, prompt, NEW))
    beam1 = np.asarray(beam_generate(cfg, params, prompt, NEW, num_beams=1))
    np.testing.assert_array_equal(beam1, greedy)


def test_beam4_scores_at_least_greedy(model_and_params):
    """With no length penalty and no EOS, the beam-4 sequence's joint
    logprob must be >= the greedy sequence's (beam search explores a
    superset of greedy's single path)."""
    cfg, model, params, prompt = model_and_params
    greedy = np.asarray(generate(cfg, params, prompt, NEW))
    beam = np.asarray(
        beam_generate(cfg, params, prompt, NEW, num_beams=4, length_penalty=0.0)
    )
    assert beam.shape == greedy.shape
    g_scores = _seq_logprob(model, params, greedy, prompt.shape[1])
    b_scores = _seq_logprob(model, params, beam, prompt.shape[1])
    for g, b in zip(g_scores, b_scores):
        assert b >= g - 1e-3, (g, b)


def test_beam_eos_stops(model_and_params):
    cfg, model, params, prompt = model_and_params
    # pick the greedy first token of row 0 as "EOS": beams finish fast and
    # the loop must EXIT EARLY (strictly fewer than NEW emitted tokens)
    greedy = np.asarray(generate(cfg, params, prompt, NEW))
    eos = int(greedy[0, prompt.shape[1]])
    out = np.asarray(
        beam_generate(
            cfg, params, prompt, NEW, num_beams=3, eos_token_id=eos, pad_token_id=0
        )
    )
    assert out.shape[0] == 2
    assert out.shape[1] < prompt.shape[1] + NEW, "no early exit on EOS"
    # row 0's returned hypothesis actually ends in EOS
    gen0 = out[0, prompt.shape[1]:]
    assert eos in gen0.tolist()


def test_engine_generate_num_beams(model_and_params):
    import deepspeed_tpu as ds

    cfg, model, params, prompt = model_and_params
    mesh_mod.reset_topology()
    engine = ds.init_inference(model, dtype="fp32")
    engine.set_params(params)
    engine._ds_config = cfg  # converted-family contract (containers set this)
    out = np.asarray(engine.generate(prompt, max_new_tokens=4, num_beams=2))
    assert out.shape[0] == 2
    with pytest.raises(ValueError, match="deterministic"):
        engine.generate(prompt, max_new_tokens=4, num_beams=2, temperature=0.7)
