"""Speculative decoding for the paged serving engine (ISSUE 4).

Load-bearing checks: speculation-on serving is token-exact against
speculation-off serving AND the dense lockstep ``decode.generate`` across
occupancy levels, mid-stream admission, eviction, and
preemption-with-recompute; every speculative round is exactly ONE verify
dispatch; compiled programs stay bounded by
``len(slot_buckets) × len(spec_lens)`` + decode buckets + prefill
programs. Injected oracle drafters drive the accept-all / partial-accept /
reject-all verification paths deterministically (the n-gram drafter's hit
rate depends on the model's output, which a random init doesn't pin down).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.inference.spec_decode import Drafter, NGramDrafter
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA on the serving path
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _prompts(n, seed=0, lo=3, hi=20):
    rs = np.random.RandomState(seed)
    return [
        rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)
        for _ in range(n)
    ]


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, **kw):
    # this suite exercises the BUCKETED verify programs (the ragged path's
    # token-exactness oracle); ragged speculation is covered by
    # test_ragged_serving.py and the engine-surface test below
    kw.setdefault("ragged", False)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    return PagedServer(cfg, params, **kw)


class OracleDrafter(Drafter):
    """Drafts each request's precomputed dense-greedy future — acceptance
    is total by construction. ``corrupt_at`` flips that index of every
    proposal, pinning the accepted-prefix length to it exactly."""

    def __init__(self, futures, corrupt_at=None, vocab=128):
        self.futures = futures  # uid -> full dense output (prompt + budget)
        self.corrupt_at = corrupt_at
        self.vocab = vocab

    def propose(self, uid, context, k):
        cont = self.futures[uid][context.size : context.size + k].copy()
        if self.corrupt_at is not None and cont.size > self.corrupt_at:
            cont[self.corrupt_at] = (cont[self.corrupt_at] + 1) % self.vocab
        return cont.astype(np.int32)


class ConstantDrafter(Drafter):
    """Always proposes the same token — a reject-(almost-)all workload that
    still forces a verify dispatch every round."""

    def __init__(self, token=0, k=None):
        self.token = int(token)
        self.k = k

    def propose(self, uid, context, k):
        k = k if self.k is None else min(k, self.k)
        return np.full(k, self.token, np.int32)


# --- drafter unit behavior --------------------------------------------------
def test_ngram_drafter_prompt_lookup():
    d = NGramDrafter(ngram_order=3)
    ctx = np.array([5, 6, 7, 5, 6, 7, 5, 6], np.int32)
    # suffix (7, 5, 6) last occurred at position 2 -> continuation [7, 5, 6]
    np.testing.assert_array_equal(d.propose(0, ctx, 4), [7, 5, 6])
    np.testing.assert_array_equal(d.propose(0, ctx, 2), [7, 5])  # k clamps
    # no repeated suffix anywhere: nothing proposed
    assert d.propose(1, np.array([1, 2, 3, 4], np.int32), 4).size == 0
    # falls back to shorter orders when the long suffix is novel
    np.testing.assert_array_equal(
        d.propose(2, np.array([9, 1, 2, 9, 8, 2], np.int32), 2), [9, 8]
    )


def test_ngram_drafter_state_is_per_request():
    d = NGramDrafter(ngram_order=2)
    a = np.array([1, 2, 1, 2], np.int32)
    b = np.array([7, 7, 7], np.int32)
    assert d.propose(0, a, 3).size == 2  # [1, 2]
    # the only earlier (7, 7) occurrence has one token of future left
    np.testing.assert_array_equal(d.propose(1, b, 3), [7])
    d.drop(0)
    assert 0 not in d._state and 1 in d._state
    # context grows incrementally between rounds (the serving pattern)
    a2 = np.concatenate([a, np.array([1], np.int32)])
    np.testing.assert_array_equal(d.propose(0, a2, 2), [2, 1])


def test_ngram_drafter_rejects_bad_order():
    with pytest.raises(ValueError, match="ngram_order"):
        NGramDrafter(ngram_order=0)


# --- token-exactness ---------------------------------------------------------
def test_spec_full_acceptance_matches_dense(model_and_params):
    """Oracle drafts (the true greedy future): every draft accepted, output
    byte-identical to dense AND to speculation-off serving, across more
    requests than slots."""
    cfg, _, params = model_and_params
    prompts = _prompts(6, seed=2)
    budgets = [10, 3, 7, 12, 1, 5]
    futures = {i: _dense(cfg, params, p, n) for i, (p, n) in enumerate(zip(prompts, budgets))}
    server = _server(cfg, params, drafter=OracleDrafter(futures))
    outs = server.serve(prompts, max_new_tokens=budgets)
    off = _server(cfg, params).serve(prompts, max_new_tokens=budgets)
    for p, n, out, out_off in zip(prompts, budgets, outs, off):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, n))
        np.testing.assert_array_equal(out, out_off)
    st = server.serve_stats()
    assert st["spec_rounds"] >= 1
    assert st["spec_accepted"] == st["spec_drafted"] > 0
    assert st["spec_accept_rate"] == 1.0
    # speculation finished the mix in fewer dispatches than one-per-token
    assert st["spec_rounds"] + st["decode_steps"] < sum(budgets)
    assert server.pool.used_pages() == 0 and server.pool.live_tokens() == 0


def test_spec_partial_acceptance_and_rejection(model_and_params):
    """Corrupted oracles pin the accepted prefix below the draft length;
    outputs must still be exact and the rejected tail's pages must all
    come back (the pool drains to zero)."""
    cfg, _, params = model_and_params
    prompts = _prompts(4, seed=3)
    futures = {i: _dense(cfg, params, p, 9) for i, p in enumerate(prompts)}
    for corrupt_at in (0, 2):
        server = _server(
            cfg, params, drafter=OracleDrafter(futures, corrupt_at=corrupt_at)
        )
        outs = server.serve(prompts, max_new_tokens=9)
        for p, out in zip(prompts, outs):
            np.testing.assert_array_equal(out, _dense(cfg, params, p, 9))
        st = server.serve_stats()
        assert st["spec_rounds"] >= 1
        assert st["spec_accepted"] < st["spec_drafted"]
        # a corrupted index caps every round's accepted prefix at that index
        assert all(
            n == 0 for i, n in enumerate(st["spec_accept_hist"]) if i > corrupt_at
        )
        assert server.pool.used_pages() == 0 and server.pool.live_tokens() == 0


def test_spec_ngram_serving_matches_dense(model_and_params):
    """The real model-free drafter end to end: long budgets let greedy
    outputs go periodic, so the n-gram lookup actually drafts — and the
    stream stays exact."""
    cfg, _, params = model_and_params
    server = _server(
        cfg, params,
        spec_decode={"enable": True, "max_draft": 4, "ngram_order": 3},
    )
    prompts = _prompts(4, seed=5, lo=4, hi=10)
    outs = server.serve(prompts, max_new_tokens=40)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 40))
    st = server.serve_stats()
    assert st["spec_rounds"] >= 1, "n-gram drafter never engaged"
    assert st["spec_accepted"] >= 1


def test_spec_admission_mid_stream(model_and_params):
    """Requests submitted while speculative rounds are in flight join
    without disturbing the streams."""
    cfg, _, params = model_and_params
    prompts = _prompts(4, seed=6)
    futures = {i: _dense(cfg, params, p, 12) for i, p in enumerate(prompts)}
    server = _server(cfg, params, drafter=OracleDrafter(futures))
    first = [server.submit(p, max_new_tokens=12) for p in prompts[:2]]
    for _ in range(3):
        server.step()
    assert server.stats["spec_rounds"] >= 1
    late = [server.submit(p, max_new_tokens=12) for p in prompts[2:]]
    results = server.run()
    for uid, p in zip(first + late, prompts):
        np.testing.assert_array_equal(results[uid], _dense(cfg, params, p, 12))


def test_spec_preemption_token_exact(model_and_params):
    """An undersized pool forces preemption while drafts are widening each
    row's page demand; recompute on re-admission must stay exact."""
    cfg, _, params = model_and_params
    prompts = _prompts(4, seed=4, lo=6, hi=14)
    futures = {i: _dense(cfg, params, p, 12) for i, p in enumerate(prompts)}
    server = _server(
        cfg, params, page_size=4, num_pages=14, max_slots=3, prefill_chunk=8,
        drafter=OracleDrafter(futures),
    )
    outs = server.serve(prompts, max_new_tokens=12)
    assert server.stats["preempted"] >= 1, "pool was sized to force preemption"
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 12))


def test_spec_eos_inside_accepted_run(model_and_params):
    """EOS landing inside an accepted draft run must retire the request at
    the EOS token exactly like sequential decode."""
    cfg, _, params = model_and_params
    prompts = _prompts(2, seed=7)
    futures = {i: _dense(cfg, params, p, 10) for i, p in enumerate(prompts)}
    # an EOS the oracle will draft: request 0's 3rd generated token
    eos = int(futures[0][prompts[0].size + 2])
    server = _server(cfg, params, drafter=OracleDrafter(futures))
    outs = server.serve(prompts, max_new_tokens=10, eos_token_id=eos)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 10, eos=eos))


def test_spec_draft_clamped_to_budget(model_and_params):
    """A drafter offering more than the remaining budget must be clamped:
    a 1-token request decodes plainly (no verify), and no output ever
    exceeds max_new_tokens."""
    cfg, _, params = model_and_params
    server = _server(cfg, params, drafter=ConstantDrafter(token=1))
    prompts = _prompts(3, seed=8)
    outs = server.serve(prompts, max_new_tokens=[1, 2, 6])
    for p, n, out in zip(prompts, [1, 2, 6], outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, n))
        assert out.size == p.size + n


def test_spec_16_request_ragged_mix_under_pool_pressure(model_and_params):
    """The bench-shaped acceptance mix: 16 ragged requests through 4 slots
    with an undersized pool (preemption fires), speculation on — the
    stream must match speculation-off paged serving AND dense generate,
    request for request."""
    cfg, _, params = model_and_params
    prompts = _prompts(16, seed=14, lo=3, hi=12)
    budgets = [max(1, 10 - (i * 10) // 32) for i in range(16)]  # ragged
    futures = {
        i: _dense(cfg, params, p, n) for i, (p, n) in enumerate(zip(prompts, budgets))
    }
    kw = dict(page_size=4, num_pages=14, max_slots=4, prefill_chunk=8)
    spec = _server(cfg, params, drafter=OracleDrafter(futures, corrupt_at=1), **kw)
    outs = spec.serve(prompts, max_new_tokens=budgets)
    off = _server(cfg, params, **kw).serve(prompts, max_new_tokens=budgets)
    for i, (p, n, a, b) in enumerate(zip(prompts, budgets, outs, off)):
        np.testing.assert_array_equal(a, futures[i])
        np.testing.assert_array_equal(a, b)
    st = spec.serve_stats()
    assert st["finished"] == 16 and st["spec_rounds"] >= 1
    assert spec.stats["preempted"] >= 1, "pool was sized to force preemption"
    assert spec.pool.used_pages() == 0 and spec.pool.live_tokens() == 0


# --- dispatch & compile budget ----------------------------------------------
def test_one_dispatch_per_spec_round_and_compile_bound(model_and_params):
    """3-wave schedule through one telemetry: exactly one paged_verify
    dispatch per speculative round, one paged_decode dispatch per plain
    step, and compiles bounded by buckets × spec_lens (+ decode buckets +
    prefill programs)."""
    cfg, _, params = model_and_params
    telemetry = CompileTelemetry()
    waves = [_prompts(2, seed=10), _prompts(4, seed=11), _prompts(2, seed=12)]
    futures = {}
    uid = 0
    for wave in waves:
        for p in wave:
            futures[uid] = _dense(cfg, params, p, 6)
            uid += 1
    server = _server(
        cfg, params, max_slots=4, telemetry=telemetry,
        spec_decode={"spec_lens": [2, 4], "max_draft": 4},
        drafter=OracleDrafter(futures),
    )
    for wave in waves:
        outs = server.serve(wave, max_new_tokens=6)
        for p, out in zip(wave, outs):
            np.testing.assert_array_equal(out, _dense(cfg, params, p, 6))
    stats = telemetry.stats()
    paged = {k: v for k, v in stats.items() if k.startswith("paged_")}
    verify = {k: v for k, v in paged.items() if k.startswith("paged_verify_")}
    assert verify, f"no verify programs dispatched: {list(stats)}"
    for name, rec in paged.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    # exactly ONE device dispatch per speculative round / decode step
    assert sum(r["dispatches"] for r in verify.values()) == server.stats["spec_rounds"]
    assert sum(
        r["dispatches"] for k, r in paged.items() if k.startswith("paged_decode_")
    ) == server.stats["decode_steps"]
    # program count bounded by the bucket × spec-length grid, not traffic
    n_buckets, n_lens = len(server.buckets), len(server.spec_lens)
    assert len(verify) <= n_buckets * n_lens
    assert len(paged) <= n_buckets * n_lens + n_buckets + 1  # + prefill chunk


def test_spec_round_pages_roll_back(model_and_params):
    """Pool accounting mid-stream: after a reject-all verify round the
    pool must hold exactly the accepted tokens (tail pages freed), not the
    full drafted width."""
    cfg, _, params = model_and_params
    server = _server(cfg, params, page_size=4, drafter=ConstantDrafter(token=3))
    prompt = _prompts(1, seed=13, lo=5, hi=6)[0]  # one prefill chunk
    uid = server.submit(prompt, max_new_tokens=12)
    server.step()  # prefill + the FIRST speculative round in one step
    assert server.stats["spec_rounds"] == 1
    req = server._active[0]
    acc = server.stats["spec_accepted"]
    got = int(server.pool.seq_lens[req.slot])
    # live tokens = prompt + accepted drafts + bonus; the drafted-but-
    # rejected tail's pages are back in the free list
    assert got == prompt.size + acc + 1
    assert server.pool._owned[req.slot] == server.pool.pages_for(got)
    server.step()
    assert server.stats["spec_rounds"] == 2 and not req.done
    got2 = int(server.pool.seq_lens[req.slot])
    assert got2 == got + (server.stats["spec_accepted"] - acc) + 1
    assert server.pool._owned[req.slot] == server.pool.pages_for(got2)
    server.run()
    assert server.result(uid) is not None


# --- engine surface ----------------------------------------------------------
def test_engine_spec_serve_and_stats(model_and_params):
    """inference.spec_decode config knobs through init_inference: exact
    output, spec observability in engine.serve_stats()."""
    cfg, model, params = model_and_params
    engine = ds.init_inference(
        model,
        dtype="fp32",
        paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8, "attn_impl": "xla"},
        spec_decode={"enable": True, "max_draft": 4, "ngram_order": 3},
    )
    engine.set_params(params)
    engine._ds_config = cfg  # converted-family contract (containers set this)
    prompts = _prompts(3, seed=9, lo=4, hi=10)
    outs = engine.serve(prompts, max_new_tokens=24)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 24))
    st = engine.serve_stats()
    for key in (
        "spec_rounds", "spec_accept_rate", "spec_mean_accepted_per_round",
        "spec_accept_hist", "pool_utilization",
    ):
        assert key in st, key
    assert st["finished"] == 3
    assert len(st["spec_accept_hist"]) == 5  # 0..max_draft
