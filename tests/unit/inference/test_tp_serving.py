"""Multi-chip tensor-parallel serving (ISSUE 13): CPU-mesh parity suite.

The whole test session runs on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``, tests/conftest.py), so the
sharded ragged programs here exercise the SAME ``shard_map``/GSPMD code
paths a TPU pod runs. Load-bearing checks:

* **byte-identical greedy streams** at tp ∈ {1, 2, 4} (fp32 weights, fp
  all-reduces) against the single-chip ragged oracle AND the dense
  lockstep ``decode.generate`` — across mid-stream admission, recompute
  preemption, prefix-cache attach, per-request spec-K verify rows, and
  fused multi-step windows;
* the serving invariants hold ON THE MESH: ≤ 2 compiled ``paged_*``
  programs, exactly 1 dispatch per scheduler step, no retrace across
  shifting waves (the analysis-side gate is
  ``test_passes.py::test_green_tp_serving``);
* the **int8 weight** contract: elementwise roundtrip error ≤
  ``max|w_channel| / 254`` (the documented bound), logits allclose within
  the bound's linear propagation, serving runs end-to-end;
* the **quantized all-reduce** contract: allclose to the fp ``psum``
  within the two-stage symmetric-int8 error model (NOT byte-identical —
  the knob trades exactness for 4x less wire traffic).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.compression.int8 import (
    QuantizedTensor,
    dequantize,
    qmatmul,
    quantize_params_int8,
    quantize_weight_int8,
)
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.scheduler import PagedServer, compiled_serving_programs
from deepspeed_tpu.inference.spec_decode import Drafter
from deepspeed_tpu.inference.tp import TPServing, quantized_all_reduce, serving_mesh
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry
from deepspeed_tpu.utils.jax_compat import shard_map

# MHA config: head axes divide by 4 so the same weights serve tp ∈ {1,2,4}
CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=4,
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _prompts(n, seed=0, lo=3, hi=20):
    rs = np.random.RandomState(seed)
    return [
        rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)
        for _ in range(n)
    ]


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, tp=None, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    return PagedServer(cfg, params, tp=tp, **kw)


def _tp(degree, **kw):
    return TPServing(mesh=serving_mesh(degree), **kw)


class MixDrafter(Drafter):
    """Row uid drafts uid % 3 tokens — rounds carry 0/1/2-draft rows at
    once, so verify resolution (global argmax + accepted prefix) runs on
    genuinely ragged spec-K rows under the sharded program."""

    def propose(self, uid, context, k):
        return np.arange(min(k, uid % 3), dtype=np.int32)


# --- byte-identical parity on the mesh --------------------------------------
@pytest.mark.parametrize("degree", [1, 2, 4])
def test_tp_matches_single_chip_mixed_serve(model_and_params, degree):
    """The acceptance core: tp ∈ {1,2,4} greedy streams byte-identical to
    the single-chip ragged oracle (and dense), with the compile/dispatch
    budget intact on the mesh — ≤ 2 paged programs, 1 dispatch/step, no
    retrace between waves."""
    cfg, _, params = model_and_params
    prompts = _prompts(6, seed=2)
    budgets = [10, 3, 7, 12, 1, 5]
    oracle = _server(cfg, params).serve(prompts, max_new_tokens=budgets)
    tel = CompileTelemetry()
    srv = _server(cfg, params, tp=_tp(degree), telemetry=tel)
    outs = srv.serve(prompts[:3], max_new_tokens=budgets[:3])
    compiles_w1 = sum(r["compiles"] for r in tel.stats().values())
    outs += srv.serve(prompts[3:], max_new_tokens=budgets[3:])  # wave 2
    for p, n, a, b in zip(prompts, budgets, outs, oracle):
        np.testing.assert_array_equal(a, b)
        np.testing.assert_array_equal(a, _dense(cfg, params, p, n))
    stats = tel.stats()
    assert compiled_serving_programs(stats) <= 2, stats.keys()
    assert sum(r["compiles"] for r in stats.values()) == compiles_w1, (
        "wave 2 retraced a sharded program"
    )
    assert sum(r["dispatches"] for r in stats.values()) == srv.stats["ragged_steps"]
    assert srv.serve_stats()["tp_degree"] == degree
    assert srv.pool.used_pages() == 0 and srv.pool.live_tokens() == 0


def test_tp_gqa_kv_head_shard(model_and_params):
    """GQA under the kv-head split: NKV=2 shards 1 kv head per chip at
    tp=2 while each chip keeps its 2 query heads — the group size is
    invariant and streams stay byte-identical."""
    cfg = TransformerConfig(**{**CFG, "num_kv_heads": 2})
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(3), toks)
    prompts = _prompts(4, seed=6)
    ref = _server(cfg, params).serve(prompts, max_new_tokens=8)
    got = _server(cfg, params, tp=_tp(2)).serve(prompts, max_new_tokens=8)
    for a, b in zip(got, ref):
        np.testing.assert_array_equal(a, b)


def test_tp_preemption_parity(model_and_params):
    """Recompute preemption under an undersized pool is pure host logic —
    the sharded path must preempt and resume byte-identically (page
    tables are replicated; only page contents shard)."""
    cfg, _, params = model_and_params
    kw = dict(page_size=4, num_pages=14, max_slots=3, prefill_chunk=8)
    prompts = _prompts(4, seed=4, lo=6, hi=14)
    srv = _server(cfg, params, tp=_tp(2), **kw)
    outs = srv.serve(prompts, max_new_tokens=12)
    assert srv.stats["preempted"] >= 1, "pool was sized to force preemption"
    for p, a in zip(prompts, outs):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, 12))
    assert srv.pool.used_pages() == 0


def test_tp_prefix_cache_attach_parity(model_and_params):
    """Prefix attach + CoW ride the sharded pools untouched: the barrier's
    donated page copy runs on the kv-head-sharded arrays, hits register,
    and streams stay byte-identical to sharing-off serving."""
    cfg, _, params = model_and_params
    rs = np.random.RandomState(21)
    sys_tokens = rs.randint(0, 128, (19,)).astype(np.int32)
    prompts = [
        np.concatenate([sys_tokens, rs.randint(0, 128, (3 + i,)).astype(np.int32)])
        for i in range(4)
    ]
    srv = _server(cfg, params, tp=_tp(2), prefix_cache=True)
    first = srv.serve(prompts[:1], max_new_tokens=4)
    rest = srv.serve(prompts[1:], max_new_tokens=4)
    assert srv.pool.stats["prefix_hit_pages"] > 0, "prefix cache never engaged"
    oracle = _server(cfg, params, prefix_cache=False).serve(prompts, max_new_tokens=4)
    for a, b in zip(first + rest, oracle):
        np.testing.assert_array_equal(a, b)


def test_tp_spec_decode_parity(model_and_params):
    """Per-request spec-K verify rows resolve through the GLOBAL argmax on
    the mesh (vocab-sharded logits): accepted prefixes and bonus tokens
    must match spec-off single-chip serving byte-for-byte."""
    cfg, _, params = model_and_params
    prompts = _prompts(4, seed=5)
    ref = _server(cfg, params).serve(prompts, max_new_tokens=8)
    srv = _server(
        cfg, params, tp=_tp(2),
        spec_decode={"max_draft": 2}, drafter=MixDrafter(),
    )
    outs = srv.serve(prompts, max_new_tokens=8)
    assert srv.stats["spec_rounds"] >= 1, "the mix never drafted"
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)


def test_tp_multistep_window_parity(model_and_params):
    """Fused multi-step windows on the mesh: the scan-of-rounds program
    shards like the single-step one (per-round all-reduces inside the
    scan), windows form, and streams stay byte-identical."""
    cfg, _, params = model_and_params
    prompts = _prompts(3, seed=7, lo=4, hi=9)
    ref = _server(cfg, params).serve(prompts, max_new_tokens=13)
    srv = _server(
        cfg, params, tp=_tp(2), multi_step={"enable": True, "horizon": 4},
    )
    outs = srv.serve(prompts, max_new_tokens=13)
    assert srv.stats["window_steps"] >= 1, "no window formed"
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)


# --- config / validation red tests ------------------------------------------
def test_tp_requires_ragged_and_divisibility(model_and_params):
    cfg, _, params = model_and_params
    with pytest.raises(ValueError, match="ragged"):
        _server(cfg, params, tp=_tp(2), ragged=False)
    bad = TransformerConfig(**{**CFG, "num_heads": 6, "num_kv_heads": 3})
    with pytest.raises(ValueError, match="divide"):
        _server(bad, params, tp=_tp(4))
    from deepspeed_tpu.inference.config import DeepSpeedInferenceConfig

    with pytest.raises(Exception, match="ragged"):
        DeepSpeedInferenceConfig(
            paged_kv={"ragged": False, "sharded": {"tp_degree": 2}}
        )
    # the FOLLOW mode (sharded.tp_degree=0 defers to tensor_parallel) with
    # the bucketed oracle stays VALID — tp_size also drives the dense
    # generate path, and pre-sharded-serving configs used exactly this
    # combination. The engine falls back to single-chip bucketed serving.
    follow = DeepSpeedInferenceConfig(
        tensor_parallel={"tp_size": 2}, paged_kv={"ragged": False}
    )
    assert follow.paged_kv.sharded.tp_degree == 0
    engine = ds.init_inference(
        TransformerLM(cfg), dtype="fp32", tensor_parallel={"tp_size": 2},
        paged_kv={"ragged": False, "page_size": 8, "max_slots": 4,
                  "prefill_chunk": 8, "attn_impl": "xla"},
    )
    engine.set_params(params)
    engine._ds_config = cfg
    assert engine._build_paged_server().tp is None  # single-chip fallback
    with pytest.raises(Exception, match="weight_quant_bits"):
        DeepSpeedInferenceConfig(paged_kv={"sharded": {"weight_quant_bits": 4}})


def test_tp_engine_knob_routing(model_and_params):
    """`paged_kv.sharded.tp_degree` routes through the engine: the built
    server runs the sharded programs and reports its degree."""
    cfg, _, params = model_and_params
    engine = ds.init_inference(
        TransformerLM(cfg),
        dtype="fp32",
        paged_kv={
            "page_size": 8, "max_slots": 4, "prefill_chunk": 8,
            "attn_impl": "xla", "sharded": {"tp_degree": 2},
        },
    )
    engine.set_params(params)
    engine._ds_config = cfg
    prompts = _prompts(2, seed=8)
    outs = engine.serve(prompts, max_new_tokens=4)
    assert all(o is not None for o in outs)
    st = engine.serve_stats()
    assert st["tp_degree"] == 2 and st["finished"] == 2
    ref = _server(cfg, params).serve(prompts, max_new_tokens=4)
    for a, b in zip(outs, ref):
        np.testing.assert_array_equal(a, b)


# --- int8 weights: the documented tolerance contract ------------------------
def test_int8_weight_roundtrip_bound(model_and_params):
    """The documented bound: per-output-channel symmetric int8 means
    ``|w - dequant(quant(w))| <= max|w_channel| / 254`` elementwise, and
    the fused-epilogue matmul equals the dequantize-then-matmul form."""
    cfg, _, params = model_and_params
    w = np.asarray(params["layers"]["wq"])  # stacked [L, H, NH*D]
    qt = quantize_weight_int8(w)
    assert isinstance(qt, QuantizedTensor) and qt.q.dtype == jnp.int8
    deq = np.asarray(dequantize(qt))
    bound = np.max(np.abs(w), axis=-2, keepdims=True) / 254.0 + 1e-7
    assert np.all(np.abs(w - deq) <= bound), (
        f"roundtrip exceeded max|w_channel|/254: "
        f"{np.max(np.abs(w - deq) / bound)}x the bound"
    )
    h = np.asarray(
        jax.random.normal(jax.random.PRNGKey(5), (3, w.shape[1]), jnp.float32)
    )
    fused = np.asarray(qmatmul(jnp.asarray(h), QuantizedTensor(qt.q[0], qt.scale[0])))
    explicit = h @ deq[0]
    np.testing.assert_allclose(fused, explicit, rtol=1e-5, atol=1e-5)


def test_int8_weights_logits_allclose_and_serving(model_and_params):
    """End-to-end int8 contract: logits of the quantized model are
    allclose to fp within the bound's linear propagation (each matmul's
    weight error ≤ 1/254 of the channel max ⇒ ~1% activations at these
    dims), and a sharded serve with int8 weights runs to completion with
    full streams."""
    cfg, _, params = model_and_params
    qparams = quantize_params_int8(params)
    assert isinstance(qparams["layers"]["wq"], QuantizedTensor)
    assert not isinstance(qparams["embed"]["tokens"], QuantizedTensor)
    prompt = _prompts(1, seed=11, lo=10, hi=11)[0]

    def logits_of(p):
        from deepspeed_tpu.inference.decode import _forward_with_cache, init_cache

        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        out, _ = _forward_with_cache(cfg, p, jnp.asarray(prompt[None]), cache, jnp.int32(0))
        return np.asarray(out)

    lf, lq = logits_of(params), logits_of(qparams)
    # ~1e-2 relative on the logit SCALE (max|logits|): 4 quantized matmuls
    # per layer × 2 layers, each contributing ≲ 1/254 relative weight error
    tol = 1e-2 * np.max(np.abs(lf))
    np.testing.assert_allclose(lq, lf, atol=tol)
    srv = _server(cfg, qparams, tp=_tp(2))
    outs = srv.serve([prompt], max_new_tokens=6)
    assert outs[0].size == prompt.size + 6 and srv.stats["finished"] == 1


# --- quantized all-reduce: the EQuARX exchange ------------------------------
def test_quantized_allreduce_allclose():
    """The quantized exchange vs the fp psum it replaces: two symmetric
    int8 stages bound the relative error at ~2/127 of the per-chunk max;
    assert well inside that (and exact shape/dtype preservation)."""
    degree = 4
    mesh = serving_mesh(degree)
    rs = np.random.RandomState(0)
    partials = jnp.asarray(rs.randn(degree, 3, 5, 16).astype(np.float32))
    from jax.sharding import PartitionSpec as P

    def run(fn):
        sm = shard_map(
            lambda xs: fn(xs[0]),
            mesh=mesh, in_specs=(P("model"),), out_specs=P(), check_vma=False,
        )
        return np.asarray(sm(partials))

    ref = run(lambda x: jax.lax.psum(x, "model"))
    got = run(lambda x: quantized_all_reduce(x, "model", degree))
    assert got.shape == ref.shape and got.dtype == ref.dtype
    scale = np.max(np.abs(ref))
    np.testing.assert_allclose(got, ref, atol=2.0 * scale * 2.0 / 127.0)
    # indivisible last dim falls back to the exact psum
    odd = jnp.asarray(rs.randn(degree, 3, 7).astype(np.float32))

    def run_odd(fn):
        sm = shard_map(
            lambda xs: fn(xs[0]),
            mesh=mesh, in_specs=(P("model"),), out_specs=P(), check_vma=False,
        )
        return np.asarray(sm(odd))

    np.testing.assert_array_equal(
        run_odd(lambda x: quantized_all_reduce(x, "model", degree)),
        run_odd(lambda x: jax.lax.psum(x, "model")),
    )


def test_quantized_allreduce_serving_allclose_contract(model_and_params):
    """Serving with quantized all-reduces completes with full streams; the
    contract is allclose-per-projection, so token streams are NOT asserted
    byte-identical — but the serve must finish, keep the dispatch budget,
    and report the knob in serve_stats."""
    cfg, _, params = model_and_params
    prompts = _prompts(3, seed=9)
    tel = CompileTelemetry()
    srv = _server(
        cfg, params, tp=_tp(4, quantized_allreduce=True), telemetry=tel,
    )
    outs = srv.serve(prompts, max_new_tokens=6)
    assert all(o.size == p.size + 6 for o, p in zip(outs, prompts))
    st = srv.serve_stats()
    assert st["tp_quantized_allreduce"] is True and st["finished"] == 3
    assert compiled_serving_programs(tel.stats()) <= 2
