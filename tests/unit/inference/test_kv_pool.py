"""Block-pool KV cache allocator tests (``inference/kv_pool.py``).

The pool is the serving layer's memory manager: pages must never be
double-booked, the trash page must never circulate, failed growth must be
all-or-nothing, and defrag must move bytes without changing what any
sequence reads back.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.kv_pool import TRASH_PAGE, PagePool, init_paged_cache
from deepspeed_tpu.models import llama_config


def _pool(num_pages=10, page_size=4, max_slots=3, max_seq_len=32):
    cfg = llama_config("tiny", num_layers=2, max_seq_len=max_seq_len)
    return PagePool(
        cfg, num_pages=num_pages, page_size=page_size, max_slots=max_slots,
        max_seq_len=max_seq_len, dtype=jnp.float32,
    )


def test_alloc_free_accounting():
    pool = _pool()
    assert pool.free_pages() == 9  # page 0 reserved
    assert pool.used_pages() == 0
    slot = pool.alloc_slot(6)  # 6 tokens @ page_size 4 -> 2 pages
    assert slot is not None
    assert pool.free_pages() == 7 and pool.used_pages() == 2
    owned = set(int(p) for p in pool.page_table[slot] if p >= 0)
    assert len(owned) == 2 and TRASH_PAGE not in owned
    pool.advance(slot, 6)
    assert pool.live_tokens() == 6
    assert pool.utilization() == pytest.approx(6 / 8)
    returned = pool.free_slot(slot)
    assert returned == 2
    assert pool.free_pages() == 9 and pool.live_tokens() == 0
    assert (pool.page_table[slot] == -1).all()


def test_pages_are_exclusive_across_slots():
    pool = _pool()
    s1 = pool.alloc_slot(8)
    s2 = pool.alloc_slot(8)
    own1 = {int(p) for p in pool.page_table[s1] if p >= 0}
    own2 = {int(p) for p in pool.page_table[s2] if p >= 0}
    assert own1.isdisjoint(own2)
    assert TRASH_PAGE not in own1 | own2


def test_ensure_is_all_or_nothing():
    pool = _pool(num_pages=6, page_size=4)  # 5 allocatable pages
    slot = pool.alloc_slot(16)  # takes 4 pages
    free_before = pool.free_pages()
    assert free_before == 1
    # growing to 28 tokens needs 7 pages total (+3): must fail AND leave the
    # single free page untouched
    assert not pool.ensure(slot, 28)
    assert pool.free_pages() == free_before
    assert pool.ensure(slot, 20)  # +1 page fits
    assert pool.free_pages() == 0


def test_admission_gating():
    pool = _pool(num_pages=6, page_size=4, max_slots=2)
    assert pool.can_admit(8)
    s1 = pool.alloc_slot(16)  # 4 of 5 pages
    assert s1 is not None
    assert not pool.can_admit(8)  # needs 2 pages, 1 free
    assert pool.alloc_slot(8) is None
    assert pool.can_admit(4)  # 1 page fits
    # a slot-exhausted pool refuses even tiny requests
    s2 = pool.alloc_slot(2)
    assert s2 is not None and pool.alloc_slot(1) is None


def test_max_seq_len_is_enforced():
    pool = _pool(max_seq_len=8, page_size=4, num_pages=10)
    slot = pool.alloc_slot(8)
    assert not pool.ensure(slot, 9)
    with pytest.raises(AssertionError):
        pool.advance(slot, 9)


def test_defrag_preserves_contents_and_compacts():
    pool = _pool(num_pages=10, page_size=4)
    s1 = pool.alloc_slot(8)
    s2 = pool.alloc_slot(8)
    # stamp every owned page with a recognizable value
    k = pool.cache.k_pages
    stamps = {}
    for s in (s1, s2):
        for pid in pool.page_table[s]:
            if pid >= 0:
                k = k.at[:, int(pid)].set(float(pid))
                stamps[(s, int(pid))] = float(pid)
    pool.set_cache(k, pool.cache.v_pages)
    # free s1 -> holes below s2's pages; defrag must close them
    pool.free_slot(s1)
    before = {
        i: float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for i, pid in enumerate(pool.page_table[s2]) if pid >= 0
    }
    moves = pool.defrag()
    live = [int(p) for p in pool.page_table[s2] if p >= 0]
    assert sorted(live) == [1, 2]  # densest prefix after the trash page
    after = {
        i: float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for i, pid in enumerate(pool.page_table[s2]) if pid >= 0
    }
    assert after == before  # same bytes visible through the table
    assert moves >= 1
    # free list must cover exactly the non-live, non-trash pages
    assert pool.free_pages() == 9 - 2
    assert pool.defrag() == 0  # already compact


def test_hbm_formula():
    cfg = llama_config("tiny", num_layers=2, max_seq_len=32)
    pool = _pool(num_pages=10, page_size=4)
    cache = init_paged_cache(cfg, num_pages=10, page_size=4, dtype=jnp.float32)
    per_token = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 4
    assert cache.bytes_per_token == per_token
    assert cache.hbm_bytes() == 10 * 4 * per_token
    slot = pool.alloc_slot(6)
    pool.advance(slot, 6)
    # live HBM counts allocated pages (page-granular), not raw tokens
    assert pool.live_hbm_bytes() == 2 * 4 * per_token


def test_rollback_frees_tail_pages():
    """Speculation's rejected-tail contract: rollback shrinks the live
    length and returns exactly the pages past the new length — LIFO, so
    they are the next ones reallocated — without ever touching page 0."""
    pool = _pool(num_pages=10, page_size=4)
    slot = pool.alloc_slot(10)  # 3 pages
    pool.advance(slot, 10)
    free_before = pool.free_pages()
    tail = int(pool.page_table[slot, 2])
    freed = pool.rollback(slot, 5)  # 10 -> 5 tokens: 2 pages suffice
    assert freed == 1
    assert int(pool.seq_lens[slot]) == 5 and int(pool._owned[slot]) == 2
    assert pool.free_pages() == free_before + 1
    assert int(pool.page_table[slot, 2]) == -1
    assert pool._free[-1] == tail and TRASH_PAGE not in pool._free
    # rollback(0) trims pre-reserved pages past the live length, not tokens
    assert pool.rollback(slot, 0) == 0
    pool.ensure(slot, 12)
    assert pool.rollback(slot, 0) == 1  # the speculative over-reserve
    assert int(pool.seq_lens[slot]) == 5 and int(pool._owned[slot]) == 2


def test_rollback_then_advance_roundtrip():
    """advance after rollback must work once pages are re-ensured, and the
    page-boundary case (rollback to an exact multiple) frees nothing."""
    pool = _pool(num_pages=10, page_size=4)
    slot = pool.alloc_slot(8)
    pool.advance(slot, 8)
    assert pool.rollback(slot, 4) == 1  # 8 -> 4: exactly one page back
    assert pool.rollback(slot, 1) == 0  # 4 -> 3: same page still needed
    assert pool.ensure(slot, 9)
    pool.advance(slot, 6)
    assert int(pool.seq_lens[slot]) == 9
    # a full rollback empties the slot but keeps it allocated
    assert pool.rollback(slot, 9) == 3
    assert int(pool.seq_lens[slot]) == 0 and int(pool._owned[slot]) == 0
    assert pool.free_pages() == 9
    with pytest.raises(ValueError, match="rollback"):
        pool.rollback(slot, 1)  # more tokens than the slot holds
    with pytest.raises(ValueError, match="rollback"):
        pool.rollback(slot, -1)


def test_rollback_interacts_with_defrag():
    """Pages freed by rollback become defrag holes; compaction must keep
    every surviving token's bytes visible through the table."""
    pool = _pool(num_pages=10, page_size=4)
    s1 = pool.alloc_slot(8)
    s2 = pool.alloc_slot(8)
    pool.advance(s1, 8)
    pool.advance(s2, 8)
    k = pool.cache.k_pages
    for s in (s1, s2):
        for pid in pool.page_table[s]:
            if pid >= 0:
                k = k.at[:, int(pid)].set(float(pid))
    pool.set_cache(k, pool.cache.v_pages)
    # roll s1 back to one page: its second page becomes a hole below s2
    pool.rollback(s1, 4)
    keep = {
        (s, i): float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for s in (s1, s2)
        for i, pid in enumerate(pool.page_table[s]) if pid >= 0
    }
    pool.defrag()
    live = sorted(
        int(p) for s in (s1, s2) for p in pool.page_table[s] if p >= 0
    )
    assert live == [1, 2, 3]  # densest prefix after the trash page
    after = {
        (s, i): float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for s in (s1, s2)
        for i, pid in enumerate(pool.page_table[s]) if pid >= 0
    }
    assert after == keep
    assert pool.free_pages() == 9 - 3


def test_rows_returns_copies():
    pool = _pool()
    slot = pool.alloc_slot(4)
    pt, lens = pool.rows([slot])
    pt[0, 0] = -7
    assert pool.page_table[slot, 0] != -7


def test_reject_degenerate_pools():
    cfg = llama_config("tiny", num_layers=2, max_seq_len=32)
    with pytest.raises(ValueError, match="reserved"):
        PagePool(cfg, num_pages=1, page_size=4, max_slots=1)


# ---------------------------------------------------------------------------
# prefix sharing: hash-of-block index, refcounts, copy-on-write
# ---------------------------------------------------------------------------
def _prefill_slot(pool, slot, tokens, stamp=None):
    """Test-side stand-in for the scheduler's prefill: write barrier +
    (optional page stamping with recognizable values) + advance + publish
    to the prefix index."""
    tokens = np.asarray(tokens, np.int32)
    n = int(tokens.size)
    cur = int(pool.seq_lens[slot])
    assert pool.prepare_write(slot, n)
    if stamp is not None:
        k = pool.cache.k_pages
        for i in range(pool.pages_for(n)):
            k = k.at[:, int(pool.page_table[slot, i])].set(float(stamp + i))
        pool.set_cache(k, pool.cache.v_pages)
    pool.advance(slot, n - cur)
    pool.register_prefix(slot, tokens)


def _page_val(pool, pid):
    return float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))


def test_prefix_attach_pays_pages_once():
    """The acceptance contract: N requests sharing a prompt prefix hold ONE
    copy of its full pages — refcounts rise, allocation doesn't."""
    pool = _pool(num_pages=20, page_size=4, max_slots=3, max_seq_len=32)
    prompt = np.arange(13, dtype=np.int32)  # 3 full pages + 1 token
    s1 = pool.alloc_slot(14, prefix_tokens=prompt)
    assert int(pool.seq_lens[s1]) == 0  # cold index: nothing attached
    _prefill_slot(pool, s1, prompt)
    assert pool.stats["registered_pages"] == 3
    used_before = pool.used_pages()
    s2 = pool.alloc_slot(14, prefix_tokens=prompt)
    s3 = pool.alloc_slot(14, prefix_tokens=prompt)
    # both attach the 3 shared pages and reserve only their private tail
    for s in (s2, s3):
        assert int(pool.seq_lens[s]) == 12  # 3 pages * 4 tokens attached
        np.testing.assert_array_equal(pool.page_table[s][:3], pool.page_table[s1][:3])
    for pid in pool.page_table[s1][:3]:
        assert int(pool._refcount[int(pid)]) == 3
    # the shared prefix cost zero new pages; each attacher only added its
    # own tail reservation (14 tokens -> 4 pages, 3 shared + 1 fresh)
    assert pool.used_pages() == used_before + 2
    assert pool.stats["prefix_hit_pages"] == 6
    assert pool.stats["prefix_hit_tokens"] == 24
    assert pool.prefix_stats()["prefix_hit_rate"] > 0


def test_prefix_survives_author_and_reattaches_from_cache():
    """Freeing the last reference parks indexed pages on the cached LRU
    (reclaimable, so free_pages counts them) — a later identical prompt
    attaches them instead of re-prefilling."""
    pool = _pool(num_pages=10, page_size=4, max_slots=2, max_seq_len=32)
    prompt = np.arange(9, dtype=np.int32)  # 2 full pages + 1
    s1 = pool.alloc_slot(10, prefix_tokens=prompt)
    _prefill_slot(pool, s1, prompt, stamp=7)
    shared = [int(p) for p in pool.page_table[s1][:2]]
    pool.free_slot(s1)
    assert pool.cached_pages() == 2  # indexed pages outlive their author
    assert pool.free_pages() == 9  # ...but stay reclaimable
    assert pool.used_pages() == 0
    s2 = pool.alloc_slot(10, prefix_tokens=prompt)
    assert int(pool.seq_lens[s2]) == 8
    assert [int(p) for p in pool.page_table[s2][:2]] == shared
    assert _page_val(pool, pool.page_table[s2][0]) == 7.0  # the author's bytes
    assert pool.cached_pages() == 0


def test_cached_pages_evicted_when_free_list_dry():
    """Allocation pressure reclaims cold cached pages (oldest first) and
    drops their index entries — sharing never causes an admission refusal."""
    pool = _pool(num_pages=6, page_size=4, max_slots=2, max_seq_len=32)
    prompt = np.arange(9, dtype=np.int32)
    s1 = pool.alloc_slot(10, prefix_tokens=prompt)  # 3 of 5 pages
    _prefill_slot(pool, s1, prompt)
    pool.free_slot(s1)
    assert pool.cached_pages() == 2 and pool.free_pages() == 5
    # a 17-token stranger needs 5 pages: both cached pages must be evicted
    s2 = pool.alloc_slot(17)
    assert s2 is not None
    assert pool.cached_pages() == 0
    assert pool.stats["cache_evictions"] == 2
    # the index is empty again: the old prompt no longer matches
    assert pool.match_prefix(prompt) == []


def test_cow_on_divergence_preserves_shared_reader():
    """A write into a SHARED page (refcount > 1) must copy, not mutate:
    the writer gets a private duplicate, the other reader and the prefix
    index keep the original bytes."""
    pool = _pool(num_pages=12, page_size=4, max_slots=3, max_seq_len=32)
    prompt = np.arange(9, dtype=np.int32)  # 2 full pages + 1
    s1 = pool.alloc_slot(10, prefix_tokens=prompt)
    _prefill_slot(pool, s1, prompt, stamp=3)  # pages stamped 3.0, 4.0
    s2 = pool.alloc_slot(10, prefix_tokens=prompt)
    orig = [int(p) for p in pool.page_table[s2][:2]]
    assert [int(p) for p in pool.page_table[s1][:2]] == orig
    # s1 diverges: speculative rollback INTO the shared second page, then a
    # re-write of positions 6.. — the write barrier must CoW page index 1
    pool.rollback(s1, 3)  # 9 -> 6 tokens, page 1 still needed
    assert pool.prepare_write(s1, 8)
    assert pool.stats["cow_copies"] == 1
    new_p1 = int(pool.page_table[s1, 1])
    assert new_p1 != orig[1]
    assert int(pool.page_table[s2, 1]) == orig[1]  # reader untouched
    assert int(pool._refcount[orig[1]]) == 1 and int(pool._refcount[new_p1]) == 1
    # the copy carries the original bytes (divergence starts from them)
    assert _page_val(pool, new_p1) == _page_val(pool, orig[1]) == 4.0
    # the index still serves the ORIGINAL page for new matches
    assert [p for p, _ in pool.match_prefix(prompt)] == orig


def test_write_barrier_invalidates_exclusive_indexed_page():
    """Re-writing an indexed page you own exclusively must drop it from
    the index (an indexed page's content is immutable) — no copy needed."""
    pool = _pool(num_pages=10, page_size=4, max_slots=2, max_seq_len=32)
    prompt = np.arange(9, dtype=np.int32)
    s1 = pool.alloc_slot(10, prefix_tokens=prompt)
    _prefill_slot(pool, s1, prompt)
    assert len(pool.match_prefix(prompt)) == 2
    # mid-page rollback: page 1 stays OWNED (9 -> 6 tokens, 2 pages keep)
    # with its index entry, so the re-write must invalidate in place
    pool.rollback(s1, 3)
    assert pool.prepare_write(s1, 8)  # rewrite positions 6..7
    assert pool.stats["cow_copies"] == 0  # exclusive: no copy
    assert pool.stats["index_invalidations"] == 1
    # page 0's content is untouched (write span starts inside page 1)
    assert len(pool.match_prefix(prompt)) == 1


def test_match_prefix_caps_at_one_token_short():
    """A fully-cached prompt must still leave >= 1 token to prefill (the
    first output token needs logits), so the match is capped."""
    pool = _pool(num_pages=10, page_size=4, max_slots=2, max_seq_len=32)
    prompt = np.arange(8, dtype=np.int32)  # exactly 2 full pages
    s1 = pool.alloc_slot(9, prefix_tokens=prompt)
    _prefill_slot(pool, s1, prompt)
    assert len(pool.match_prefix(prompt)) == 1  # (8 - 1) // 4 = 1 page cap
    longer = np.arange(9, dtype=np.int32)
    assert len(pool.match_prefix(longer)) == 2  # 9 tokens may use both


def test_defrag_remaps_shared_pages_and_index():
    """Defrag with sharing: a page referenced by two tables moves ONCE,
    both tables and the hash index follow, refcounts survive."""
    pool = _pool(num_pages=12, page_size=4, max_slots=3, max_seq_len=32)
    filler = pool.alloc_slot(8)  # occupies low pages, freed later -> holes
    prompt = np.arange(9, dtype=np.int32)
    s1 = pool.alloc_slot(10, prefix_tokens=prompt)
    _prefill_slot(pool, s1, prompt, stamp=5)
    s2 = pool.alloc_slot(10, prefix_tokens=prompt)
    pool.free_slot(filler)
    shared_before = [int(p) for p in pool.page_table[s1][:2]]
    val_before = [_page_val(pool, p) for p in shared_before]
    pool.defrag()
    shared_after = [int(p) for p in pool.page_table[s1][:2]]
    np.testing.assert_array_equal(pool.page_table[s2][:2], shared_after)
    assert [_page_val(pool, p) for p in shared_after] == val_before
    assert all(int(pool._refcount[p]) == 2 for p in shared_after)
    # the index moved with the pages: a fresh match returns the new ids
    assert [p for p, _ in pool.match_prefix(prompt)] == shared_after


# ---------------------------------------------------------------------------
# randomized partition invariant (the CoW/refcount soak)
# ---------------------------------------------------------------------------
def _check_partition(pool):
    """free ∪ cached ∪ referenced exactly partitions pages 1..N-1; the
    refcount array equals the table reference counts; the hash index is a
    bijection onto live pages; per-slot lengths fit their owned pages."""
    N = pool.num_pages
    refs = {}
    for s in range(pool.max_slots):
        owned = int(pool._owned[s])
        row = pool.page_table[s]
        assert (row[owned:] == -1).all(), f"slot {s}: stale entries past owned"
        for i in range(owned):
            p = int(row[i])
            assert p > 0, f"slot {s} references the trash page"
            refs[p] = refs.get(p, 0) + 1
        live = int(pool.seq_lens[s])
        assert live <= owned * pool.page_size
        assert pool.pages_for(live) <= owned
    for p in range(N):
        assert int(pool._refcount[p]) == refs.get(p, 0), f"refcount drift on page {p}"
    fset, cset, rset = set(pool._free), set(pool._cached), set(refs)
    assert len(pool._free) == len(fset), "duplicate free-list entries"
    assert TRASH_PAGE not in fset | cset | rset
    assert fset.isdisjoint(cset) and fset.isdisjoint(rset) and cset.isdisjoint(rset)
    assert fset | cset | rset == set(range(1, N)), "pool partition broken"
    assert set(pool._page_hash) <= cset | rset, "index points at a free page"
    assert cset <= set(pool._page_hash), "cached page without an index entry"
    for page, key in pool._page_hash.items():
        assert pool._hash_index.get(key) == page
    assert len(pool._hash_index) == len(pool._page_hash)


def test_randomized_admit_rollback_preempt_defrag_partition():
    """Soak the allocator with arbitrary admit / attach / prefill / decode
    / rollback / preempt(free) / defrag sequences — heavy prompt reuse so
    attach, CoW, invalidation, caching, and eviction all fire — checking
    the full partition invariant after every operation. Catches exactly
    the refcount leaks a CoW bug would introduce."""
    P = 4
    for seed in (0, 1, 2):
        rs = np.random.RandomState(seed)
        pool = _pool(num_pages=16, page_size=P, max_slots=4, max_seq_len=40)
        # shared corpus: slots draw prompts from few streams -> real sharing
        corpus = [rs.randint(0, 50, (40,)).astype(np.int32) for _ in range(3)]
        slots = {}  # slot -> its context tokens (grows as it "decodes")
        saw = {"cow": False, "attach": False, "evict": False}
        for _ in range(140):
            op = rs.randint(6)
            if op == 0 or not slots:  # admit with a (often shared) prompt
                stream = corpus[rs.randint(len(corpus))]
                n = int(rs.randint(5, 20))
                prompt = stream[:n].copy()
                slot = pool.alloc_slot(n + 1, prefix_tokens=prompt)
                if slot is not None:
                    if int(pool.seq_lens[slot]) > 0:
                        saw["attach"] = True
                    assert pool.prepare_write(slot, n)
                    pool.advance(slot, n - int(pool.seq_lens[slot]))
                    pool.register_prefix(slot, prompt)
                    slots[slot] = prompt
            elif op == 1:  # decode a few tokens (shared continuations)
                slot = list(slots)[rs.randint(len(slots))]
                ctx = slots[slot]
                g = int(rs.randint(1, 6))
                new_len = int(pool.seq_lens[slot]) + g
                if new_len <= pool.max_seq_len and pool.prepare_write(slot, new_len):
                    if pool.stats["cow_copies"]:
                        saw["cow"] = True
                    pool.advance(slot, g)
                    # deterministic continuation: same prefix -> same tokens,
                    # so decoded pages are shareable too
                    ext = (ctx.sum() + np.arange(g)) % 50
                    slots[slot] = ctx = np.concatenate([ctx, ext.astype(np.int32)])
                    pool.register_prefix(slot, ctx)
            elif op == 2:  # speculative rollback
                slot = list(slots)[rs.randint(len(slots))]
                live = int(pool.seq_lens[slot])
                if live > 1:
                    n = int(rs.randint(1, min(live, 6)))
                    pool.rollback(slot, n)
                    slots[slot] = slots[slot][: live - n]
            elif op == 3:  # preempt / finish
                slot = list(slots)[rs.randint(len(slots))]
                pool.free_slot(slot)
                del slots[slot]
            elif op == 4:
                pool.defrag()
            else:  # growth that may evict cold cached pages
                slot = list(slots)[rs.randint(len(slots))]
                target = int(pool.seq_lens[slot]) + int(rs.randint(1, 10))
                evicted_before = pool.stats["cache_evictions"]
                if target <= pool.max_seq_len and pool.prepare_write(slot, target):
                    pool.advance(slot, target - int(pool.seq_lens[slot]))
                    ext = np.zeros(target - slots[slot].size, np.int32)
                    if ext.size:
                        slots[slot] = np.concatenate([slots[slot], ext])
                if pool.stats["cache_evictions"] > evicted_before:
                    saw["evict"] = True
            _check_partition(pool)
        # the soak must actually exercise the sharing machinery
        assert saw["attach"], f"seed {seed}: no prefix attach happened"
        assert pool.stats["registered_pages"] > 0
