"""Block-pool KV cache allocator tests (``inference/kv_pool.py``).

The pool is the serving layer's memory manager: pages must never be
double-booked, the trash page must never circulate, failed growth must be
all-or-nothing, and defrag must move bytes without changing what any
sequence reads back.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.kv_pool import TRASH_PAGE, PagePool, init_paged_cache
from deepspeed_tpu.models import llama_config


def _pool(num_pages=10, page_size=4, max_slots=3, max_seq_len=32):
    cfg = llama_config("tiny", num_layers=2, max_seq_len=max_seq_len)
    return PagePool(
        cfg, num_pages=num_pages, page_size=page_size, max_slots=max_slots,
        max_seq_len=max_seq_len, dtype=jnp.float32,
    )


def test_alloc_free_accounting():
    pool = _pool()
    assert pool.free_pages() == 9  # page 0 reserved
    assert pool.used_pages() == 0
    slot = pool.alloc_slot(6)  # 6 tokens @ page_size 4 -> 2 pages
    assert slot is not None
    assert pool.free_pages() == 7 and pool.used_pages() == 2
    owned = set(int(p) for p in pool.page_table[slot] if p >= 0)
    assert len(owned) == 2 and TRASH_PAGE not in owned
    pool.advance(slot, 6)
    assert pool.live_tokens() == 6
    assert pool.utilization() == pytest.approx(6 / 8)
    returned = pool.free_slot(slot)
    assert returned == 2
    assert pool.free_pages() == 9 and pool.live_tokens() == 0
    assert (pool.page_table[slot] == -1).all()


def test_pages_are_exclusive_across_slots():
    pool = _pool()
    s1 = pool.alloc_slot(8)
    s2 = pool.alloc_slot(8)
    own1 = {int(p) for p in pool.page_table[s1] if p >= 0}
    own2 = {int(p) for p in pool.page_table[s2] if p >= 0}
    assert own1.isdisjoint(own2)
    assert TRASH_PAGE not in own1 | own2


def test_ensure_is_all_or_nothing():
    pool = _pool(num_pages=6, page_size=4)  # 5 allocatable pages
    slot = pool.alloc_slot(16)  # takes 4 pages
    free_before = pool.free_pages()
    assert free_before == 1
    # growing to 28 tokens needs 7 pages total (+3): must fail AND leave the
    # single free page untouched
    assert not pool.ensure(slot, 28)
    assert pool.free_pages() == free_before
    assert pool.ensure(slot, 20)  # +1 page fits
    assert pool.free_pages() == 0


def test_admission_gating():
    pool = _pool(num_pages=6, page_size=4, max_slots=2)
    assert pool.can_admit(8)
    s1 = pool.alloc_slot(16)  # 4 of 5 pages
    assert s1 is not None
    assert not pool.can_admit(8)  # needs 2 pages, 1 free
    assert pool.alloc_slot(8) is None
    assert pool.can_admit(4)  # 1 page fits
    # a slot-exhausted pool refuses even tiny requests
    s2 = pool.alloc_slot(2)
    assert s2 is not None and pool.alloc_slot(1) is None


def test_max_seq_len_is_enforced():
    pool = _pool(max_seq_len=8, page_size=4, num_pages=10)
    slot = pool.alloc_slot(8)
    assert not pool.ensure(slot, 9)
    with pytest.raises(AssertionError):
        pool.advance(slot, 9)


def test_defrag_preserves_contents_and_compacts():
    pool = _pool(num_pages=10, page_size=4)
    s1 = pool.alloc_slot(8)
    s2 = pool.alloc_slot(8)
    # stamp every owned page with a recognizable value
    k = pool.cache.k_pages
    stamps = {}
    for s in (s1, s2):
        for pid in pool.page_table[s]:
            if pid >= 0:
                k = k.at[:, int(pid)].set(float(pid))
                stamps[(s, int(pid))] = float(pid)
    pool.cache = pool.cache._replace(k_pages=k)
    # free s1 -> holes below s2's pages; defrag must close them
    pool.free_slot(s1)
    before = {
        i: float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for i, pid in enumerate(pool.page_table[s2]) if pid >= 0
    }
    moves = pool.defrag()
    live = [int(p) for p in pool.page_table[s2] if p >= 0]
    assert sorted(live) == [1, 2]  # densest prefix after the trash page
    after = {
        i: float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for i, pid in enumerate(pool.page_table[s2]) if pid >= 0
    }
    assert after == before  # same bytes visible through the table
    assert moves >= 1
    # free list must cover exactly the non-live, non-trash pages
    assert pool.free_pages() == 9 - 2
    assert pool.defrag() == 0  # already compact


def test_hbm_formula():
    cfg = llama_config("tiny", num_layers=2, max_seq_len=32)
    pool = _pool(num_pages=10, page_size=4)
    cache = init_paged_cache(cfg, num_pages=10, page_size=4, dtype=jnp.float32)
    per_token = 2 * cfg.num_layers * cfg.num_kv_heads * cfg.head_dim * 4
    assert cache.bytes_per_token == per_token
    assert cache.hbm_bytes() == 10 * 4 * per_token
    slot = pool.alloc_slot(6)
    pool.advance(slot, 6)
    # live HBM counts allocated pages (page-granular), not raw tokens
    assert pool.live_hbm_bytes() == 2 * 4 * per_token


def test_rollback_frees_tail_pages():
    """Speculation's rejected-tail contract: rollback shrinks the live
    length and returns exactly the pages past the new length — LIFO, so
    they are the next ones reallocated — without ever touching page 0."""
    pool = _pool(num_pages=10, page_size=4)
    slot = pool.alloc_slot(10)  # 3 pages
    pool.advance(slot, 10)
    free_before = pool.free_pages()
    tail = int(pool.page_table[slot, 2])
    freed = pool.rollback(slot, 5)  # 10 -> 5 tokens: 2 pages suffice
    assert freed == 1
    assert int(pool.seq_lens[slot]) == 5 and int(pool._owned[slot]) == 2
    assert pool.free_pages() == free_before + 1
    assert int(pool.page_table[slot, 2]) == -1
    assert pool._free[-1] == tail and TRASH_PAGE not in pool._free
    # rollback(0) trims pre-reserved pages past the live length, not tokens
    assert pool.rollback(slot, 0) == 0
    pool.ensure(slot, 12)
    assert pool.rollback(slot, 0) == 1  # the speculative over-reserve
    assert int(pool.seq_lens[slot]) == 5 and int(pool._owned[slot]) == 2


def test_rollback_then_advance_roundtrip():
    """advance after rollback must work once pages are re-ensured, and the
    page-boundary case (rollback to an exact multiple) frees nothing."""
    pool = _pool(num_pages=10, page_size=4)
    slot = pool.alloc_slot(8)
    pool.advance(slot, 8)
    assert pool.rollback(slot, 4) == 1  # 8 -> 4: exactly one page back
    assert pool.rollback(slot, 1) == 0  # 4 -> 3: same page still needed
    assert pool.ensure(slot, 9)
    pool.advance(slot, 6)
    assert int(pool.seq_lens[slot]) == 9
    # a full rollback empties the slot but keeps it allocated
    assert pool.rollback(slot, 9) == 3
    assert int(pool.seq_lens[slot]) == 0 and int(pool._owned[slot]) == 0
    assert pool.free_pages() == 9
    with pytest.raises(ValueError, match="rollback"):
        pool.rollback(slot, 1)  # more tokens than the slot holds
    with pytest.raises(ValueError, match="rollback"):
        pool.rollback(slot, -1)


def test_rollback_interacts_with_defrag():
    """Pages freed by rollback become defrag holes; compaction must keep
    every surviving token's bytes visible through the table."""
    pool = _pool(num_pages=10, page_size=4)
    s1 = pool.alloc_slot(8)
    s2 = pool.alloc_slot(8)
    pool.advance(s1, 8)
    pool.advance(s2, 8)
    k = pool.cache.k_pages
    for s in (s1, s2):
        for pid in pool.page_table[s]:
            if pid >= 0:
                k = k.at[:, int(pid)].set(float(pid))
    pool.cache = pool.cache._replace(k_pages=k)
    # roll s1 back to one page: its second page becomes a hole below s2
    pool.rollback(s1, 4)
    keep = {
        (s, i): float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for s in (s1, s2)
        for i, pid in enumerate(pool.page_table[s]) if pid >= 0
    }
    pool.defrag()
    live = sorted(
        int(p) for s in (s1, s2) for p in pool.page_table[s] if p >= 0
    )
    assert live == [1, 2, 3]  # densest prefix after the trash page
    after = {
        (s, i): float(np.asarray(pool.cache.k_pages[0, int(pid), 0, 0, 0]))
        for s in (s1, s2)
        for i, pid in enumerate(pool.page_table[s]) if pid >= 0
    }
    assert after == keep
    assert pool.free_pages() == 9 - 3


def test_rows_returns_copies():
    pool = _pool()
    slot = pool.alloc_slot(4)
    pt, lens = pool.rows([slot])
    pt[0, 0] = -7
    assert pool.page_table[slot, 0] != -7


def test_reject_degenerate_pools():
    cfg = llama_config("tiny", num_layers=2, max_seq_len=32)
    with pytest.raises(ValueError, match="reserved"):
        PagePool(cfg, num_pages=1, page_size=4, max_slots=1)
