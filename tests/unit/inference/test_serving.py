"""Continuous-batching paged-KV serving tests.

Load-bearing checks (ISSUE 2 acceptance): paged greedy decode is
token-exact against the dense lockstep ``decode.generate`` across varying
occupancy, mid-stream admission, and eviction/preemption; and over a
3-wave admit/finish/admit schedule the compile telemetry shows ≤1 compile
per shape bucket and exactly one ``paged_decode_*`` dispatch per decode
step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA on the serving path
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _prompts(n, seed=0, lo=3, hi=20):
    rs = np.random.RandomState(seed)
    return [
        rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)
        for _ in range(n)
    ]


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, **kw):
    # this suite exercises the BUCKETED per-shape programs (the ragged
    # path's token-exactness oracle); the ragged default is covered by
    # test_ragged_serving.py and the engine-surface test below
    kw.setdefault("ragged", False)
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    return PagedServer(cfg, params, **kw)


def test_paged_matches_dense_varying_occupancy(model_and_params):
    """More requests than slots, ragged prompt lengths, ragged budgets:
    every output must equal the request's standalone dense greedy decode."""
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(6, seed=2)
    budgets = [10, 3, 7, 12, 1, 5]
    outs = server.serve(prompts, max_new_tokens=budgets)
    for p, n, out in zip(prompts, budgets, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, n))
    assert server.stats["finished"] == 6
    # occupancy varied: 6 requests through 4 slots means a second wave
    assert server.stats["admitted"] == 6
    # pool fully drained once everything finished
    assert server.pool.used_pages() == 0 and server.pool.live_tokens() == 0


def test_admission_mid_stream(model_and_params):
    """Requests submitted while others are mid-decode join the running
    batch without disturbing in-flight sequences."""
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(4, seed=3)
    first = [server.submit(p, max_new_tokens=12) for p in prompts[:2]]
    for _ in range(4):  # prefill + a few decode steps for wave 1
        server.step()
    assert server.stats["decode_steps"] >= 2
    late = [server.submit(p, max_new_tokens=12) for p in prompts[2:]]
    results = server.run()
    for uid, p in zip(first + late, prompts):
        np.testing.assert_array_equal(results[uid], _dense(cfg, params, p, 12))


def test_eviction_preemption_is_token_exact(model_and_params):
    """An undersized pool forces preemption mid-stream; recompute on
    re-admission must reproduce the exact greedy continuation."""
    cfg, _, params = model_and_params
    server = _server(
        cfg, params, page_size=4, num_pages=14, max_slots=3, prefill_chunk=8
    )
    prompts = _prompts(4, seed=4, lo=6, hi=14)
    outs = server.serve(prompts, max_new_tokens=12)
    assert server.stats["preempted"] >= 1, "pool was sized to force preemption"
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 12))


def test_eos_finishes_request_early(model_and_params):
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(2, seed=5)
    # derive each prompt's first greedy token and use row 0's as "EOS"
    probe = _dense(cfg, params, prompts[0], 1)
    eos = int(probe[-1])
    outs = server.serve(prompts, max_new_tokens=10, eos_token_id=eos)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 10, eos=eos))
    # row 0 emitted eos immediately: prompt + the single eos token
    assert outs[0].size == prompts[0].size + 1 and outs[0][-1] == eos


def test_retrace_guard_and_single_dispatch_per_step(model_and_params):
    """3-wave admit/finish/admit schedule: ≤1 compile per shape bucket,
    exactly one paged_decode dispatch per decode step, and every prompt
    chunk through ONE compiled prefill program."""
    cfg, _, params = model_and_params
    telemetry = CompileTelemetry()
    server = _server(cfg, params, max_slots=4, telemetry=telemetry)
    waves = [_prompts(2, seed=6), _prompts(4, seed=7), _prompts(2, seed=8)]
    for wave in waves:
        outs = server.serve(wave, max_new_tokens=6)
        for p, out in zip(wave, outs):
            np.testing.assert_array_equal(out, _dense(cfg, params, p, 6))
    stats = telemetry.stats()
    paged = {k: v for k, v in stats.items() if k.startswith("paged_")}
    assert paged, f"no paged programs instrumented: {list(stats)}"
    for name, rec in paged.items():
        assert rec["compiles"] <= 1, f"{name} recompiled: {rec}"
    decode_dispatches = sum(
        rec["dispatches"] for name, rec in stats.items()
        if name.startswith("paged_decode_")
    )
    assert decode_dispatches == server.stats["decode_steps"]
    prefill_dispatches = sum(
        rec["dispatches"] for name, rec in stats.items()
        if name.startswith("paged_prefill_")
    )
    assert prefill_dispatches == server.stats["prefill_chunks"]
    # bucketed shapes: program count bounded by the bucket set, not traffic
    assert len(paged) <= len(server.buckets) + 1


def test_engine_serve_and_compile_stats(model_and_params):
    """The engine-level surface: paged_kv config knobs, serve() (on the
    default RAGGED path), and the inference compile_stats() satellite
    (forward + decode loop programs)."""
    cfg, model, params = model_and_params
    engine = ds.init_inference(
        model,
        dtype="fp32",
        paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8, "attn_impl": "xla"},
    )
    engine.set_params(params)
    engine._ds_config = cfg  # converted-family contract (containers set this)
    prompts = _prompts(3, seed=9)
    outs = engine.serve(prompts, max_new_tokens=6)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 6))
    stats = engine.compile_stats()
    assert any(k.startswith("paged_ragged_") for k in stats)
    sstats = engine.serve_stats()
    assert sstats["finished"] == 3 and sstats["decode_steps"] >= 1
    # acceptance: exactly ONE ragged dispatch per scheduler step, observed
    # through the engine's own compile_stats()
    assert sum(
        rec["dispatches"] for name, rec in stats.items()
        if name.startswith("paged_ragged_")
    ) == sstats["ragged_steps"]
    # satellite: the jitted forward and the kv decode loop are instrumented
    toks = jnp.asarray(np.stack([np.resize(prompts[0], 8)]))
    engine(toks)
    engine.generate(toks, max_new_tokens=4)
    stats = engine.compile_stats()
    assert stats["forward"]["dispatches"] >= 1
    assert "kv_prefill" in stats and "kv_decode_loop" in stats
    assert stats["kv_decode_loop"]["compiles"] <= 1


def test_paged_matches_dense_gpt2_family():
    """Learned positions + tied embeddings + MHA (the gpt2 shape) through
    the paged path — per-row position gathers must stay exact."""
    from deepspeed_tpu.models.config import gpt2_config

    cfg = gpt2_config(
        "tiny", num_layers=2, max_seq_len=64, flash_attention=False,
        dtype="float32", vocab_size=128, hidden_size=64, num_heads=4,
    )
    model = TransformerLM(cfg)
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, cfg.vocab_size, (n,)).astype(np.int32) for n in (4, 11)]
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(prompts[0][None]))
    server = PagedServer(
        cfg, params, page_size=8, max_slots=2, prefill_chunk=8,
        attn_impl="xla", dtype=jnp.float32,
    )
    outs = server.serve(prompts, max_new_tokens=5)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 5))


def test_prefill_chunk_one_and_results_drain(model_and_params):
    """prefill_chunk=1 must take the causal prefill path (its T==1 programs
    are chunks, not decode steps), and serve() must drain its results so a
    long-lived server never accumulates past outputs."""
    cfg, _, params = model_and_params
    server = _server(cfg, params, max_slots=1, prefill_chunk=1)
    prompts = _prompts(2, seed=12, lo=2, hi=4)
    outs = server.serve(prompts, max_new_tokens=2)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 2))
    assert server._results == {}  # drained by serve()
    with pytest.raises(ValueError, match="max_new_tokens"):
        server.serve(prompts, max_new_tokens=[2])


def test_prefill_pad_tail_never_aliases_live_pages():
    """Regression: a final prompt chunk whose pad positions run past the
    table width used to clamp onto the LAST live column and overwrite real
    prompt k/v (positions 112..127 -> table slot 7 -> clamped to column 6 =
    positions 96..111 here). Pad slots must write to the trash page."""
    cfg = TransformerConfig(**{**CFG, "max_seq_len": 112})
    model = TransformerLM(cfg)
    rs = np.random.RandomState(15)
    prompt = rs.randint(0, cfg.vocab_size, (104,)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), jnp.asarray(prompt[None, :8]))
    server = PagedServer(
        cfg, params, page_size=16, max_slots=2, prefill_chunk=32,
        attn_impl="xla", dtype=jnp.float32,
    )
    out = server.serve([prompt], max_new_tokens=8)[0]
    np.testing.assert_array_equal(out, _dense(cfg, params, prompt, 8))


def test_serve_rejects_oversized_requests(model_and_params):
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    with pytest.raises(ValueError, match="max_seq_len"):
        server.submit(np.zeros(60, np.int32), max_new_tokens=10)
    # a request that could never fit the pool is rejected at submit, not
    # discovered by an unfixable preemption loop mid-stream
    tiny = PagedServer(
        cfg, params, page_size=4, num_pages=3, max_slots=2,
        prefill_chunk=8, attn_impl="xla", dtype=jnp.float32, max_seq_len=64,
    )
    with pytest.raises(ValueError, match="pages"):
        tiny.submit(np.zeros(4, np.int32), max_new_tokens=20)
