"""Serving crash recovery: the request journal + PagedServer replay.

Guarantees under test: a crash at any serving instant (mid-step before the
journal flush, torn tail mid-append) loses NOTHING a restart cannot
re-derive — the rebuilt server replays the journal and every stream resumes
**byte-identically** from its last emitted token (the preemption-recompute
machinery driven from disk). Corruption a crash cannot explain (a bad
record inside a sealed segment, valid records after a broken one) raises
``JournalCorruptError`` — red tests."""

import os

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.inference.journal import (
    JournalCorruptError,
    RequestJournal,
)
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.utils import chaos

CFG = TransformerConfig(
    vocab_size=256, hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
    max_seq_len=96, norm="rmsnorm", position="rope", activation="swiglu",
    use_bias=False, tie_embeddings=False, flash_attention=False,
)
PAGED = {"page_size": 8, "max_slots": 4, "prefill_chunk": 8}

rs = np.random.RandomState(0)
PROMPTS = [rs.randint(0, CFG.vocab_size, (12,)).astype(np.int32) for _ in range(4)]
# a shared system prompt for the prefix-cache recovery case
SHARED = rs.randint(0, CFG.vocab_size, (16,)).astype(np.int32)
SHARED_PROMPTS = [
    np.concatenate([SHARED, rs.randint(0, CFG.vocab_size, (6 + i,)).astype(np.int32)])
    for i in range(3)
]


@pytest.fixture(autouse=True)
def _disarm_chaos():
    yield
    chaos.uninstall()


def _engine(journal_dir=None, **paged_over):
    mesh_mod.reset_topology()
    kw = dict(dtype="bf16", paged_kv={**PAGED, **paged_over})
    if journal_dir is not None:
        kw["journal"] = {"enabled": True, "dir": str(journal_dir)}
    eng = ds.init_inference(TransformerLM(CFG), **kw)
    eng.init_params(np.stack(PROMPTS))
    eng._ds_config = CFG
    eng._paged_server = eng._build_paged_server()
    return eng


# ---------------------------------------------------------------------------
# journal units
# ---------------------------------------------------------------------------
class TestJournalUnits:
    def test_roundtrip_submit_emit_finish(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1, 2, 3], np.int32), 8, None, "default")
        j.append_emit(0, 7)
        j.append_emit(0, 9)
        j.append_submit(1, np.asarray([4], np.int32), 4, 3, "tenantB")
        j.append_finish(0)
        j.sync()
        states, next_uid = RequestJournal.replay(str(tmp_path))
        assert next_uid == 2
        assert states[0].finished and states[0].generated == [7, 9]
        np.testing.assert_array_equal(states[0].prompt, [1, 2, 3])
        assert not states[1].finished and states[1].eos_token_id == 3
        assert states[1].tenant == "tenantB"

    def test_replay_preserves_timing_stamps(self, tmp_path):
        """The submit record's ts and the one-shot first-token record keep
        TTFT honest across a live-fleet re-route: replay returns the
        original stamps (absent records replay as None — a fresh process
        must restamp against its own clock)."""
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1, 2], np.int32), 8, None, "default",
                        t_submit=2.5)
        j.append_first_token(0, 3.25)
        j.append_emit(0, 7)
        j.append_submit(1, np.asarray([4], np.int32), 4, None, "default")
        j.sync()
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].t_submit == 2.5 and states[0].t_first == 3.25
        assert states[1].t_submit is None and states[1].t_first is None

    def test_seeded_resubmit_replaces_state(self, tmp_path):
        """Recovery compaction: a later submit record with pre-seeded
        emissions resets the uid's state (old segments stay replayable)."""
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1], np.int32), 8, None, "default")
        j.append_emit(0, 5)
        j.append_submit(0, np.asarray([1], np.int32), 8, None, "default",
                        generated=[5])
        j.append_emit(0, 6)
        j.sync()
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].generated == [5, 6]

    def test_implicit_done_budget_and_eos(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1], np.int32), 2, None, "default")
        j.append_emit(0, 5)
        j.append_emit(0, 6)  # budget hit; crash ate the finish record
        j.append_submit(1, np.asarray([1], np.int32), 8, 3, "default")
        j.append_emit(1, 3)  # EOS
        j.sync()
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].done and states[1].done

    def test_segment_rotation_and_cross_segment_replay(self, tmp_path):
        j = RequestJournal(str(tmp_path), segment_bytes=128)
        j.append_submit(0, np.arange(8, dtype=np.int32), 64, None, "default")
        j.sync()
        for t in range(20):
            j.append_emit(0, t)
            j.sync()  # rotates whenever the active segment passes 128B
        assert j.segments_sealed >= 2
        names = sorted(os.listdir(tmp_path))
        assert any(n.endswith(".jrnl") for n in names)
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].generated == list(range(20))

    def test_torn_tail_of_active_segment_is_dropped(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1], np.int32), 8, None, "default")
        for t in range(4):
            j.append_emit(0, t)
        j.sync()
        seg = [n for n in os.listdir(tmp_path) if n.endswith(".open")][0]
        path = os.path.join(tmp_path, seg)
        with open(path, "r+b") as f:  # tear mid-record, like a real crash
            f.truncate(os.path.getsize(path) - 7)
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].generated == [0, 1, 2]  # the torn emit is gone

    def test_double_crash_torn_tails_stay_tolerable(self, tmp_path):
        """Crash 1 tears seg_000000.open; recovery opens seg_000001. A
        second crash (torn or not) must still replay — an old .open torn
        tail is a crash artifact forever, not corruption."""
        j1 = RequestJournal(str(tmp_path))
        j1.append_submit(0, np.asarray([1], np.int32), 8, None, "default")
        j1.append_emit(0, 4)
        j1.sync()
        seg0 = os.path.join(tmp_path, "seg_000000.open")
        with open(seg0, "r+b") as f:  # crash 1 tears the tail
            f.truncate(os.path.getsize(seg0) - 5)
        states, next_uid = RequestJournal.replay(str(tmp_path))
        assert states[0].generated == []
        j2 = RequestJournal(str(tmp_path))  # recovery writer: seg_000001
        j2.append_submit(0, np.asarray([1], np.int32), 8, None, "default",
                         generated=[])
        j2.append_emit(0, 4)
        j2.sync()
        # crash 2, then a THIRD replay over both torn/partial segments
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].generated == [4]
        assert len(RequestJournal.segments(str(tmp_path))) == 2

    def test_corrupt_sealed_segment_raises(self, tmp_path):
        j = RequestJournal(str(tmp_path), segment_bytes=1)  # seal every sync
        j.append_submit(0, np.asarray([1], np.int32), 8, None, "default")
        j.sync()
        assert j.segments_sealed == 1
        sealed = [n for n in os.listdir(tmp_path) if n.endswith(".jrnl")][0]
        path = os.path.join(tmp_path, sealed)
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 5)
        with pytest.raises(JournalCorruptError, match="sealed"):
            RequestJournal.replay(str(tmp_path))

    def test_valid_records_after_a_bad_one_raise(self, tmp_path):
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1], np.int32), 8, None, "default")
        j.append_emit(0, 1)
        j.append_emit(0, 2)
        j.sync()
        seg = [n for n in os.listdir(tmp_path) if n.endswith(".open")][0]
        path = os.path.join(tmp_path, seg)
        with open(path, "rb") as f:
            lines = f.readlines()
        lines[1] = b"deadbeef corrupted-not-torn\n"  # mid-file damage
        with open(path, "wb") as f:  # noqa: DS-R008 — test writes damage in place
            f.writelines(lines)
        with pytest.raises(JournalCorruptError, match="valid records after"):
            RequestJournal.replay(str(tmp_path))

    def test_chaos_truncate_at_append_is_survivable(self, tmp_path):
        """The journal.append injection point + truncate action: the torn
        tail is dropped at replay, everything fsynced earlier survives."""
        j = RequestJournal(str(tmp_path))
        j.append_submit(0, np.asarray([1], np.int32), 8, None, "default")
        j.sync()
        j.append_emit(0, 1)
        chaos.install(chaos.ChaosSchedule(
            [chaos.ChaosRule("journal.append", action="truncate", nbytes=5)]
        ))
        with pytest.raises(chaos.ChaosKilled):
            j.sync()
        chaos.uninstall()
        states, _ = RequestJournal.replay(str(tmp_path))
        assert states[0].generated == []  # the torn emit never happened
        np.testing.assert_array_equal(states[0].prompt, [1])


# ---------------------------------------------------------------------------
# crash-restart through the serving engine
# ---------------------------------------------------------------------------
class TestServeRecovery:
    def _reference(self, prompts, max_new):
        eng = _engine()
        return eng.serve(prompts, max_new_tokens=max_new)

    @pytest.mark.parametrize("kill_step", [1, 3])
    def test_mid_step_crash_streams_resume_byte_identical(
        self, tmp_path, eight_devices, kill_step
    ):
        ref = self._reference(PROMPTS, 16)

        eng = _engine(tmp_path)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=16) for p in PROMPTS]
        chaos.install(chaos.ChaosSchedule(
            [chaos.ChaosRule("serve.mid_step", hit=kill_step)]
        ))
        with pytest.raises(chaos.ChaosKilled):
            srv.run()
        chaos.uninstall()

        # restart: a fresh engine over the same journal dir replays it
        eng2 = _engine(tmp_path)
        srv2 = eng2._paged_server
        assert srv2.stats["recovered"] == len(PROMPTS)
        srv2.run()
        outs = [srv2.take_result(u) for u in uids]
        for got, want in zip(outs, ref):
            np.testing.assert_array_equal(got, want)
        srv2.pool.integrity_check()

    def test_recovery_with_prefix_cache_shared_prompts(self, tmp_path, eight_devices):
        """Re-prefill of recovered requests rides the prefix cache: shared
        system prompts attach instead of recomputing, and the streams stay
        byte-identical."""
        ref = self._reference(SHARED_PROMPTS, 12)

        eng = _engine(tmp_path, prefix_cache=True)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=12) for p in SHARED_PROMPTS]
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("serve.mid_step", hit=4)]))
        with pytest.raises(chaos.ChaosKilled):
            srv.run()
        chaos.uninstall()

        eng2 = _engine(tmp_path, prefix_cache=True)
        srv2 = eng2._paged_server
        srv2.run()
        for uid, want in zip(uids, ref):
            np.testing.assert_array_equal(srv2.take_result(uid), want)

    def test_finished_results_survive_restart(self, tmp_path, eight_devices):
        eng = _engine(tmp_path)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=6) for p in PROMPTS]
        srv.run()
        done = {u: srv.result(u) for u in uids}
        assert all(v is not None for v in done.values())

        # crash AFTER completion, before anyone fetched the results
        eng2 = _engine(tmp_path)
        srv2 = eng2._paged_server
        assert srv2.stats["recovered"] == 0  # nothing live to re-run
        for u in uids:
            np.testing.assert_array_equal(srv2.take_result(u), done[u])

    def test_new_submissions_after_recovery_get_fresh_uids(self, tmp_path, eight_devices):
        eng = _engine(tmp_path)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=4) for p in PROMPTS[:2]]
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("serve.mid_step", hit=1)]))
        with pytest.raises(chaos.ChaosKilled):
            srv.run()
        chaos.uninstall()

        eng2 = _engine(tmp_path)
        srv2 = eng2._paged_server
        new_uid = srv2.submit(PROMPTS[2], max_new_tokens=4)
        assert new_uid not in uids  # the journal advanced the uid counter
        srv2.run()
        assert srv2.take_result(new_uid) is not None
        srv2.pool.integrity_check()

    def test_recovery_compacts_and_retires_old_segments(self, tmp_path, eight_devices):
        """Repeated crash/recover cycles must not grow the journal: each
        recovery re-journals the full state (live + finished) into one
        fresh segment and retires everything it supersedes."""
        eng = _engine(tmp_path)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=6) for p in PROMPTS]
        srv.run()
        done = {u: srv.result(u) for u in uids}
        for _ in range(3):
            eng2 = _engine(tmp_path)  # restart: replay + compact + retire
            srv2 = eng2._paged_server
            assert len(RequestJournal.segments(str(tmp_path))) == 1
            for u in uids:
                np.testing.assert_array_equal(srv2.result(u), done[u])

    def test_journal_disabled_leaves_no_files(self, tmp_path, eight_devices):
        eng = _engine()  # no journal config
        eng.serve(PROMPTS[:2], max_new_tokens=4)
        assert eng._paged_server.journal is None
        assert list(tmp_path.iterdir()) == []

    def test_spec_decode_streams_survive_crash(self, tmp_path, eight_devices):
        """Speculative serving journals through the same _emit path: a
        crash mid-round recovers byte-identically (drafts are host-side
        scratch — only accepted tokens are journaled)."""
        def eng_spec(jd=None):
            mesh_mod.reset_topology()
            kw = dict(
                dtype="bf16", paged_kv={**PAGED, "attn_impl": "xla"},
                spec_decode={"enable": True, "max_draft": 3},
            )
            if jd is not None:
                kw["journal"] = {"enabled": True, "dir": str(jd)}
            e = ds.init_inference(TransformerLM(CFG), **kw)
            e.init_params(np.stack(PROMPTS))
            e._ds_config = CFG
            e._paged_server = e._build_paged_server()
            return e

        ref = eng_spec().serve(PROMPTS, max_new_tokens=12)
        eng = eng_spec(tmp_path)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=12) for p in PROMPTS]
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("serve.mid_step", hit=2)]))
        with pytest.raises(chaos.ChaosKilled):
            srv.run()
        chaos.uninstall()
        eng2 = eng_spec(tmp_path)
        srv2 = eng2._paged_server
        srv2.run()
        for uid, want in zip(uids, ref):
            np.testing.assert_array_equal(srv2.take_result(uid), want)


# ---------------------------------------------------------------------------
# multi-step windows: one durability point per window, crash mid-window
# ---------------------------------------------------------------------------
MS = {"multi_step": {"enable": True, "horizon": 4}}


class TestMultiStepWindowRecovery:
    @pytest.mark.parametrize("hit", [1, 2])
    def test_mid_window_crash_streams_resume_byte_identical(
        self, tmp_path, eight_devices, hit
    ):
        """A crash INSIDE a window's host phase (every token of the window
        buffered in the journal, none acked) replays byte-identically from
        the last acked token — the window's whole emission is re-derived
        by the greedy re-prefill, whether the restarted engine windows or
        not."""
        ref = _engine(**MS).serve(PROMPTS, max_new_tokens=16)

        eng = _engine(tmp_path, **MS)
        srv = eng._paged_server
        uids = [srv.submit(p, max_new_tokens=16) for p in PROMPTS]
        chaos.install(chaos.ChaosSchedule(
            [chaos.ChaosRule("serve.mid_window", hit=hit)]
        ))
        with pytest.raises(chaos.ChaosKilled):
            srv.run()
        chaos.uninstall()
        assert srv.stats["window_steps"] >= hit  # the armed point really fired

        # restart once windowed, once single-step: the journal contract is
        # identical — byte-identical resumption from the last acked token
        over = MS if hit == 1 else {}
        eng2 = _engine(tmp_path, **over)
        srv2 = eng2._paged_server
        assert srv2.stats["recovered"] == len(PROMPTS)
        srv2.run()
        for uid, want in zip(uids, ref):
            np.testing.assert_array_equal(srv2.take_result(uid), want)
        srv2.pool.integrity_check()

    def test_window_journal_syncs_once_per_window(self, tmp_path, eight_devices):
        """Durability is amortized with the dispatches: buffered tokens
        land in ONE ``journal.sync`` per scheduler step, so a window's
        worth of tokens costs a single durability point — far fewer syncs
        than emitted tokens (the single-step path pays one per token)."""
        eng = _engine(tmp_path, **MS)
        srv = eng._paged_server
        outs = eng.serve(PROMPTS, max_new_tokens=13)
        ref = _engine(**MS).serve(PROMPTS, max_new_tokens=13)
        for got, want in zip(outs, ref):
            np.testing.assert_array_equal(got, want)
        st = srv.serve_stats()
        assert st["window_steps"] >= 1
        syncs = [
            s for s in eng.tracer.spans() if s["name"] == "serve.journal_sync"
        ]
        steps = [s for s in eng.tracer.spans() if s["name"] == "serve.step"]
        assert len(syncs) == len(steps)  # one durability point per step
        # the amortization: a window's tokens share one sync
        assert len(syncs) < st["emitted_tokens"], (len(syncs), st["emitted_tokens"])
