"""One ragged serving program (ISSUE 8): unified prefill+decode+verify
dispatch that never retraces.

Load-bearing checks: with ``ragged=True`` (the default) every scheduler
step is ONE dispatch of the unified ``build_ragged_step`` program and the
greedy output streams are BYTE-IDENTICAL to the bucketed per-shape path
(``ragged=False``, the token-exactness oracle) and to the dense lockstep
``decode.generate`` — across mid-stream admission, preemption+resume on
the chunk grid, prefix-cache attach, a per-request spec-K mix, and EOS
landing inside an accepted draft run. Compile telemetry must show ≤ 2
compiled serving programs for a full mixed serve and 1 dispatch per step
(the companion analysis gate lives in
``tests/unit/analysis/test_passes.py::test_green_ragged_serving_program_and_compile_gate``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.scheduler import PagedServer, compiled_serving_programs
from deepspeed_tpu.inference.spec_decode import Drafter
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.compile_telemetry import CompileTelemetry

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA on the serving path
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _prompts(n, seed=0, lo=3, hi=20):
    rs = np.random.RandomState(seed)
    return [
        rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)
        for _ in range(n)
    ]


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, ragged=True, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    return PagedServer(cfg, params, ragged=ragged, **kw)


class MixKDrafter(Drafter):
    """Per-request spec-K mix: request uid drafts its precomputed greedy
    future, but only ``uid % (cap+1)`` tokens of it — every ragged round
    carries rows with DIFFERENT draft counts (incl. zero) at once, the
    shape the bucketed path could only serve by freezing K per program."""

    def __init__(self, futures, cap=3):
        self.futures = futures
        self.cap = cap

    def propose(self, uid, context, k):
        k = min(k, uid % (self.cap + 1))
        return self.futures[uid][context.size : context.size + k].astype(np.int32)


# --- token exactness: ragged vs bucketed vs dense ---------------------------
def test_ragged_matches_bucketed_and_dense_mixed_serve(model_and_params):
    """The core exactness oracle: same ragged request mix through both
    paths, byte-identical streams, pool drained."""
    cfg, _, params = model_and_params
    prompts = _prompts(6, seed=2)
    budgets = [10, 3, 7, 12, 1, 5]
    ragged = _server(cfg, params, ragged=True)
    outs = ragged.serve(prompts, max_new_tokens=budgets)
    bucketed = _server(cfg, params, ragged=False)
    oracle = bucketed.serve(prompts, max_new_tokens=budgets)
    for p, n, a, b in zip(prompts, budgets, outs, oracle):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, n))
        np.testing.assert_array_equal(a, b)
    assert ragged.stats["finished"] == 6
    assert ragged.stats["ragged_steps"] >= 1 and bucketed.stats["ragged_steps"] == 0
    assert ragged.pool.used_pages() == 0 and ragged.pool.live_tokens() == 0


def test_ragged_admission_mid_stream(model_and_params):
    """Requests submitted while others are mid-decode join the SAME ragged
    dispatch as running decoders: their prefill chunks ride along instead
    of stealing steps, and nothing disturbs in-flight streams."""
    cfg, _, params = model_and_params
    server = _server(cfg, params)
    prompts = _prompts(4, seed=3)
    first = [server.submit(p, max_new_tokens=12) for p in prompts[:2]]
    for _ in range(6):  # prefill + several decode steps for wave 1
        server.step()
    assert server.stats["decode_steps"] >= 1
    late = [server.submit(p, max_new_tokens=12) for p in prompts[2:]]
    results = server.run()
    for uid, p in zip(first + late, prompts):
        np.testing.assert_array_equal(results[uid], _dense(cfg, params, p, 12))
    # the late admissions' chunks coexisted with wave-1 decoders: every
    # step was still exactly one dispatch
    assert server.stats["ragged_steps"] >= server.stats["decode_steps"]


def test_ragged_prefill_coexists_with_decode(model_and_params):
    """A long multi-chunk prompt admitted next to a short one: once the
    short request starts decoding, the long one's remaining chunks share
    its dispatches — total dispatches stay well under the bucketed path's
    chunks + decode steps."""
    cfg, _, params = model_and_params
    rs = np.random.RandomState(9)
    short = rs.randint(0, 128, (4,)).astype(np.int32)
    long = rs.randint(0, 128, (40,)).astype(np.int32)
    server = _server(cfg, params)
    uids = [server.submit(short, max_new_tokens=10),
            server.submit(long, max_new_tokens=4)]
    results = server.run()
    np.testing.assert_array_equal(results[uids[0]], _dense(cfg, params, short, 10))
    np.testing.assert_array_equal(results[uids[1]], _dense(cfg, params, long, 4))
    st = server.stats
    # 40-token prompt = 5 chunks; the short request decodes through 4+ of
    # those same dispatches — strictly fewer total dispatches than the
    # bucketed schedule's (chunks + decode steps)
    assert st["prefill_chunks"] >= 6
    assert st["ragged_steps"] < st["prefill_chunks"] + st["decode_steps"]


def test_ragged_preemption_resume_on_chunk_grid(model_and_params):
    """An undersized pool forces preemption mid-stream; the resumed prefill
    realigns to the chunk grid and the recomputed continuation is exact —
    in BOTH paths, and identical between them."""
    cfg, _, params = model_and_params
    kw = dict(page_size=4, num_pages=14, max_slots=3, prefill_chunk=8)
    prompts = _prompts(4, seed=4, lo=6, hi=14)
    ragged = _server(cfg, params, ragged=True, **kw)
    outs = ragged.serve(prompts, max_new_tokens=12)
    assert ragged.stats["preempted"] >= 1, "pool was sized to force preemption"
    oracle = _server(cfg, params, ragged=False, **kw).serve(prompts, max_new_tokens=12)
    for p, a, b in zip(prompts, outs, oracle):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, 12))
        np.testing.assert_array_equal(a, b)
    assert ragged.pool.used_pages() == 0


def test_ragged_prefix_cache_attach(model_and_params):
    """Warm prefix attaches (chunk-grid realigned resume after an attach
    that lands mid-grid) ride the ragged path unchanged: second serve of
    shared-prefix prompts attaches pages AND stays byte-identical."""
    cfg, _, params = model_and_params
    rs = np.random.RandomState(21)
    sys_tokens = rs.randint(0, 128, (19,)).astype(np.int32)  # 2 pages + 3 mid-grid
    prompts = [
        np.concatenate([sys_tokens, rs.randint(0, 128, (3 + i,)).astype(np.int32)])
        for i in range(4)
    ]
    server = _server(cfg, params, prefix_cache=True)
    first = server.serve(prompts[:1], max_new_tokens=4)
    rest = server.serve(prompts[1:], max_new_tokens=4)
    assert server.pool.stats["prefix_hit_pages"] > 0, "prefix cache never engaged"
    off = _server(cfg, params, prefix_cache=False)
    oracle = off.serve(prompts, max_new_tokens=4)
    for p, a, b in zip(prompts, first + rest, oracle):
        np.testing.assert_array_equal(a, _dense(cfg, params, p, 4))
        np.testing.assert_array_equal(a, b)


def test_ragged_per_request_spec_k_mix(model_and_params):
    """Per-request spec-K inside one dispatch — the shape the bucketed
    path cannot express (its verify programs freeze K): rows drafting 0,
    1, 2, and 3 tokens verify together, streams stay byte-identical to
    spec-off serving and dense."""
    cfg, _, params = model_and_params
    prompts = _prompts(4, seed=5)
    futures = {i: _dense(cfg, params, p, 12) for i, p in enumerate(prompts)}
    server = _server(
        cfg, params, drafter=MixKDrafter(futures), spec_decode={"max_draft": 3}
    )
    outs = server.serve(prompts, max_new_tokens=12)
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(out, futures[i])
    st = server.serve_stats()
    assert st["spec_rounds"] >= 1 and st["spec_accepted"] >= 1
    # the mix really was ragged: some rounds carried zero-draft rows next
    # to drafted ones (uid 0 never drafts; uids 1-3 do)
    assert st["decode_steps"] >= 1
    # all of it through ONE program width — no per-K verify programs
    assert server.pool.used_pages() == 0


def test_ragged_eos_in_accepted_run(model_and_params):
    """EOS landing inside an accepted draft run retires the request at the
    EOS token exactly like sequential decode, on the ragged path."""
    cfg, _, params = model_and_params
    prompts = _prompts(2, seed=7)
    futures = {i: _dense(cfg, params, p, 10) for i, p in enumerate(prompts)}
    eos = int(futures[0][prompts[0].size + 2])

    class FullDrafter(Drafter):
        def propose(self, uid, context, k):
            return futures[uid][context.size : context.size + k].astype(np.int32)

    server = _server(cfg, params, drafter=FullDrafter())
    outs = server.serve(prompts, max_new_tokens=10, eos_token_id=eos)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 10, eos=eos))
    assert server.stats["spec_rounds"] >= 1


# --- compile budget & dispatch contract -------------------------------------
def test_ragged_compile_budget_and_one_dispatch_per_step(model_and_params):
    """3-wave shifting mix through one telemetry: ≤ 2 compiled serving
    programs TOTAL (warmup aside, no wave adds a compile), exactly one
    ragged dispatch per scheduler step, and ZERO bucketed programs."""
    cfg, _, params = model_and_params
    telemetry = CompileTelemetry()
    server = _server(cfg, params, telemetry=telemetry)
    waves = [_prompts(2, seed=6), _prompts(4, seed=7), _prompts(2, seed=8)]
    compiles = []
    for wave in waves:
        outs = server.serve(wave, max_new_tokens=6)
        for p, out in zip(wave, outs):
            np.testing.assert_array_equal(out, _dense(cfg, params, p, 6))
        compiles.append(sum(r["compiles"] for r in telemetry.stats().values()))
    stats = telemetry.stats()
    assert all(n.startswith("paged_ragged_") for n in stats), stats.keys()
    assert compiled_serving_programs(stats) <= 2, stats
    assert compiles[1] == compiles[0] and compiles[2] == compiles[0], compiles
    assert sum(r["dispatches"] for r in stats.values()) == server.stats["ragged_steps"]


def test_ragged_knob_through_engine(model_and_params):
    """paged_kv.ragged=False routes the engine's serve() to the bucketed
    oracle; the default routes to the ragged program. Outputs identical."""
    cfg, model, params = model_and_params
    outs = {}
    for ragged in (True, False):
        engine = ds.init_inference(
            model,
            dtype="fp32",
            paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8,
                      "attn_impl": "xla", "ragged": ragged},
        )
        engine.set_params(params)
        engine._ds_config = cfg  # converted-family contract
        prompts = _prompts(3, seed=11)
        outs[ragged] = engine.serve(prompts, max_new_tokens=5)
        names = list(engine.compile_stats())
        if ragged:
            assert any(n.startswith("paged_ragged_") for n in names), names
            assert engine.serve_stats()["ragged_steps"] >= 1
        else:
            assert any(n.startswith("paged_decode_") for n in names), names
            assert not any(n.startswith("paged_ragged_") for n in names)
    for a, b in zip(outs[True], outs[False]):
        np.testing.assert_array_equal(a, b)
