"""Production-traffic layer tests: prefix-cached KV sharing, SLA
multi-tenant scheduling, and the trace-replay load harness.

Load-bearing checks (ISSUE 6 acceptance):

* with prefix caching AND the tenant scheduler enabled, greedy output
  streams are byte-identical to sharing-off single-tenant serving for the
  same request set — across preemption, priority scheduling, and warm
  prefix attaches;
* N requests with a common prefix hold its KV pages exactly once
  (asserted via pool refcounts/accounting mid-stream);
* the replay harness reports p50/p99 TTFT/TPOT and shows no tenant
  starved under overload.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference import decode
from deepspeed_tpu.inference.scheduler import PagedServer, Request
from deepspeed_tpu.inference.traffic import MultiTenantServer, SLAPolicy, TenantSpec
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.utils.loadgen import (
    TenantLoad,
    TraceRequest,
    VirtualClock,
    make_trace,
    replay,
)

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,  # GQA on the serving path
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _dense(cfg, params, prompt, n, eos=None):
    return np.asarray(decode.generate(cfg, params, prompt[None], n, eos_token_id=eos))[0]


def _server(cfg, params, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("prefix_cache", True)
    return PagedServer(cfg, params, **kw)


def _sys_prompt(seed=21, n=16):
    rs = np.random.RandomState(seed)
    return rs.randint(0, CFG["vocab_size"], (n,)).astype(np.int32)


def _shared_prompts(n, sys_tokens, seed=22, lo=3, hi=8):
    rs = np.random.RandomState(seed)
    return [
        np.concatenate(
            [sys_tokens, rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)]
        )
        for _ in range(n)
    ]


# ---------------------------------------------------------------------------
# acceptance: token-exactness with sharing + tenants (+ preemption) on
# ---------------------------------------------------------------------------
def test_traffic_prefix_cached_streams_byte_identical(model_and_params):
    """Prefix caching + SLA tenant scheduling + an undersized pool (forced
    preemption) vs the dense single-request decode: every output stream
    byte-identical, and the prefix cache actually engaged."""
    cfg, _, params = model_and_params
    sys_tokens = _sys_prompt()
    prompts = _shared_prompts(6, sys_tokens)
    budgets = [10, 3, 7, 12, 1, 5]
    # sized to force preemption even though sharing shrinks the footprint
    # (the shared 16-token prefix is 4 pages paid once instead of per-slot;
    # 16 pages still preempts under the ragged step cadence, where a
    # finishing prefill's first decode lands a step later than bucketed)
    base = _server(
        cfg, params, page_size=4, num_pages=16, max_slots=3, prefill_chunk=8
    )
    server = MultiTenantServer(
        base,
        tenants=[
            TenantSpec(name="gold", weight=3.0, priority=1),
            TenantSpec(name="free", weight=1.0),
        ],
    )
    tenants = ["gold" if i % 2 else "free" for i in range(6)]
    outs = server.serve(prompts, max_new_tokens=budgets, tenant=tenants)
    for p, n, out in zip(prompts, budgets, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, n))
    assert base.stats["preempted"] >= 1, "pool was sized to force preemption"
    stats = server.serve_stats()
    assert stats["prefix"]["prefix_hit_tokens"] > 0, "prefix cache never engaged"
    assert stats["prefix_cached_tokens"] > 0
    # per-tenant breakdowns ride in serve_stats
    assert stats["tenants"]["gold"]["finished"] == 3
    assert stats["tenants"]["free"]["finished"] == 3
    assert stats["tenants"]["gold"]["budget_share"] == pytest.approx(0.6)


def test_shared_prompt_pages_allocated_once_mid_stream(model_and_params):
    """Acceptance: while N requests sharing a system prompt are live, the
    prompt's full pages appear once in the pool with refcount N."""
    cfg, _, params = model_and_params
    sys_tokens = _sys_prompt()  # 16 tokens = 2 full pages at page_size 8
    server = _server(cfg, params)
    # warm: one request pays the prefill and publishes the pages
    warm = _shared_prompts(1, sys_tokens, seed=30)
    server.serve(warm, max_new_tokens=2)
    assert server.pool.stats["registered_pages"] >= 2
    prompts = _shared_prompts(3, sys_tokens, seed=31)
    uids = [server.submit(p, max_new_tokens=6) for p in prompts]
    for _ in range(3):  # admit + prefill everyone past the shared prefix
        server.step()
    live = [r for r in server._active]
    assert len(live) == 3
    rows = [server.pool.page_table[r.slot][:2] for r in live]
    for row in rows[1:]:
        np.testing.assert_array_equal(row, rows[0])  # one copy, three tables
    for pid in rows[0]:
        assert int(server.pool._refcount[int(pid)]) == 3
    assert server.pool.stats["prefix_hit_pages"] == 6  # 3 attaches x 2 pages
    results = server.run()
    for uid, p in zip(uids, prompts):
        np.testing.assert_array_equal(results[uid], _dense(cfg, params, p, 6))
    # drained: only the cached prefix pages remain (refcount 0, reclaimable)
    assert server.pool.used_pages() == 0


def test_preempted_request_reattaches_its_own_prefix(model_and_params):
    """A preempted request's re-prefill matches the pages it registered
    before eviction — recompute preemption gets cheaper, stays exact."""
    cfg, _, params = model_and_params
    server = _server(
        cfg, params, page_size=4, num_pages=16, max_slots=3, prefill_chunk=8
    )
    rs = np.random.RandomState(33)
    prompts = [
        rs.randint(0, cfg.vocab_size, (n,)).astype(np.int32) for n in (14, 12, 10, 9)
    ]
    outs = server.serve(prompts, max_new_tokens=12)
    assert server.stats["preempted"] >= 1
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 12))


# ---------------------------------------------------------------------------
# SLA policy mechanics
# ---------------------------------------------------------------------------
def _fake_req(uid, tenant):
    return Request(uid=uid, prompt=np.zeros(4, np.int32), max_new_tokens=4,
                   tenant=tenant)


def test_sla_policy_preemption_victim_ordering():
    """Victims: lowest priority class first, most-over-budget tenant next,
    youngest admission last — and always total."""
    policy = SLAPolicy({
        "hi": TenantSpec(name="hi", priority=1),
        "lo": TenantSpec(name="lo", priority=0),
        "lo2": TenantSpec(name="lo2", priority=0),
    })
    hi, lo_old, lo_young = _fake_req(0, "hi"), _fake_req(1, "lo"), _fake_req(2, "lo")
    # priority dominates: the hi request survives even though it is younger
    assert policy.preemption_victim([lo_old, hi, lo_young], None) is lo_young
    # same class: most-over-budget tenant yields first
    policy.served = {"lo": 100, "lo2": 0}
    lo2 = _fake_req(3, "lo2")
    assert policy.preemption_victim([lo2, lo_old], None) is lo_old
    # only a high-priority candidate left: liveness beats priority
    assert policy.preemption_victim([hi], None) is hi


def test_sla_policy_admission_prefers_underserved_and_priority():
    policy = SLAPolicy({
        "a": TenantSpec(name="a", weight=1.0),
        "b": TenantSpec(name="b", weight=1.0),
        "vip": TenantSpec(name="vip", priority=2),
    })
    qa, qb = _fake_req(0, "a"), _fake_req(1, "b")
    policy.served = {"a": 50, "b": 10}
    pick = policy.next_admission([qa, qb], None)
    assert pick is qb  # underserved tenant first
    vip = _fake_req(2, "vip")
    assert policy.next_admission([qa, qb, vip], None) is vip  # priority wins


def test_sla_deficit_spans_backlog_periods_only():
    """Tokens served while others were idle must not buy an unbounded
    catch-up window: a newly backlogged tenant joins at the current
    service floor, and a drained tenant's counter resets."""
    policy = SLAPolicy({
        "a": TenantSpec(name="a", weight=1.0),
        "b": TenantSpec(name="b", weight=1.0),
    })
    qa, qb = _fake_req(0, "a"), _fake_req(1, "b")
    # a runs alone and racks up service
    policy.next_admission([qa], None)
    policy.served["a"] = 1000.0
    # b floods while a is STILL backlogged: b joins at a's floor, not 0 —
    # a is not locked out for a 1000-token catch-up window
    assert policy.next_admission([qa, qb], None) is qa  # tie -> first seen
    assert policy.served["b"] == 1000.0
    # a drains completely; its lifetime counter dies with the backlog
    policy.next_admission([qb], None)
    assert "a" not in policy.served
    # when a returns it competes from the current floor immediately
    policy.served["b"] = 40.0
    policy.next_admission([qa, qb], None)
    assert policy.served["a"] == 40.0


def test_admission_control_rejects_over_queue_cap(model_and_params):
    cfg, _, params = model_and_params
    server = MultiTenantServer(
        _server(cfg, params),
        tenants=[TenantSpec(name="capped", max_queued=2)],
    )
    prompts = _shared_prompts(5, _sys_prompt(), seed=40)
    uids = [server.submit(p, max_new_tokens=3, tenant="capped") for p in prompts]
    assert uids[2:] == [None, None, None]  # queue cap sheds the overflow
    assert all(u is not None for u in uids[:2])
    server.run()
    stats = server.serve_stats()
    assert stats["tenants"]["capped"]["rejected"] == 3
    assert stats["tenants"]["capped"]["finished"] == 2
    with pytest.raises(KeyError, match="unknown tenant"):
        server.submit(prompts[0], tenant="nobody")


# ---------------------------------------------------------------------------
# load generator
# ---------------------------------------------------------------------------
def test_make_trace_deterministic_and_heavy_tailed():
    tenants = [
        TenantLoad(name="a", rate=20.0, pareto_alpha=1.3, n_prefixes=2,
                   prefix_len=16, shared_prefix_prob=0.7),
        TenantLoad(name="b", rate=10.0),
    ]
    t1 = make_trace(tenants, horizon_s=2.0, vocab_size=128, seed=5)
    t2 = make_trace(tenants, horizon_s=2.0, vocab_size=128, seed=5)
    assert len(t1) == len(t2) > 10
    for r1, r2 in zip(t1, t2):
        assert r1.at == r2.at and r1.tenant == r2.tenant
        np.testing.assert_array_equal(r1.prompt, r2.prompt)
    assert [r.at for r in t1] == sorted(r.at for r in t1)
    # a different seed produces a different trace
    t3 = make_trace(tenants, horizon_s=2.0, vocab_size=128, seed=6)
    assert len(t3) != len(t1) or any(
        r1.at != r3.at for r1, r3 in zip(t1, t3)
    )
    # the shared-prefix mixture fires: repeated full prefixes exist
    shared = [r for r in t1 if r.tenant == "a" and r.prefix_id >= 0]
    assert len(shared) > 2
    heads = {r.prompt[:16].tobytes() for r in shared}
    assert len(heads) <= 2  # drawn from the tenant's 2 system prompts


def test_virtual_clock_replay_is_deterministic(model_and_params):
    """Same trace + same virtual clock -> identical latency report."""
    cfg, _, params = model_and_params

    def run_once():
        ck = VirtualClock(step_cost_s=0.02)
        server = _server(cfg, params, clock=ck)
        trace = make_trace(
            [TenantLoad(name="a", rate=15.0, prompt_len=(4, 10),
                        max_new_tokens=(2, 5), n_prefixes=1, prefix_len=8)],
            horizon_s=1.0, vocab_size=cfg.vocab_size, seed=7,
        )
        return replay(server, trace, clock=ck, keep_outputs=False)

    r1, r2 = run_once(), run_once()
    assert r1["ttft_ms"] == r2["ttft_ms"]
    assert r1["tpot_ms"] == r2["tpot_ms"]
    assert r1["steps"] == r2["steps"]
    assert r1["ttft_ms"]["count"] == r1["n_requests"] - r1["n_rejected"]
    assert r1["ttft_ms"]["p99"] >= r1["ttft_ms"]["p50"] > 0


# ---------------------------------------------------------------------------
# the traffic-replay smoke (wired into tools/fast_tests.sh): 2 tenants,
# shared prefixes, overload flood vs trickle — no starvation, SLA fairness
# beats FIFO for the trickle tenant, streams byte-identical to sharing-off
# single-tenant serving
# ---------------------------------------------------------------------------
def _flood_trickle_trace(sys_tokens):
    rs = np.random.RandomState(50)
    trace = []
    for i in range(10):  # tenant A floods the server at t~0
        tail = rs.randint(0, CFG["vocab_size"], (3 + i % 4,)).astype(np.int32)
        trace.append(TraceRequest(
            at=0.001 * i, tenant="flood",
            prompt=np.concatenate([sys_tokens, tail]), max_new_tokens=5,
        ))
    for j in range(4):  # tenant B trickles in while A's backlog drains
        trace.append(TraceRequest(
            at=0.15 + 0.4 * j, tenant="trickle",
            prompt=rs.randint(0, CFG["vocab_size"], (8,)).astype(np.int32),
            max_new_tokens=5,
        ))
    trace.sort(key=lambda r: r.at)
    for i, r in enumerate(trace):
        r.index = i
    return trace


def _replay_once(cfg, params, trace, sla: bool):
    ck = VirtualClock(step_cost_s=0.05)
    server = _server(cfg, params, max_slots=2, clock=ck)
    if sla:
        server = MultiTenantServer(server, tenants=[
            TenantSpec(name="flood", weight=1.0, ttft_target_ms=20_000),
            TenantSpec(name="trickle", weight=1.0, ttft_target_ms=2_000),
        ])
    return replay(server, trace, clock=ck)


def test_traffic_replay_smoke_no_starvation_and_exact(model_and_params):
    cfg, _, params = model_and_params
    sys_tokens = _sys_prompt(seed=51)
    trace = _flood_trickle_trace(sys_tokens)
    rep = _replay_once(cfg, params, trace, sla=True)
    # everyone finished, nobody starved, latency percentiles reported
    assert rep["n_rejected"] == 0
    assert rep["starved_tenants"] == []
    for name in ("flood", "trickle"):
        assert rep["tenants"][name]["finished"] == rep["tenants"][name]["offered"]
        assert rep["tenants"][name]["ttft_ms"]["p50"] > 0
    # the flood shares its system prompt: the pool paid it once
    assert rep["prefix_hit_rate"] > 0.2
    # deficit fairness: the trickle tenant is not stuck behind the flood —
    # its median TTFT beats the flood's, and beats its own TTFT under FIFO
    fifo = _replay_once(cfg, params, trace, sla=False)
    sla_trickle = rep["tenants"]["trickle"]["ttft_ms"]["p50"]
    assert sla_trickle < rep["tenants"]["flood"]["ttft_ms"]["p50"]
    assert sla_trickle <= fifo["tenants"]["trickle"]["ttft_ms"]["p50"]
    # acceptance: byte-identical to sharing-off single-tenant serving
    off = _server(cfg, params, max_slots=2, prefix_cache=False)
    expected = off.serve([r.prompt for r in trace],
                         max_new_tokens=[r.max_new_tokens for r in trace])
    for r, exp in zip(trace, expected):
        np.testing.assert_array_equal(rep["outputs"][r.index], exp)


def test_engine_traffic_wiring(model_and_params):
    """Engine surface: paged_kv.prefix_cache + traffic config build a
    MultiTenantServer under serve(); serve_stats carries the per-tenant
    budget breakdowns."""
    cfg, model, params = model_and_params
    engine = ds.init_inference(
        model,
        dtype="fp32",
        paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8,
                  "attn_impl": "xla", "prefix_cache": True},
        traffic={"enabled": True,
                 "tenants": [{"name": "default", "weight": 2.0},
                             {"name": "batch", "weight": 1.0, "priority": -1}]},
    )
    engine.set_params(params)
    engine._ds_config = cfg  # converted-family contract (containers set this)
    prompts = _shared_prompts(3, _sys_prompt(seed=60), seed=61)
    outs = engine.serve(prompts, max_new_tokens=6)
    for p, out in zip(prompts, outs):
        np.testing.assert_array_equal(out, _dense(cfg, params, p, 6))
    stats = engine.serve_stats()
    assert isinstance(engine._paged_server, MultiTenantServer)
    assert stats["tenants"]["default"]["budget_share"] == pytest.approx(2 / 3)
    assert stats["tenants"]["batch"]["priority"] == -1
    assert "prefix" in stats and "ttft_ms" in stats
