"""Injection-policy breadth tests: every new architecture's converted
weights must reproduce the HF torch model's outputs (reference
``tests/unit/inference/test_inference.py`` parametrized-zoo pattern).
Megatron layouts have no installable HF model, so those policies are
exercised on handcrafted state dicts in the Megatron naming.
"""

from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")

import deepspeed_tpu as ds  # noqa: E402
import deepspeed_tpu.parallel.mesh as mesh_mod  # noqa: E402


def _logits(engine, toks):
    return np.asarray(engine.forward(toks.astype(np.int32)), np.float32)


class TestBertInjection:
    def test_hidden_parity_with_torch(self):
        cfg = transformers.BertConfig(
            vocab_size=128,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=64,
            hidden_dropout_prob=0.0,
            attention_probs_dropout_prob=0.0,
        )
        model = transformers.BertModel(cfg)
        model.eval()
        toks = np.random.RandomState(0).randint(0, 128, (2, 10)).astype(np.int64)
        with torch.no_grad():
            hidden = model(torch.from_numpy(toks)).last_hidden_state.numpy()
        wte = model.embeddings.word_embeddings.weight.detach().numpy()
        ref = hidden @ wte.T  # our tied head on the encoder output

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = _logits(engine, toks)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


class TestDistilBertInjection:
    def test_hidden_parity_with_torch(self):
        cfg = transformers.DistilBertConfig(
            vocab_size=128,
            dim=32,
            n_layers=2,
            n_heads=4,
            hidden_dim=64,
            max_position_embeddings=64,
            dropout=0.0,
            attention_dropout=0.0,
        )
        model = transformers.DistilBertModel(cfg)
        model.eval()
        toks = np.random.RandomState(1).randint(0, 128, (2, 9)).astype(np.int64)
        with torch.no_grad():
            hidden = model(torch.from_numpy(toks)).last_hidden_state.numpy()
        wte = model.embeddings.word_embeddings.weight.detach().numpy()
        ref = hidden @ wte.T

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = _logits(engine, toks)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


class TestGPTNeoInjection:
    def test_logits_parity_with_torch(self):
        cfg = transformers.GPTNeoConfig(
            vocab_size=128,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            max_position_embeddings=64,
            attention_types=[[["global"], 2]],  # all-global: full parity
            resid_dropout=0.0,
            embed_dropout=0.0,
            attention_dropout=0.0,
        )
        model = transformers.GPTNeoForCausalLM(cfg)
        model.eval()
        toks = np.random.RandomState(2).randint(0, 128, (2, 12)).astype(np.int64)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks)).logits.numpy()

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = _logits(engine, toks)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


class TestGPTNeoDecode:
    def test_kv_generate_matches_torch_greedy(self):
        """The KV-cache decode path must honor GPT-Neo's unscaled attention
        (attn_softmax_scale=1.0), not re-apply 1/sqrt(D)."""
        cfg = transformers.GPTNeoConfig(
            vocab_size=128,
            hidden_size=32,
            num_layers=2,
            num_heads=4,
            max_position_embeddings=64,
            attention_types=[[["global"], 2]],
            resid_dropout=0.0,
            embed_dropout=0.0,
            attention_dropout=0.0,
        )
        model = transformers.GPTNeoForCausalLM(cfg)
        model.eval()
        toks = np.random.RandomState(7).randint(0, 128, (2, 6)).astype(np.int64)
        with torch.no_grad():
            ref = model.generate(
                torch.from_numpy(toks), max_new_tokens=4, do_sample=False
            ).numpy()

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = np.asarray(engine.generate(toks.astype(np.int32), max_new_tokens=4))
        np.testing.assert_array_equal(out, ref)


class TestCLIPTextInjection:
    def test_hidden_parity_with_torch(self):
        cfg = transformers.CLIPTextConfig(
            vocab_size=99,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=32,
            hidden_act="quick_gelu",
        )
        model = transformers.CLIPTextModel(cfg)
        model.eval()
        toks = np.random.RandomState(3).randint(0, 99, (2, 8)).astype(np.int64)
        with torch.no_grad():
            hidden = model(torch.from_numpy(toks)).last_hidden_state.numpy()
        wte = model.text_model.embeddings.token_embedding.weight.detach().numpy()
        ref = hidden @ wte.T

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = _logits(engine, toks)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def _megatron_sd(L=2, H=32, NH=4, V=128, I=64, T=64, E=0, seed=0):
    """Handcrafted Megatron-LM GPT state dict (per-head interleaved qkv)."""
    rs = np.random.RandomState(seed)
    D = H // NH
    sd = {
        "language_model.embedding.word_embeddings.weight": rs.randn(V, H) * 0.02,
        "language_model.embedding.position_embeddings.weight": rs.randn(T, H) * 0.02,
        "language_model.transformer.final_layernorm.weight": np.ones(H),
        "language_model.transformer.final_layernorm.bias": np.zeros(H),
    }
    for i in range(L):
        p = f"language_model.transformer.layers.{i}."
        sd[p + "input_layernorm.weight"] = np.ones(H)
        sd[p + "input_layernorm.bias"] = np.zeros(H)
        sd[p + "attention.query_key_value.weight"] = rs.randn(3 * H, H) * 0.02
        sd[p + "attention.query_key_value.bias"] = np.zeros(3 * H)
        sd[p + "attention.dense.weight"] = rs.randn(H, H) * 0.02
        sd[p + "attention.dense.bias"] = np.zeros(H)
        sd[p + "post_attention_layernorm.weight"] = np.ones(H)
        sd[p + "post_attention_layernorm.bias"] = np.zeros(H)
        if E:
            sd[p + "mlp.deepspeed_moe.gate.wg.weight"] = rs.randn(E, H) * 0.02
            for e in range(E):
                q = p + f"mlp.deepspeed_moe.experts.deepspeed_experts.{e}."
                sd[q + "dense_h_to_4h.weight"] = rs.randn(I, H) * 0.02
                sd[q + "dense_h_to_4h.bias"] = np.zeros(I)
                sd[q + "dense_4h_to_h.weight"] = rs.randn(H, I) * 0.02
                sd[q + "dense_4h_to_h.bias"] = np.zeros(H)
        else:
            sd[p + "mlp.dense_h_to_4h.weight"] = rs.randn(I, H) * 0.02
            sd[p + "mlp.dense_h_to_4h.bias"] = np.zeros(I)
            sd[p + "mlp.dense_4h_to_h.weight"] = rs.randn(H, I) * 0.02
            sd[p + "mlp.dense_4h_to_h.bias"] = np.zeros(H)
    return {k: np.asarray(v, np.float32) for k, v in sd.items()}


class _MegatronCfg:
    model_type = "megatron_gpt"
    vocab_size = 128
    hidden_size = 32
    num_layers = 2
    num_attention_heads = 4
    ffn_hidden_size = 64
    max_position_embeddings = 64


class TestMegatronInjection:
    def test_dense_converts_and_runs(self):
        from deepspeed_tpu.module_inject.containers import policy_for
        from deepspeed_tpu.models.transformer import TransformerLM

        policy = policy_for("megatron_gpt")
        cfg = policy.build_config(_MegatronCfg())
        cfg.dtype = "float32"
        params = policy.convert_weights(_megatron_sd(), cfg)
        import jax
        import jax.numpy as jnp

        model = TransformerLM(cfg)
        toks = np.random.RandomState(5).randint(0, 128, (2, 10)).astype(np.int32)
        logits = model.apply(jax.tree_util.tree_map(jnp.asarray, params), toks, train=False)
        assert logits.shape == (2, 10, 128)
        assert np.isfinite(np.asarray(logits)).all()

    def test_moe_converts_and_runs(self):
        mesh_mod.reset_topology()
        from deepspeed_tpu.module_inject.replace_module import replace_transformer_layer
        from deepspeed_tpu.models.moe_transformer import MoETransformerLM

        class MoECfg(_MegatronCfg):
            model_type = "megatron_gpt_moe"
            num_experts = 2

        sd = _megatron_sd(E=2)
        ds_model, params = replace_transformer_layer(model=sd, model_config=MoECfg(), dtype="float32")
        assert isinstance(ds_model, MoETransformerLM)
        assert params["layers"]["moe"]["experts"]["w_in"].shape == (2, 2, 32, 64)
        import jax
        import jax.numpy as jnp

        toks = np.random.RandomState(6).randint(0, 128, (2, 10)).astype(np.int32)
        logits = ds_model.apply(jax.tree_util.tree_map(jnp.asarray, params), toks, train=False)
        assert logits.shape == (2, 10, 128)
        assert np.isfinite(np.asarray(logits)).all()


def test_registry_covers_reference_archs():
    from deepspeed_tpu.module_inject.containers import policy_for

    for arch in [
        "gpt2", "llama", "mistral", "opt", "gpt_neox", "bloom", "gptj",
        "bert", "distilbert", "gpt_neo", "megatron_gpt", "megatron_gpt_moe", "clip",
    ]:
        assert policy_for(arch) is not None


class TestGPTJInjection:
    def test_logits_parity_with_torch(self):
        """GPT-J exact parity: shared-ln parallel residual, PARTIAL rotary
        (rotary_dim < head_dim) in HF's interleaved convention (absorbed by
        the conversion-time qk permutation), biased untied head."""
        cfg = transformers.GPTJConfig(
            vocab_size=128,
            n_embd=32,
            n_layer=2,
            n_head=4,
            rotary_dim=4,  # head_dim=8: partial rotary exercised
            n_positions=64,
            resid_pdrop=0.0,
            embd_pdrop=0.0,
            attn_pdrop=0.0,
        )
        model = transformers.GPTJForCausalLM(cfg)
        model.eval()
        toks = np.random.RandomState(7).randint(0, 128, (2, 12)).astype(np.int64)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks)).logits.numpy()

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = _logits(engine, toks)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


class TestGPTNeoXInjection:
    @pytest.mark.parametrize("parallel", [True, False])
    def test_logits_parity_with_torch(self, parallel):
        """NeoX parity in BOTH residual modes (use_parallel_residual is a
        checkpoint-level switch) with partial rotary (rotary_pct=0.5)."""
        cfg = transformers.GPTNeoXConfig(
            vocab_size=128,
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=4,
            intermediate_size=64,
            max_position_embeddings=64,
            rotary_pct=0.5,
            use_parallel_residual=parallel,
            hidden_dropout=0.0,
            attention_dropout=0.0,
        )
        model = transformers.GPTNeoXForCausalLM(cfg)
        model.eval()
        toks = np.random.RandomState(8).randint(0, 128, (2, 12)).astype(np.int64)
        with torch.no_grad():
            ref = model(torch.from_numpy(toks)).logits.numpy()

        mesh_mod.reset_topology()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        out = _logits(engine, toks)
        np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)
