"""KV-cache decode tests.

The load-bearing check mirrors the reference's inference-kernel numerics
tests (``tests/unit/ops/transformer/inference/``): cached incremental decode
must produce the same logits trajectory as the full-sequence forward.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.inference.decode import build_decoder, generate, init_cache
from deepspeed_tpu.models import TransformerLM, gpt2_config, llama_config


def _logits_full(model, params, tokens):
    return model.apply(params, tokens, train=False)


@pytest.mark.parametrize(
    "cfg_fn,kwargs",
    [
        (llama_config, dict(num_layers=2, max_seq_len=64)),
        (gpt2_config, dict(num_layers=2, max_seq_len=64)),
    ],
)
def test_decode_matches_full_forward(cfg_fn, kwargs):
    cfg = cfg_fn("tiny", **kwargs)
    cfg.flash_attention = False
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    B, T = 2, 12
    toks = jax.random.randint(jax.random.PRNGKey(1), (B, T), 0, cfg.vocab_size)
    params = model.init(rng, toks)

    full_logits = _logits_full(model, params, toks)  # [B, T, V]

    prefill, decode_step = build_decoder(cfg)
    prompt = 5
    cache = init_cache(cfg, B, T)
    logits, cache = prefill(params, toks[:, :prompt], cache)
    np.testing.assert_allclose(
        np.asarray(logits, np.float32),
        np.asarray(full_logits[:, prompt - 1, :], np.float32),
        rtol=2e-2, atol=2e-2,
    )
    for pos in range(prompt, T):
        logits, cache = decode_step(params, toks[:, pos], cache, jnp.int32(pos))
        np.testing.assert_allclose(
            np.asarray(logits, np.float32),
            np.asarray(full_logits[:, pos, :], np.float32),
            rtol=2e-2, atol=2e-2,
            err_msg=f"divergence at position {pos}",
        )


def test_generate_greedy_matches_naive():
    cfg = llama_config("tiny", num_layers=2, max_seq_len=64)
    cfg.flash_attention = False
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    B, T = 1, 6
    toks = jax.random.randint(jax.random.PRNGKey(2), (B, T), 0, cfg.vocab_size)
    params = model.init(rng, toks)

    out = generate(cfg, params, toks, max_new_tokens=8)
    assert out.shape == (B, T + 8)
    # naive: re-run the full forward each step, argmax the last position
    cur = np.asarray(toks)
    for _ in range(8):
        logits = _logits_full(model, params, jnp.asarray(cur))
        nxt = np.asarray(jnp.argmax(logits[:, -1, :], axis=-1))[:, None]
        cur = np.concatenate([cur, nxt], axis=1)
    np.testing.assert_array_equal(np.asarray(out), cur)


def test_cache_shapes():
    cfg = llama_config("tiny", num_layers=3, max_seq_len=32)
    cache = init_cache(cfg, batch=2, max_len=16)
    assert cache.k.shape == (3, 2, 16, cfg.num_kv_heads, cfg.head_dim)
    assert cache.max_len == 16


def test_generate_pads_finished_rows_with_eos():
    """Rows that emit EOS must keep emitting EOS, not arbitrary tokens."""
    cfg = llama_config("tiny", num_layers=2, max_seq_len=64)
    cfg.flash_attention = False
    model = TransformerLM(cfg)
    rng = jax.random.PRNGKey(0)
    B, T = 2, 4
    toks = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, cfg.vocab_size)
    params = model.init(rng, toks)

    # pick the first token row 0 would greedily emit as the "eos" id so that
    # row 0 finishes immediately while row 1 (different prompt) continues
    probe = generate(cfg, params, toks, max_new_tokens=1)
    eos = int(np.asarray(probe)[0, T])
    out = np.asarray(generate(cfg, params, toks, max_new_tokens=6, eos_token_id=eos))
    row0_new = out[0, T:]
    first_eos = int(np.argmax(row0_new == eos))
    assert row0_new[first_eos] == eos
    assert (row0_new[first_eos:] == eos).all(), f"post-EOS tokens not padded: {row0_new}"


@pytest.mark.parametrize("shared", [True, False])
def test_decode_matches_full_forward_parallel_residual(shared):
    """GPT-J/NeoX-flavored decode: parallel residual (shared ln_1 or dual
    norms), PARTIAL rotary, biased untied head — the cached trajectory must
    match the full forward exactly."""
    from deepspeed_tpu.models.config import TransformerConfig

    cfg = TransformerConfig(
        vocab_size=128,
        hidden_size=32,
        num_layers=2,
        num_heads=4,
        max_seq_len=64,
        norm="layernorm",
        position="rope",
        rope_dim=4,  # head_dim=8: partial rotary
        activation="gelu",
        use_bias=True,
        qkv_bias=False,
        tie_embeddings=False,
        parallel_residual=True,
        shared_parallel_norm=shared,
        lm_head_bias=True,
        flash_attention=False,
        dtype="float32",
    )
    model = TransformerLM(cfg)
    B, T = 2, 12
    toks = jax.random.randint(jax.random.PRNGKey(3), (B, T), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(2), toks)

    full_logits = _logits_full(model, params, toks)  # [B, T, V]
    prefill, decode_step = build_decoder(cfg)
    cache = init_cache(cfg, B, T, dtype=jnp.float32)
    logits, cache = prefill(params, toks[:, :4], cache)
    np.testing.assert_allclose(
        np.asarray(logits), np.asarray(full_logits[:, 3]), rtol=1e-4, atol=1e-4
    )
    for t in range(4, T):
        logits, cache = decode_step(params, toks[:, t], cache, jnp.int32(t))
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full_logits[:, t]), rtol=1e-4, atol=1e-4
        )
