"""Serving request-span lifecycle tests (ISSUE 10).

The PagedServer records per-step phase spans (admit / pack / dispatch /
emit / journal_sync) and per-request lifecycle spans (submit → admit →
first_token → finish, with preempt instants and tenant / prefix-hit /
spec-accept attributes) onto the engine's tracer. These tests drive the
real scheduler across admission, preemption, and speculative decoding and
assert the timeline tells the true story — plus the engine-surface
``observability()`` merge and the Perfetto trace export for a serving
run."""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
from deepspeed_tpu.inference.scheduler import PagedServer
from deepspeed_tpu.models import TransformerLM
from deepspeed_tpu.models.config import TransformerConfig
from deepspeed_tpu.profiling.tracer import MetricsRegistry, Tracer

CFG = dict(
    vocab_size=128,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    num_kv_heads=2,
    max_seq_len=64,
    norm="rmsnorm",
    position="rope",
    activation="swiglu",
    use_bias=False,
    tie_embeddings=False,
    flash_attention=False,
    dtype="float32",
)


@pytest.fixture(scope="module")
def model_and_params():
    cfg = TransformerConfig(**CFG)
    model = TransformerLM(cfg)
    toks = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), toks)
    return cfg, model, params


def _server(cfg, params, tracer=None, metrics=None, **kw):
    kw.setdefault("page_size", 8)
    kw.setdefault("max_slots", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("attn_impl", "xla")
    kw.setdefault("dtype", jnp.float32)
    return PagedServer(cfg, params, tracer=tracer, metrics=metrics, **kw)


def _lifecycle(tracer, uid):
    """(ph, name) sequence of the async records for one request uid."""
    return [
        (r["ph"], r["name"])
        for r in tracer.spans()
        if r["ph"] in ("b", "n", "e") and r.get("id") == uid
    ]


def _prompts(n, seed=0, lo=3, hi=20):
    rs = np.random.RandomState(seed)
    return [
        rs.randint(0, CFG["vocab_size"], (int(rs.randint(lo, hi)),)).astype(np.int32)
        for _ in range(n)
    ]


def test_request_lifecycle_submit_admit_first_token_finish(model_and_params):
    cfg, _, params = model_and_params
    tr, m = Tracer(), MetricsRegistry()
    server = _server(cfg, params, tracer=tr, metrics=m)
    uids = [server.submit(p, max_new_tokens=6, tenant="acme") for p in _prompts(3)]
    server.run()
    for uid in uids:
        names = _lifecycle(tr, uid)
        assert names[0] == ("b", f"req{uid}")
        assert ("n", "admit") in names
        assert ("n", "first_token") in names
        assert names[-1] == ("e", f"req{uid}")
        # chronology: admit before first_token before finish
        assert names.index(("n", "admit")) < names.index(("n", "first_token"))
    # finish attrs carry the serving story
    end = [r for r in tr.spans() if r["ph"] == "e" and r.get("id") == uids[0]][0]
    assert end["attrs"]["tenant"] == "acme"
    assert end["attrs"]["tokens"] == 6
    assert end["attrs"]["admissions"] == 1
    assert end["attrs"]["ttft_ms"] >= 0.0
    # step phases + metrics observed
    phases = tr.phase_summary()
    for name in ("serve.step", "serve.admit", "serve.pack", "serve.dispatch", "serve.emit"):
        assert phases[name]["count"] >= 1, name
    assert m.snapshot()["counters"]["serve.tokens"] == 18.0
    assert m.snapshot()["histograms"]["serve.ttft_ms"]["count"] == 3


def test_preemption_leaves_preempt_instant_and_readmission(model_and_params):
    """A pool sized to force recompute-preemption: the victim's span trail
    shows preempt → admit again, and its finish attrs count both
    admissions. Output correctness is covered by the serving suites; here
    the TIMELINE is the contract."""
    cfg, _, params = model_and_params
    tr = Tracer()
    server = _server(cfg, params, tracer=tr, num_pages=7, max_slots=2)
    uids = [server.submit(p, max_new_tokens=16) for p in _prompts(2, seed=3, lo=10, hi=14)]
    server.run()
    assert server.stats["preempted"] >= 1
    preempted = [
        r.get("id") for r in tr.spans() if r["ph"] == "n" and r["name"] == "preempt"
    ]
    assert preempted, "no preempt instant recorded"
    uid = preempted[0]
    names = _lifecycle(tr, uid)
    i_pre = names.index(("n", "preempt"))
    assert ("n", "admit") in names[i_pre:], "no re-admission after preempt"
    end = [r for r in tr.spans() if r["ph"] == "e" and r.get("id") == uid][0]
    assert end["attrs"]["admissions"] >= 2


def test_spec_decode_attrs_on_finish(model_and_params):
    """With the n-gram drafter engaged on a motif prompt, the request's
    finish span reports how many drafts it sent and how many were
    accepted (the per-request speculation story)."""
    cfg, _, params = model_and_params
    tr = Tracer()
    server = _server(
        cfg, params,
        spec_decode={"enable": True, "max_draft": 3, "ngram_order": 2},
    )
    server.tracer = tr
    motif = np.array([5, 9, 5, 9, 5, 9, 5, 9, 5, 9], np.int32)
    uid = server.submit(motif, max_new_tokens=8)
    server.run()
    assert server.stats["spec_drafted"] > 0  # the drafter engaged
    end = [r for r in tr.spans() if r["ph"] == "e" and r.get("id") == uid][0]
    assert end["attrs"]["spec_drafted"] == server.stats["spec_drafted"]
    assert end["attrs"]["spec_accepted"] == server.stats["spec_accepted"]


def test_journal_sync_phase_present(model_and_params, tmp_path):
    from deepspeed_tpu.inference.journal import RequestJournal

    cfg, _, params = model_and_params
    tr = Tracer()
    journal = RequestJournal(str(tmp_path / "j"))
    server = _server(cfg, params, tracer=tr, journal=journal)
    server.serve(_prompts(2, seed=5), max_new_tokens=4)
    assert tr.phase_summary()["serve.journal_sync"]["count"] >= 1


def test_prefix_cached_attr_rides_admit_event(model_and_params):
    """Second serve of a shared prompt attaches cached full pages; the
    admit instant reports how many context tokens the request did NOT
    re-prefill."""
    cfg, _, params = model_and_params
    tr = Tracer()
    server = _server(cfg, params, tracer=tr, prefix_cache=True)
    prompt = np.arange(1, 25, dtype=np.int32) % CFG["vocab_size"]
    server.serve([prompt], max_new_tokens=2)
    uid2 = server.submit(prompt, max_new_tokens=2)
    server.run()
    admit2 = [
        r for r in tr.spans()
        if r["ph"] == "n" and r["name"] == "admit" and r.get("id") == uid2
    ][0]
    assert admit2["attrs"]["prefix_cached"] > 0
    end = [r for r in tr.spans() if r["ph"] == "e" and r.get("id") == uid2][0]
    assert end["attrs"]["prefix_cached"] == admit2["attrs"]["prefix_cached"]


def test_engine_observability_merged_report_and_trace(model_and_params, tmp_path):
    """The acceptance surface: ONE observability() call returns the merged
    report (timeline + metrics + compile + analysis + serve stats), and
    the hub exports a Perfetto-loadable trace for the serving run."""
    cfg, model, params = model_and_params
    engine = ds.init_inference(
        model,
        dtype="fp32",
        paged_kv={"page_size": 8, "max_slots": 4, "prefill_chunk": 8,
                  "attn_impl": "xla"},
    )
    engine.set_params(params)
    engine._ds_config = cfg  # converted-family contract
    engine.serve(_prompts(3, seed=7), max_new_tokens=4)
    rep = engine.observability()
    assert set(rep) >= {"timeline", "metrics", "compile", "analysis", "serve"}
    assert rep["timeline"]["phases"]["serve.step"]["count"] >= 1
    assert rep["serve"]["finished"] == 3
    assert any(n.startswith("paged_") for n in rep["compile"])
    # the analysis merge is the real report (violations counted), not a stub
    assert rep["analysis"]["totals"]["violations"] == 0
    # Perfetto trace for a serving run
    path = engine.observability_hub.export_chrome_trace(str(tmp_path / "serve.json"))
    obj = json.load(open(path))
    phs = {e["ph"] for e in obj["traceEvents"]}
    assert {"X", "b", "e"} <= phs  # phase spans + request lifecycles
    names = {e["name"] for e in obj["traceEvents"]}
    assert "serve.dispatch" in names


def test_chaos_kill_mid_emit_leaks_no_open_spans(model_and_params, tmp_path):
    """A ChaosKilled fired from inside the emit path (the journal.append
    hook runs between serve.emit's enter and exit) must unwind through the
    span context managers without leaving phantom open spans — the
    flight-recorder's open_spans answer stays truthful for the rest of the
    process after an in-process recovery."""
    from deepspeed_tpu.inference.journal import RequestJournal
    from deepspeed_tpu.utils import chaos

    cfg, _, params = model_and_params
    tr = Tracer()
    server = _server(
        cfg, params, tracer=tr, journal=RequestJournal(str(tmp_path / "j"))
    )
    server.submit(np.arange(1, 9, dtype=np.int32), max_new_tokens=6)
    try:
        chaos.install(chaos.ChaosSchedule([chaos.ChaosRule("journal.append", hit=2)]))
        with pytest.raises(chaos.ChaosKilled):
            server.run()
    finally:
        chaos.uninstall()
    assert tr.open_spans() == []


def test_multi_tenant_server_exposes_tracer(model_and_params):
    from deepspeed_tpu.inference.traffic import MultiTenantServer

    cfg, _, params = model_and_params
    tr = Tracer()
    inner = _server(cfg, params, tracer=tr)
    mt = MultiTenantServer(inner, tenants=[{"name": "a", "weight": 1.0}])
    assert mt.tracer is tr
    mt.serve(_prompts(1, seed=9), max_new_tokens=2, tenant="a")
    assert tr.phase_summary()["serve.step"]["count"] >= 1
