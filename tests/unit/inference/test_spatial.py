"""Spatial (diffusers) model family: UNet/VAE forward, TP parity, injection.

Reference scope: ``deepspeed/module_inject/replace_module.py:86``
(generic_injection over UNet/VAE), ``module_inject/containers/{unet,vae}.py``,
``csrc/spatial/csrc/opt_bias_add.cu`` (here: XLA fusion)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import (
    AutoencoderKL,
    UNet2DConditionModel,
    UNetConfig,
    VAEConfig,
)
from deepspeed_tpu.parallel.mesh import MeshConfig


def _unet_batch(rs, B=2, size=8, cin=4, ctx_dim=32, ctx_len=6):
    return {
        "sample": rs.randn(B, size, size, cin).astype(np.float32),
        "timesteps": rs.randint(0, 1000, (B,)).astype(np.int32),
        "context": rs.randn(B, ctx_len, ctx_dim).astype(np.float32),
    }


class TestUNet:
    def test_forward_shape(self):
        cfg = UNetConfig(block_channels=(16, 32), groups=4, num_heads=2, context_dim=32)
        model = UNet2DConditionModel(cfg)
        rs = np.random.RandomState(0)
        batch = _unet_batch(rs)
        params = model.init(jax.random.PRNGKey(0), batch)
        out = model.apply(params, batch, train=False)
        assert out.shape == (2, 8, 8, cfg.out_channels)
        assert np.isfinite(np.asarray(out)).all()

    def test_spec_tree_matches_params(self):
        cfg = UNetConfig(block_channels=(16, 32), groups=4, num_heads=2)
        model = UNet2DConditionModel(cfg)
        params = model.init(jax.random.PRNGKey(0))
        specs = model.tp_partition_rules(params)
        # same treedef: zipping must not raise
        from jax.sharding import PartitionSpec

        jax.tree_util.tree_map(
            lambda p, s: None,
            params,
            specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )

    def test_tp2_matches_single_device(self):
        """Sharded (model=2) UNet forward ≡ replicated forward — the conv
        column/row specs must be math-preserving (GSPMD inserts the psum)."""
        cfg = UNetConfig(block_channels=(16, 32), groups=4, num_heads=2, context_dim=32)
        model = UNet2DConditionModel(cfg)
        rs = np.random.RandomState(1)
        batch = _unet_batch(rs)

        mesh_mod.reset_topology()
        params = model.init(jax.random.PRNGKey(0), batch)
        ref = np.asarray(model.apply(params, batch, train=False))

        mesh_mod.reset_topology()
        mesh_mod.initialize_topology(MeshConfig(model=2, data=4))
        engine = ds.init_inference(model, dtype="fp32")
        engine.set_params(params)
        out = np.asarray(engine(batch))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_generic_injection_wraps_spatial(self):
        from deepspeed_tpu.module_inject.replace_module import generic_injection

        mesh_mod.reset_topology()
        cfg = UNetConfig(block_channels=(16, 32), groups=4, num_heads=2, context_dim=32)
        engine = generic_injection(UNet2DConditionModel(cfg), dtype="fp32")
        rs = np.random.RandomState(2)
        batch = _unet_batch(rs)
        out = np.asarray(engine(batch))
        assert out.shape == (2, 8, 8, cfg.out_channels)
        # non-spatial input passes through untouched
        sentinel = object()
        assert generic_injection(sentinel) is sentinel


class TestVAE:
    def test_roundtrip_shapes(self):
        cfg = VAEConfig(block_channels=(16, 32), groups=4)
        model = AutoencoderKL(cfg)
        rs = np.random.RandomState(0)
        x = rs.randn(2, 16, 16, 3).astype(np.float32)
        params = model.init(jax.random.PRNGKey(0))
        mean, logvar = model.encode(params, x)
        assert mean.shape == (2, 4, 4, cfg.latent_channels)
        assert logvar.shape == mean.shape
        recon = model.decode(params, mean)
        assert recon.shape == x.shape
        assert np.isfinite(np.asarray(recon)).all()

    def test_tp2_matches_single_device(self):
        cfg = VAEConfig(block_channels=(16, 32), groups=4)
        model = AutoencoderKL(cfg)
        rs = np.random.RandomState(1)
        x = rs.randn(2, 16, 16, 3).astype(np.float32)

        mesh_mod.reset_topology()
        params = model.init(jax.random.PRNGKey(0))
        ref = np.asarray(model.apply(params, x, train=False))

        mesh_mod.reset_topology()
        mesh_mod.initialize_topology(MeshConfig(model=2, data=4))
        engine = ds.init_inference(model, dtype="fp32")
        engine.set_params(params)
        out = np.asarray(engine(x))
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)
