"""Pre-sharded inference checkpoints (reference: tests/unit/inference/
test_checkpoint_sharding.py; save_mp_checkpoint_path at
deepspeed/inference/engine.py:406): shard files split model-axis leaves,
the manifest drives reassembly, and an engine started from the manifest
produces identical logits."""

import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.inference.mp_checkpoint import (
    MANIFEST_NAME,
    is_mp_checkpoint,
    load_mp_checkpoint,
    save_mp_checkpoint,
)


class TestLayout:
    def test_roundtrip_with_sharded_and_replicated_leaves(self, tmp_path):
        rs = np.random.RandomState(0)
        params = {
            "embed": {"tokens": rs.randn(16, 8).astype(np.float32)},
            "layers": {
                "wq": rs.randn(2, 8, 12).astype(np.float32),
                "norm": rs.randn(2, 8).astype(np.float32),
            },
        }
        specs = {
            "embed": {"tokens": None},
            "layers": {"wq": P(None, None, "model"), "norm": None},
        }
        mpath = save_mp_checkpoint(params, specs, str(tmp_path), tag="t", tp_size=4)
        assert os.path.basename(mpath) == MANIFEST_NAME
        with open(mpath) as f:
            manifest = json.load(f)
        assert manifest["tp_size"] == 4
        assert manifest["shard_dims"] == {"layers/wq": 2}
        # each tp file holds a 12/4-wide slice of wq and nothing replicated
        with np.load(tmp_path / manifest["tp"][1]) as z:
            assert z["layers|wq"].shape == (2, 8, 3)
            assert list(z.files) == ["layers|wq"]
        with np.load(tmp_path / manifest["non_tp"]) as z:
            assert set(z.files) == {"embed|tokens", "layers|norm"}

        loaded, _ = load_mp_checkpoint(mpath)
        for path in ("embed", "layers"):
            for k, v in params[path].items():
                np.testing.assert_array_equal(loaded[path][k], v)

    def test_indivisible_leaf_stays_replicated(self, tmp_path):
        params = {"w": np.arange(10, dtype=np.float32).reshape(2, 5)}
        specs = {"w": P(None, "model")}
        mpath = save_mp_checkpoint(params, specs, str(tmp_path), tp_size=4)
        with open(mpath) as f:
            manifest = json.load(f)
        assert manifest["shard_dims"] == {}  # 5 % 4 != 0: kept whole
        loaded, _ = load_mp_checkpoint(str(tmp_path))
        np.testing.assert_array_equal(loaded["w"], params["w"])

    def test_is_mp_checkpoint_detection(self, tmp_path):
        assert not is_mp_checkpoint(str(tmp_path))
        save_mp_checkpoint({"w": np.ones((2, 2), np.float32)}, {"w": None}, str(tmp_path))
        assert is_mp_checkpoint(str(tmp_path))
        assert is_mp_checkpoint(os.path.join(tmp_path, MANIFEST_NAME))


class TestEngineFlow:
    def _model(self):
        from deepspeed_tpu.models import TransformerLM, llama_config

        return TransformerLM(llama_config("tiny", num_layers=2, remat=False))

    def test_save_load_identical_logits(self, tmp_path, eight_devices):
        mesh_mod.reset_topology()
        model = self._model()
        engine = ds.init_inference(model, dtype="bf16", tensor_parallel={"tp_size": 2})
        toks = np.random.RandomState(0).randint(0, model.config.vocab_size, (2, 16)).astype(np.int32)
        engine.init_params(toks)
        ref_logits = np.asarray(jax.device_get(engine(toks)), np.float32)
        mpath = engine.save_mp_checkpoint(str(tmp_path))
        with open(mpath) as f:
            manifest = json.load(f)
        assert manifest["tp_size"] == 2
        assert manifest["shard_dims"], "TP=2 must shard at least the projections"

        # fresh engine boots straight from the manifest (init_inference
        # checkpoint= path, reference engine.py:406)
        mesh_mod.reset_topology()
        engine2 = ds.init_inference(
            self._model(), dtype="bf16", tensor_parallel={"tp_size": 2}, checkpoint=mpath
        )
        logits2 = np.asarray(jax.device_get(engine2(toks)), np.float32)
        np.testing.assert_allclose(logits2, ref_logits, rtol=2e-2, atol=1e-3)

    def test_auto_save_via_config_path(self, tmp_path, eight_devices):
        mesh_mod.reset_topology()
        model = self._model()
        engine = ds.init_inference(
            model, dtype="bf16", save_mp_checkpoint_path=str(tmp_path)
        )
        toks = np.random.RandomState(0).randint(0, model.config.vocab_size, (2, 16)).astype(np.int32)
        engine.init_params(toks)  # set_params triggers the write
        assert os.path.isfile(os.path.join(tmp_path, MANIFEST_NAME))

    def test_save_before_weights_raises(self, eight_devices):
        mesh_mod.reset_topology()
        engine = ds.init_inference(self._model(), dtype="bf16")
        with pytest.raises(RuntimeError, match="before weights"):
            engine.save_mp_checkpoint("/tmp/nope")
