"""Inference model profiling (reference: tests/unit/inference/
test_model_profiling.py; engine.py:167,518): per-forward latency recording,
cleared on read."""

import numpy as np
import pytest

import deepspeed_tpu as ds
import deepspeed_tpu.parallel.mesh as mesh_mod
from deepspeed_tpu.models import TransformerLM, llama_config


def _engine():
    mesh_mod.reset_topology()
    model = TransformerLM(llama_config("tiny", num_layers=2, remat=False))
    engine = ds.init_inference(model, dtype="bf16")
    toks = np.random.RandomState(0).randint(0, model.config.vocab_size, (2, 16)).astype(np.int32)
    engine.init_params(toks)
    return engine, toks


def test_model_times_records_each_forward(eight_devices):
    engine, toks = _engine()
    engine.profile_model_time()
    for _ in range(3):
        engine(toks)
    times = engine.model_times()
    assert len(times) == 3
    assert all(t > 0 for t in times)
    assert engine.model_times() == []  # cleared on read


def test_model_times_requires_enable(eight_devices):
    engine, toks = _engine()
    engine(toks)
    with pytest.raises(AssertionError, match="not enabled"):
        engine.model_times()


def test_generate_is_profiled(eight_devices):
    engine, toks = _engine()
    engine.profile_model_time()
    engine.generate(toks[:, :4], max_new_tokens=4)
    engine.generate(toks[:, :4], max_new_tokens=4)
    times = engine.model_times()
    assert len(times) == 2 and all(t > 0 for t in times)


def test_profiling_does_not_change_output(eight_devices):
    engine, toks = _engine()
    base = np.asarray(engine(toks), np.float32)
    engine.profile_model_time()
    prof = np.asarray(engine(toks), np.float32)
    np.testing.assert_array_equal(base, prof)
