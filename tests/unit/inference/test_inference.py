"""Injection-policy inference tests.

Reference analog: ``tests/unit/inference/test_inference.py`` (parametrized
over the HF zoo). Here the load-bearing check is logits parity: a tiny HF
GPT-2 converted through the injection policy must produce the same logits
as the torch model.
"""

from __future__ import annotations

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def _tiny_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=128,
        n_positions=64,
        n_embd=32,
        n_layer=2,
        n_head=4,
        resid_pdrop=0.0,
        embd_pdrop=0.0,
        attn_pdrop=0.0,
    )
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    return model


class TestGPT2Injection:
    def test_logits_parity_with_torch(self):
        import deepspeed_tpu as ds

        model = _tiny_gpt2()
        toks = np.random.RandomState(0).randint(0, 128, (2, 10)).astype(np.int64)
        with torch.no_grad():
            ref_logits = model(torch.from_numpy(toks)).logits.numpy()

        engine = ds.init_inference(
            model, dtype="fp32", replace_with_kernel_inject=True
        )
        out = np.asarray(engine.forward(toks.astype(np.int32)), np.float32)
        np.testing.assert_allclose(out, ref_logits, rtol=2e-3, atol=2e-3)

    def test_generate_kv_cached(self):
        import deepspeed_tpu as ds

        model = _tiny_gpt2()
        engine = ds.init_inference(model, dtype="fp32", replace_with_kernel_inject=True)
        toks = np.random.RandomState(1).randint(0, 128, (1, 6)).astype(np.int32)
        out = engine.generate(toks, max_new_tokens=5)
        assert np.asarray(out).shape == (1, 11)
        # greedy parity with torch generate
        with torch.no_grad():
            ref = model.generate(
                torch.from_numpy(toks.astype(np.int64)),
                max_new_tokens=5,
                do_sample=False,
                pad_token_id=0,
            ).numpy()
        np.testing.assert_array_equal(np.asarray(out), ref)


class TestPolicyConfigs:
    def test_llama_policy_config(self):
        from deepspeed_tpu.module_inject.containers import policy_for

        c = transformers.LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        )
        cfg = policy_for("llama").build_config(c)
        assert cfg.norm == "rmsnorm" and cfg.position == "rope"
        assert cfg.activation == "swiglu" and cfg.num_kv_heads == 2

    def test_opt_policy_config(self):
        c = transformers.OPTConfig(
            vocab_size=256, hidden_size=64, ffn_dim=128,
            num_hidden_layers=2, num_attention_heads=4,
        )
        from deepspeed_tpu.module_inject.containers import policy_for

        cfg = policy_for("opt").build_config(c)
        assert cfg.activation == "relu" and cfg.position == "learned"

    def test_unknown_raises(self):
        from deepspeed_tpu.module_inject.containers import policy_for

        with pytest.raises(ValueError):
            policy_for("not_a_model")
